"""Train-on-traffic loop harness (round-19 tentpole, ROADMAP item 2).

Drives the full online-learning data path end to end and records what it
actually sustains:

- an append-only JSONL event log written by ONE environment thread: every
  accepted prediction event followed (after a bounded random delay) by
  its reward event — the delayed-feedback stream a bandit loop sees;
- the `OnlineLearnerRunner` (train/online_loop.py) tailing the log
  concurrently: `RewardJoiner` exactly-once joins, `VWOnlineRing`
  incremental updates, atomic {learner, joiner, cursor} snapshots, and
  the gated publish leg into a `ModelRegistry`;
- `--scenario throughput` (default): no faults, no fleet — the loop's
  headline numbers: applied examples/s, reward-to-applied lag p50/p99,
  update->publish->swap latency, and the holdout-window MSE trajectory
  (the regret-facing number docs/ONLINE.md tracks);
- `--scenario chaos`: the same loop but traffic is REAL — client threads
  post rows through a ServingCoordinator gateway to registry-backed
  worker processes serving the loop's own published weights — under four
  injected fault classes, each of which must heal with zero
  accepted-request loss and an incident bundle:
    worker_kill     one serving worker terminated mid-run (evict +
                    rebalance, clients retry to acceptance);
    learner_kill    `TrainingFaultInjector` kills the learner at a join
                    boundary; the resumed learner must land on a digest
                    BIT-IDENTICAL to an uninterrupted offline replay of
                    the same event log (zero lost / zero double-applied);
    reward_storm    `RewardFaultInjector` duplicates/delays/drops reward
                    events; the joiner's refusal tallies must reconcile
                    EXACTLY against the injector's independent ground
                    truth;
    corrupt_publish a published version is corrupted before its canary
                    rollout; the digest gate must fail the swap and the
                    rollout must auto-roll-back.

Outputs: a markdown row block on stdout (append to docs/PERF.md) and a
JSON summary at --out (defaults docs/ONLINE_loop.json /
docs/ONLINE_chaos.json; bench.py embeds them in `extra.online_loop`).
Armed in scripts/tpu_recovery_watch.sh; env knobs for quick runs:
MEASURE_ONLINE_EVENTS, MEASURE_ONLINE_WORKERS, MEASURE_ONLINE_CLIENTS.
"""

import argparse
import heapq
import json
import multiprocessing as mp
import os
import queue
import random
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

NUM_FEATURES = 64       # numBits=6
ROW_W = 4
SERVICE = "online"
HORIZON_S = 30.0
SNAPSHOT_EVERY = 128
PUBLISH_EVERY = 256
HOLDOUT_EVERY = 10
DEADLINE_MS = 10_000


def _true_weights(seed: int = 3):
    rng = random.Random(seed)
    return [rng.uniform(-1.0, 1.0) for _ in range(NUM_FEATURES)]


def _estimator():
    from mmlspark_tpu.models.vw import VowpalWabbitRegressor
    return VowpalWabbitRegressor(numBits=6)


def _joiner(n_events):
    # The harness is open-loop: the whole stream is enqueued at once, so
    # in-flight predictions can burst toward n_events before their
    # rewards come due. Size the joiner's RAM bound to the burst — the
    # default 4096 would hit the no-spill overflow path and evict live
    # predictions as reward_timeout on a fault-free run. Production
    # loops bound memory with spill_dir instead.
    from mmlspark_tpu.resilience.rewardjoin import RewardJoiner
    return RewardJoiner(horizon_s=HORIZON_S,
                        max_pending_mem=max(4096, 2 * n_events))


# --------------------------------------------------- environment writer

class EnvWriter(threading.Thread):
    """The single log writer: accepted predictions in, {prediction,
    delayed reward} events out. Rewards are the environment's ground
    truth (linear cost + noise) released when due, each passed through
    the optional `RewardFaultInjector` — so the log IS the at-least-once
    stream the joiner must make exactly-once."""

    def __init__(self, log_path, true_w, injector=None, seed=7,
                 delay_range=(0.05, 1.0)):
        super().__init__(daemon=True)
        self.log_path = log_path
        self.true_w = true_w
        self.injector = injector
        self.delay_range = delay_range
        self._rng = random.Random(seed)
        self._q = queue.Queue()
        self._pending = []      # heap of (due, seq, reward_event)
        self._seq = 0
        self.predictions = 0
        self.rewards = 0
        self.done = threading.Event()

    def submit(self, key, indices):
        self._q.put((key, list(indices)))

    def close(self):
        self._q.put(None)

    def _flush_due(self, now):
        from mmlspark_tpu.io.streaming import append_jsonl
        while self._pending and self._pending[0][0] <= now:
            _, _, rew = heapq.heappop(self._pending)
            events = (self.injector.mutate(rew) if self.injector
                      else [rew])
            for ev in events:
                append_jsonl(self.log_path, ev)
            self.rewards += 1

    def run(self):
        from mmlspark_tpu.io.streaming import append_jsonl
        closed = False
        while not (closed and not self._pending):
            self._flush_due(time.perf_counter())
            try:
                item = self._q.get(timeout=0.02)
            except queue.Empty:
                continue
            if item is None:
                closed = True
                continue
            key, indices = item
            ts = time.perf_counter()
            append_jsonl(self.log_path, {
                "kind": "prediction", "key": key, "ts": ts,
                "indices": indices, "values": [1.0] * len(indices),
                "probability": 1.0})
            self.predictions += 1
            cost = sum(self.true_w[j] for j in indices) \
                + self._rng.gauss(0.0, 0.05)
            due = ts + self._rng.uniform(*self.delay_range)
            self._seq += 1
            heapq.heappush(self._pending, (due, self._seq, {
                "kind": "reward", "key": key, "ts": due, "cost": cost}))
        self.done.set()


# ------------------------------------------------------ incident bundles

class IncidentWriter:
    """One atomic JSON bundle per injected fault class: what fired, the
    loop/joiner/chaos tallies at that instant, and the most recent
    coordinator system events (the learner's own online_* events land
    there too via the runner's event_log)."""

    def __init__(self, directory):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self.paths = []
        self.classes = []

    def write(self, reason, detail, **sections):
        from mmlspark_tpu.resilience.elastic import atomic_write_text
        bundle = {"reason": reason, "detail": detail,
                  "wall_utc": time.strftime("%Y-%m-%d %H:%M:%S UTC",
                                            time.gmtime()),
                  **sections}
        path = os.path.join(self.directory,
                            f"{len(self.paths):02d}_{reason}.json")
        atomic_write_text(path, json.dumps(bundle, indent=1, default=str))
        self.paths.append(path)
        self.classes.append(reason)
        print(f"  incident bundle: {reason} ({detail})", flush=True)
        return path


def _recent_events(event_log, n=40):
    try:
        return list(event_log.events())[-n:]
    except Exception:  # noqa: BLE001 - bundles must not fail the run
        return []


# ------------------------------------------------------- learner driving

class LearnerDriver:
    """Owns the runner across kills: drives `step()` until the traffic
    is done and the source runs dry, rebuilding (= resuming from the
    snapshot store) whenever an injected kill lands. Counts busy wall
    time so examples/s reflects the loop, not the idle polls."""

    def __init__(self, mk_runner, traffic_done, incidents=None,
                 chaos_counts=None):
        self.mk_runner = mk_runner
        self.traffic_done = traffic_done
        self.incidents = incidents
        self.chaos_counts = chaos_counts if chaos_counts is not None else {}
        self.runner = mk_runner()
        self.busy_s = 0.0
        self.totals = {"snapshots": 0, "publishes": 0, "kills": 0,
                       "resumes": 0}

    def _absorb(self):
        self.totals["snapshots"] += self.runner.counts["snapshots"]
        self.totals["publishes"] += self.runner.counts["publishes"]

    def drain(self):
        from mmlspark_tpu.resilience import Preempted
        from mmlspark_tpu.resilience.chaos import InjectedKill
        idle = 0
        while True:
            t0 = time.perf_counter()
            try:
                n = self.runner.step()
            except (InjectedKill, Preempted) as exc:
                self.busy_s += time.perf_counter() - t0
                self._absorb()
                self.totals["kills"] += 1
                if self.incidents is not None:
                    self.incidents.write(
                        "learner_kill", repr(exc),
                        loop_counts=dict(self.runner.counts),
                        joiner_counts=dict(self.runner.joiner.counts),
                        chaos_counts=dict(self.chaos_counts))
                self.runner = self.mk_runner()   # resume from the store
                self.totals["resumes"] += self.runner.counts["resumes"]
                continue
            self.busy_s += time.perf_counter() - t0
            if n:
                idle = 0
                continue
            if self.traffic_done.is_set():
                idle += 1
                if idle >= 3:
                    break
            time.sleep(0.01)
        self._absorb()
        self.totals["resumes"] = max(self.totals["resumes"],
                                     self.runner.counts["resumes"])
        return self.runner


def _lag_quantiles(reg):
    def ms(name, q):
        v = reg.quantile(name, q)
        return round(v * 1e3, 2) if v is not None else None
    return {
        "reward_to_applied_p50_ms": ms("online_reward_lag_seconds", 0.5),
        "reward_to_applied_p99_ms": ms("online_reward_lag_seconds", 0.99),
        "publish_swap_p50_ms": ms("online_publish_swap_seconds", 0.5),
        "publish_swap_p99_ms": ms("online_publish_swap_seconds", 0.99),
    }


def _holdout_trajectory(runner, initial_state, final_state):
    """MSE of the untrained model vs the final learner on the FINAL
    held-out window: the accuracy-improves-over-the-run evidence."""
    from mmlspark_tpu.train.online_loop import _eval_holdout
    if runner.gate is None or not runner.gate.window:
        return None
    first = _eval_holdout(initial_state, runner.gate.window, ROW_W)
    last = _eval_holdout(final_state, runner.gate.window, ROW_W)
    return {"initial_mse": round(first["weighted_mse"], 4),
            "final_mse": round(last["weighted_mse"], 4),
            "window": first["examples"]}


# --------------------------------------------------- throughput scenario

def run_throughput(n_events: int) -> dict:
    from mmlspark_tpu.io.registry import ModelRegistry
    from mmlspark_tpu.io.streaming import JsonlEventSource
    from mmlspark_tpu.models.vw.sgd import init_state
    from mmlspark_tpu.observability import MetricsRegistry, set_registry
    from mmlspark_tpu.resilience import CheckpointStore
    from mmlspark_tpu.train.online_loop import (ModelPublisher,
                                                OnlineLearnerRunner)

    reg = MetricsRegistry()
    prev = set_registry(reg)
    work = tempfile.mkdtemp(prefix="online_loop_")
    log_path = os.path.join(work, "events.jsonl")
    registry = ModelRegistry(os.path.join(work, "registry"))
    store = CheckpointStore(os.path.join(work, "ckpt"), keep_last=4)
    true_w = _true_weights()
    env = EnvWriter(log_path, true_w, delay_range=(0.02, 0.5))
    env.start()

    rng = random.Random(11)
    for i in range(n_events):
        env.submit(f"k{i:07d}",
                   sorted(rng.sample(range(NUM_FEATURES), ROW_W)))
    env.close()

    publisher = ModelPublisher(registry, set_current=True)
    runner = OnlineLearnerRunner(
        _estimator(), JsonlEventSource(log_path), row_width=ROW_W,
        store=store, joiner=_joiner(n_events), horizon_s=HORIZON_S,
        snapshot_every=SNAPSHOT_EVERY,
        publish_every=PUBLISH_EVERY, holdout_every=HOLDOUT_EVERY,
        publisher=publisher)
    driver = LearnerDriver(lambda: runner, env.done)
    t0 = time.perf_counter()
    runner = driver.drain()
    runner.joiner.advance(time.perf_counter() + 10 * HORIZON_S)
    final_state, digest = runner.finalize()
    trajectory = _holdout_trajectory(runner, init_state(NUM_FEATURES),
                                     final_state)
    wall = time.perf_counter() - t0

    from mmlspark_tpu.resilience import REFUSAL_REASONS
    summary = {
        "scenario": "throughput",
        "events": n_events,
        "duration_s": round(wall, 2),
        "learner_busy_s": round(driver.busy_s, 2),
        "examples_per_s": round(
            runner.counts["trained"] / max(driver.busy_s, 1e-9), 1),
        "loop_counts": dict(runner.counts),
        "joiner_counts": dict(runner.joiner.counts),
        "refusals": sum(runner.joiner.counts[r]
                        for r in REFUSAL_REASONS),
        "publisher_counts": dict(publisher.counts),
        "learner_digest": digest,
        "holdout": trajectory,
        **_lag_quantiles(reg),
    }
    set_registry(prev)
    return summary


# -------------------------------------------------- chaos serving fleet

def _vw_loader(vdir, manifest):
    """Registry loader for the serving workers: the loop's published
    weights.npz -> dense linear scorer (module-level so spawn-context
    processes can pickle the RegistryModelSource around it)."""
    from mmlspark_tpu.models.vw.sgd import state_from_bytes
    with open(os.path.join(vdir, "weights.npz"), "rb") as fh:
        state = state_from_bytes(fh.read())
    w = np.asarray(state.w, np.float32)
    b = float(np.asarray(state.bias))

    def handler(df):
        x = np.asarray(df["features"], np.float32)
        return df.with_column("prediction", (x @ w + b).astype(np.float32))
    return handler


def _worker_main(coord_url, partition, registry_dir, ready, stop):
    import jax
    jax.config.update("jax_platforms", "cpu")
    from mmlspark_tpu.io.distributed_serving import DistributedServingServer
    from mmlspark_tpu.io.registry import RegistryModelSource

    server = DistributedServingServer(
        None, coord_url, SERVICE, partition=partition,
        machine=f"online-{partition}", port=0,
        max_batch_size=256, max_latency_ms=0.5,
        heartbeat_interval_s=0.25, max_queue=4096,
        model_source=RegistryModelSource(registry_dir, _vw_loader)).start()
    ready.set()
    stop.wait()
    server.stop()


class _TrafficClient(threading.Thread):
    """Posts single-row bodies through the gateway; every eventually-
    accepted (200, well-formed payload) request becomes a prediction
    event in the loop. Retryable failures (503/504, connection drops —
    a worker just died, the gateway is rebalancing) are retried to
    acceptance; a request that exhausts its retry budget or gets a
    malformed 200 payload is ACCEPTED-REQUEST LOSS."""

    def __init__(self, cid, gateway_url, n_requests, env, counters,
                 lock):
        super().__init__(daemon=True)
        self.cid = cid
        self.url = f"{gateway_url}/gateway/{SERVICE}"
        self.n_requests = n_requests
        self.env = env
        self.counters = counters
        self.lock = lock
        self._rng = random.Random(100 + cid)

    def _post(self, body):
        req = urllib.request.Request(
            self.url, data=body,
            headers={"Content-Type": "application/octet-stream",
                     "X-Deadline-Ms": str(DEADLINE_MS)})
        with urllib.request.urlopen(req, timeout=30.0) as r:
            return r.read()

    def run(self):
        from mmlspark_tpu.io import rowcodec
        for i in range(self.n_requests):
            indices = sorted(self._rng.sample(range(NUM_FEATURES), ROW_W))
            x = np.zeros((1, NUM_FEATURES), np.float32)
            x[0, indices] = 1.0
            body = rowcodec.encode("features", x)
            accepted = False
            for attempt in range(40):
                try:
                    payload = self._post(body)
                    _, preds = rowcodec.decode(payload)
                    if preds.shape[0] == 1 and np.isfinite(preds).all():
                        accepted = True
                    else:
                        with self.lock:
                            self.counters["bad_payload"] += 1
                    break
                except urllib.error.HTTPError as e:
                    if e.code in (503, 504):
                        with self.lock:
                            self.counters["retries"] += 1
                        time.sleep(0.05 + 0.05 * min(attempt, 4))
                        continue
                    with self.lock:
                        self.counters["errors"] += 1
                    break
                except Exception:  # noqa: BLE001 - connection-level retry
                    with self.lock:
                        self.counters["retries"] += 1
                    time.sleep(0.05 + 0.05 * min(attempt, 4))
            if accepted:
                with self.lock:
                    self.counters["accepted"] += 1
                self.env.submit(f"c{self.cid}r{i:06d}", indices)
            else:
                with self.lock:
                    self.counters["lost"] += 1


def run_chaos(n_events: int, n_workers: int, n_clients: int) -> dict:
    from mmlspark_tpu.io.distributed_serving import ServingCoordinator
    from mmlspark_tpu.io.registry import ModelRegistry
    from mmlspark_tpu.io.streaming import JsonlEventSource
    from mmlspark_tpu.models.vw.sgd import init_state, state_to_bytes
    from mmlspark_tpu.observability import MetricsRegistry, set_registry
    from mmlspark_tpu.resilience import CheckpointStore
    from mmlspark_tpu.resilience.chaos import (RewardFaultInjector,
                                               TrainingFaultInjector)
    from mmlspark_tpu.train.online_loop import (ModelPublisher,
                                                OnlineLearnerRunner,
                                                offline_replay)

    reg = MetricsRegistry()
    prev = set_registry(reg)
    work = tempfile.mkdtemp(prefix="online_chaos_")
    log_path = os.path.join(work, "events.jsonl")
    rdir = os.path.join(work, "registry")
    registry = ModelRegistry(rdir, keep_last=8)
    store = CheckpointStore(os.path.join(work, "ckpt"), keep_last=4)
    incidents = IncidentWriter(os.path.join(work, "incidents"))

    # v1: the untrained model the fleet serves while the loop warms up
    registry.publish(
        {"weights.npz": state_to_bytes(init_state(NUM_FEATURES))},
        extra={"kind": "online_loop"}, set_current=True)

    coord = ServingCoordinator(
        heartbeat_timeout_s=2.0, registry=reg, coalesce_max=8,
        canary_beats=2, rollout_timeout_s=8.0).start()
    ctx = mp.get_context("spawn")
    procs, stops = [], []
    for p in range(n_workers):
        ready, stop = ctx.Event(), ctx.Event()
        proc = ctx.Process(target=_worker_main,
                           args=(coord.url, p, rdir, ready, stop),
                           daemon=True)
        proc.start()
        procs.append(proc)
        stops.append(stop)
        if not ready.wait(60):
            raise RuntimeError("serving worker failed to start")

    # reward storm: seeded duplicate/delay/drop faults on the reward
    # stream — the injector's counts are the independent ground truth
    reward_inj = RewardFaultInjector(
        seed=19, duplicate_rate=0.08, delay_rate=0.05, drop_rate=0.05,
        horizon_s=HORIZON_S)
    env = EnvWriter(log_path, _true_weights(), injector=reward_inj,
                    delay_range=(0.05, 1.0))
    env.start()

    # the publish leg rolls new versions through the coordinator; the
    # holdout gate doubles as the rollout monitor (a worse canary rolls
    # back like a corrupt artifact). The monitor reads the CURRENT
    # runner's live window through `holder` so a learner kill/resume
    # does not strand it on a dead gate object.
    holder = {}
    rollouts = []

    def rollout_fn(version):
        try:
            # the canary pointer is the monitor's handle on what is
            # being judged (the coordinator tracks workers, not the
            # model registry)
            registry.set_canary(version)
            coord.start_rollout(SERVICE, version)
            rollouts.append({"version": version, "state": "started"})
        except Exception as exc:  # noqa: BLE001 - a busy rollout is not fatal
            rollouts.append({"version": version,
                             "skipped": str(exc)[:120]})

    def monitor():
        try:
            runner = holder.get("runner")
            if runner is None or runner.gate is None:
                return None
            return runner.gate.rollout_monitor(registry)()
        except Exception:  # noqa: BLE001 - a racing window read is not a breach
            return None
    coord.add_rollout_monitor(monitor)

    # promote the registry CURRENT pointer when a rollout completes so
    # the holdout gate's incumbent tracks what the fleet actually serves
    promoter_stop = threading.Event()

    def promoter():
        promoted = set()
        while not promoter_stop.is_set():
            ro = coord.rollout_status(SERVICE) or {}
            if ro.get("state") == "done":
                target = int(ro.get("target", 0))
                if target and target not in promoted:
                    registry.set_current(target)
                    promoted.add(target)
                    rollouts.append({"version": target,
                                     "state": "promoted"})
            promoter_stop.wait(0.2)
    promoter_thread = threading.Thread(target=promoter, daemon=True)
    promoter_thread.start()

    train_inj = TrainingFaultInjector(seed=0, kill_at_chunk=2)

    def mk_runner():
        runner = OnlineLearnerRunner(
            _estimator(), JsonlEventSource(log_path), row_width=ROW_W,
            store=store, joiner=_joiner(n_events), horizon_s=HORIZON_S,
            snapshot_every=SNAPSHOT_EVERY, publish_every=PUBLISH_EVERY,
            holdout_every=HOLDOUT_EVERY,
            publisher=ModelPublisher(registry, rollout_fn=rollout_fn),
            event_log=coord.events)
        train_inj.arm(runner)
        holder["runner"] = runner
        return runner

    lock = threading.Lock()
    counters = {"accepted": 0, "lost": 0, "bad_payload": 0,
                "retries": 0, "errors": 0}
    per_client = n_events // n_clients
    clients = [_TrafficClient(c, coord.url, per_client, env, counters,
                              lock) for c in range(n_clients)]
    t0 = time.perf_counter()
    for c in clients:
        c.start()

    # worker kill: terminate one worker a third of the way through the
    # traffic; the gateway must evict it and clients retry to acceptance
    worker_kills = [0]

    def killer():
        target = max(1, (per_client * n_clients) // 3)
        while True:
            with lock:
                if counters["accepted"] >= target:
                    break
            time.sleep(0.05)
        procs[0].terminate()
        worker_kills[0] += 1
        with lock:
            snap = dict(counters)
        incidents.write("worker_kill",
                        f"terminated worker 0 of {n_workers} at "
                        f"{snap['accepted']} accepted requests",
                        client_counters=snap,
                        system_events=_recent_events(coord.events))
    kill_thread = threading.Thread(target=killer, daemon=True)
    kill_thread.start()

    # the learner drains the log CONCURRENTLY with the traffic; a closer
    # thread ends the environment once every client has finished
    def closer():
        for c in clients:
            c.join()
        env.close()
    closer_thread = threading.Thread(target=closer, daemon=True)
    closer_thread.start()

    driver = LearnerDriver(mk_runner, env.done, incidents=incidents,
                           chaos_counts=reward_inj.counts)
    runner = driver.drain()
    closer_thread.join(30.0)
    kill_thread.join(10.0)
    wall = time.perf_counter() - t0

    # flush the join buffer far past the horizon: every dropped reward's
    # prediction must surface as a counted reward_timeout
    runner.joiner.advance(time.perf_counter() + 10 * HORIZON_S)
    final_state, digest = runner.finalize()
    trajectory = _holdout_trajectory(runner, init_state(NUM_FEATURES),
                                     final_state)

    # reward-storm reconciliation: ground truth vs the joiner, EXACT
    jc = dict(runner.joiner.counts)
    fc = dict(reward_inj.counts)
    identities = {
        "joined == ok + duplicate_reward":
            jc["joined"] == fc["ok"] + fc["duplicate_reward"],
        "duplicate == duplicate_reward":
            jc["duplicate"] == fc["duplicate_reward"],
        "expired == delay_reward": jc["expired"] == fc["delay_reward"],
        "reward_timeout == drop_reward":
            jc["reward_timeout"] == fc["drop_reward"],
        "no unknown_key": jc["unknown_key"] == 0,
        "no malformed": jc["malformed"] == 0,
    }
    reconciliation = {"exact": all(identities.values()),
                      "identities": identities,
                      "joiner": jc, "injected": fc}
    incidents.write("reward_storm",
                    f"{fc['rewards']} rewards through seeded "
                    f"duplicate/delay/drop faults",
                    reconciliation=reconciliation,
                    system_events=_recent_events(coord.events))

    # digest parity: the killed-and-resumed learner vs an uninterrupted
    # offline replay of the exact same event log
    oracle = offline_replay(
        _estimator(), JsonlEventSource(log_path), row_width=ROW_W,
        joiner=_joiner(n_events), horizon_s=HORIZON_S,
        snapshot_every=SNAPSHOT_EVERY, holdout_every=HOLDOUT_EVERY)
    parity = digest == oracle

    # corrupt publish: a fresh version, corrupted on disk, rolled out —
    # the swap's digest gate must fail and the rollout auto-roll-back
    corrupt_state = {"state": "not_attempted"}
    vbad = registry.publish(
        {"weights.npz": state_to_bytes(final_state),
         "meta.json": json.dumps({"learner_digest": digest}).encode()},
        extra={"kind": "online_loop"})
    TrainingFaultInjector.corrupt_version_payload(registry, vbad)
    registry.set_canary(vbad)
    started = False
    for _ in range(100):
        try:
            coord.start_rollout(SERVICE, vbad)
            started = True
            break
        except ValueError:
            time.sleep(0.2)
    if started:
        deadline = time.time() + 30.0
        while time.time() < deadline:
            ro = coord.rollout_status(SERVICE) or {}
            if ro.get("state") in ("done", "rolled_back"):
                break
            time.sleep(0.1)
        ro = coord.rollout_status(SERVICE) or {}
        corrupt_state = {"version": vbad, "state": ro.get("state"),
                         "reason": ro.get("reason")}
    incidents.write("corrupt_publish",
                    f"v{vbad} corrupted on disk, rollout ended "
                    f"{corrupt_state.get('state')!r}",
                    rollout=corrupt_state,
                    system_events=_recent_events(coord.events))

    with lock:
        tallies = dict(counters)
    summary = {
        "scenario": "chaos",
        "events": per_client * n_clients,
        "workers": n_workers,
        "clients": n_clients,
        "duration_s": round(wall, 2),
        "learner_busy_s": round(driver.busy_s, 2),
        "examples_per_s": round(
            runner.counts["trained"] / max(driver.busy_s, 1e-9), 1),
        "loop_counts": dict(runner.counts),
        "loop_totals": dict(driver.totals),
        "client_counters": tallies,
        "rollouts": rollouts,
        "holdout": trajectory,
        "learner_digest": digest,
        **_lag_quantiles(reg),
        "chaos": {
            "accepted_lost": tallies["lost"] + tallies["bad_payload"],
            "worker_kills": worker_kills[0],
            "learner_kills": driver.totals["kills"],
            "resumes": driver.totals["resumes"],
            "digest_parity": parity,
            "oracle_digest": oracle,
            "reward_reconciliation": reconciliation,
            "corrupt_publish": corrupt_state,
            "incident_classes": list(incidents.classes),
            "incident_paths": list(incidents.paths),
        },
    }

    promoter_stop.set()
    promoter_thread.join(5.0)
    for p, st in zip(procs, stops):
        if p.is_alive():
            st.set()
    for p in procs:
        p.join(10.0)
        if p.is_alive():
            p.terminate()
    coord.stop()
    set_registry(prev)
    return summary


# ----------------------------------------------------------------- main

def _gate_chaos(s) -> int:
    rc = 0
    chaos = s["chaos"]
    if chaos["accepted_lost"]:
        print(f"  !! accepted-request loss: {chaos['accepted_lost']}")
        rc = 1
    if not (chaos["learner_kills"] >= 1 and chaos["resumes"] >= 1):
        print("  !! learner kill/resume never fired")
        rc = 1
    if not chaos["digest_parity"]:
        print(f"  !! resumed learner digest {s['learner_digest']} != "
              f"offline replay {chaos['oracle_digest']}")
        rc = 1
    if not chaos["reward_reconciliation"]["exact"]:
        print(f"  !! reward reconciliation inexact: "
              f"{chaos['reward_reconciliation']['identities']}")
        rc = 1
    if chaos["corrupt_publish"].get("state") != "rolled_back":
        print(f"  !! corrupt publish ended "
              f"{chaos['corrupt_publish'].get('state')!r}, wanted "
              f"'rolled_back'")
        rc = 1
    missing = ({"worker_kill", "learner_kill", "reward_storm",
                "corrupt_publish"} - set(chaos["incident_classes"]))
    if missing:
        print(f"  !! missing incident bundles: {sorted(missing)}")
        rc = 1
    return rc


def _gate_throughput(s) -> int:
    rc = 0
    if s["loop_counts"]["joined"] != s["events"]:
        print(f"  !! joined {s['loop_counts']['joined']} != "
              f"{s['events']} events (fault-free run must join all)")
        rc = 1
    if s["refusals"]:
        print(f"  !! {s['refusals']} refusals on a fault-free stream")
        rc = 1
    if not s["publisher_counts"]["published"]:
        print("  !! nothing published")
        rc = 1
    return rc


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--scenario", default="throughput",
                    choices=("throughput", "chaos"))
    ap.add_argument("--events", type=int, default=int(
        os.environ.get("MEASURE_ONLINE_EVENTS", "0")) or None)
    ap.add_argument("--workers", type=int, default=int(
        os.environ.get("MEASURE_ONLINE_WORKERS", "4")))
    ap.add_argument("--clients", type=int, default=int(
        os.environ.get("MEASURE_ONLINE_CLIENTS", "4")))
    ap.add_argument("--out", default=None)
    args = ap.parse_args()
    if args.out is None:
        args.out = {"throughput": "docs/ONLINE_loop.json",
                    "chaos": "docs/ONLINE_chaos.json"}[args.scenario]
    n_events = args.events or \
        (8000 if args.scenario == "throughput" else 4000)

    print(f"== online loop: {args.scenario}, {n_events} events",
          flush=True)
    if args.scenario == "throughput":
        summary = run_throughput(n_events)
        rc = _gate_throughput(summary)
    else:
        summary = run_chaos(n_events, args.workers, args.clients)
        rc = _gate_chaos(summary)

    record = {
        "host": "cpu",
        "scenario": args.scenario,
        "date_utc": time.strftime("%Y-%m-%d %H:%M UTC", time.gmtime()),
        **summary,
    }
    print(json.dumps({k: v for k, v in record.items()
                      if k not in ("chaos", "rollouts")}, indent=1),
          flush=True)
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(record, f, indent=1)
    print(f"wrote {args.out}")

    lc = record["loop_counts"]
    print(f"\n| scenario | ex/s | reward->applied p50/p99 | "
          f"publish->swap p50 | joined | publishes |")
    print("|---|---|---|---|---|---|")
    print(f"| {record['scenario']} | {record['examples_per_s']:.0f} | "
          f"{record['reward_to_applied_p50_ms']} / "
          f"{record['reward_to_applied_p99_ms']} ms | "
          f"{record['publish_swap_p50_ms']} ms | {lc['joined']} | "
          f"{lc.get('publishes', 0)} |")
    return rc


if __name__ == "__main__":
    sys.exit(main())
