#!/usr/bin/env python
"""Cold start before/after: persistent compile cache + AOT-exported executables.

Two fleet-critical bring-up paths (ROADMAP item 3 / ISSUE-11), each measured
cold vs warm in FRESH subprocesses so nothing in-process can leak warmth:

1. **Serving worker cold-start-to-first-reply.** The worker serves the hot
   entry-point portfolio the compile layer routes (GBDT raw-predict batch
   buckets, the ResNet-50 featurizer forward, a 12-layer transformer
   classifier forward) and — like the real pool — only takes traffic after
   warming every program it serves. The clock runs from worker bring-up
   start to the first HTTP reply.
   - cold: empty XLA cache, no AOT artifacts (full trace + compile per
     program — the hung-ResNet-50-compile shape that wedged the pool)
   - warm: the "second worker" shape — AOT artifacts exported at publish
     time (pre-compiled executables + jax.export fallbacks) plus the
     persistent XLA cache a previous worker filled
2. **Preempt -> resume-to-first-chunk.** A checkpointed fit is preempted at
   a chunk boundary (PR 10 drain/chaos machinery); the resume is clocked
   from fit() entry to its first chunk commit.
   - cold: empty XLA cache (the resume pays the full chunk-program compile)
   - warm: the cache the original fit filled (same GBDTConfig + shapes =>
     executable deserialization instead of compilation)

Emits one JSON document (stdout + --out); docs/SERVING.md and
docs/RESILIENCE.md table the numbers. The acceptance gate is
warm_speedup >= 5x on the serving path; cache-hit counters in each child's
cache_stats prove the warm path really loaded executables instead of
compiling. CPU-measured here; the on-chip run is armed in
scripts/tpu_recovery_watch.sh.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# serving portfolio shapes
GBDT_ROWS, GBDT_FEATS, GBDT_ITERS, GBDT_LEAVES = 4000, 16, 120, 31
GBDT_BUCKETS = (1, 2, 4, 8, 16, 32, 64)
TFM_LAYERS, TFM_D, TFM_HEADS, TFM_SEQ = 12, 256, 4, 32
RN50_BATCH = 1

# resume shapes (small: resume-to-first-chunk should expose the
# chunk-program compile, not bulk execution — the chunk program compiles in
# ~1 s on this host regardless of row count)
FIT_ROWS, FIT_ITERS, FIT_CHUNK = 512, 48, 12


def _gbdt_data(n=GBDT_ROWS):
    import numpy as np
    rng = np.random.default_rng(7)
    x = rng.normal(size=(n, GBDT_FEATS)).astype(np.float32)
    y = (x[:, 0] + 0.5 * x[:, 1] * x[:, 2] > 0).astype(np.float64)
    return x, y


def _tfm_params():
    import jax
    from mmlspark_tpu.models.deep.transformer import init_encoder_params
    return init_encoder_params(jax.random.PRNGKey(0), TFM_LAYERS, TFM_D,
                               TFM_HEADS, TFM_D * 4)


def _rn50():
    import jax
    import jax.numpy as jnp
    from mmlspark_tpu.models.deep.dnn import GraphModel
    from mmlspark_tpu.models.deep.resnet import _ZOO
    sch = _ZOO["ResNet50"]()
    h, w, c = sch.input_dims
    var = sch.module.init(jax.random.PRNGKey(0),
                          jnp.zeros((1, h, w, c), jnp.float32))
    return GraphModel(sch.module, var, sch)


def _tfm_fwd():
    from mmlspark_tpu.models.deep.transformer import encoder_forward

    def fwd(p, x):
        return encoder_forward(p, x, TFM_HEADS)
    return fwd


# ---------------------------------------------------------------------------
# child bodies (fresh subprocesses; each prints one JSON line)
# ---------------------------------------------------------------------------

def child_publish(work: str) -> None:
    """Publish step: train/init the portfolio, export every AOT artifact."""
    import jax
    import numpy as np
    from jax import export as jax_export

    from mmlspark_tpu import DataFrame
    from mmlspark_tpu.compile.aot import AOTStore, compile_for_export
    from mmlspark_tpu.models.lightgbm import LightGBMClassifier
    x, y = _gbdt_data()
    model = LightGBMClassifier(numIterations=GBDT_ITERS,
                               numLeaves=GBDT_LEAVES).fit(
        DataFrame({"features": x, "label": y}))
    b = model.booster
    np.savez(os.path.join(work, "model.npz"), **b.save_arrays())
    with open(os.path.join(work, "model.json"), "w") as f:
        json.dump(b.to_dict(), f)
    b.export_serving_artifacts(os.path.join(work, "aot_gbdt"),
                               batch_sizes=GBDT_BUCKETS)
    gm = _rn50()
    gm.export_serving_artifacts(os.path.join(work, "aot_rn50"),
                                batch_sizes=(RN50_BATCH,), layers=("pool",))
    p = _tfm_params()
    store = AOTStore(os.path.join(work, "aot_tfm"))
    fn = jax.jit(_tfm_fwd())
    specs = (jax.tree.map(lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype),
                          p),
             jax.ShapeDtypeStruct((1, TFM_SEQ, TFM_D), "float32"))
    store.save("encoder_b1", jax_export.export(fn)(*specs),
               compiled=compile_for_export(fn, *specs),
               extra={"entry_point": "transformer_encoder_fwd"})
    print(json.dumps({"ok": True}))


def _load_booster(work: str):
    import numpy as np

    from mmlspark_tpu.models.lightgbm.booster import Booster
    with open(os.path.join(work, "model.json")) as f:
        meta = json.load(f)
    arrays = dict(np.load(os.path.join(work, "model.npz")))
    return Booster.from_parts(meta, arrays)


def child_serve(work: str, *, aot: bool) -> None:
    """One serving worker: bring-up -> portfolio warm -> first HTTP reply."""
    t_proc = time.perf_counter()
    import urllib.request

    import jax
    import jax.numpy as jnp
    import numpy as np

    from mmlspark_tpu.compile import cache_stats
    from mmlspark_tpu.compile.aot import (AOTStore, load_serving_callable)
    from mmlspark_tpu.compile.cache import cached_jit
    from mmlspark_tpu.io.serving import ServingServer
    t_import = time.perf_counter() - t_proc

    t0 = time.perf_counter()
    booster = _load_booster(work)
    gm = _rn50()
    tfm_p = _tfm_params()
    if aot:
        booster.load_serving_artifacts(os.path.join(work, "aot_gbdt"))
        gm.load_serving_artifacts(os.path.join(work, "aot_rn50"))
    t_model = time.perf_counter() - t0

    def handler(df):
        xb = np.stack([np.asarray(v, np.float32) for v in df["features"]])
        return df.with_column("prediction", booster.score(xb))

    digests = {}
    t0 = time.perf_counter()
    # portfolio warm-up: the worker is serviceable only once every program
    # it serves is resident (a request on an unwarmed program pays its
    # compile inline — the exact hazard this PR removes)
    for bk in GBDT_BUCKETS:
        out = booster.raw_predict(np.zeros((bk, booster.num_features),
                                           np.float32))
        digests[f"gbdt_b{bk}"] = float(np.asarray(out).sum())
    h, w, c = gm.schema.input_dims
    xb = jnp.zeros((RN50_BATCH, h, w, c), jnp.float32)
    out = gm._aot_apply("pool", gm.variables, xb)
    if out is None:
        out = gm.apply_fn("pool")(gm.variables, xb)
    digests["rn50_pool"] = float(np.asarray(out).sum())
    xt = jnp.zeros((1, TFM_SEQ, TFM_D), jnp.float32)
    tf_fn = None
    if aot:
        tf_fn = load_serving_callable(
            AOTStore(os.path.join(work, "aot_tfm")), "encoder_b1",
            (tfm_p, xt))
    if tf_fn is None:
        tf_fn = cached_jit(_tfm_fwd(), key=("cold_start_tfm",),
                           name="transformer_encoder_fwd")
    digests["tfm"] = float(np.asarray(tf_fn(tfm_p, xt)).sum())
    srv = ServingServer(handler, reply_col="prediction", port=0,
                        max_latency_ms=0.0).start()
    body = json.dumps(
        {"features": [0.1] * booster.num_features}).encode()
    req = urllib.request.Request(
        srv.url, data=body, headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=600) as r:
        reply = json.loads(r.read())
    first_reply_s = time.perf_counter() - t0
    srv.stop()
    digests["reply"] = reply["prediction"]
    print(json.dumps({
        "import_s": round(t_import, 3),
        "model_load_s": round(t_model, 3),
        "start_to_first_reply_s": round(first_reply_s, 4),
        "digests": digests,
        "cache_stats": cache_stats(),
    }))


def child_fit(work: str) -> None:
    """Original fit, preempted at a chunk boundary: fills the snapshot AND
    the warm compile cache (the chunk program compiled before the kill)."""
    from mmlspark_tpu import DataFrame
    from mmlspark_tpu.models.lightgbm import LightGBMClassifier
    from mmlspark_tpu.resilience.chaos import (InjectedKill,
                                               TrainingFaultInjector)
    x, y = _gbdt_data(FIT_ROWS)
    est = LightGBMClassifier(numIterations=FIT_ITERS, numLeaves=GBDT_LEAVES,
                             checkpointDir=os.path.join(work, "ck"),
                             itersPerCall=FIT_CHUNK)
    TrainingFaultInjector(kill_at_chunk=1).arm(est)
    t0 = time.perf_counter()
    try:
        est.fit(DataFrame({"features": x, "label": y}))
        killed = False
    except InjectedKill:
        killed = True
    print(json.dumps({"fit_s": round(time.perf_counter() - t0, 3),
                      "preempted": killed}))


def child_resume(work: str) -> None:
    """Elastic resume from the mid-fit snapshot: fit() entry -> first chunk
    commit (same config => same chunk program as the original fit)."""
    from mmlspark_tpu import DataFrame
    from mmlspark_tpu.compile import cache_stats
    from mmlspark_tpu.models.lightgbm import LightGBMClassifier
    x, y = _gbdt_data(FIT_ROWS)
    est = LightGBMClassifier(numIterations=FIT_ITERS, numLeaves=GBDT_LEAVES,
                             checkpointDir=os.path.join(work, "ck"),
                             itersPerCall=FIT_CHUNK)
    first_chunk = {}
    t0 = time.perf_counter()
    est._chunk_boundary_hook = lambda ci, si: first_chunk.setdefault(
        "s", time.perf_counter() - t0)
    model = est.fit(DataFrame({"features": x, "label": y}))
    digest = float(model.booster.raw_predict(x[:64]).sum())
    print(json.dumps({
        "resume_fit_s": round(time.perf_counter() - t0, 3),
        "resume_to_first_chunk_s": round(first_chunk.get("s", -1), 4),
        "digest": digest,
        "cache_stats": cache_stats(),
    }))


# ---------------------------------------------------------------------------
# parent orchestration
# ---------------------------------------------------------------------------

def _run_child(mode: str, work: str, cache_dir: str, extra=()) -> dict:
    env = dict(os.environ)
    env["MMLSPARK_COMPILE_CACHE"] = "1"
    env["MMLSPARK_COMPILE_CACHE_DIR"] = cache_dir
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child", mode,
         "--work", work, *extra],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=3600)
    if out.returncode != 0:
        raise RuntimeError(f"child {mode} failed:\n{out.stdout}\n{out.stderr}")
    return json.loads(out.stdout.strip().splitlines()[-1])


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--child", default=None)
    ap.add_argument("--work", default=None)
    ap.add_argument("--aot", action="store_true")
    ap.add_argument("--out", default=None,
                    help="also write the result JSON here")
    args = ap.parse_args()

    if args.child:
        {"publish": child_publish,
         "fit": child_fit,
         "resume": child_resume,
         "serve": lambda w: child_serve(w, aot=args.aot),
         }[args.child](args.work)
        return 0

    work = tempfile.mkdtemp(prefix="cold_start_")
    cold1 = os.path.join(work, "xla-cold-serve")
    cold2 = os.path.join(work, "xla-cold-resume")
    warm = os.path.join(work, "xla-warm")

    print("== publish: train + export AOT artifacts", file=sys.stderr)
    _run_child("publish", work, os.path.join(work, "xla-publish"))

    # best-of-rounds on BOTH paths (scheduler-noise damping on a shared
    # host — the same min-of-rounds discipline as bench.py's min-of-fits
    # and tests/test_serving_latency.py's best-of-3)
    serve_cold_runs, serve_warm_runs = [], []
    for i in range(2):
        print(f"== serving cold #{i} (empty cache, no AOT)",
              file=sys.stderr)
        serve_cold_runs.append(
            _run_child("serve", work, f"{cold1}-{i}"))
    print("== serving prime (first warm worker fills the persistent cache)",
          file=sys.stderr)
    _run_child("serve", work, warm, extra=("--aot",))
    for i in range(2):
        print(f"== serving warm #{i} (AOT + persistent cache)",
              file=sys.stderr)
        serve_warm_runs.append(
            _run_child("serve", work, warm, extra=("--aot",)))
    key = "start_to_first_reply_s"
    serve_cold = min(serve_cold_runs, key=lambda r: r[key])
    serve_warm = min(serve_warm_runs, key=lambda r: r[key])
    assert serve_cold["digests"] == serve_warm["digests"], (
        "digest mismatch between fresh-JIT and AOT-loaded predictions:\n"
        f"cold: {serve_cold['digests']}\nwarm: {serve_warm['digests']}")

    print("== original checkpointed fit, preempted at a chunk boundary",
          file=sys.stderr)
    fit = _run_child("fit", work, warm)
    # the resume's chunk program is a DIFFERENT executable from the fresh
    # fit's (restored init margins change the traced config), so the warm
    # row is the fleet's resume-storm shape: a previous resume attempt of
    # this worker (re-preempted or re-scheduled) already compiled it. Every
    # measured resume starts from the SAME snapshot (directory copied).
    import shutil
    ck, ck_bak = os.path.join(work, "ck"), os.path.join(work, "ck.bak")
    shutil.copytree(ck, ck_bak)

    def _fresh_ck():
        shutil.rmtree(ck, ignore_errors=True)
        shutil.copytree(ck_bak, ck)

    print("== resume cold (empty cache)", file=sys.stderr)
    resume_cold = _run_child("resume", work, cold2)
    print("== resume prime (first resume attempt fills the cache)",
          file=sys.stderr)
    _fresh_ck()
    _run_child("resume", work, warm)
    print("== resume warm (re-scheduled resume: original attempt's cache)",
          file=sys.stderr)
    _fresh_ck()
    resume_warm = _run_child("resume", work, warm)
    assert resume_cold["digest"] == resume_warm["digest"], (
        "resumed boosters diverged between cold and warm compile paths")

    import jax
    serve_speedup = (serve_cold["start_to_first_reply_s"]
                     / max(serve_warm["start_to_first_reply_s"], 1e-9))
    resume_speedup = (resume_cold["resume_to_first_chunk_s"]
                      / max(resume_warm["resume_to_first_chunk_s"], 1e-9))
    doc = {
        "benchmark": "cold_start",
        "device": jax.devices()[0].device_kind,
        "platform": jax.default_backend(),
        "serving_portfolio": {
            "gbdt": {"rows": GBDT_ROWS, "features": GBDT_FEATS,
                     "iters": GBDT_ITERS, "buckets": list(GBDT_BUCKETS)},
            "rn50_featurizer": {"batch": RN50_BATCH},
            "transformer": {"layers": TFM_LAYERS, "d_model": TFM_D,
                            "seq": TFM_SEQ}},
        "serving": {"cold": serve_cold, "warm": serve_warm,
                    "cold_runs_s": [r[key] for r in serve_cold_runs],
                    "warm_runs_s": [r[key] for r in serve_warm_runs],
                    "warm_speedup": round(serve_speedup, 2)},
        "resume": {"shape": {"rows": FIT_ROWS, "iters": FIT_ITERS,
                             "chunk_iters": FIT_CHUNK},
                   "fit": fit, "cold": resume_cold, "warm": resume_warm,
                   "warm_speedup": round(resume_speedup, 2)},
        "gate_5x_serving": serve_speedup >= 5.0,
    }
    text = json.dumps(doc, indent=1)
    print(text)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
    # exit status reflects the acceptance gate so the watcher logs a failure
    return 0 if serve_speedup >= 5.0 else 3


if __name__ == "__main__":
    sys.exit(main())
