"""Measure per-iteration training cost of the three split-scan modes on the
live chip: eager/full, eager/compact, lazy. Run from the repo root.

Methodology (docs/KERNELS.md): per-iter = (wall(24 iters) - wall(4 iters))/20
so setup, dispatch RTT and compile are excluded; min over repeats to shed
shared-pool throttling noise. Writes one line per mode to stdout and appends
to docs/PERF_scan_modes.log.
"""

import os
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mmlspark_tpu.ops.boosting import GBDTConfig, make_train_fn

LOG = os.path.join(os.path.dirname(__file__), "..", "docs",
                   "PERF_scan_modes.log")


def main(n=1_000_000, f=28, b=64, lcap=31):
    rng = np.random.default_rng(0)
    binned = jnp.asarray(rng.integers(0, b, size=(n, f), dtype=np.int8))
    coef = rng.normal(size=f)
    yv = jnp.asarray(((np.asarray(binned, np.float32) @ coef)
                      > coef.sum() * b / 2).astype(np.float32))
    w = jnp.ones((n,), jnp.float32)
    it_ = jnp.ones((n,), jnp.float32)
    margin = jnp.zeros((n, 1), jnp.float32)
    key = jax.random.PRNGKey(0)
    dev = jax.devices()[0]
    print("device:", dev, flush=True)
    with open(LOG, "a") as fh:
        fh.write(f"== {time.strftime('%Y-%m-%d %H:%M:%S UTC', time.gmtime())}"
                 f" on {dev} n={n} f={f} b={b} L={lcap}\n")

    # proven modes first, the heaviest compiles last, each mode fenced by
    # its own try — one failure must not lose the others' measurements
    # (the healthy-pool window this runs in is rare), and the log is
    # appended after EVERY mode for the same reason. The (refresh, scan,
    # splits_per_pass) triples cover strict eager, lazy, batched top-k
    # (k=4, 8) and compact.
    for refresh, scan, spp in (("eager", "full", 1), ("lazy", "full", 1),
                               ("eager", "full", 4), ("eager", "full", 8),
                               ("eager", "compact", 1)):
        try:
            cfg = GBDTConfig(num_iterations=24, num_leaves=lcap, max_bins=b,
                             hist_method="pallas", hist_chunk=4096,
                             split_refresh=refresh, split_scan=scan,
                             splits_per_pass=spp,
                             objective="binary")
            tr24 = make_train_fn(cfg)
            tr4 = make_train_fn(cfg._replace(num_iterations=4))
            f24 = jax.jit(
                lambda *a: jax.tree_util.tree_leaves(tr24(*a))[0].sum())
            f4 = jax.jit(
                lambda *a: jax.tree_util.tree_leaves(tr4(*a))[0].sum())
            t0 = time.time()
            float(f24(binned, yv, w, it_, margin, key))
            float(f4(binned, yv, w, it_, margin, key))
            compile_s = time.time() - t0
            t24, t4 = [], []
            for _ in range(3):
                t0 = time.perf_counter()
                float(f4(binned, yv, w, it_, margin, key))
                t4.append(time.perf_counter() - t0)
                t0 = time.perf_counter()
                float(f24(binned, yv, w, it_, margin, key))
                t24.append(time.perf_counter() - t0)
            per = (min(t24) - min(t4)) / 20 * 1e3
            tag = f"{refresh}/{scan}" + (f"/k{spp}" if spp > 1 else "")
            line = (f"{tag}: per-iter {per:7.2f} ms "
                    f"(compile+first {compile_s:.0f}s, 4it {min(t4):.2f}s, "
                    f"24it {min(t24):.2f}s)")
        except Exception as e:  # noqa: BLE001 - keep the other modes
            line = (f"{refresh}/{scan}/k{spp}: FAILED "
                    f"{type(e).__name__}: {str(e)[:200]}")
        print(line, flush=True)
        with open(LOG, "a") as fh:
            fh.write(line + "\n")


if __name__ == "__main__":
    main()
