"""Measure the multi-host training fabric: the pod-slice scaling ladder
(ISSUE 15, ROADMAP item 4).

Armed in scripts/tpu_recovery_watch.sh. Behavior:

- Locally (CPU, the default): a VIRTUAL pod slice — H subprocess hosts,
  each a separate OS process with its own
  ``XLA_FLAGS=--xla_force_host_platform_device_count=D`` backend, joined
  through the real rendezvous contract (parallel/rendezvous.py
  coordinator -> roster barrier -> gated jax.distributed/gloo init,
  exactly the path `multihost.connect` drives on a pod). The 1-host rung
  is the same worker at H=1 (single-controller mesh fit), so the scaling
  ratio compares like against like. CPU-mesh numbers validate scaling
  STRUCTURE (digest parity across host counts, chooser topology fields,
  measured cross-host allreduce vs the ICI/DCN wall model), not absolute
  throughput.
- On a pod slice (each host launched by the pool runner with
  MEASURE_PODSLICE_WORKER=1 + a shared coordinator address): the same
  worker body runs on real ICI/DCN — the 1->2->4-host ladder the watcher
  arms for the next multi-host window.

Per rung: warm + timed fits of ``LightGBMClassifier(numTasks=H*D)``
(process-local binning/transfer via multihost.binned_to_device), the
strategy decision's hosts/devices_per_host/inter-host-bytes fields, the
structural fit digest (must be identical across EVERY rung and host), and
a measured global-mesh child-slice allreduce wall beside the closed-form
``allreduce_wall_model_s`` prediction. Rows append to
docs/PERF_podslice.log; the launcher writes one summary JSON (--out).
"""

import argparse
import hashlib
import json
import os
import re
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
# the ONE reap-safe subprocess-host launcher (try/finally kill + hard
# per-worker timeout) is shared with the multi-host tests — this script
# runs from a repo checkout, where tests/ is always present
sys.path.insert(0, os.path.join(_REPO, "tests"))
from multihost_harness import free_port, launch_hosts  # noqa: E402

LOG = os.path.join(os.path.dirname(__file__), "..", "docs",
                   "PERF_podslice.log")

#: CPU-mesh problem shape: bounded (~15 s/rung on a 24-core box) but
#: non-trivial — NaN-bearing, weighted, row count not a multiple of any
#: rung's device count, scatter hist (the CPU-mesh discipline of
#: measure_multichip_fit.py)
N_ROWS, N_FEATURES, ITERS, BINS, LEAVES = 60_003, 16, 10, 32, 15


def _log(row):
    line = json.dumps(row)
    print(line, flush=True)
    try:
        with open(LOG, "a") as fh:
            fh.write(line + "\n")
    except OSError:
        pass


def _data():
    import numpy as np
    rng = np.random.default_rng(7)
    x = rng.normal(size=(N_ROWS, N_FEATURES)).astype(np.float32)
    x[rng.random((N_ROWS, N_FEATURES)) < 0.05] = np.nan
    y = (np.nansum(x[:, :4], axis=1) > 0).astype(np.float64)
    w = rng.uniform(0.5, 2.0, size=N_ROWS).astype(np.float32)
    return x, y, w


def _struct_digest(model_string: str) -> str:
    """Structural digest of a STRAIGHT fit's model_string (split records
    only — leaf values carry cross-process reduction-order fp noise).
    The canonical definition: tests/test_multihost_fabric.py imports it
    from here. NOT valid for a RESUMED booster, whose model_string
    renumbers nodes from the BFS slot layout (parse_model_string first —
    test_elastic)."""
    struct = "\n".join(l for l in model_string.splitlines()
                       if l.split("=")[0] in
                       ("split_feature", "threshold", "decision_type",
                        "left_child", "right_child", "num_leaves"))
    return hashlib.sha256(struct.encode()).hexdigest()


# ----------------------------------------------------------------- worker

def worker(args) -> int:
    """One host of the rung: rendezvous -> fit -> rows on stdout (the
    launcher keeps process 0's). Runs identically on the virtual CPU
    fabric and on a real pod-slice host."""
    from mmlspark_tpu.core.dataframe import DataFrame
    from mmlspark_tpu.models.lightgbm import LightGBMClassifier
    from mmlspark_tpu.parallel import mesh as meshlib
    from mmlspark_tpu.parallel import multihost as mh
    from mmlspark_tpu.parallel import strategy as stratlib

    sess = mh.connect(args.coordinator, args.hosts, name=args.name,
                      jax_port=args.jax_port or None, deadline_s=120.0,
                      heartbeat_interval_s=1.0)
    topo = sess.topology
    ndev = topo.devices
    x, y, w = _data()
    df = DataFrame({"features": x, "label": y, "w": w})
    clf = LightGBMClassifier(numIterations=ITERS, numLeaves=LEAVES,
                             maxBin=BINS, numTasks=ndev, weightCol="w",
                             histMethod="scatter")
    t0 = time.time()
    mdl = clf.fit(df)                                   # compile + warm
    warm = time.time() - t0
    walls = []
    for _ in range(2):
        t0 = time.time()
        mdl = clf.fit(df)
        walls.append(time.time() - t0)
    dec = mdl.booster.fit_strategy
    row = {"row": "rung", "hosts": topo.hosts,
           "devices_per_host": topo.devices_per_host, "ndev": ndev,
           "process_id": topo.process_id,
           "n": N_ROWS, "iters": ITERS,
           "strategy": dec["strategy"],
           "decision_hosts": dec.get("hosts"),
           "decision_devices_per_host": dec.get("devices_per_host"),
           "dp_inter_host_bytes_per_split":
               dec.get("dp_inter_host_bytes_per_split"),
           "voting_inter_host_bytes_per_split":
               dec.get("voting_inter_host_bytes_per_split"),
           "warm_wall_s": round(warm, 2),
           "wall_s": [round(w_, 2) for w_ in walls],
           "rows_iter_per_s": round(N_ROWS * ITERS / min(walls), 1),
           "pipelined": bool(clf._last_fit_pipelined),
           "digest": _struct_digest(mdl.booster.model_string())}
    # measured cross-host allreduce on the GLOBAL mesh vs the hierarchical
    # ICI/DCN wall model — the grounding the chooser's hosts term rests on
    arw = stratlib.measure_allreduce_wall_s(meshlib.get_mesh(ndev),
                                            N_FEATURES, BINS, reps=3)
    payload = stratlib.comm_bytes_per_split(N_FEATURES, BINS, LEAVES, 20,
                                            "data_parallel")
    row["allreduce_wall_child_slice_ms"] = round(arw * 1e3, 3)
    row["allreduce_wall_model_ms"] = round(
        stratlib.allreduce_wall_model_s(payload, ndev, topo.hosts) * 1e3, 4)
    row["allreduce_effective_bytes_per_s"] = round(
        2.0 * (ndev - 1) / ndev * payload / arw, 1) if ndev > 1 else None
    print("ROW " + json.dumps(row), flush=True)
    sess.close()
    return 0


# ---------------------------------------------------------------- launcher

def _launch_rung(hosts: int, dph: int, timeout_s: float):
    """One virtual rung: coordinator here, H subprocess hosts, each on
    its own D-device CPU backend, launched through the shared reap-safe
    harness (tests/multihost_harness.launch_hosts). Returns process 0's
    rows after cross-checking every host's digest."""
    from mmlspark_tpu.parallel.rendezvous import RendezvousCoordinator
    coord = RendezvousCoordinator(hosts, heartbeat_timeout_s=15.0).start()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   env.get("XLA_FLAGS", ""))
    env["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={dph}").strip()
    try:
        outs = launch_hosts(
            [[sys.executable, "-u", os.path.abspath(__file__),
              "--worker", "--coordinator", coord.address,
              "--hosts", str(hosts), "--jax-port", str(free_port()),
              "--name", f"vhost{i}"] for i in range(hosts)],
            env, timeout_s=timeout_s, per_worker_timeout_s=timeout_s)
    finally:
        coord.stop()
    rows, digests = [], []
    for rc, out, err in outs:
        if rc != 0:
            raise RuntimeError(f"rung {hosts}x{dph} worker failed rc={rc}: "
                               f"{err[-1500:]}")
        for line in out.splitlines():
            if line.startswith("ROW "):
                r = json.loads(line[4:])
                digests.append(r["digest"])
                if r["process_id"] == 0:
                    rows.append(r)
    if len(digests) != hosts or not rows:
        raise RuntimeError(f"rung {hosts}x{dph}: expected {hosts} worker "
                           f"rows, got {len(digests)}")
    if len(set(digests)) != 1:
        raise RuntimeError(f"rung {hosts}x{dph}: hosts disagree on the "
                           f"fit digest: {digests}")
    return rows[0]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--worker", action="store_true")
    ap.add_argument("--coordinator", default="")
    ap.add_argument("--hosts", type=int, default=2)
    ap.add_argument("--jax-port", type=int, default=0)
    ap.add_argument("--name", default="")
    ap.add_argument("--dph", type=int, default=8,
                    help="devices per host (virtual CPU backend size)")
    ap.add_argument("--ladder", default="1,2",
                    help="comma host-count ladder (watcher arms 1,2,4)")
    ap.add_argument("--rung-timeout-s", type=float, default=600.0)
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(__file__), "..", "docs", "PODSLICE_cpu.json"))
    args = ap.parse_args()
    if args.worker:
        sys.exit(worker(args))

    from mmlspark_tpu.parallel import strategy as stratlib
    ladder = [int(h) for h in args.ladder.split(",") if h.strip()]
    _log({"row": "start", "ladder": ladder, "devices_per_host": args.dph,
          "n": N_ROWS, "iters": ITERS,
          "start": time.strftime("%Y-%m-%d %H:%M:%S UTC", time.gmtime())})
    summary = {"devices_per_host": args.dph, "n": N_ROWS, "iters": ITERS,
               "rungs": [], "dcn_dominance_hosts_predicted":
                   stratlib.dcn_dominance_hosts(args.dph)}
    base_rate, base_digest = None, None
    for hosts in ladder:
        try:
            row = _launch_rung(hosts, args.dph, args.rung_timeout_s)
        except Exception as e:  # noqa: BLE001 - one rung must not cost the rest
            _log({"row": "rung", "hosts": hosts, "error": str(e)[:500]})
            summary["rungs"].append({"hosts": hosts, "error": str(e)[:500]})
            continue
        if base_rate is None:
            base_rate, base_digest = row["rows_iter_per_s"], row["digest"]
        row["speedup_vs_1host"] = round(row["rows_iter_per_s"] / base_rate, 3)
        row["scaling_efficiency"] = round(
            row["rows_iter_per_s"] / (base_rate * hosts), 3)
        # the acceptance digest: every rung of the ladder must train the
        # structurally identical model (the cross-host fit changes WHERE
        # rows are binned, never WHAT is learned)
        row["digest_matches_1host"] = bool(row["digest"] == base_digest)
        _log(row)
        summary["rungs"].append(row)
        if not row["digest_matches_1host"]:
            _log({"row": "digest_mismatch", "hosts": hosts,
                  "digest": row["digest"], "base": base_digest})
    ok = [r for r in summary["rungs"] if "error" not in r]
    summary["measured_rungs"] = len(ok)
    summary["digest_parity_all_rungs"] = bool(
        ok and all(r["digest_matches_1host"] for r in ok))
    out = os.path.abspath(args.out)
    with open(out, "w") as fh:
        json.dump(summary, fh, indent=1)
    _log({"row": "summary", "out": out,
          "measured_rungs": summary["measured_rungs"],
          "digest_parity_all_rungs": summary["digest_parity_all_rungs"]})
    sys.exit(0 if summary["digest_parity_all_rungs"] else 1)


if __name__ == "__main__":
    main()
