import time, numpy as np, jax, jax.numpy as jnp
from functools import partial
from mmlspark_tpu.ops.histogram import hist_slots_onehot
from mmlspark_tpu.ops.pallas_kernels import hist_slots_pallas
print(jax.devices(), flush=True)
rng = np.random.default_rng(0)
N, F, B, L = 1_000_000, 28, 64, 31
binned = jnp.asarray(rng.integers(0, B, (N, F)), jnp.uint8)
slot = jnp.asarray(rng.integers(0, L, (N,)), jnp.int32)
gh = jnp.asarray(rng.normal(size=(N, 3)), jnp.float32)

def bench(name, fn):
    f = jax.jit(fn)
    t0 = time.perf_counter()
    out = f(binned, slot, gh); out.block_until_ready()
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter(); R = 10
    for _ in range(R): out = f(binned, slot, gh)
    out.block_until_ready()
    dt = (time.perf_counter() - t0) / R
    print(f'{name}: {dt*1e3:.2f} ms/pass (compile {compile_s:.1f}s)', flush=True)

for chunk in (2048, 8192, 32768):
    bench(f'onehot bf16 chunk={chunk}', partial(hist_slots_onehot, num_slots=L, num_bins=B, chunk=chunk, dtype='bf16'))
for br in (1024, 2048, 4096, 8192):
    for ft in (4, 14, 28):
        bench(f'pallas br={br} ft={ft}', partial(hist_slots_pallas, num_slots=L, num_bins=B, block_rows=br, feat_tile=ft))
