"""TPU histogram-kernel sweep: measured operating table for docs/KERNELS.md.

Times every (method, chunk, dtype) candidate of the all-slots histogram at
bench shapes on the live backend, prints a markdown table, then times one
full LightGBMClassifier.fit at the winning config. Run on a real chip; on
CPU it still works but measures the scatter path (see docs/KERNELS.md)."""

import time

import numpy as np


def main() -> None:
    import jax
    import jax.numpy as jnp
    from mmlspark_tpu.ops.histogram import hist_slots

    dev = jax.devices()[0]
    print(f"backend: {dev.platform} ({dev})", flush=True)
    rng = np.random.default_rng(0)
    n, f, b, l = 1_000_000, 28, 64, 31
    binned = jnp.asarray(rng.integers(0, b, (n, f)), jnp.uint8)
    slot = jnp.asarray(rng.integers(0, l, (n,)), jnp.int32)
    gh = jnp.asarray(rng.normal(size=(n, 3)), jnp.float32)

    candidates = [("onehot", c, d) for c in (2048, 8192, 32768)
                  for d in ("bf16", "f32")]
    candidates += [("pallas", c, d) for c in (1024, 2048, 4096, 8192)
                   for d in ("bf16", "f32")]
    if dev.platform == "cpu":
        candidates.append(("scatter", 512, "f32"))

    rows = []
    for method, chunk, dtype in candidates:
        try:
            fn = jax.jit(lambda bi, sl, g, m=method, c=chunk, d=dtype:
                         hist_slots(bi, sl, g, l, b, m, c, d))
            t0 = time.perf_counter()
            fn(binned, slot, gh).block_until_ready()
            compile_s = time.perf_counter() - t0
            reps = 10
            t0 = time.perf_counter()
            for _ in range(reps):
                out = fn(binned, slot, gh)
            out.block_until_ready()
            ms = (time.perf_counter() - t0) / reps * 1e3
            rows.append((method, chunk, dtype, ms, compile_s))
            print(f"  {method:7s} chunk={chunk:<6d} {dtype}: "
                  f"{ms:8.2f} ms/pass (compile {compile_s:.1f}s)", flush=True)
        except Exception as e:  # noqa: BLE001 - variant may not lower
            print(f"  {method:7s} chunk={chunk:<6d} {dtype}: FAILED "
                  f"{type(e).__name__}: {str(e)[:120]}", flush=True)

    rows.sort(key=lambda r: r[3])
    print(f"\n| method | chunk | dtype | ms/pass ({n//1000}k x {f}, "
          f"B={b}, L={l}) |")
    print("|---|---|---|---|")
    for method, chunk, dtype, ms, _ in rows:
        print(f"| {method} | {chunk} | {dtype} | {ms:.2f} |")

    best = rows[0]
    print(f"\nwinner: {best[0]} chunk={best[1]} {best[2]}", flush=True)

    # one full fit at the winner (100 iters, the bench problem)
    from mmlspark_tpu.core.dataframe import DataFrame
    from mmlspark_tpu.models.lightgbm import LightGBMClassifier
    x = rng.normal(size=(n, f)).astype(np.float32)
    y = ((x @ rng.normal(size=f)) > 0).astype(np.float64)
    df = DataFrame({"features": x, "label": y})
    clf = LightGBMClassifier(numIterations=100, numLeaves=l, maxBin=b,
                             histMethod=best[0], histChunk=best[1],
                             histDtype=best[2], numTasks=1)
    t0 = time.perf_counter()
    clf.fit(df)
    print(f"fit #1 (compile+run): {time.perf_counter() - t0:.1f}s",
          flush=True)
    t0 = time.perf_counter()
    clf.fit(df)
    wall = time.perf_counter() - t0
    print(f"fit #2 (run): {wall:.1f}s = "
          f"{n * 100 / wall / 1e6:.2f}M rows*iter/s", flush=True)


if __name__ == "__main__":
    main()
