"""TPU histogram-kernel sweep: measured operating table for docs/KERNELS.md.

Times every (method, chunk, dtype) candidate of the all-slots histogram at
bench shapes on the live backend, prints a markdown table, then times one
full LightGBMClassifier.fit at the winning config. Run on a real chip; on
CPU it still works but measures the scatter path (see docs/KERNELS.md)."""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    import jax
    from mmlspark_tpu.ops.autotune import measure_hist

    dev = jax.devices()[0]
    print(f"backend: {dev.platform} ({dev})", flush=True)
    inner = 8
    print(f"paired-difference timing ({inner} vs {3 * inner} scan-amortized "
          f"passes; relay round trip cancels per pair)", flush=True)
    n, f, b, l = 1_000_000, 28, 64, 31

    candidates = [("onehot", c, d) for c in (2048, 8192, 32768)
                  for d in ("bf16", "f32")]
    candidates += [("pallas", c, d) for c in (2048, 4096, 8192, 16384)
                   for d in ("bf16", "f32")]
    if dev.platform == "cpu":
        candidates.append(("scatter", 512, "f32"))

    rows = []
    for method, chunk, dtype in candidates:
        try:
            t0 = time.perf_counter()
            sec = measure_hist(method, chunk, n, f, b, l, dtype,
                               inner=inner)
            total_s = time.perf_counter() - t0
            ms = sec * 1e3
            rows.append((method, chunk, dtype, ms, total_s))
            print(f"  {method:7s} chunk={chunk:<6d} {dtype}: "
                  f"{ms:8.2f} ms/pass (probe {total_s:.1f}s)", flush=True)
        except Exception as e:  # noqa: BLE001 - variant may not lower
            print(f"  {method:7s} chunk={chunk:<6d} {dtype}: FAILED "
                  f"{type(e).__name__}: {str(e)[:120]}", flush=True)

    rows.sort(key=lambda r: r[3])
    print(f"\n| method | chunk | dtype | ms/pass ({n//1000}k x {f}, "
          f"B={b}, L={l}) |")
    print("|---|---|---|---|")
    for method, chunk, dtype, ms, _ in rows:
        print(f"| {method} | {chunk} | {dtype} | {ms:.2f} |")

    best = rows[0]
    print(f"\nwinner: {best[0]} chunk={best[1]} {best[2]}", flush=True)

    # one full fit at the winner (100 iters, the bench problem)
    from mmlspark_tpu.core.dataframe import DataFrame
    from mmlspark_tpu.models.lightgbm import LightGBMClassifier
    rng = np.random.default_rng(0)
    x = rng.normal(size=(n, f)).astype(np.float32)
    y = ((x @ rng.normal(size=f)) > 0).astype(np.float64)
    df = DataFrame({"features": x, "label": y})
    clf = LightGBMClassifier(numIterations=100, numLeaves=l, maxBin=b,
                             histMethod=best[0], histChunk=best[1],
                             histDtype=best[2], numTasks=1)
    t0 = time.perf_counter()
    clf.fit(df)
    print(f"fit #1 (compile+run): {time.perf_counter() - t0:.1f}s",
          flush=True)
    t0 = time.perf_counter()
    clf.fit(df)
    wall = time.perf_counter() - t0
    print(f"fit #2 (run): {wall:.1f}s = "
          f"{n * 100 / wall / 1e6:.2f}M rows*iter/s", flush=True)


if __name__ == "__main__":
    main()
