"""Measure the mesh-default multi-chip fit path: 1 -> 2 -> 4 -> 8 scaling
rows for docs/PERF.md (ISSUE 9).

Armed for the next healthy pool window — scripts/tpu_recovery_watch.sh
runs this FIRST. Behavior:

- On an accelerator with >= 2 visible chips: the real scaling ladder.
- On a 1-device backend (single-chip grant or CPU fallback): re-execs
  itself onto an 8-device host-platform CPU mesh
  (XLA_FLAGS=--xla_force_host_platform_device_count=8) so the ladder is
  still MEASURED — CPU-mesh numbers validate scaling structure (comm
  model, digest parity, overlap), not absolute throughput, and the
  on-chip run stays armed in the watcher for the next multi-chip window.

Per ndev rung: warm + timed fits of LightGBMClassifier(numTasks=ndev)
(parallelism='auto' — the strategy chooser decides the learner), sampled
train AUC + held-out AUC with the PROMOTION GATE anchored to the serial
rung (a rung whose held-out AUC drops more than the gate is recorded but
flagged not-promotable), the strategy decision + closed-form comm bytes,
a measured child-slice allreduce wall on the rung's mesh, and (largest
rung) the per-shard straggler gap from an instrumented fit. Every row is
appended to docs/PERF_multichip.log and printed as one JSON line.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

LOG = os.path.join(os.path.dirname(__file__), "..", "docs",
                   "PERF_multichip.log")
AUC_GATE = 0.002
CPU_MESH_ENV = "MULTICHIP_CPU_MESH"


def _log(row):
    line = json.dumps(row)
    print(line, flush=True)
    with open(LOG, "a") as fh:
        fh.write(line + "\n")


def main():
    if os.environ.get(CPU_MESH_ENV):
        # forced CPU mesh: the flags must land before jax imports; an
        # existing device-count pin is REPLACED (not deferred to), so the
        # re-exec'd child always sees 8 devices
        import re
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                       os.environ.get("XLA_FLAGS", ""))
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
        import jax
        devs = jax.devices()
        init_err = None
    else:
        import bench
        _jx, devs, init_err, _ = bench._patient_backend_bringup()
        import jax

    on_accel = devs[0].platform not in ("cpu",)
    # recursion guard: a child already running under CPU_MESH_ENV never
    # re-execs again — if it still sees one device it measures the
    # 1-rung ladder and says so, instead of spawning itself forever
    if len(devs) < 2 and not os.environ.get(CPU_MESH_ENV):
        # single device (one-chip grant or CPU fallback): measure the
        # ladder on the virtual CPU mesh instead; the on-chip multi-chip
        # run stays armed in tpu_recovery_watch.sh for a pod-slice window
        _log({"row": "reexec_cpu_mesh", "visible_devices": len(devs),
              "platform": devs[0].platform, "init_err": init_err,
              "note": "multi-chip ladder measured on 8-device CPU mesh; "
                      "on-chip run armed for the next multi-chip window"})
        env = dict(os.environ, **{CPU_MESH_ENV: "1"})
        sys.exit(subprocess.call([sys.executable, "-u",
                                  os.path.abspath(__file__)], env=env))

    from mmlspark_tpu.core.dataframe import DataFrame
    from mmlspark_tpu.models.lightgbm import LightGBMClassifier
    from mmlspark_tpu.observability import (get_registry,
                                            publish_multichip_fit)
    from mmlspark_tpu.parallel import mesh as meshlib
    from mmlspark_tpu.parallel import strategy as stratlib
    from sklearn.metrics import roc_auc_score

    _log({"start": time.strftime("%Y-%m-%d %H:%M:%S UTC", time.gmtime()),
          "device": str(devs[0]), "n_devices": len(devs),
          "on_accel": on_accel, "init_err": init_err})

    # CPU-mesh shape sized for a bounded run on a virtual mesh (8 XLA CPU
    # devices over the host cores): ~15 s serial, ~3 s at 8 shards —
    # structure-validating, not absolute-throughput (the chip shape runs
    # the bench problem with the autotuned kernel)
    if on_accel:
        n, f, iters, bins, leaves = 4_000_000, 28, 100, 64, 31
        fit_kw = {}
    else:
        n, f, iters, bins, leaves = 50_000, 28, 10, 32, 15
        fit_kw = {"histMethod": "scatter"}
    rng = np.random.default_rng(0)
    x = rng.normal(size=(n, f)).astype(np.float32)
    coef = rng.normal(size=f)

    def label_of(xs):
        return ((xs @ coef + 0.5 * xs[:, 0] * xs[:, 1]
                 + rng.normal(scale=1.0, size=len(xs))) > 0
                ).astype(np.float64)

    y = label_of(x)
    df = DataFrame({"features": x, "label": y})
    n_ho = 100_000 if on_accel else 40_000
    x_ho = rng.normal(size=(n_ho, f)).astype(np.float32)
    y_ho = label_of(x_ho)
    idx = rng.choice(n, min(n, 100_000), replace=False)

    ladder = [nd for nd in (1, 2, 4, 8) if nd <= len(devs)]
    base_rate, base_auc_ho = None, None
    t0_all = time.time()
    for nd in ladder:
        try:
            clf = LightGBMClassifier(numIterations=iters, numLeaves=leaves,
                                     maxBin=bins, numTasks=nd, **fit_kw)
            t0 = time.time()
            mdl = clf.fit(df)                       # compile + warm
            warm = time.time() - t0
            walls = []
            for _ in range(2):
                t0 = time.time()
                mdl = clf.fit(df)
                walls.append(time.time() - t0)
                if time.time() - t0_all > 1500:
                    break
            wall = min(walls)
            rate = n * iters / wall
            a_tr = roc_auc_score(y[idx], mdl.booster.score(x[idx]))
            a_ho = roc_auc_score(y_ho, mdl.booster.score(x_ho))
            dec = mdl.booster.fit_strategy
            row = {"row": "scaling", "ndev": nd, "n": n, "iters": iters,
                   "strategy": dec["strategy"],
                   "voting_advantage": round(dec["advantage"], 3),
                   "comm_bytes_per_split_dp": dec["dp_bytes_per_split"],
                   "comm_bytes_per_split_voting":
                       dec["voting_bytes_per_split"],
                   "warm_wall_s": round(warm, 2),
                   "wall_s": [round(w_, 2) for w_ in walls],
                   "rows_iter_per_s": round(rate, 1),
                   "auc_sample": round(a_tr, 4),
                   "auc_holdout": round(a_ho, 4)}
            if base_rate is None:
                base_rate, base_auc_ho = rate, a_ho
            row["speedup_vs_1dev"] = round(rate / base_rate, 3)
            row["scaling_efficiency"] = round(rate / (base_rate * nd), 3)
            # AUC-gated promotion, anchored to the serial rung of THIS run
            row["auc_gate_ok"] = bool(a_ho >= base_auc_ho - AUC_GATE)
            if nd > 1:
                arw = stratlib.measure_allreduce_wall_s(
                    meshlib.get_mesh(nd), f, bins, reps=5)
                row["allreduce_wall_child_slice_ms"] = round(arw * 1e3, 3)
                publish_multichip_fit(stratlib.StrategyDecision(**dec),
                                      allreduce_wall_s=arw)
            _log(row)
        except Exception as e:  # noqa: BLE001 - one rung must not cost the rest
            _log({"row": "scaling", "ndev": nd, "error": str(e)[:300]})

    # straggler gap at the largest rung: instrumented fit (barriers added
    # — NOT a throughput number, so it runs after the timed ladder)
    try:
        nd = ladder[-1]
        if nd > 1:
            clf = LightGBMClassifier(numIterations=min(iters, 10),
                                     numLeaves=leaves, maxBin=bins,
                                     numTasks=nd, collectFitTimings=True,
                                     **fit_kw)
            tm = clf.fit(df).booster.fit_timings
            gap = tm.get("shard_straggler_gap_s", {}).get("total_s")
            _log({"row": "straggler_gap", "ndev": nd,
                  "gap_s": round(gap, 4) if gap is not None else None})
    except Exception as e:  # noqa: BLE001
        _log({"row": "straggler_gap", "error": str(e)[:300]})

    # final summary: telemetry snapshot slice (the same registry bench
    # embeds), proving the decision + comm gauges are scrapeable
    try:
        snap = get_registry().snapshot()
        keep = {k: v for k, v in snap.items() if k.startswith("gbdt_fit_")}
        _log({"row": "registry", "gbdt_fit_series": sorted(keep)})
    except Exception as e:  # noqa: BLE001
        _log({"row": "registry", "error": str(e)[:200]})


if __name__ == "__main__":
    main()
