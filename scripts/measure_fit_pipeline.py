"""Measure the host/device fit pipeline on the live chip (ISSUE 7).

Armed for the next healthy pool window (scripts/tpu_recovery_watch.sh runs
this first). Three measurements, each fenced so one failure cannot cost
the rest, every result appended to docs/PERF_fit_pipeline.log and printed
as one JSON line per row:

1. 4M x 28: sequential instrumented fit (collectFitTimings,
   fitPipeline='off') -> the binning / device-transfer / boosting
   decomposition, confirming the binning NaN fastpath on chip
   (docs/PERF.md predicts 7.89 s -> 1.84 s at 4M);
2. 4M x 28: pipelined instrumented fit (fitPipeline='on') -> the
   FitTimeline construction wall + measured overlap ratio, plus the
   cross-run ratio 1 - pipelined_construction / (seq binning + transfer);
3. 11M x 28 x 100 (HIGGS scale, the north-star row): warm + timed
   pipelined fits with the round-5 promoted mode (splitsPerPass=8,
   itersPerCall=50 — ahead-dispatched chunks) -> rows*iter/s and
   vs_baseline (>= 27.5M rows*iter/s = 1.0x single-H100).

Run from the repo root. Uses bench.py's patient bring-up so a wedged pool
degrades to a logged CPU run instead of a hang.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

LOG = os.path.join(os.path.dirname(__file__), "..", "docs",
                   "PERF_fit_pipeline.log")
BASELINE = 27.5e6


def _log(row):
    line = json.dumps(row)
    print(line, flush=True)
    with open(LOG, "a") as fh:
        fh.write(line + "\n")


def main():
    import bench
    jx, devs, init_err, _ = bench._patient_backend_bringup()
    dev = str(devs[0])
    _log({"start": time.strftime("%Y-%m-%d %H:%M:%S UTC", time.gmtime()),
          "device": dev, "init_err": init_err})
    on_accel = devs[0].platform not in ("cpu",)

    from mmlspark_tpu.core.dataframe import DataFrame
    from mmlspark_tpu.models.lightgbm import LightGBMClassifier

    n, f, iters = (4_000_000, 28, 100) if on_accel else (200_000, 28, 10)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(n, f)).astype(np.float32)
    coef = rng.normal(size=f)
    y = ((x @ coef + 0.5 * x[:, 0] * x[:, 1]
          + rng.normal(scale=1.0, size=n)) > 0).astype(np.float64)
    df = DataFrame({"features": x, "label": y})

    def clf(**kw):
        return LightGBMClassifier(numIterations=iters, numLeaves=31,
                                  maxBin=64, numTasks=1, splitsPerPass=8,
                                  **kw)

    seq = None
    try:  # 1) sequential decomposition (NaN-fastpath confirmation at 4M)
        m = clf(collectFitTimings=True, fitPipeline="off").fit(df)
        seq = {k: round(v["total_s"], 3)
               for k, v in m.booster.fit_timings.items()
               if isinstance(v, dict) and "total_s" in v}
        _log({"row": "sequential_decomposition", "n": n, "phases_s": seq})
    except Exception as e:  # noqa: BLE001
        _log({"row": "sequential_decomposition", "error": str(e)[:300]})

    try:  # 2) pipelined construction + overlap ratio
        from mmlspark_tpu.utils.profiling import fit_pipeline_overlap_record
        m = clf(collectFitTimings=True, fitPipeline="on",
                itersPerCall=50).fit(df)
        rec = fit_pipeline_overlap_record(m.booster.fit_timings, seq)
        _log({"row": "pipelined_overlap", "n": n, **(rec or {})})
    except Exception as e:  # noqa: BLE001
        _log({"row": "pipelined_overlap", "error": str(e)[:300]})

    if not on_accel:
        _log({"row": "higgs11m", "skipped": "cpu fallback"})
        return
    try:  # 3) the north-star row: 11M x 28 x 100 pipelined
        n11 = 11_000_000
        x11 = rng.normal(size=(n11, f)).astype(np.float32)
        y11 = ((x11 @ coef + 0.5 * x11[:, 0] * x11[:, 1]
                + rng.normal(scale=1.0, size=n11)) > 0).astype(np.float64)
        df11 = DataFrame({"features": x11, "label": y11})
        c11 = clf(itersPerCall=50)       # auto-pipelines at 11M serial f32
        t0 = time.time()
        m11 = c11.fit(df11)
        walls = [time.time() - t0]
        for _ in range(2):
            t0 = time.time()
            m11 = c11.fit(df11)
            walls.append(time.time() - t0)
        from sklearn.metrics import roc_auc_score
        ho = rng.choice(n11, 100_000, replace=False)
        auc = roc_auc_score(y11[ho], m11.booster.score(x11[ho]))
        rate = n11 * iters / min(walls)
        _log({"row": "higgs11m", "mode": "batched-k8 ipc=50 pipelined",
              "walls_s": [round(w, 2) for w in walls],
              "rows_iter_per_s": round(rate, 1),
              "vs_baseline": round(rate / BASELINE, 4),
              "auc_sample": round(auc, 4)})
    except Exception as e:  # noqa: BLE001
        _log({"row": "higgs11m", "error": str(e)[:300]})


if __name__ == "__main__":
    main()
