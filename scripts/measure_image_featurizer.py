"""ImageFeaturizer forward throughput on chip (round-2 verdict #5).

Measures the jitted ResNet-50 headless forward (the CNTKModel.scala:30-140
hot-loop replacement) in images/s at the zoo's native 224x224 input, with
the docs/KERNELS.md paired-difference methodology so the relay RTT cancels.
Appends results to stdout for docs/PERF.md.
"""

import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def main():
    import jax
    import jax.numpy as jnp

    devs = jax.devices()
    if devs[0].platform == "cpu":
        print("no accelerator — refusing to record CPU numbers as TPU")
        return 1

    from mmlspark_tpu.models.deep import ModelDownloader

    gm = ModelDownloader().download_by_name("ResNet50")
    h, w, c = gm.schema.input_dims
    rng = np.random.default_rng(0)

    print("| batch | device ms/batch | images/s | date |")
    print("|---|---|---|---|")
    stamp = time.strftime("%Y-%m-%d %H:%M UTC", time.gmtime())
    for batch in (8, 32, 64):
        xb = jnp.asarray(rng.normal(size=(batch, h, w, c)), jnp.float32)

        # apply(..., capture="pool") returns the pooled features directly
        fwd = jax.jit(lambda v, x_: gm.module.apply(v, x_, capture="pool"))

        def k_calls(k):
            def run(x_):
                def body(acc, j):
                    xj = x_ * (1.0 + 1e-6 * j.astype(jnp.float32))
                    return acc + jnp.sum(fwd(gm.variables, xj)), None
                acc, _ = jax.lax.scan(body, jnp.float32(0.0),
                                      jnp.arange(k))
                return acc
            return jax.jit(run)

        inner = 8
        fn1, fn3 = k_calls(inner), k_calls(3 * inner)
        float(fn1(xb))
        float(fn3(xb))
        diffs = []
        for _ in range(3):
            t0 = time.perf_counter()
            float(fn1(xb))
            t1 = time.perf_counter()
            float(fn3(xb))
            t2 = time.perf_counter()
            diffs.append(((t2 - t1) - (t1 - t0)) / (2 * inner))
        per_batch = float(np.median(diffs))
        print(f"| {batch} | {per_batch * 1e3:.2f} | "
              f"{batch / per_batch:.0f} | {stamp} |", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
