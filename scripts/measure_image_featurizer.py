"""ImageFeaturizer forward throughput on chip (round-2 verdict #5).

Measures the jitted ResNet-50 headless forward (the CNTKModel.scala:30-140
hot-loop replacement) in images/s at the zoo's native 224x224 input.

Methodology: async-dispatch pipelining instead of the scan-of-forwards used
by the kernel sweeps — jax dispatches queue without blocking, so timing N
sequential calls with ONE host fetch at the end costs N x device-time +
one relay RTT; the (2N calls) - (N calls) difference cancels the RTT and
the fetch. This avoids jitting a scan over the whole ResNet (which
compiled for minutes on the relay toolchain and timed the first attempt
out); the plain forward compiles once.
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax
    import jax.numpy as jnp

    devs = jax.devices()
    if devs[0].platform == "cpu":
        print("no accelerator — refusing to record CPU numbers as TPU")
        return 1

    from mmlspark_tpu.models.deep import ModelDownloader

    gm = ModelDownloader().download_by_name("ResNet50")
    h, w, c = gm.schema.input_dims
    rng = np.random.default_rng(0)
    fwd = jax.jit(lambda v, x_: gm.module.apply(v, x_, capture="pool"))

    stamp = time.strftime("%Y-%m-%d %H:%M UTC", time.gmtime())
    print("| batch | device ms/batch | images/s | date |")
    print("|---|---|---|---|")
    for batch in (8, 32, 64):
        xb = jnp.asarray(rng.normal(size=(batch, h, w, c)), jnp.float32)
        out = fwd(gm.variables, xb)
        jax.block_until_ready(out)               # compile + settle

        def loop(k):
            t0 = time.perf_counter()
            o = None
            for _ in range(k):
                o = fwd(gm.variables, xb)
            float(jnp.sum(o))                    # one fetch barrier
            return time.perf_counter() - t0

        loop(4)
        diffs = []
        for _ in range(3):
            t1 = loop(8)
            t2 = loop(16)
            diffs.append((t2 - t1) / 8)
        per_batch = float(np.median(diffs))
        print(f"| {batch} | {per_batch * 1e3:.2f} | "
              f"{batch / per_batch:.0f} | {stamp} |", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
