"""ImageFeaturizer forward throughput on chip (round-2 verdict #5).

Measures the jitted headless forward (the CNTKModel.scala:30-140 hot-loop
replacement) in images/s across the zoo ladder — ResNet-DigitsClutter32
(32x32), ResNet18-ish (64x64), ResNet50 (224x224) — smallest compile
first and each model fenced, so one model's hang/failure cannot cost the
others' rows (the ResNet-50 compile hung >35 min on 2026-08-01).

Methodology: async-dispatch pipelining instead of the scan-of-forwards used
by the kernel sweeps — jax dispatches queue without blocking, so timing N
sequential calls with ONE host fetch at the end costs N x device-time +
one relay RTT; the (2N calls) - (N calls) difference cancels the RTT and
the fetch. This avoids jitting a scan over the whole ResNet (which
compiled for minutes on the relay toolchain and timed the first attempt
out); the plain forward compiles once.
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax
    import jax.numpy as jnp

    devs = jax.devices()
    if devs[0].platform == "cpu":
        print("no accelerator — refusing to record CPU numbers as TPU")
        return 1

    from mmlspark_tpu.models.deep import ModelDownloader

    rng = np.random.default_rng(0)
    stamp = time.strftime("%Y-%m-%d %H:%M UTC", time.gmtime())
    print("| model | batch | device ms/batch | images/s | date |",
          flush=True)
    print("|---|---|---|---|---|", flush=True)
    # smallest compile first: a hang on the big ResNet-50 224x224 compile
    # (observed >35 min on 2026-08-01, suspected pool hang) must not cost
    # the rows the smaller models can land in the same window
    for name in ("ResNet-DigitsClutter32", "ResNet18-ish", "ResNet50"):
      try:
        gm = ModelDownloader().download_by_name(name)
        h, w, c = gm.schema.input_dims
        fwd = jax.jit(lambda v, x_, _gm=gm: _gm.module.apply(
            v, x_, capture="pool"))
        for batch in (8, 64):
            xb = jnp.asarray(rng.normal(size=(batch, h, w, c)), jnp.float32)
            out = fwd(gm.variables, xb)
            jax.block_until_ready(out)               # compile + settle

            def loop(k):
                t0 = time.perf_counter()
                o = None
                for _ in range(k):
                    o = fwd(gm.variables, xb)
                float(jnp.sum(o))                    # one fetch barrier
                return time.perf_counter() - t0

            loop(4)
            diffs = []
            for _ in range(3):
                t1 = loop(8)
                t2 = loop(16)
                diffs.append((t2 - t1) / 8)
            per_batch = float(np.median(diffs))
            print(f"| {name} | {batch} | {per_batch * 1e3:.2f} | "
                  f"{batch / per_batch:.0f} | {stamp} |", flush=True)
      except Exception as e:  # noqa: BLE001 - one model must not cost the rest
        print(f"| {name} | - | FAILED {type(e).__name__}: {str(e)[:120]} | "
              f"- | {stamp} |", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
