"""One-shot fleet snapshot: every /metrics + /health in one JSON.

The PR 14 operator tool: given one coordinator URL, walk the fleet (the
coordinator's /health names the services, /routes/<service> names the
workers) and scrape every member's /health and /metrics into a single
JSON document — the "what does the whole fleet look like RIGHT NOW"
answer that previously took N curl invocations and a text editor.

Metrics are embedded two ways per member: `totals` (each family summed
across label sets — the compact cross-worker comparison view) and, with
--full-metrics, the raw Prometheus text. `collect_fleet` is importable:
scripts/measure_serving_load.py snapshots the fleet at the end of every
run and bench.py lifts it into the emitted record (`extra.fleet`), so the
armed chip window captures fleet forensics for free.

`--assert-healthy` (ISSUE 20) turns the snapshot into a GATE: exit
non-zero when any fleet member is unreachable, any SLO is breached, or
a swap/rollout has been stuck in a non-terminal state longer than
`--stuck-after` seconds — so CI and the production-day scorecard can
use one flag instead of parsing the JSON by hand.

Usage:
    python scripts/fleet_status.py --coordinator http://127.0.0.1:8000 \
        [--out fleet.json] [--full-metrics] [--assert-healthy]
"""

import argparse
import json
import os
import re
import sys
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _get(url: str, timeout: float = 5.0) -> str:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read().decode()


def _prom_totals(text: str) -> dict:
    """Prometheus text -> {family: summed value} (histograms contribute
    their _count/_sum series; buckets are dropped — the compact view)."""
    out = {}
    for m in re.finditer(r"^([a-z_][a-z0-9_]*?)(?:{[^}]*})? "
                         r"([0-9.e+-]+(?:[0-9])?)$", text, re.M):
        name = m.group(1)
        if name.endswith("_bucket"):
            continue
        try:
            out[name] = out.get(name, 0.0) + float(m.group(2))
        except ValueError:
            continue
    return out


def _member(base_url: str, full_metrics: bool, fetch) -> dict:
    member = {"url": base_url}
    try:
        member["health"] = json.loads(fetch(base_url.rstrip("/")
                                            + "/health"))
    except Exception as e:  # noqa: BLE001 - absence IS the finding
        member["health_error"] = str(e)[:200]
    try:
        text = fetch(base_url.rstrip("/") + "/metrics")
        member["metrics_totals"] = _prom_totals(text)
        if full_metrics:
            member["metrics_text"] = text
    except Exception as e:  # noqa: BLE001
        member["metrics_error"] = str(e)[:200]
    return member


def collect_fleet(coordinator_url: str, full_metrics: bool = False,
                  fetch=_get) -> dict:
    """The whole fleet's /health + /metrics in one dict (the bench/
    measure-harness embedding entry point; `fetch` injectable for
    tests)."""
    snap = {"ts": round(time.time(), 3),
            "coordinator": _member(coordinator_url, full_metrics, fetch),
            "workers": {}}
    services = ((snap["coordinator"].get("health") or {})
                .get("services") or {})
    snap["services"] = dict(services)
    for service in sorted(services):
        try:
            routes = json.loads(fetch(coordinator_url.rstrip("/")
                                      + f"/routes/{service}"))
        except Exception as e:  # noqa: BLE001
            snap["workers"][service] = {"routes_error": str(e)[:200]}
            continue
        members = {}
        for r in routes:
            key = f"{r['machine']}:{r['partition']}"
            members[key] = _member(f"http://{r['host']}:{r['port']}",
                                   full_metrics, fetch)
        snap["workers"][service] = members
    return snap


def assert_healthy(snap: dict, stuck_after_s: float = 120.0,
                   now_monotonic=None) -> list:
    """The `--assert-healthy` predicate: a list of problem strings
    (empty == healthy). Problems, per the ISSUE 20 gate contract:

    - unreachable member: the coordinator or any routed worker whose
      /health fetch failed;
    - SLO breach: any SLO in the coordinator's health block with
      `breached` true;
    - stuck swap/rollout: a rollout sitting in a NON-terminal state
      (canary/promoting) longer than `stuck_after_s` — the record's
      `started_s` is a time.monotonic stamp, so the caller on the same
      host passes `now_monotonic` (defaults to time.monotonic())."""
    problems = []
    coord = snap.get("coordinator") or {}
    if "health" not in coord:
        problems.append("coordinator unreachable: "
                        + str(coord.get("health_error", "no health")))
        return problems   # nothing below is trustworthy without it
    health = coord["health"] or {}
    for service, members in (snap.get("workers") or {}).items():
        if "routes_error" in members:
            problems.append(f"{service}: routes unreachable: "
                            f"{members['routes_error']}")
            continue
        for key, member in members.items():
            if "health" not in member:
                problems.append(
                    f"{service}/{key} unreachable: "
                    f"{member.get('health_error', 'no health')}")
    for slo_name, st in (health.get("slo") or {}).items():
        if st.get("breached"):
            problems.append(
                f"SLO {slo_name} breached (burn fast "
                f"{st.get('burn_fast')} slow {st.get('burn_slow')})")
    now = time.monotonic() if now_monotonic is None else now_monotonic
    for service, ro in (health.get("rollouts") or {}).items():
        state = ro.get("state")
        if state in ("canary", "promoting"):
            age = now - float(ro.get("started_s", now))
            if age > stuck_after_s:
                problems.append(
                    f"rollout {service} stuck in {state!r} for "
                    f"{age:.0f}s (> {stuck_after_s:.0f}s)")
    return problems


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--coordinator", required=True,
                    help="coordinator base URL, e.g. http://127.0.0.1:8000")
    ap.add_argument("--out", default=None,
                    help="write the snapshot JSON here (default: stdout)")
    ap.add_argument("--full-metrics", action="store_true",
                    help="embed raw Prometheus text per member, not just "
                         "family totals")
    ap.add_argument("--assert-healthy", action="store_true",
                    help="exit non-zero on any unreachable member, SLO "
                         "breach, or stuck swap/rollout state")
    ap.add_argument("--stuck-after", type=float, default=120.0,
                    help="seconds before a non-terminal rollout state "
                         "counts as stuck (with --assert-healthy)")
    args = ap.parse_args()
    snap = collect_fleet(args.coordinator, full_metrics=args.full_metrics)
    payload = json.dumps(snap, indent=1)
    if args.out:
        with open(args.out, "w") as f:
            f.write(payload)
        print(f"wrote {args.out}")
    else:
        print(payload)
    if args.assert_healthy:
        problems = assert_healthy(snap, stuck_after_s=args.stuck_after)
        for p in problems:
            print(f"UNHEALTHY: {p}", file=sys.stderr)
        if problems:
            return 2
        print("fleet healthy", file=sys.stderr)
        return 0
    # a snapshot that could not even reach the coordinator is a failure;
    # partial worker scrape errors are data, not failures
    return 0 if "health" in snap["coordinator"] else 1


if __name__ == "__main__":
    sys.exit(main())
