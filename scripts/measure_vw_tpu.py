"""VW sparse-SGD training throughput on chip.

The reference's VW path is a per-row JNI hot loop (`example.learn()`,
VowpalWabbitBase.scala:235-266); here the whole multi-pass minibatched SGD
is one jit program (models/vw/sgd.py). Measures end-to-end fit wall (host
hashing included) and the device-only pass rate via a second fit of the
identical program (compile cached), on a VW-shaped problem: 1M rows, 2^18
weight table, ~30 active features/row.
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax

    devs = jax.devices()
    if devs[0].platform == "cpu":
        print("no accelerator — refusing to record CPU numbers as TPU")
        return 1

    from mmlspark_tpu import DataFrame
    from mmlspark_tpu.models.vw import VowpalWabbitClassifier

    rng = np.random.default_rng(0)
    n, f = 1_000_000, 30
    x = rng.normal(size=(n, f)).astype(np.float32)
    y = ((x @ rng.normal(size=f)) > 0).astype(np.float64)
    df = DataFrame({"features": x, "label": y})

    # grid: the default engine (adaptive+normalized+invariant), plain SGD
    # (1 table instead of 3 -> fewer scatters per step), and the minibatch
    # ladder (the documented TPU fidelity/speed knob: larger minibatches
    # cut lax.scan steps/pass; fidelity-vs-upstream is pinned at 256)
    cases = [("default mb=256", dict(numPasses=1)),
             ("default mb=256 x3", dict(numPasses=3)),
             ("plain_sgd mb=256", dict(numPasses=1, adaptive=False,
                                       normalized=False, invariant=False)),
             ("default mb=2048", dict(numPasses=1, minibatchSize=2048)),
             ("default mb=8192", dict(numPasses=1, minibatchSize=8192))]
    for tag, kw in cases:
        passes = kw.get("numPasses", 1)
        clf = VowpalWabbitClassifier(numBits=18, numTasks=1, **kw)
        t0 = time.time()
        clf.fit(df)
        warm = time.time() - t0
        t0 = time.time()
        m = clf.fit(df)
        wall = time.time() - t0
        rate = n * passes / wall
        stamp = time.strftime("%Y-%m-%d %H:%M UTC", time.gmtime())
        print(f"{tag}: warm {warm:.1f}s timed {wall:.1f}s = "
              f"{rate / 1e6:.2f}M examples/s ({stamp})", flush=True)
        del m
    return 0


if __name__ == "__main__":
    sys.exit(main())
