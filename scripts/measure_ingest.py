"""Measure the out-of-core training data plane (ISSUE 18, ROADMAP item 4).

Armed in scripts/tpu_recovery_watch.sh. Two measurements:

1. INGEST LADDER (single process): ``stream_fit_arrays`` rows/s over the
   shard-size x ring-depth x ndev grid on a synthetic store, peak host
   RSS sampled per cell (/proc VmHWM via shardstore.host_rss_bytes).
   Rows append to docs/PERF_ingest.log; the run writes one summary JSON
   (--out) whose table docs/PERF.md quotes.
2. BIG FIT (--big, the acceptance run): a synthetic store too large to
   ever materialize (written by a STREAMING generator — no full array
   exists at any point) is fit on the VIRTUAL 2-host mesh (the
   measure_podslice.py subprocess fabric: real rendezvous -> gated
   jax.distributed init, each host streaming ONLY the shards its row
   span lives in). Each worker asserts the RSS bound inline:

       peak_rss - rss_before_fit
           <= local_device_bytes                  (binned + y/w/t/mg;
                                                   host RAM on the CPU
                                                   backend, HBM on chip)
            + rows_local * k * TRAIN_WS_BYTES_PER_ROW
                                                  (boosting working set:
                                                   scores/grads/hess +
                                                   XLA per-iter temps —
                                                   device memory too)
            + RING_SLACK_FACTOR * ring_depth * shard_bytes
            + FIXED_SLACK                         (XLA compile buffers)

   i.e. bounded by DEVICE-RESIDENT state (input arrays + the training
   program's working set, both O(rows_local)) + the prefetch ring —
   never by the raw dataset bytes on disk or the TOTAL row count
   (docs/DATA.md pins the contract). The
   launcher also fits store-vs-in-memory at a size both routes can run
   and requires bit-identical model strings (digest parity).

CPU-mesh numbers validate the STRUCTURE (bounded RSS, parity, scaling
shape), not absolute throughput — the chip run is armed in the watcher.
"""

import argparse
import json
import os
import re
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.join(_REPO, "tests"))

LOG = os.path.join(os.path.dirname(__file__), "..", "docs",
                   "PERF_ingest.log")

#: big-fit problem shape: data-plane-bound on purpose (tiny trees, 16
#: bins) — the measurement is ingest + bounded RSS, not split quality
BIG_FEATURES, BIG_ITERS, BIG_LEAVES, BIG_BINS = 4, 2, 7, 16
#: RSS-bound slack terms (the docs/DATA.md contract): ring buffers cycle
#: through numpy staging + codec views + device_put landing copies, and
#: XLA keeps compile-time buffers alive
RING_SLACK_FACTOR = 4
FIXED_SLACK_BYTES = 768 << 20
#: boosting working set per LOCAL row per class: the training program's
#: device memory (scores/grads/hess f32, scatter-hist index temporaries,
#: XLA per-iteration buffers), which on the CPU backend is host RSS.
#: Phase-decomposed measurement (20M rows, 8 devices, f=4/k=1): stream
#: HWM 1192 MB vs fit HWM 3553 MB -> ~137 B/row of fit-phase transients;
#: the 100M 2-host run lands ~147 B/row all-in. 160 covers both with
#: margin while staying O(rows_local) — the bound NEVER scales with the
#: total row count or raw dataset bytes on disk.
TRAIN_WS_BYTES_PER_ROW = 160


def _log(row):
    line = json.dumps(row)
    print(line, flush=True)
    try:
        with open(LOG, "a") as fh:
            fh.write(line + "\n")
    except OSError:
        pass


def write_synthetic(path, rows, features, rows_per_shard, seed=7,
                    block_rows=1_000_000):
    """Streaming synthetic writer: generates block_rows at a time into
    ShardStoreWriter.append — peak RAM is O(block), never O(rows), so
    the same generator writes the 100M-row store on a 16 GB host."""
    import numpy as np
    from mmlspark_tpu.io import shardstore as sstore
    rng = np.random.default_rng(seed)
    t0 = time.time()
    with sstore.ShardStoreWriter(path, rows_per_shard) as w:
        done = 0
        while done < rows:
            r = min(block_rows, rows - done)
            x = rng.normal(size=(r, features)).astype(np.float32)
            x[rng.random((r, features)) < 0.02] = np.nan
            y = np.nan_to_num(x[:, 0] * 0.5 + x[:, -1]).astype(np.float64)
            wgt = rng.uniform(0.5, 2.0, size=r).astype(np.float32)
            w.append(x, y, wgt)
            done += r
    store = sstore.ShardStore(path)
    return store, time.time() - t0


def _store_row_bytes(store):
    import numpy as np
    return sum(np.dtype(c["dtype"]).itemsize
               * (store.num_features if nm == "features" else 1)
               for nm, c in store.columns.items())


def rss_bound_bytes(store, rows_local, k, ring_depth):
    """The docs/DATA.md bound for one host's fit-attributed RSS growth."""
    shard_bytes = (max(int(s["rows"]) for s in store.shards)
                   * _store_row_bytes(store))
    device_local = rows_local * (store.num_features + 4 * 4 + 4 * k)
    train_ws = rows_local * k * TRAIN_WS_BYTES_PER_ROW
    return (device_local + train_ws
            + RING_SLACK_FACTOR * ring_depth * shard_bytes
            + FIXED_SLACK_BYTES)


# ------------------------------------------------------------- big worker

def worker(args) -> int:
    """One host of the 2-host acceptance fit: rendezvous -> fit straight
    from the store path -> inline RSS-bound assertion -> ROW on stdout."""
    from mmlspark_tpu.io import shardstore as sstore
    from mmlspark_tpu.models.lightgbm import LightGBMRegressor
    from mmlspark_tpu.parallel import multihost as mh
    from measure_podslice import _struct_digest

    sess = mh.connect(args.coordinator, args.hosts, name=args.name,
                      jax_port=args.jax_port or None, deadline_s=300.0,
                      heartbeat_interval_s=1.0)
    topo = sess.topology
    store = sstore.ShardStore(args.store)
    n = store.rows
    rss0 = sstore.host_rss_bytes() or 0
    reg = LightGBMRegressor(numIterations=BIG_ITERS, numLeaves=BIG_LEAVES,
                            maxBin=BIG_BINS, numTasks=topo.devices,
                            weightCol="w", histMethod="scatter")
    t0 = time.time()
    mdl = reg.fit(args.store)
    wall = time.time() - t0
    peak = sstore.host_rss_bytes(peak=True) or 0
    rows_local = -(-n // topo.hosts)
    bound = rss_bound_bytes(store, rows_local, 1, args.ring_depth)
    grew = max(0, peak - rss0)
    row = {"row": "bigfit", "hosts": topo.hosts, "ndev": topo.devices,
           "process_id": topo.process_id, "n": n,
           "features": store.num_features, "iters": BIG_ITERS,
           "wall_s": round(wall, 1),
           "rows_iter_per_s": round(n * BIG_ITERS / wall, 1),
           "rss_before_mb": rss0 >> 20, "rss_peak_mb": peak >> 20,
           "rss_grew_mb": grew >> 20, "rss_bound_mb": bound >> 20,
           "rss_within_bound": bool(grew <= bound),
           "digest": _struct_digest(mdl.booster.model_string())}
    print("ROW " + json.dumps(row), flush=True)
    sess.close()
    # the acceptance assertion lives IN the harness: a worker whose RSS
    # escaped the bound fails its rung, which fails the run
    assert grew <= bound, (
        f"host {topo.process_id}: fit-attributed RSS {grew >> 20} MB "
        f"exceeds the bound {bound >> 20} MB "
        f"(ring_depth={args.ring_depth})")
    return 0


def _launch_big(args):
    from multihost_harness import free_port, launch_hosts
    from mmlspark_tpu.parallel.rendezvous import RendezvousCoordinator
    hosts = args.hosts
    coord = RendezvousCoordinator(hosts, heartbeat_timeout_s=60.0).start()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   env.get("XLA_FLAGS", ""))
    env["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={args.dph}"
    ).strip()
    try:
        outs = launch_hosts(
            [[sys.executable, "-u", os.path.abspath(__file__),
              "--worker", "--coordinator", coord.address,
              "--hosts", str(hosts), "--jax-port", str(free_port()),
              "--name", f"vhost{i}", "--store", args.store,
              "--ring-depth", str(args.ring_depth)]
             for i in range(hosts)],
            env, timeout_s=args.big_timeout_s,
            per_worker_timeout_s=args.big_timeout_s)
    finally:
        coord.stop()
    rows, digests = [], []
    for rc, out, err in outs:
        if rc != 0:
            raise RuntimeError(
                f"big-fit worker failed rc={rc}: {err[-1500:]}")
        for line in out.splitlines():
            if line.startswith("ROW "):
                r = json.loads(line[4:])
                digests.append(r["digest"])
                rows.append(r)
    if len(rows) != hosts:
        raise RuntimeError(f"expected {hosts} worker rows, got {len(rows)}")
    if len(set(digests)) != 1:
        raise RuntimeError(f"hosts disagree on the fit digest: {digests}")
    if not all(r["rss_within_bound"] for r in rows):
        raise RuntimeError("a host escaped the RSS bound: "
                           + json.dumps(rows))
    return rows


def _parity_check(tmp):
    """Digest parity store-vs-memory at a size BOTH routes can run —
    raw model_string equality, same gate as tests/test_shardstore.py."""
    import numpy as np
    from mmlspark_tpu.core.dataframe import DataFrame
    from mmlspark_tpu.io import shardstore as sstore
    from mmlspark_tpu.models.lightgbm import LightGBMRegressor
    rng = np.random.default_rng(3)
    n = 60_003
    x = rng.normal(size=(n, BIG_FEATURES)).astype(np.float32)
    x[rng.random((n, BIG_FEATURES)) < 0.02] = np.nan
    y = np.nan_to_num(x[:, 0]).astype(np.float64)
    w = rng.uniform(0.5, 2.0, size=n).astype(np.float32)
    d = os.path.join(tmp, "parity")
    sstore.write_store(d, x, y, weight=w, rows_per_shard=7_000)
    kw = dict(numIterations=BIG_ITERS, numLeaves=BIG_LEAVES,
              maxBin=BIG_BINS, numTasks=8, weightCol="w")
    m_mem = LightGBMRegressor(**kw).fit(
        DataFrame({"features": x, "label": y, "w": w}))
    m_st = LightGBMRegressor(**kw).fit(d)
    return m_mem.booster.model_string() == m_st.booster.model_string()


# ---------------------------------------------------------------- ladder

def run_ladder(args, tmp):
    """stream_fit_arrays rows/s over shard-size x ring-depth x ndev,
    single process (serial + sharded routes; the multi-host route is the
    big fit's job)."""
    import numpy as np  # noqa: F401 - jax init ordering
    from mmlspark_tpu.io import shardstore as sstore
    from mmlspark_tpu.parallel import mesh as meshlib
    cells = []
    for shard_rows in args.ladder_shard_rows:
        d = os.path.join(tmp, f"ladder_{shard_rows}")
        store, t_write = write_synthetic(
            d, args.ladder_rows, args.ladder_features, shard_rows)
        _log({"row": "store", "rows": store.rows,
              "shards": len(store.shards), "rows_per_shard": shard_rows,
              "write_s": round(t_write, 1),
              "write_rows_per_s": round(store.rows / t_write, 1)})
        bm = sstore.fit_bin_mapper(store, BIG_BINS, 200_000, 0)
        for ndev in args.ladder_ndev:
            mesh = None if ndev == 1 else meshlib.get_mesh(ndev)
            for ring_depth in args.ladder_ring:
                t0 = time.time()
                binned, _aux = sstore.stream_fit_arrays(
                    bm, store, mesh=mesh, ring_depth=ring_depth)
                binned.block_until_ready()
                wall = time.time() - t0
                del binned, _aux
                cell = {"row": "cell", "rows": store.rows,
                        "rows_per_shard": shard_rows, "ndev": ndev,
                        "ring_depth": ring_depth,
                        "wall_s": round(wall, 2),
                        "rows_per_s": round(store.rows / wall, 1),
                        "rss_peak_mb":
                            (sstore.host_rss_bytes(peak=True) or 0) >> 20}
                _log(cell)
                cells.append(cell)
    return cells


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--worker", action="store_true")
    ap.add_argument("--coordinator", default="")
    ap.add_argument("--hosts", type=int, default=2)
    ap.add_argument("--jax-port", type=int, default=0)
    ap.add_argument("--name", default="")
    ap.add_argument("--store", default="",
                    help="worker/big: shard-store directory")
    ap.add_argument("--ring-depth", type=int, default=2)
    ap.add_argument("--dph", type=int, default=8)
    ap.add_argument("--big", action="store_true",
                    help="run the big-fit acceptance rung")
    ap.add_argument("--big-rows", type=int, default=100_000_000)
    ap.add_argument("--big-shard-rows", type=int, default=2_000_000)
    ap.add_argument("--big-timeout-s", type=float, default=3600.0)
    ap.add_argument("--skip-ladder", action="store_true")
    ap.add_argument("--ladder-rows", type=int, default=8_000_000)
    ap.add_argument("--ladder-features", type=int, default=8)
    ap.add_argument("--ladder-shard-rows", type=int, nargs="+",
                    default=[500_000, 2_000_000])
    ap.add_argument("--ladder-ring", type=int, nargs="+", default=[1, 2, 4])
    ap.add_argument("--ladder-ndev", type=int, nargs="+", default=[1, 8])
    ap.add_argument("--tmp", default="",
                    help="scratch dir for synthetic stores (NOT cleaned "
                         "when given; default: a fresh TemporaryDirectory)")
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(__file__), "..", "docs", "INGEST_cpu.json"))
    args = ap.parse_args()
    if args.worker:
        sys.exit(worker(args))

    import tempfile
    ctx = (tempfile.TemporaryDirectory() if not args.tmp else None)
    tmp = ctx.name if ctx else args.tmp
    if args.tmp:
        os.makedirs(tmp, exist_ok=True)
    summary = {"dph": args.dph, "cells": [], "bigfit": None,
               "digest_parity_small": None}
    _log({"row": "start", "big": bool(args.big),
          "ladder_rows": args.ladder_rows,
          "start": time.strftime("%Y-%m-%d %H:%M:%S UTC", time.gmtime())})
    try:
        if not args.skip_ladder:
            summary["cells"] = run_ladder(args, tmp)
        summary["digest_parity_small"] = bool(_parity_check(tmp))
        _log({"row": "parity",
              "digest_parity_small": summary["digest_parity_small"]})
        if args.big:
            big_dir = os.path.join(tmp, "big")
            store, t_write = write_synthetic(
                big_dir, args.big_rows, BIG_FEATURES, args.big_shard_rows)
            _log({"row": "store", "rows": store.rows,
                  "shards": len(store.shards), "write_s": round(t_write, 1),
                  "write_rows_per_s": round(store.rows / t_write, 1)})
            rows = _launch_big(argparse.Namespace(
                hosts=args.hosts, dph=args.dph, store=big_dir,
                ring_depth=args.ring_depth,
                big_timeout_s=args.big_timeout_s))
            for r in rows:
                _log(r)
            summary["bigfit"] = rows
    finally:
        if ctx is not None:
            ctx.cleanup()
    ok = summary["digest_parity_small"] and (
        not args.big or (summary["bigfit"] is not None
                         and all(r["rss_within_bound"]
                                 for r in summary["bigfit"])))
    out = os.path.abspath(args.out)
    with open(out, "w") as fh:
        json.dump(summary, fh, indent=1)
    _log({"row": "summary", "out": out, "ok": bool(ok)})
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
