"""Train the SECOND bundled zoo checkpoint — the harder anchor
(round-4 verdict missing #3 / next-round #6).

`ResNet-Digits` (train_zoo_checkpoint.py) anchors the zoo mechanism on an
easy task (centered 16x16 digits, 0.98 test accuracy). This script trains a
DEEPER network on a substantially harder offline task so the
ImageFeaturizer transfer path has a quality claim that means something:

**DigitsClutter-32**: 32x32 canvas; the 16x16-upscaled sklearn digit is
placed at a RANDOM OFFSET; two quarter-size distractor fragments cropped
from OTHER digit images land in random corners at reduced intensity;
Gaussian pixel noise on top. Classification stays 10-class but now demands
translation invariance and clutter rejection — a linear probe on raw
pixels drops to ~55% where centered digits give ~95%.

Split hygiene: each base image contributes TWO clutter variants, and both
land on the SAME side of the 80/20 split (split by base image, then
augment) so no pixel content leaks train->test.

Model: ResNet(stage_sizes=(2, 2)) — twice the block depth of the first
anchor. Seed-pinned, CPU-trainable in ~10 min on 1 vCPU.

Outputs (committed to the repo):
    mmlspark_tpu/models/deep/zoo/ResNet-DigitsClutter32.npz
    zoo/MANIFEST.json — entry MERGED alongside ResNet-Digits

Reference analogue: the CNTK zoo's multiple models with per-model schemas
(downloader/ModelDownloader.scala:27-250, Schema.scala).

Run: python scripts/train_zoo_checkpoint2.py
"""

import hashlib
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
import optax

jax.config.update("jax_platforms", "cpu")

from mmlspark_tpu.models.deep.resnet import ResNet, save_params  # noqa: E402
from mmlspark_tpu.models.deep.zoo_tasks import (CLUTTER_SEED,  # noqa: E402
                                                CLUTTER_VARIANTS,
                                                make_clutter_dataset)

SEED = CLUTTER_SEED
ZOO_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "mmlspark_tpu", "models", "deep", "zoo")
NAME = "ResNet-DigitsClutter32"
H = W = 32
VARIANTS = CLUTTER_VARIANTS


def main():
    xtr, ytr, xte, yte = make_clutter_dataset()
    print(f"train {xtr.shape} test {xte.shape}", flush=True)
    mean, std = 0.5, 0.5
    xtr_n = (xtr - mean) / std
    xte_n = (xte - mean) / std

    model = ResNet(stage_sizes=(2, 2), num_classes=10)
    params = model.init(jax.random.PRNGKey(SEED),
                        jnp.zeros((1, H, W, 3), jnp.float32))

    tx = optax.adam(1e-3)
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state, xb, yb):
        def loss_fn(p):
            logits = model.apply(p, xb)
            logp = jax.nn.log_softmax(logits)
            return -jnp.mean(jnp.take_along_axis(logp, yb[:, None], axis=1))
        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    @jax.jit
    def predict(params, xb):
        return jnp.argmax(model.apply(params, xb), axis=1)

    def test_acc(params):
        preds = []
        for lo in range(0, len(yte), 512):
            preds.append(np.asarray(predict(
                params, jnp.asarray(xte_n[lo:lo + 512]))))
        return float((np.concatenate(preds) == yte).mean())

    rng = np.random.default_rng(SEED)
    bs = 128
    best_acc, best_params = 0.0, params
    for epoch in range(40):
        order = rng.permutation(len(ytr))
        losses = []
        for lo in range(0, len(ytr) - bs + 1, bs):
            idx = order[lo:lo + bs]
            params, opt_state, loss = step(
                params, opt_state, jnp.asarray(xtr_n[idx]),
                jnp.asarray(ytr[idx]))
            losses.append(float(loss))
        acc = test_acc(params)
        if acc > best_acc:
            best_acc, best_params = acc, params
        print(f"epoch {epoch}: loss {np.mean(losses):.4f} "
              f"test acc {acc:.4f}", flush=True)
        if best_acc >= 0.96 and epoch >= 15:
            break

    os.makedirs(ZOO_DIR, exist_ok=True)
    ckpt = os.path.join(ZOO_DIR, f"{NAME}.npz")
    save_params(ckpt, best_params)
    sha = hashlib.sha256(open(ckpt, "rb").read()).hexdigest()
    entry = {
        "name": NAME,
        "uri": f"{NAME}.npz",
        "sha256": sha,
        "size": os.path.getsize(ckpt),
        "inputDims": [H, W, 3],
        "testAccuracy": round(best_acc, 4),
        "dataset": ("DigitsClutter-32: sklearn digits composed onto 32x32 "
                    "at random offset + 2 distractor fragments + noise; "
                    f"{VARIANTS} variants/base, split by base image, "
                    f"seed {SEED}"),
    }
    mpath = os.path.join(ZOO_DIR, "MANIFEST.json")
    manifest = json.load(open(mpath)) if os.path.exists(mpath) else []
    manifest = [m for m in manifest if m["name"] != NAME] + [entry]
    with open(mpath, "w") as f:
        json.dump(manifest, f, indent=1)
    print(f"saved {ckpt} ({os.path.getsize(ckpt)/1e6:.2f} MB) "
          f"sha256 {sha[:12]}… test acc {best_acc:.4f}")


if __name__ == "__main__":
    main()
