"""On-chip microprofile of the GBDT per-split bookkeeping.

The measured fit decomposition (docs/PERF.md) at 1M x 28 x 100 iters is
~116 ms/iter = 31 all-slots passes x 2.9 ms + ~26 ms of split bookkeeping
(~0.9 ms/split).  The histogram pass is near its formulation's arithmetic
floor, so the bookkeeping is the next target.  This script isolates the
candidate costs on the live chip:

  1. column gather  col = binned[:, feat]  with a TRACED feat
     (XLA gather over the minor axis) vs the transposed layout
     dynamic_slice(bins_t, (feat, 0), (1, N)) (contiguous read)
  2. slot_of_row update (where over [N])
  3. _best_split_per_slot on 2 and 31 slots
  4. the all-slots pallas pass and the lazy-mode leaf-sums contraction

Timing methodology (docs/KERNELS.md): paired-difference of two
scan-amortized jit programs so the relay round trip cancels, with the
workload EXPLICITLY step-dependent — every fn takes the scan index j as its
first argument and must fold it into an input, otherwise XLA's while-loop
invariant code motion hoists the body and the reading is garbage (both
earlier versions of this script hit exactly that: float-only perturbation
left the integer workloads hoisted and reporting ~0)."""

import time

import numpy as np

import jax
import jax.numpy as jnp


def timed(fn, *args, reps=50):
    """Paired-difference scan-amortized ms per call of fn(j, *args)."""

    def mk(k):
        @jax.jit
        def many(*a):
            def body(c, j):
                out = fn(j, *a)
                leaf = jax.tree_util.tree_leaves(out)[0]
                return c + leaf.reshape(-1)[0].astype(jnp.float32), None
            c, _ = jax.lax.scan(body, jnp.float32(0.0), jnp.arange(k))
            return c
        return many

    m1, m3 = mk(reps), mk(3 * reps)
    float(m1(*args))                         # compile; fetch = barrier
    float(m3(*args))
    d = []
    for _ in range(3):
        t0 = time.perf_counter()
        float(m1(*args))
        t1 = time.perf_counter()
        float(m3(*args))
        d.append((time.perf_counter() - t1) - (t1 - t0))
    return float(np.median(d)) / (2 * reps) * 1e3   # ms/call


def main():
    n, f, b, lcap = 1_000_000, 28, 64, 31
    rng = np.random.default_rng(0)
    binned = jnp.asarray(rng.integers(0, b, size=(n, f), dtype=np.int8))
    bins_t = jnp.asarray(np.ascontiguousarray(np.asarray(binned).T))
    slot = jnp.asarray(rng.integers(0, lcap, size=(n,), dtype=np.int32))
    gh3 = jnp.asarray(rng.normal(size=(n, 3)).astype(np.float32))

    print(f"device: {jax.devices()[0]}")

    def gather_minor(j, binned):
        return jnp.take(binned, j % f, axis=1).astype(jnp.int32)

    def slice_t(j, bins_t):
        return jax.lax.dynamic_slice(
            bins_t, (j % f, 0), (1, bins_t.shape[1]))[0].astype(jnp.int32)

    print(f"col gather [N,F] minor-axis : {timed(gather_minor, binned):8.3f} ms")
    print(f"col slice  [F,N] contiguous : {timed(slice_t, bins_t):8.3f} ms")

    def slot_update(j, slot, col):
        go_right = col > (j % b)
        return jnp.where((slot == j % lcap) & go_right, 31, slot)

    col = jnp.take(binned, 13, axis=1).astype(jnp.int32)
    print(f"slot_of_row where update    : {timed(slot_update, slot, col):8.3f} ms")

    from mmlspark_tpu.ops.boosting import (GBDTConfig, HParams,
                                           _best_split_per_slot)
    cfg = GBDTConfig(num_iterations=1, num_leaves=lcap, max_bins=b)
    hp = HParams.from_config(cfg)
    fmask = jnp.ones((f,), bool)

    for slots in (2, lcap):
        hists = jnp.asarray(rng.normal(size=(slots, f, b, 3)).astype(np.float32))
        sums = hists[:, 0].sum(axis=1)

        def rescan(j, hists, sums):
            return _best_split_per_slot(
                hists * (1.0 + 1e-6 * j.astype(jnp.float32)), sums, cfg,
                fmask, hp)

        print(f"_best_split_per_slot ({slots:2d} sl): "
              f"{timed(rescan, hists, sums):8.3f} ms")

    from mmlspark_tpu.ops.pallas_kernels import hist_slots_pallas

    def pallas_pass(j, binned, slot, gh3):
        g = gh3 * (1.0 + 1e-6 * j.astype(jnp.float32))
        return hist_slots_pallas(binned, slot, g, lcap, b)

    print(f"hist pallas all-slots pass  : "
          f"{timed(pallas_pass, binned, slot, gh3, reps=20):8.3f} ms")

    def leaf_sums(j, slot, gh3):
        # fold j into BOTH operands — a j-invariant slot would let LICM
        # hoist the one-hot materialization and underreport the epilogue
        g = gh3 * (1.0 + 1e-6 * j.astype(jnp.float32))
        s = (slot + j) % lcap
        oh = (s[:, None] == jnp.arange(lcap)[None, :]).astype(jnp.float32)
        return jnp.dot(oh.T, g, preferred_element_type=jnp.float32)

    print(f"leaf-sums onehot contraction: "
          f"{timed(leaf_sums, slot, gh3, reps=20):8.3f} ms")


if __name__ == "__main__":
    main()
