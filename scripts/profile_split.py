"""On-chip microprofile of the GBDT per-split bookkeeping.

The measured fit decomposition (docs/PERF.md) at 1M x 28 x 100 iters is
~116 ms/iter = 31 all-slots passes x 2.9 ms + ~26 ms of split bookkeeping
(~0.9 ms/split).  The histogram pass is near its formulation's arithmetic
floor, so the bookkeeping is the next target.  This script isolates the
candidate costs on the live chip:

  1. column gather  col = binned[:, feat]  with a TRACED feat
     (XLA gather over the minor axis) vs the transposed layout
     dynamic_slice(bins_t, (feat, 0), (1, N)) (contiguous read)
  2. slot_of_row update (where over [N])
  3. _best_split_per_slot on 2 slots
  4. a full scan-amortized fit at numLeaves in {2, 31} to re-derive the
     per-split slope

Timing methodology matches docs/KERNELS.md: scan-amortized repeats inside
one jit program, host-fetch barrier, dispatch RTT subtracted via a null
program.
"""

import time

import numpy as np

import jax
import jax.numpy as jnp


def timed(fn, *args, reps=50):
    """Paired-difference scan-amortized wall per call.

    The scanned body must DEPEND on the step index, or XLA's while-loop
    invariant code motion hoists fn out and the timing divides one execution
    by reps (this bit the first version of this script): the first float
    argument is perturbed by 1e-6*j per step. The per-call time is
    (wall(3k) - wall(k)) / 2k so the relay round trip cancels per pair."""

    def mk(k):
        @jax.jit
        def many(*a):
            def body(c, j):
                aj = [x * (1.0 + 1e-6 * j.astype(jnp.float32))
                      if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)
                      else x for x in a]
                out = fn(*aj)
                leaf = jax.tree_util.tree_leaves(out)[0]
                return c + leaf.reshape(-1)[0].astype(jnp.float32), None
            c, _ = jax.lax.scan(body, jnp.float32(0.0), jnp.arange(k))
            return c
        return many

    m1, m3 = mk(reps), mk(3 * reps)
    float(m1(*args))                         # compile; fetch = barrier
    float(m3(*args))
    d = []
    for _ in range(3):
        t0 = time.perf_counter()
        float(m1(*args))
        t1 = time.perf_counter()
        float(m3(*args))
        d.append((time.perf_counter() - t1) - (t1 - t0))
    import numpy as _np
    return float(_np.median(d)) / (2 * reps) * 1e3   # ms/call


def main():
    n, f, b, lcap = 1_000_000, 28, 64, 31
    rng = np.random.default_rng(0)
    binned = jnp.asarray(rng.integers(0, b, size=(n, f), dtype=np.int8))
    bins_t = jnp.asarray(np.ascontiguousarray(np.asarray(binned).T))
    slot = jnp.asarray(rng.integers(0, lcap, size=(n,), dtype=np.int32))
    gh3 = jnp.asarray(rng.normal(size=(n, 3)).astype(np.float32))
    feat = jnp.int32(13)
    thresh = jnp.int32(31)

    print(f"device: {jax.devices()[0]}")

    def gather_minor(binned, feat):
        return jnp.take(binned, feat, axis=1).astype(jnp.int32)

    def slice_t(bins_t, feat):
        return jax.lax.dynamic_slice(bins_t, (feat, 0), (1, bins_t.shape[1]))[0].astype(jnp.int32)

    print(f"col gather [N,F] minor-axis : {timed(gather_minor, binned, feat):8.3f} ms")
    print(f"col slice  [F,N] contiguous : {timed(slice_t, bins_t, feat):8.3f} ms")

    def slot_update(slot, col):
        go_right = col > thresh
        return jnp.where((slot == 3) & go_right, 31, slot)

    col = slice_t(bins_t, feat)
    print(f"slot_of_row where update    : {timed(slot_update, slot, col):8.3f} ms")

    from mmlspark_tpu.ops.boosting import GBDTConfig, HParams, _best_split_per_slot
    cfg = GBDTConfig(num_iterations=1, num_leaves=lcap, max_bins=b)
    hp = HParams.from_config(cfg)
    hists = jnp.asarray(rng.normal(size=(2, f, b, 3)).astype(np.float32))
    sums = hists[:, 0].sum(axis=1)
    fmask = jnp.ones((f,), bool)

    def rescan(hists, sums):
        return _best_split_per_slot(hists, sums, cfg, fmask, hp)

    print(f"_best_split_per_slot (2 sl) : {timed(rescan, hists, sums):8.3f} ms")

    hists_l = jnp.asarray(rng.normal(size=(lcap, f, b, 3)).astype(np.float32))
    sums_l = hists_l[:, 0].sum(axis=1)

    def rescan_all(hists, sums):
        return _best_split_per_slot(hists, sums, cfg, fmask, hp)

    print(f"_best_split_per_slot (31sl) : {timed(rescan_all, hists_l, sums_l):8.3f} ms")

    from mmlspark_tpu.ops.histogram import hist_slots_onehot
    from mmlspark_tpu.ops.pallas_kernels import hist_slots_pallas
    print(f"hist pallas all-slots pass  : "
          f"{timed(lambda b_, s, g: hist_slots_pallas(b_, s, g, lcap, b), binned, slot, gh3, reps=20):8.3f} ms")

    # leaf-stat onehot contraction (lazy/voting epilogue)
    def leaf_sums(slot, gh3):
        oh = (slot[:, None] == jnp.arange(lcap)[None, :]).astype(jnp.float32)
        return jnp.dot(oh.T, gh3, preferred_element_type=jnp.float32)

    print(f"leaf-sums onehot contraction: {timed(leaf_sums, slot, gh3, reps=20):8.3f} ms")


if __name__ == "__main__":
    main()
