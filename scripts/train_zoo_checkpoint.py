"""Train the bundled zoo checkpoint (round-3 verdict #5).

The reference ships real pretrained CNTK checkpoints through its model zoo
(downloader/ModelDownloader.scala:27-250) so ImageFeaturizer transfer
learning has a quality anchor. This environment has zero egress, so the
anchor is trained HERE, deterministically, on the only real image dataset
available offline (sklearn digits, 1797 8x8 grayscale images, the same
family as the reference's MNIST demo) and committed to the repo:

    mmlspark_tpu/models/deep/zoo/ResNet-Digits.npz   (~2 MB)
    mmlspark_tpu/models/deep/zoo/MANIFEST.json       (sha256, dims)

ModelDownloader serves it through RemoteRepository's file:// scheme, so
the full manifest + checksum + cache mechanism is exercised, and
tests/test_downloader.py gates the documented accuracy.

Run: python scripts/train_zoo_checkpoint.py  (CPU, ~5-10 min, seed-pinned)
"""

import hashlib
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
import optax

jax.config.update("jax_platforms", "cpu")

from mmlspark_tpu.models.deep.resnet import ResNet, save_params  # noqa: E402

SEED = 7
ZOO_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "mmlspark_tpu", "models", "deep", "zoo")
NAME = "ResNet-Digits"
H = W = 16


def load_digits_16x16():
    from sklearn.datasets import load_digits
    d = load_digits()
    x8 = d.images.astype(np.float32) / 16.0            # [N, 8, 8] in [0, 1]
    x = np.repeat(np.repeat(x8, 2, axis=1), 2, axis=2)  # nearest 16x16
    x = np.stack([x] * 3, axis=-1)                      # [N, 16, 16, 3]
    y = d.target.astype(np.int32)
    rng = np.random.default_rng(SEED)
    order = rng.permutation(len(y))
    n_tr = int(0.8 * len(y))
    tr, te = order[:n_tr], order[n_tr:]
    return x[tr], y[tr], x[te], y[te]


def main():
    xtr, ytr, xte, yte = load_digits_16x16()
    mean, std = 0.5, 0.5
    xtr_n = (xtr - mean) / std
    xte_n = (xte - mean) / std

    model = ResNet(stage_sizes=(1, 1), num_classes=10)
    variables = model.init(jax.random.PRNGKey(SEED),
                           jnp.zeros((1, H, W, 3), jnp.float32))
    params = variables

    tx = optax.adam(1e-3)
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state, xb, yb):
        def loss_fn(p):
            logits = model.apply(p, xb)
            logp = jax.nn.log_softmax(logits)
            return -jnp.mean(jnp.take_along_axis(
                logp, yb[:, None], axis=1))
        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    @jax.jit
    def predict(params, xb):
        return jnp.argmax(model.apply(params, xb), axis=1)

    rng = np.random.default_rng(SEED)
    bs = 128
    for epoch in range(30):
        order = rng.permutation(len(ytr))
        losses = []
        for lo in range(0, len(ytr) - bs + 1, bs):
            idx = order[lo:lo + bs]
            params, opt_state, loss = step(
                params, opt_state, jnp.asarray(xtr_n[idx]),
                jnp.asarray(ytr[idx]))
            losses.append(float(loss))
        pred = np.asarray(predict(params, jnp.asarray(xte_n)))
        acc = float((pred == yte).mean())
        print(f"epoch {epoch}: loss {np.mean(losses):.4f} "
              f"test acc {acc:.4f}", flush=True)
        if acc >= 0.98 and epoch >= 10:
            break

    os.makedirs(ZOO_DIR, exist_ok=True)
    ckpt = os.path.join(ZOO_DIR, f"{NAME}.npz")
    save_params(ckpt, params)
    sha = hashlib.sha256(open(ckpt, "rb").read()).hexdigest()
    manifest = [{
        "name": NAME,
        "uri": f"{NAME}.npz",
        "sha256": sha,
        "size": os.path.getsize(ckpt),
        "inputDims": [H, W, 3],
        "testAccuracy": round(acc, 4),
        "dataset": "sklearn load_digits 16x16x3, 80/20 split seed 7",
    }]
    with open(os.path.join(ZOO_DIR, "MANIFEST.json"), "w") as f:
        json.dump(manifest, f, indent=1)
    print(f"saved {ckpt} ({os.path.getsize(ckpt)/1e6:.2f} MB) "
          f"sha256 {sha[:12]}… test acc {acc:.4f}")


if __name__ == "__main__":
    main()
