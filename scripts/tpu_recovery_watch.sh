#!/bin/bash
# Patient TPU recovery watcher. The shared-pool backend wedges after a client
# is killed mid-dispatch (observed rounds 2/4/5: init then hangs ~25 min per
# attempt before erroring UNAVAILABLE). This watcher probes WITHOUT killing
# anything — each probe is allowed to hang until the backend itself answers
# or errors — and on the first healthy probe runs the round-5 pending queue
# in priority order, each step fenced so one failure cannot cost the rest.
#
# Preemption drain (PR 10): checkpointed fits honor SIGTERM by finishing the
# in-flight chunk, snapshotting, and exiting cleanly within the grace budget
# (resilience/elastic.PreemptionDrain, docs/RESILIENCE.md). The watcher runs
# each step as a tracked child and FORWARDS its own TERM/INT to it, so a
# pool preemption of the watcher drains the fit instead of orphan-killing it
# mid-write — the next watcher run resumes from the durable snapshot.
#
# Usage: nohup bash scripts/tpu_recovery_watch.sh >> docs/tpu_watch.log 2>&1 &
cd "$(dirname "$0")/.." || exit 1
CHILD=0
forward_term() {
  echo "== watcher signalled $(date -u +%FT%TZ) — draining child $CHILD"
  if [ "$CHILD" != 0 ]; then
    kill -TERM "$CHILD" 2>/dev/null
    wait "$CHILD" 2>/dev/null
  fi
  exit 143
}
trap forward_term TERM INT
run() {
  "$@" &
  CHILD=$!
  wait "$CHILD"
  local rc=$?
  CHILD=0
  return $rc
}
echo "== watcher start $(date -u +%FT%TZ)"
while true; do
  if python - <<'EOF'
import jax
import jax.numpy as jnp
x = jnp.ones((128, 128), jnp.bfloat16)
assert jax.devices()[0].platform != "cpu"
float((x @ x).sum())
EOF
  then
    # last-known-healthy marker: resilience/bringup.py seeds its probe
    # cadence from this (fresh marker => 3x shorter inter-probe backoff)
    date +%s > scripts/tpu_last_healthy
    echo "== chip healthy $(date -u +%FT%TZ) — running the pending queue"
    echo "== multichip fit scaling ladder (round-9 tentpole) $(date -u +%FT%TZ)"
    run python -u scripts/measure_multichip_fit.py
    echo "== fit pipeline overlap (round-7 tentpole) $(date -u +%FT%TZ)"
    run python -u scripts/measure_fit_pipeline.py
    echo "== pod-slice multi-host ladder (round-15 tentpole) $(date -u +%FT%TZ)"
    # 1->2->4-host ladder: on a real pod window the pool runner launches
    # per-host workers; from one host this measures what the grant allows
    # and logs fenced per-rung errors for the rest (docs/MULTIHOST.md)
    run python -u scripts/measure_podslice.py --ladder 1,2,4 --out docs/PODSLICE_chip.json
    echo "== out-of-core ingest ladder + bounded-RSS big fit (round-17 tentpole) $(date -u +%FT%TZ)"
    # shard-size x ring-depth x ndev rows/s grid, then the 100M-row
    # streaming fit with the per-host RSS bound asserted in-harness
    # (docs/DATA.md contract); scratch stores live on local disk
    run python -u scripts/measure_ingest.py --big --tmp /tmp/ingest_chip --out docs/INGEST_chip.json
    if ! run python -u scripts/quick_fit_probe.py; then
      echo "== quick fit probe FAILED $(date -u +%FT%TZ); back to probing"
      sleep 120
      continue
    fi
    echo "== serving (incl. HTTP->TPU->reply E2E) $(date -u +%FT%TZ)"
    run python -u scripts/measure_serving_tpu.py
    echo "== serving sustained load (round-12 tentpole) $(date -u +%FT%TZ)"
    run python -u scripts/measure_serving_load.py --out docs/SERVING_load_chip_host.json
    echo "== model lifecycle: hot swap under load (round-13 tentpole) $(date -u +%FT%TZ)"
    run python -u scripts/measure_serving_load.py --scenario swap --out docs/SERVING_swap_chip_host.json
    echo "== model lifecycle: autoscaler ramp (round-13 tentpole) $(date -u +%FT%TZ)"
    run python -u scripts/measure_serving_load.py --scenario autoscale --out docs/SERVING_autoscale_chip_host.json
    echo "== train-on-traffic loop: throughput + chaos (round-19 tentpole) $(date -u +%FT%TZ)"
    # fault-free loop numbers (ex/s, reward->applied lag, publish->swap),
    # then the chaos run: worker kill + learner kill + reward storm +
    # corrupt publish, gated on zero accepted loss, digest parity vs the
    # offline replay, and exact reward reconciliation (docs/ONLINE.md)
    run python -u scripts/measure_online_loop.py --out docs/ONLINE_loop_chip.json
    run python -u scripts/measure_online_loop.py --scenario chaos --out docs/ONLINE_chaos_chip.json
    echo "== production day: diurnal traffic + scripted fault timeline + scorecard (round-20 tentpole) $(date -u +%FT%TZ)"
    # ONE command replays the whole day from one master seed: ramp ->
    # peak (canary rollout + worker kill) -> burst (corrupt artifact) ->
    # trough (autoscale-down + learner preemption); exits non-zero
    # unless the machine-checked scorecard passes (docs/SCENARIOS.md);
    # bench.py lifts the JSON into extra.production_day
    run python -u scripts/run_production_day.py --out docs/PRODUCTION_DAY_chip.json
    echo "== cold start: compile cache + AOT (round-11 tentpole) $(date -u +%FT%TZ)"
    run python -u scripts/measure_cold_start.py --out docs/COLD_START_chip.json
    echo "== bench (validates binning fast path on chip) $(date -u +%FT%TZ)"
    run python -u bench.py
    echo "== vw throughput (validates shared-index fast path) $(date -u +%FT%TZ)"
    run python -u scripts/measure_vw_tpu.py
    echo "== vw hot-path ladder: fused tables + ahead-dispatch ring, targets >=1M ex/s (round-16 tentpole) $(date -u +%FT%TZ)"
    run python -u scripts/measure_vw_throughput.py --out docs/VW_THROUGHPUT_chip.json
    echo "== image featurizer ladder $(date -u +%FT%TZ)"
    run python -u scripts/measure_image_featurizer.py
    echo "== watcher done $(date -u +%FT%TZ)"
    exit 0
  fi
  echo "== probe failed $(date -u +%FT%TZ); sleeping 120s"
  sleep 120
done
