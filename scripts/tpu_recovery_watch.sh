#!/bin/bash
# Patient TPU recovery watcher. The shared-pool backend wedges after a client
# is killed mid-dispatch (observed twice in round 2: init then hangs ~26 min
# per attempt before erroring UNAVAILABLE). This watcher probes WITHOUT
# killing anything — each probe is allowed to hang until the backend itself
# answers or errors — and on the first healthy probe runs the pending
# measurements + bench, logging into the repo.
#
# Usage: nohup bash scripts/tpu_recovery_watch.sh >> docs/tpu_watch.log 2>&1 &
cd "$(dirname "$0")/.." || exit 1
echo "== watcher start $(date -u +%FT%TZ)"
while true; do
  if python - <<'EOF'
import jax
import jax.numpy as jnp
x = jnp.ones((128, 128), jnp.bfloat16)
assert jax.devices()[0].platform != "cpu"
float((x @ x).sum())
EOF
  then
    echo "== chip healthy $(date -u +%FT%TZ) — running measurements"
    if ! python -u scripts/quick_fit_probe.py; then
      echo "== quick fit probe FAILED $(date -u +%FT%TZ); back to probing"
      sleep 120
      continue
    fi
    echo "== image featurizer $(date -u +%FT%TZ)"
    python -u scripts/measure_image_featurizer.py
    echo "== scan modes (incl. batched k=4/k=8) $(date -u +%FT%TZ)"
    python -u scripts/measure_scan_modes.py
    echo "== vw throughput $(date -u +%FT%TZ)"
    python -u scripts/measure_vw_tpu.py
    echo "== split bookkeeping microprofile $(date -u +%FT%TZ)"
    python -u scripts/profile_split.py
    echo "== bench $(date -u +%FT%TZ)"
    python -u bench.py
    echo "== watcher done $(date -u +%FT%TZ)"
    exit 0
  fi
  echo "== probe failed $(date -u +%FT%TZ); sleeping 120s"
  sleep 120
done
