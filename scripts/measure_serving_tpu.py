"""On-device serving dispatch measurement (round-2 verdict #4).

Bounds the TPU-resident serving latency the relay hides: the reference's
continuous-mode claim is sub-millisecond (README.md:23,
docs/mmlspark-serving.md:93), and docs/SERVING.md's p50 0.127 ms was
measured on the CPU host because the ~65 ms tunnel RTT swamps any direct
HTTP measurement against the chip.

Methodology = docs/KERNELS.md paired-difference timing: the per-call device
cost of the resident scoring program is the difference between a 3k-call and
a k-call lax.scan program (RTT cancels within each pair); the host fetch of
a scalar is the barrier. Reported per batch size: device time per call,
derived requests/s, plus the one-way dispatch overhead estimate.

Writes a markdown row block to stdout; append to docs/SERVING.md.
"""

import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def main():
    import jax
    import jax.numpy as jnp

    devs = jax.devices()
    if devs[0].platform == "cpu":
        print("no accelerator — refusing to record CPU numbers as TPU")
        return 1

    from mmlspark_tpu import DataFrame
    from mmlspark_tpu.models.lightgbm import LightGBMClassifier

    rng = np.random.default_rng(0)
    f = 28
    x = rng.normal(size=(200_000, f)).astype(np.float32)
    y = ((x @ rng.normal(size=f)) > 0).astype(np.float64)
    model = LightGBMClassifier(numIterations=100, numLeaves=31,
                               maxBin=64, numTasks=1).fit(
        DataFrame({"features": x, "label": y}))
    booster = model.booster

    # resident device-side scoring program: the PRODUCTION serving hot call
    # (Booster.raw_predict's jit core — float thresholds applied in-kernel,
    # no host binning; vmap over trees at serving batch sizes,
    # booster.py _raw_predict_jit) followed by the sigmoid link
    t_used = booster._used_iters()
    trees = jax.tree.map(lambda a: jnp.asarray(a[:t_used]), booster.trees)
    thresholds = jax.tree.map(lambda a: jnp.asarray(a[:t_used]),
                              booster.thresholds)
    init = jnp.float32(booster.init_score)

    from mmlspark_tpu.ops.boosting import tree_apply_raw

    def score_once(xb):
        def one_tree(tree, thr):
            return tree.leaf_value[tree_apply_raw(tree, xb, thr)]
        vals = jax.vmap(one_tree)(trees, thresholds)          # [T, N]
        return jax.nn.sigmoid(init + vals.sum(axis=0))

    rows = []
    for batch in (1, 8, 64, 256, 1024):
        xb = jnp.asarray(x[:batch])

        def k_calls(k):
            def run(b):
                def body(acc, j):
                    # j-dependent perturbation so XLA cannot hoist the
                    # loop-invariant call out of the scan (defeats CSE;
                    # the tiny float jitter does not change control flow)
                    bj = b + (j % 2).astype(jnp.float32) * 1e-6
                    return acc + jnp.sum(score_once(bj)), None
                acc, _ = jax.lax.scan(body, jnp.float32(0.0),
                                      jnp.arange(k))
                return acc
            return jax.jit(run)

        inner = 32
        fn1, fn3 = k_calls(inner), k_calls(3 * inner)
        float(fn1(xb))    # compile + settle
        float(fn3(xb))
        diffs = []
        for _ in range(5):
            t0 = time.perf_counter()
            float(fn1(xb))
            t1 = time.perf_counter()
            float(fn3(xb))
            t2 = time.perf_counter()
            diffs.append(((t2 - t1) - (t1 - t0)) / (2 * inner))
        per_call = float(np.median(diffs))
        rows.append((batch, per_call))
        print(f"batch {batch:5d}: device {per_call * 1e3:8.3f} ms/call "
              f"= {batch / per_call:10.0f} rows/s", flush=True)

    # one-way dispatch overhead: wall of a trivial fetch
    t0 = time.perf_counter()
    for _ in range(5):
        float(jnp.float32(1.0) + 1.0)
    rtt = (time.perf_counter() - t0) / 5
    print(f"dispatch+fetch round trip ~ {rtt * 1e3:.1f} ms (relay)")
    print()
    print("| batch | device ms/call | rows/s | date |")
    print("|---|---|---|---|")
    stamp = time.strftime("%Y-%m-%d %H:%M UTC", time.gmtime())
    for batch, per_call in rows:
        print(f"| {batch} | {per_call * 1e3:.3f} | "
              f"{batch / per_call:.0f} | {stamp} |")

    # --- end-to-end HTTP -> TPU inference -> reply (round-4 verdict #4) ---
    # Real localhost HTTP through the production asyncio listener + batcher
    # with a handler that scores ON THE CHIP (jit scoring program + device
    # fetch per batch). On this environment every device fetch crosses the
    # ~relay RTT measured above — a physics floor no framework code can
    # remove — so the p50/p99 decompose as (listener+batcher, measured
    # sub-ms vs a numpy handler in tests/test_serving_latency.py) +
    # (device dispatch, the per-call rows above) + relay. On a TPU host
    # with the chip on PCIe the relay term vanishes and the composition is
    # sub-ms end-to-end; both rows land in docs/SERVING.md.
    import json
    import urllib.request

    from mmlspark_tpu.io.serving import ServingServer

    score_jit = jax.jit(score_once)

    def tpu_handler(df):
        xb = jnp.asarray(np.stack(df["features"]).astype(np.float32))
        proba = np.asarray(score_jit(xb))       # device fetch (relay RTT)
        return df.with_column("scored", proba.astype(np.float64))

    # max_latency_ms=0.0: a lone request must not sit in the dynamic
    # batcher waiting for companions — this row measures the
    # latency-optimal single-request config (the reference's continuous
    # mode is per-request; throughput configs raise the window instead).
    # An isolated registry: this measurement run's histogram must not mix
    # with whatever the process-global registry already accumulated.
    from mmlspark_tpu.observability import MetricsRegistry
    reg = MetricsRegistry()
    srv = ServingServer(tpu_handler, reply_col="scored", port=0,
                        vector_cols=("features",),
                        max_batch_size=64, max_latency_ms=0.0,
                        registry=reg).start()
    try:
        example = {"features": [float(v) for v in x[0]]}
        body = json.dumps(example).encode()
        # compile + settle BEFORE anything lands in the histogram:
        # warmup() runs the handler in-process, bypassing the batcher (no
        # histogram observation), so the first HTTP request below is
        # steady-state — without this the jit compile would own the p99
        srv.warmup(example)
        for i in range(120):
            with urllib.request.urlopen(
                    urllib.request.Request(srv.url, data=body), timeout=30):
                pass
        # p50/p99 and shed-rate come from the SERVER's registry — the same
        # series a /metrics scrape exports — not a client-side stopwatch
        # list, so this script and a production scrape can never disagree.
        # (The server histogram measures enqueue->reply; the client-side
        # socket+parse adds ~the listener overhead bounded sub-ms in
        # tests/test_serving_latency.py.)
        lbl = {"instance": srv.metrics_label}
        p50 = reg.quantile("serving_request_latency_seconds", 0.5, lbl)
        p99 = reg.quantile("serving_request_latency_seconds", 0.99, lbl)
        snap = reg.snapshot()
        # shed-rate over everything RECEIVED: dispatched + shed + expired
        # (serving_requests_total counts only batch-dispatched requests)
        received = (reg.total("serving_requests_total")
                    + reg.total("serving_shed_total")
                    + reg.total("serving_expired_total"))
        shed_rate = (reg.total("serving_shed_total") / received
                     if received else 0.0)
        print()
        print(f"HTTP->TPU->reply (batch-1, localhost, relay in path; "
              f"registry scrape): "
              f"p50 {p50 * 1e3:.2f} ms  p99 {p99 * 1e3:.2f} ms  "
              f"shed-rate {shed_rate:.3f}  "
              f"(relay RTT ~{rtt * 1e3:.0f} ms of that; "
              f"listener+batcher sub-ms per test_serving_latency)")
        print(json.dumps({"serving_telemetry": snap}))
    finally:
        srv.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
