"""Sustained serving load harness (round-12 tentpole, ROADMAP item 2).

Drives >= 100k row-requests/s of MIXED batch sizes through the
ServingCoordinator gateway for minutes on the host CPU path and records
what the serving data plane actually sustains:

- worker fleet: N separate OS processes, each a DistributedServingServer
  (continuous deadline-driven batching, binary row decode, heartbeat load
  reports feeding the gateway's least-loaded router);
- clients: keep-alive socket threads posting binary-format bodies whose
  row counts cycle the mixed-size schedule (1/8/64/256 rows per request —
  "requests/s" below counts ROWS, i.e. logical single-row requests, the
  unit the chip-side 1.1M rows/s number uses), every request carrying an
  X-Deadline-Ms budget so the continuous batcher is exercised end to end;
- gateway: keep-alive forwards, request coalescing, least-loaded routing;
- chaos variant: the same run with a seeded FaultInjector failing 30% of
  gateway forwards PLUS one worker killed mid-run (it must be evicted and
  traffic rebalanced) — the acceptance bar is ZERO accepted (HTTP 200)
  requests with a wrong/missing payload, every reply accounted for.

Outputs: a markdown row block on stdout (append to docs/SERVING.md) and a
JSON summary at --out (default docs/SERVING_load.json; bench.py embeds it
in its emitted record's `extra.serving_load`). Armed in
scripts/tpu_recovery_watch.sh; env knobs for quick runs:
MEASURE_LOAD_S (per-variant seconds, default 120), MEASURE_LOAD_CLIENTS,
MEASURE_LOAD_WORKERS, MEASURE_LOAD_SKIP_CHAOS=1.
"""

import argparse
import json
import multiprocessing as mp
import os
import re
import socket
import sys
import threading
import time
import urllib.request

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

FEATURES = 16
BATCH_MIX = (1, 8, 64, 256)
DEADLINE_MS = 10_000
SERVICE = "load"


def _weights() -> np.ndarray:
    return (np.arange(FEATURES, dtype=np.float32) + 1.0) / FEATURES


def _worker_main(coord_url: str, partition: int, ready, stop) -> None:
    """One serving worker in its own process (own GIL): numpy linear
    scorer — the host-path cost model; the chip handler swaps in the
    jitted booster (scripts/measure_serving_tpu.py)."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    from mmlspark_tpu.io.distributed_serving import DistributedServingServer

    w = _weights()

    def handler(df):
        x = np.asarray(df["features"], np.float32)
        return df.with_column("prediction", (x @ w).astype(np.float32))

    server = DistributedServingServer(
        handler, coord_url, SERVICE, partition=partition,
        machine=f"load-{partition}", port=0,
        max_batch_size=1024, max_latency_ms=0.5,
        heartbeat_interval_s=0.25, max_queue=4096).start()
    ready.set()
    stop.wait(3600)
    server.stop()


class _Client(threading.Thread):
    """Keep-alive HTTP/1.1 client hammering the gateway with binary
    bodies of mixed row counts; verifies EVERY 200 payload exactly."""

    def __init__(self, host, port, path, bodies, expected, deadline_s,
                 stop_ev):
        super().__init__(daemon=True)
        self.addr = (host, port)
        self.path = path.encode()
        self.bodies = bodies          # [(nrows, body, expected_first)]
        self.deadline_s = deadline_s
        self.stop_ev = stop_ev
        self.expected = expected
        self.sent = 0
        self.ok_requests = 0
        self.ok_rows = 0
        self.shed = 0
        self.expired = 0
        self.errors = 0
        self.bad_payload = 0
        self.lost = 0

    def _connect(self):
        s = socket.create_connection(self.addr, timeout=30.0)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return s

    def run(self):
        from mmlspark_tpu.io import rowcodec
        sock = self._connect()
        buf = b""
        i = 0
        head_tpl = (b"POST %s HTTP/1.1\r\nHost: x\r\n"
                    b"Content-Type: application/octet-stream\r\n"
                    b"X-Deadline-Ms: %d\r\n"
                    b"Content-Length: %%d\r\n\r\n"
                    % (self.path, DEADLINE_MS))
        while not self.stop_ev.is_set():
            nrows, body, exp_first = self.bodies[i % len(self.bodies)]
            i += 1
            try:
                sock.sendall(head_tpl % len(body) + body)
                self.sent += 1
                # read one response
                while b"\r\n\r\n" not in buf:
                    chunk = sock.recv(262144)
                    if not chunk:
                        raise ConnectionError("closed")
                    buf += chunk
                head, _, rest = buf.partition(b"\r\n\r\n")
                status = int(head.split(b" ", 2)[1])
                length = 0
                for ln in head.split(b"\r\n"):
                    if ln.lower().startswith(b"content-length:"):
                        length = int(ln.split(b":", 1)[1])
                while len(rest) < length:
                    chunk = sock.recv(262144)
                    if not chunk:
                        raise ConnectionError("closed")
                    rest += chunk
                payload, buf = rest[:length], rest[length:]
                if status == 200:
                    _, preds = rowcodec.decode(payload)
                    if (preds.shape[0] != nrows
                            or abs(float(preds[0]) - exp_first) > 1e-4):
                        self.bad_payload += 1
                    else:
                        self.ok_requests += 1
                        self.ok_rows += nrows
                elif status == 503:
                    self.shed += 1
                elif status == 504:
                    self.expired += 1
                else:
                    self.errors += 1
            except Exception:
                # connection died mid-request (gateway restart, teardown
                # race): the in-flight request got NO reply
                self.lost += 1
                try:
                    sock.close()
                except Exception:
                    pass
                if self.stop_ev.is_set():
                    return
                try:
                    sock = self._connect()
                    buf = b""
                except Exception:
                    time.sleep(0.05)
        try:
            sock.close()
        except Exception:
            pass


def _scrape(url: str) -> str:
    with urllib.request.urlopen(url, timeout=10.0) as r:
        return r.read().decode()


def _prom_value(text: str, name: str) -> float:
    total = 0.0
    for m in re.finditer(rf"^{name}(?:{{[^}}]*}})? ([0-9.e+-]+)$", text,
                         re.M):
        total += float(m.group(1))
    return total


def _spawn_workers(ctx, coord_url, n):
    """Each worker gets its OWN stop event: terminate()-ing a worker that
    shares an Event can kill it while it holds the event's internal lock,
    deadlocking the parent's later set() (observed on the chaos path)."""
    procs, readies, stops = [], [], []
    for p in range(n):
        ready = ctx.Event()
        stop = ctx.Event()
        proc = ctx.Process(target=_worker_main,
                           args=(coord_url, p, ready, stop), daemon=True)
        proc.start()
        procs.append(proc)
        readies.append(ready)
        stops.append(stop)
    for r in readies:
        if not r.wait(60):
            raise RuntimeError("worker failed to start/register")
    return procs, stops


def run_variant(chaos: bool, duration_s: float, n_workers: int,
                n_clients: int) -> dict:
    from mmlspark_tpu.io import rowcodec
    from mmlspark_tpu.io.distributed_serving import ServingCoordinator
    from mmlspark_tpu.io.http import KeepAliveTransport
    from mmlspark_tpu.observability import MetricsRegistry, set_registry
    from mmlspark_tpu.resilience import FaultInjector

    # fresh process-global registry per variant: worker processes have
    # their own; the gateway's series live here
    reg = MetricsRegistry()
    prev = set_registry(reg)
    injector = None
    transport = None
    if chaos:
        transport = KeepAliveTransport()
        injector = FaultInjector(seed=12, error_rate=0.3)
    coord = ServingCoordinator(
        heartbeat_timeout_s=2.0, registry=reg,
        forward_transport=(injector.wrap(transport) if chaos else None),
        coalesce_max=8).start()
    ctx = mp.get_context("spawn")
    procs, worker_stops = _spawn_workers(ctx, coord.url, n_workers)

    w = _weights()
    rng = np.random.default_rng(5)
    bodies = []
    for nrows in BATCH_MIX:
        x = rng.normal(size=(nrows, FEATURES)).astype(np.float32)
        bodies.append((nrows, rowcodec.encode("features", x),
                       float(x[0] @ w)))

    stop_clients = threading.Event()
    import urllib.parse
    parsed = urllib.parse.urlsplit(coord.url)
    clients = [_Client(parsed.hostname, parsed.port,
                       f"/gateway/{SERVICE}", bodies, w,
                       DEADLINE_MS / 1000.0, stop_clients)
               for _ in range(n_clients)]
    t0 = time.perf_counter()
    for c in clients:
        c.start()
    killed_at = None
    if chaos:
        # kill one worker a third of the way in: it must be evicted and
        # the fleet rebalanced with zero accepted-request loss
        time.sleep(max(duration_s / 3.0, 1.0))
        procs[0].terminate()
        killed_at = time.perf_counter() - t0
        time.sleep(max(duration_s * 2.0 / 3.0, 1.0))
    else:
        time.sleep(duration_s)
    stop_clients.set()
    for c in clients:
        c.join(15.0)
    wall = time.perf_counter() - t0

    # worker-side scrape BEFORE teardown: batch fill + request accounting
    worker_stats = []
    for s in coord.routes(SERVICE):
        try:
            text = _scrape(f"http://{s.host}:{s.port}/metrics")
            cnt = _prom_value(text, "serving_batch_rows_count")
            tot = _prom_value(text, "serving_batch_rows_sum")
            worker_stats.append({
                "worker": f"{s.machine}:{s.partition}",
                "batches": cnt,
                "mean_batch_rows": round(tot / cnt, 2) if cnt else 0.0,
                "requests": _prom_value(text, "serving_requests_total"),
                "shed": _prom_value(text, "serving_shed_total"),
                "coalesced_packs": _prom_value(
                    text, "serving_coalesced_packs_total"),
            })
        except Exception as e:
            worker_stats.append({"worker": f"{s.machine}:{s.partition}",
                                 "scrape_error": str(e)[:100]})

    # trace exemplars: a few gateway traces with their per-attempt spans
    exemplars = []
    seen = set()
    for ev in list(coord.events.events())[-400:]:
        tid = ev.get("trace_id")
        if tid and tid not in seen:
            seen.add(tid)
            spans = [{k: v for k, v in e.items() if k != "trace_id"}
                     for e in coord.events.events(tid)]
            exemplars.append({"trace_id": tid, "spans": spans[:8]})
        if len(exemplars) >= 3:
            break

    lbl = {"instance": coord.metrics_label}
    p50 = reg.quantile("gateway_request_latency_seconds", 0.5, lbl)
    p99 = reg.quantile("gateway_request_latency_seconds", 0.99, lbl)
    sent = sum(c.sent for c in clients)
    ok_req = sum(c.ok_requests for c in clients)
    ok_rows = sum(c.ok_rows for c in clients)
    shed = sum(c.shed for c in clients)
    expired = sum(c.expired for c in clients)
    errors = sum(c.errors for c in clients)
    bad = sum(c.bad_payload for c in clients)
    lost = sum(c.lost for c in clients)
    mean_fill_rows = [ws["mean_batch_rows"] for ws in worker_stats
                      if ws.get("batches")]
    summary = {
        "variant": "chaos" if chaos else "baseline",
        "duration_s": round(wall, 1),
        "workers": n_workers,
        "clients": n_clients,
        "batch_mix_rows": list(BATCH_MIX),
        "client_requests": sent,
        "ok_requests": ok_req,
        "ok_rows": ok_rows,
        "row_requests_per_s": round(ok_rows / wall, 1),
        "client_requests_per_s": round(sent / wall, 1),
        "shed": shed,
        "expired": expired,
        "errors": errors,
        "bad_payload_on_200": bad,
        "no_reply_lost": lost,
        "shed_rate": round(shed / sent, 5) if sent else 0.0,
        "gateway_p50_ms": round(p50 * 1e3, 3) if p50 else None,
        "gateway_p99_ms": round(p99 * 1e3, 3) if p99 else None,
        "coalesced_forwards": reg.total("gateway_coalesced_forwards_total"),
        "coalesced_requests": reg.total("gateway_coalesced_requests_total"),
        "route_decisions": reg.total("gateway_route_decisions_total"),
        "forward_failures": reg.total("gateway_forward_failures_total"),
        "evictions": reg.total("gateway_evictions_total"),
        "worker_stats": worker_stats,
        "mean_batch_rows": (round(float(np.mean(mean_fill_rows)), 1)
                            if mean_fill_rows else 0.0),
        "trace_exemplars": exemplars,
    }
    if chaos:
        summary["injected"] = dict(injector.counts)
        summary["worker_killed_at_s"] = round(killed_at, 1)

    for p, st in zip(procs, worker_stops):
        if p.is_alive():
            st.set()
    for p in procs:
        p.join(10.0)
        if p.is_alive():
            p.terminate()
    coord.stop()
    set_registry(prev)
    return summary


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="docs/SERVING_load.json")
    ap.add_argument("--duration-s", type=float, default=float(
        os.environ.get("MEASURE_LOAD_S", "120")))
    ap.add_argument("--workers", type=int, default=int(
        os.environ.get("MEASURE_LOAD_WORKERS", "4")))
    ap.add_argument("--clients", type=int, default=int(
        os.environ.get("MEASURE_LOAD_CLIENTS", "32")))
    ap.add_argument("--target-rows-s", type=float, default=100_000.0)
    args = ap.parse_args()

    variants = [False]
    if os.environ.get("MEASURE_LOAD_SKIP_CHAOS") != "1":
        variants.append(True)
    results = []
    for chaos in variants:
        tag = "chaos" if chaos else "baseline"
        print(f"== {tag}: {args.duration_s:.0f}s, {args.workers} workers, "
              f"{args.clients} clients", flush=True)
        s = run_variant(chaos, args.duration_s, args.workers, args.clients)
        results.append(s)
        print(json.dumps({k: v for k, v in s.items()
                          if k not in ("worker_stats", "trace_exemplars")},
                         indent=1), flush=True)

    record = {
        "host": "cpu",
        "date_utc": time.strftime("%Y-%m-%d %H:%M UTC", time.gmtime()),
        "target_row_requests_per_s": args.target_rows_s,
        "variants": results,
    }
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(record, f, indent=1)
    print(f"wrote {args.out}")

    print("\n| variant | rows/s (row-requests/s) | client req/s | p50 | "
          "p99 | shed rate | mean batch rows | accepted lost |")
    print("|---|---|---|---|---|---|---|---|")
    rc = 0
    for s in results:
        accepted_lost = s["bad_payload_on_200"]
        print(f"| {s['variant']} | {s['row_requests_per_s']:.0f} | "
              f"{s['client_requests_per_s']:.0f} | "
              f"{s['gateway_p50_ms']} ms | {s['gateway_p99_ms']} ms | "
              f"{s['shed_rate']:.4f} | {s['mean_batch_rows']} | "
              f"{accepted_lost} |")
        if s["variant"] == "baseline" \
                and s["row_requests_per_s"] < args.target_rows_s:
            print(f"  !! baseline below target "
                  f"{args.target_rows_s:.0f} rows/s")
            rc = 1
        if accepted_lost:
            print("  !! accepted (200) requests with wrong payload")
            rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
