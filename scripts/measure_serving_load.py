"""Sustained serving load harness CLI (rounds 12-14, ROADMAP item 2).

The measured legs themselves now live in `mmlspark_tpu/io/loadgen.py`
(ISSUE 20 satellite: the production-day scenario engine composes the
same fleet setup/traffic/observability pieces instead of duplicating
them); this script is the thin CLI that keeps the historical contract:

- `--scenario load` — >= 100k mixed-size row-requests/s through the
  ServingCoordinator gateway, plus the chaos variant (30% injected
  forward faults + one worker kill, zero accepted-request loss).
- `--scenario swap` — registry-backed fleet with a mid-run canary ->
  promote rollout; the chaos variant corrupts the target artifact,
  kills a worker mid-rollout, and injects forward faults — the rollout
  must auto-roll-back with zero accepted-request loss.
- `--scenario autoscale` — ramped load against a 2-worker base fleet;
  the Autoscaler must grow 2 -> 4 and retire back to 2, zero loss.

Outputs: a markdown row block on stdout (append to docs/SERVING.md) and
a JSON summary at --out (defaults: docs/SERVING_load.json /
docs/SERVING_swap.json / docs/SERVING_autoscale.json; bench.py embeds
them in its emitted record's `extra`). Armed in
scripts/tpu_recovery_watch.sh; env knobs for quick runs:
MEASURE_LOAD_S (per-variant seconds, default 120), MEASURE_LOAD_CLIENTS,
MEASURE_LOAD_WORKERS, MEASURE_LOAD_SKIP_CHAOS=1.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mmlspark_tpu.io.loadgen import (  # noqa: E402
    BATCH_MIX, DEADLINE_MS, FEATURES, SERVICE, LoadClient,
    arm_observability, client_tallies, harvest_observability, make_handler,
    prom_by_label, prom_value, ref_weights, registry_loader,
    run_autoscale_variant, run_load_variant, run_swap_variant, scrape,
    spawn_workers, worker_main)

# backward-compatible aliases: external callers (and the @slow mini-run
# tests) historically imported the script's private names
run_variant = run_load_variant
_weights = ref_weights
_make_handler = make_handler
_registry_loader = registry_loader
_worker_main = worker_main
_Client = LoadClient
_scrape = scrape
_arm_observability = arm_observability
_harvest_observability = harvest_observability
_prom_value = prom_value
_prom_by_label = prom_by_label
_spawn_workers = spawn_workers
_client_tallies = client_tallies


def _gate_swap(results) -> int:
    rc = 0
    for s in results:
        chaos = s["variant"] == "swap_chaos"
        if s["bad_payload_on_200"] or s["no_reply_lost"]:
            print(f"  !! {s['variant']}: accepted-request loss "
                  f"(bad={s['bad_payload_on_200']} "
                  f"lost={s['no_reply_lost']})")
            rc = 1
        if not chaos and s["shed"]:
            print(f"  !! swap: {s['shed']} requests shed during rollout")
            rc = 1
        want = "rolled_back" if chaos else "done"
        if s["rollout_final_state"] != want:
            print(f"  !! {s['variant']}: rollout ended "
                  f"{s['rollout_final_state']!r}, wanted {want!r}")
            rc = 1
        if not chaos and len(s["replies_by_version_index"]) < 2:
            print("  !! swap: replies never flipped to the new version")
            rc = 1
        if chaos and s["replies_by_version_index"].get(1):
            print("  !! swap_chaos: corrupt version answered traffic")
            rc = 1
    return rc


def _gate_autoscale(s) -> int:
    rc = 0
    if s["bad_payload_on_200"] or s["no_reply_lost"]:
        print(f"  !! autoscale: accepted-request loss "
              f"(bad={s['bad_payload_on_200']} lost={s['no_reply_lost']})")
        rc = 1
    # the full acceptance ramp must reach 4 workers; short mini-runs
    # (tests) gate on growth happening at all (MEASURE_AS_MIN_PEAK=3)
    min_peak = int(os.environ.get("MEASURE_AS_MIN_PEAK", "4"))
    if s["peak_workers"] < min_peak:
        print(f"  !! autoscale: never grew to {min_peak} workers "
              f"(peak {s['peak_workers']})")
        rc = 1
    if s["final_workers"] != 2:
        print(f"  !! autoscale: did not retire back to 2 "
              f"(final {s['final_workers']})")
        rc = 1
    return rc


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--scenario", default="load",
                    choices=("load", "swap", "autoscale"))
    ap.add_argument("--out", default=None)
    ap.add_argument("--duration-s", type=float, default=float(
        os.environ.get("MEASURE_LOAD_S", "120")))
    ap.add_argument("--workers", type=int, default=int(
        os.environ.get("MEASURE_LOAD_WORKERS", "4")))
    ap.add_argument("--clients", type=int, default=int(
        os.environ.get("MEASURE_LOAD_CLIENTS", "32")))
    ap.add_argument("--target-rows-s", type=float, default=100_000.0)
    ap.add_argument("--no-collect", action="store_true",
                    help="disable the trace collector + flight recorder "
                         "(the A/B arm of the collector-overhead table in "
                         "docs/OBSERVABILITY.md)")
    args = ap.parse_args()
    if args.out is None:
        args.out = {"load": "docs/SERVING_load.json",
                    "swap": "docs/SERVING_swap.json",
                    "autoscale": "docs/SERVING_autoscale.json"}[
                        args.scenario]

    results = []
    rc = 0
    if args.scenario == "load":
        variants = [False]
        if os.environ.get("MEASURE_LOAD_SKIP_CHAOS") != "1":
            variants.append(True)
        for chaos in variants:
            tag = "chaos" if chaos else "baseline"
            print(f"== {tag}: {args.duration_s:.0f}s, {args.workers} "
                  f"workers, {args.clients} clients", flush=True)
            results.append(run_load_variant(chaos, args.duration_s,
                                            args.workers, args.clients,
                                            collect=not args.no_collect))
    elif args.scenario == "swap":
        variants = [False]
        if os.environ.get("MEASURE_LOAD_SKIP_CHAOS") != "1":
            variants.append(True)
        for chaos in variants:
            tag = "swap_chaos" if chaos else "swap"
            print(f"== {tag}: {args.duration_s:.0f}s, {args.workers} "
                  f"workers, {args.clients} clients", flush=True)
            results.append(run_swap_variant(chaos, args.duration_s,
                                            args.workers, args.clients,
                                            collect=not args.no_collect))
    else:
        print(f"== autoscale: {args.duration_s:.0f}s ramp, "
              f"{args.clients} ramp clients", flush=True)
        results.append(run_autoscale_variant(args.duration_s,
                                             args.clients,
                                             collect=not args.no_collect))
    for s in results:
        print(json.dumps({k: v for k, v in s.items()
                          if k not in ("worker_stats", "trace_exemplars",
                                       "fleet_series", "fleet",
                                       "incidents")},
                         indent=1), flush=True)
        for inc in s.get("incidents", []):
            print(f"  incident: {inc['reason']} ({inc['detail']}) — "
                  f"{len(inc['traces']['slowest'])} slowest / "
                  f"{len(inc['traces']['failed'])} failed traces, "
                  f"{len(inc['system_events'])} system events", flush=True)

    record = {
        "host": "cpu",
        "scenario": args.scenario,
        "date_utc": time.strftime("%Y-%m-%d %H:%M UTC", time.gmtime()),
        "target_row_requests_per_s": args.target_rows_s,
        "variants": results,
    }
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(record, f, indent=1)
    print(f"wrote {args.out}")

    if args.scenario == "swap":
        print("\n| variant | rows/s | p50 | p99 | rollout | resolved "
              "| shed | accepted lost |")
        print("|---|---|---|---|---|---|---|---|")
        for s in results:
            print(f"| {s['variant']} | {s['row_requests_per_s']:.0f} | "
                  f"{s['gateway_p50_ms']} ms | {s['gateway_p99_ms']} ms | "
                  f"{s['rollout_final_state']} | "
                  f"{s['rollout_resolved_at_s']}s | {s['shed']} | "
                  f"{s['bad_payload_on_200'] + s['no_reply_lost']} |")
        return _gate_swap(results)
    if args.scenario == "autoscale":
        s = results[0]
        print(f"\n| workers 2->{s['peak_workers']}->{s['final_workers']} "
              f"| rows/s {s['row_requests_per_s']:.0f} | "
              f"p99 {s['gateway_p99_ms']} ms | shed {s['shed']} | "
              f"lost {s['no_reply_lost'] + s['bad_payload_on_200']} |")
        return _gate_autoscale(s)

    print("\n| variant | rows/s (row-requests/s) | client req/s | p50 | "
          "p99 | shed rate | mean batch rows | accepted lost |")
    print("|---|---|---|---|---|---|---|---|")
    for s in results:
        accepted_lost = s["bad_payload_on_200"]
        print(f"| {s['variant']} | {s['row_requests_per_s']:.0f} | "
              f"{s['client_requests_per_s']:.0f} | "
              f"{s['gateway_p50_ms']} ms | {s['gateway_p99_ms']} ms | "
              f"{s['shed_rate']:.4f} | {s['mean_batch_rows']} | "
              f"{accepted_lost} |")
        if s["variant"] == "baseline" \
                and s["row_requests_per_s"] < args.target_rows_s:
            print(f"  !! baseline below target "
                  f"{args.target_rows_s:.0f} rows/s")
            rc = 1
        if accepted_lost:
            print("  !! accepted (200) requests with wrong payload")
            rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
