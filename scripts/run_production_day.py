"""One replayable production day against the serving fleet (ISSUE 20).

ONE command composes every resilience subsystem the repo proved one
fault at a time: seeded diurnal traffic (ramp -> peak -> burst ->
trough) from the io/loadgen.py harness, a scripted fault timeline on
one clock — canary rollout at peak, worker kill mid-rollout, corrupt
artifact publish in the burst, autoscale-down in the trough, an
online-learner preemption (the PR 19 loop) — and a machine-checkable
scorecard JSON (resilience/scenario.py `build_scorecard`):

- per-phase SLO adherence from the PR 14 monitors (burst judged but
  exempt: shedding inside the error budget IS the flash-crowd design),
- zero accepted-request loss across all injected faults,
- one flight-recorder incident bundle per injected fault class
  (`chaos_bundles=True` arms the chaos trigger),
- chaos counters reconciled EXACTLY against injector ground truth,
- a worker-seconds cost proxy beating the no-autoscaler baseline leg
  (static provisioning at the peak fleet for the whole day),
- fault-schedule determinism: the whole multi-injector plan re-derives
  from the master seed (chaos.derive_seed) to an identical digest.

Two modes share the scorecard logic (the acceptance contract):

- `--mode full` (default): subprocess registry-backed workers, binary
  keep-alive clients, the real gateway/autoscaler/rollout machinery.
  Armed in scripts/tpu_recovery_watch.sh; bench.py embeds the JSON as
  `extra.production_day`. Env knobs: PRODUCTION_DAY_S (default 180),
  PRODUCTION_DAY_CLIENTS, PRODUCTION_DAY_SEED, PRODUCTION_DAY_ERROR_RATE.
- `--mode mini`: the tier-1 leg (tests/test_production_day.py) — one
  injected clock drives the engine, SLO monitor, autoscaler, and flight
  recorder over an in-process fleet; a 120-scenario-second day runs in
  a few real seconds with zero sleeps of scenario length.

Outputs: scorecard table on stdout (exit code = scorecard verdict) and
the full summary JSON at --out (defaults: docs/PRODUCTION_DAY.json /
docs/PRODUCTION_DAY_mini.json). docs/SCENARIOS.md narrates the day.
"""

import argparse
import json
import os
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from mmlspark_tpu.resilience.scenario import (  # noqa: E402
    ScenarioChaos, ScenarioEngine, ScenarioTimeline, build_scorecard,
    cost_proxy, diurnal_phases, judge_slo, reconcile_chaos)

SERVICE_MINI = "svc"
MINI_ERROR_RATE = 0.12

# the learner leg's compact synthetic stream (the PR 19 loop's shape)
ROW_W = 4
NUM_FEATURES = 64   # numBits=6


class _FakeClock:
    """The mini run's single injected clock: `sleep` advances it, so a
    120-scenario-second day costs zero real waiting."""

    def __init__(self, t=0.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def sleep(self, dt):
        self.t += dt


def _build_chaos(seed, error_rate, registry=None, event_log=None):
    """The run's whole fault plan from ONE master seed — called twice
    with identical construction (once for the planned schedule digest,
    once live), which is exactly the replay contract the scorecard's
    `fault_schedule_deterministic` check proves."""
    chaos = ScenarioChaos(seed, registry=registry, event_log=event_log)
    chaos.fault_injector("gateway_forward", error_rate=error_rate,
                         event_log=event_log)
    chaos.training_injector("learner", kill_at_chunk=1)
    return chaos


def _incident_reasons(recorder):
    out = []
    for p in recorder.incidents:
        try:
            with open(p) as f:
                out.append({"reason": json.load(f)["reason"], "path": p})
        except Exception:  # noqa: BLE001 - a torn bundle is its own finding
            out.append({"reason": "unreadable", "path": p})
    return out


# ------------------------------------------------------- the learner leg

def _write_learner_events(path, n, seed):
    """Seeded synthetic prediction/reward traffic: linear true costs,
    bounded reward delay, event-time order (the PR 19 stream shape)."""
    import random
    from mmlspark_tpu.io.streaming import append_jsonl
    rng = random.Random(seed)
    true_w = [rng.uniform(-1, 1) for _ in range(NUM_FEATURES)]
    t, pending = 0.0, []
    for i in range(n):
        t += 0.01
        idx = sorted(rng.sample(range(NUM_FEATURES), ROW_W))
        append_jsonl(path, {"kind": "prediction", "key": f"k{i:06d}",
                            "ts": t, "indices": idx,
                            "values": [1.0] * ROW_W, "probability": 1.0})
        cost = sum(true_w[j] for j in idx) + rng.gauss(0, 0.05)
        pending.append((t + rng.uniform(0.05, 2.0), f"k{i:06d}", cost))
        pending.sort()
        while pending and pending[0][0] <= t:
            rts, k, c = pending.pop(0)
            append_jsonl(path, {"kind": "reward", "key": k, "ts": rts,
                                "cost": c})
    for rts, k, c in sorted(pending):
        append_jsonl(path, {"kind": "reward", "key": k, "ts": rts,
                            "cost": c})


def _learner_leg(chaos, workdir, n_events=256):
    """The trough's online-learner preemption: the master-seed-derived
    TrainingFaultInjector kills the runner at a chunk boundary, a fresh
    runner resumes from the durable snapshot, and the finished state's
    digest must equal an uninterrupted offline replay of the same seeded
    log — the PR 19 exactly-once contract, inside the production day."""
    from mmlspark_tpu.io.streaming import JsonlEventSource
    from mmlspark_tpu.models.vw import VowpalWabbitRegressor
    from mmlspark_tpu.resilience import CheckpointStore, InjectedKill
    from mmlspark_tpu.train.online_loop import (OnlineLearnerRunner,
                                                offline_replay)

    inj = chaos.injectors["learner"]
    path = os.path.join(workdir, "learner_events.jsonl")
    _write_learner_events(path, n_events, chaos.master_seed % 100000)
    kw = dict(row_width=ROW_W, horizon_s=10.0, snapshot_every=64,
              holdout_every=10)
    oracle = offline_replay(VowpalWabbitRegressor(numBits=6),
                            JsonlEventSource(path), **kw)
    store_dir = os.path.join(workdir, "learner_ckpt")
    r1 = OnlineLearnerRunner(VowpalWabbitRegressor(numBits=6),
                             JsonlEventSource(path),
                             store=CheckpointStore(store_dir), ndev=1, **kw)
    inj.arm(r1)
    killed = False
    try:
        r1.run(idle_limit=2)
    except InjectedKill:
        killed = True
        # the designated commit point for the scripted fault class
        chaos.record_scripted("learner_preempt",
                              kill_at_chunk=inj.kill_at_chunk)
    r2 = OnlineLearnerRunner(VowpalWabbitRegressor(numBits=6),
                             JsonlEventSource(path),
                             store=CheckpointStore(store_dir), ndev=1, **kw)
    resumes = r2.counts["resumes"]
    r2.run(idle_limit=2)
    _, digest = r2.finalize()
    return {"events": n_events, "killed": killed, "resumes": resumes,
            "joined": r2.counts["joined"], "digest": digest,
            "digest_matches_offline_replay": digest == oracle}


# ------------------------------------------------------------- mini mode

def run_mini(seed=20, total_s=120.0, tick_s=2.0, out=None,
             work_dir=None):
    """The tier-1 production day: in-process gateway + workers, one
    injected clock, compressed timeline, full scorecard. Returns the
    summary dict (tests assert on it directly)."""
    from mmlspark_tpu.io.autoscale import Autoscaler
    from mmlspark_tpu.io.distributed_serving import (ServiceInfo,
                                                     ServingCoordinator,
                                                     _default_transport)
    from mmlspark_tpu.io.loadgen import registry_loader
    from mmlspark_tpu.io.registry import ModelRegistry
    from mmlspark_tpu.io.serving import ServingServer
    from mmlspark_tpu.observability import (FlightRecorder, MetricsRegistry,
                                            SLOMonitor, TraceCollector,
                                            set_registry)
    from mmlspark_tpu.resilience import Deadline
    from mmlspark_tpu.resilience.chaos import TrainingFaultInjector
    from mmlspark_tpu.resilience.policy import RetryPolicy

    work_dir = work_dir or tempfile.mkdtemp(prefix="production_day_mini_")
    inc_dir = os.path.join(work_dir, "incidents")
    os.makedirs(inc_dir, exist_ok=True)

    planned_digest = _build_chaos(seed, MINI_ERROR_RATE).schedule_digest()

    reg = MetricsRegistry()
    prev = set_registry(reg)
    coord = None
    live = []                       # [(server, info)] — the routed fleet
    stop_heal = threading.Event()
    try:
        coord = ServingCoordinator(
            registry=reg, heartbeat_timeout_s=300.0, slo_monitor=None,
            forward_retry=RetryPolicy(attempts=8, backoff_s=0.01,
                                      multiplier=1.2, max_backoff_s=0.05,
                                      jitter=0.0),
            forward_transport=None).start()
        chaos = _build_chaos(seed, MINI_ERROR_RATE, registry=reg,
                             event_log=coord.events)
        injector = chaos.injectors["gateway_forward"]
        coord._transport = injector.wrap(_default_transport)

        clock = _FakeClock(0.0)
        slo = SLOMonitor.gateway_defaults(
            registry=reg, event_log=coord.events, clock=clock,
            fast_window_s=10.0, slow_window_s=45.0)

        collector = TraceCollector(registry=reg)
        collector.add_gateway(coord.metrics_label, event_log=coord.events)

        def handler_v(value):
            return lambda df: df.with_column(
                "prediction", np.full(len(df), value, np.float32))

        def add_worker(value=1.0):
            srv = ServingServer(handler_v(value), port=0,
                                max_latency_ms=0.5, registry=reg).start()
            info = ServiceInfo(SERVICE_MINI, "127.0.0.1", srv.port,
                               f"m{srv.port}", len(live))
            coord.register(info)
            handle = (srv, info)
            live.append(handle)
            collector.add_worker(info.machine,
                                 endpoint=f"127.0.0.1:{srv.port}",
                                 event_log=srv.events)
            return handle

        for _ in range(2):
            add_worker()

        # chaos evicts; the healer stands in for heartbeat re-registration
        def heal():
            while not stop_heal.wait(0.02):
                try:
                    if len(coord.routes(SERVICE_MINI)) < len(live):
                        for _, info in list(live):
                            coord.register(info)
                except Exception:  # noqa: BLE001
                    pass
        threading.Thread(target=heal, daemon=True).start()

        recorder = FlightRecorder(
            collector, inc_dir, registry=reg, clock=clock,
            window_s=30.0, cooldown_s=1.0, chaos_bundles=True,
            health_fn=coord.health, rollouts_fn=coord.rollouts_status,
            workers_fn=lambda: [(f"127.0.0.1:{s.port}",
                                 f"http://127.0.0.1:{s.port}")
                                for s, _ in live],
            slo=slo)

        # the autoscaler rides the same injected clock; the queue-depth
        # signal is scripted per phase (the subprocess fleet's organic
        # signal is the full run's job — here the CONTROL LOOP is under
        # test: burst saturates -> grow, trough idles -> shrink)
        depth = {"v": 4.0}

        def signals():
            return [depth["v"] for _ in coord.routes(SERVICE_MINI)]

        def spawn():
            return add_worker()

        def retire(handle):
            srv, info = handle
            if handle in live:
                live.remove(handle)
            coord.deregister(SERVICE_MINI, info)
            srv.stop()

        scaler = Autoscaler(signals, spawn, retire,
                            min_workers=1, max_workers=3,
                            high_queue_depth=8.0, low_queue_depth=1.0,
                            up_after=2, down_after=2, cooldown_s=6.0,
                            interval_s=1.0, ewma_alpha=1.0, clock=clock,
                            registry=reg, event_log=coord.events)

        phases = diurnal_phases(total_s)
        ph = {p.name: p for p in phases}
        phase_samples = {p.name: [] for p in phases}
        tallies = {"client_requests": 0, "ok_requests": 0, "shed": 0,
                   "expired": 0, "errors": 0, "bad_payload_on_200": 0,
                   "no_reply_lost": 0}
        fleet_series = []
        gw_url = coord.url + f"/gateway/{SERVICE_MINI}"
        ok_values = (1.0, 2.0)      # v1 and post-rollout v2 predictions
        req_i = [0]

        def post_traffic(n):
            for _ in range(n):
                req_i[0] += 1
                tallies["client_requests"] += 1
                body = json.dumps({"x": float(req_i[0] % 7)}).encode()
                try:
                    rq = urllib.request.Request(
                        gw_url, data=body,
                        headers={"X-Trace-Id": f"day-{req_i[0]:05d}",
                                 Deadline.HEADER: "8000"})
                    with urllib.request.urlopen(rq, timeout=10.0) as r:
                        payload = r.read()
                    pred = json.loads(payload).get("prediction")
                    preds = pred if isinstance(pred, list) else [pred]
                    if preds and all(
                            any(abs(float(p) - v) <= 1e-6
                                for v in ok_values) for p in preds):
                        tallies["ok_requests"] += 1
                    else:
                        tallies["bad_payload_on_200"] += 1
                except urllib.error.HTTPError as e:
                    if e.code == 503:
                        tallies["shed"] += 1
                    elif e.code == 504:
                        tallies["expired"] += 1
                    else:
                        tallies["errors"] += 1
                except Exception:  # noqa: BLE001 - no reply at all
                    tallies["no_reply_lost"] += 1

        # ---------------------------------------------- scripted timeline
        timeline = ScenarioTimeline()
        mreg = ModelRegistry(os.path.join(work_dir, "model_registry"),
                             keep_last=4)
        swap_outcomes = {}
        learner_summary = {}

        def canary_rollout():
            srv, _ = live[0]
            res = srv.hot_swap(lambda: handler_v(2.0), 2, wait_s=10.0)
            swap_outcomes["canary_rollout"] = res.outcome

        def worker_kill():
            chaos.record_scripted("worker_kill", phase="peak")
            handle = live[-1]
            live.remove(handle)     # the healer must NOT resurrect it
            srv, info = handle
            coord.deregister(SERVICE_MINI, info)
            srv.stop()

        def corrupt_artifact():
            chaos.record_scripted("corrupt_artifact", phase="burst")
            w = (np.arange(8, dtype=np.float32) + 1.0)
            v = mreg.publish({"weights.bin": w.tobytes()})
            TrainingFaultInjector.corrupt_version_payload(mreg, v)

            def load_fn():
                # the registry digest gate fails the LOAD on the swap
                # thread -> counted rollback, old handler keeps serving
                vdir, manifest = mreg.resolve(v)
                return registry_loader(vdir, manifest)
            srv, _ = live[0]
            res = srv.hot_swap(load_fn, v, wait_s=10.0)
            swap_outcomes["corrupt_artifact"] = res.outcome

        def learner_preempt():
            learner_summary.update(_learner_leg(chaos, work_dir))

        timeline.at(ph["peak"].start_s + 4.0, "canary_rollout",
                    canary_rollout)
        timeline.at(ph["peak"].start_s + 10.0, "worker_kill", worker_kill)
        timeline.at(ph["burst"].start_s + 2.0, "corrupt_artifact",
                    corrupt_artifact)
        timeline.at(ph["trough"].start_s + 4.0, "learner_preempt",
                    learner_preempt)

        def on_phase(phase):
            depth["v"] = {"ramp": 4.0, "peak": 5.0, "burst": 12.0,
                          "trough": 0.2}[phase.name]

        def on_tick(phase):
            post_traffic(max(1, round(phase.traffic * 3)))
            slo.tick()
            phase_samples[phase.name].append(slo.status())
            scaler.tick()
            recorder.tick()
            fleet_series.append({"t": round(engine.now(), 1),
                                 "workers": len(coord.routes(
                                     SERVICE_MINI))})

        engine = ScenarioEngine(phases, timeline, clock=clock,
                                sleep=clock.sleep, tick_s=tick_s,
                                registry=reg, on_phase=on_phase,
                                on_tick=on_tick)
        engine.run()
        stop_heal.set()
        recorder.tick()             # trailing events -> final bundles

        # ------------------------------------------------- the judgment
        phase_slo = {name: judge_slo(s)
                     for name, s in phase_samples.items()}
        incidents = _incident_reasons(recorder)
        baseline = max((s["workers"] for s in fleet_series), default=2)
        cost = cost_proxy(fleet_series, total_s, baseline)
        scorecard = build_scorecard(
            registry=reg, phases=phases, phase_slo=phase_slo,
            tallies=tallies,
            incident_reasons=[i["reason"] for i in incidents],
            chaos=chaos, cost=cost, schedule_digest=planned_digest)

        summary = {
            "mode": "mini", "seed": seed, "total_s": total_s,
            "tick_s": tick_s,
            "phases": engine.phase_log,
            "timeline": engine.timeline.fired,
            "traffic": tallies,
            "phase_slo": phase_slo,
            "swap_outcomes": swap_outcomes,
            "learner": learner_summary,
            "autoscaler_actions": [
                {**a, "t": round(a["t"], 1)} for a in scaler.actions],
            "fleet_series": fleet_series,
            "cost_proxy": cost,
            "chaos": {
                "master_seed": seed,
                "schedule_digest": chaos.schedule_digest(),
                "planned_digest": planned_digest,
                "injected": {name: dict(inj.counts)
                             for name, inj in chaos.injectors.items()},
                "scripted": dict(chaos.scripted),
            },
            "reconciliation": reconcile_chaos(chaos, reg),
            "incidents": incidents,
            "scorecard": scorecard.as_dict(),
        }
        if out:
            os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
            with open(out, "w") as f:
                json.dump(summary, f, indent=1)
        return summary
    finally:
        stop_heal.set()
        for srv, _ in list(live):
            try:
                srv.stop()
            except Exception:  # noqa: BLE001
                pass
        if coord is not None:
            coord.stop()
        set_registry(prev)


# ------------------------------------------------------------- full mode

def run_full(seed=None, total_s=None, n_clients=None, out=None,
             workers=2):
    """The full production day against a subprocess registry-backed
    fleet: loadgen workers + keep-alive binary clients, the real rollout
    state machine, the heartbeat-signal autoscaler, and the scripted
    fault timeline — judged by the same `build_scorecard` as the mini
    run, plus the fleet_status --assert-healthy gate at day end."""
    import multiprocessing as mp
    import urllib.parse
    from mmlspark_tpu.io import rowcodec
    from mmlspark_tpu.io.autoscale import Autoscaler
    from mmlspark_tpu.io.distributed_serving import ServingCoordinator
    from mmlspark_tpu.io.http import KeepAliveTransport
    from mmlspark_tpu.io.loadgen import (DEADLINE_MS, FEATURES, SERVICE,
                                         LoadClient, arm_observability,
                                         client_tallies,
                                         harvest_observability,
                                         make_bodies, make_handler,
                                         ref_weights, spawn_workers,
                                         stop_workers)
    from mmlspark_tpu.io.registry import ModelRegistry, golden_reply_digest
    from mmlspark_tpu.observability import MetricsRegistry, set_registry
    from mmlspark_tpu.resilience.chaos import TrainingFaultInjector
    from fleet_status import assert_healthy, collect_fleet

    seed = (int(os.environ.get("PRODUCTION_DAY_SEED", "20"))
            if seed is None else int(seed))
    total_s = (float(os.environ.get("PRODUCTION_DAY_S", "180"))
               if total_s is None else float(total_s))
    n_clients = (int(os.environ.get("PRODUCTION_DAY_CLIENTS", "24"))
                 if n_clients is None else int(n_clients))
    # 2%: forward errors transiently EVICT the victim until its next
    # heartbeat, so at production-day request rates a higher rate keeps
    # the routing table perpetually decimated and starves the
    # autoscaler's queue-depth signal — episodic chaos, not a flood
    error_rate = float(os.environ.get("PRODUCTION_DAY_ERROR_RATE", "0.02"))
    # the proven deficit knob from loadgen.run_autoscale_variant: 7 ms
    # per batch + max_batch_size=64 makes the peak/burst client pool a
    # genuine 2-worker capacity deficit, so the autoscaler's queue-depth
    # signal actually fires (grow at peak, retire in the trough)
    slow_ms = float(os.environ.get("PRODUCTION_DAY_SLOW_MS", "7"))

    planned_digest = _build_chaos(seed, error_rate).schedule_digest()
    work_dir = tempfile.mkdtemp(prefix="production_day_")

    # ------------------------------------------- model registry versions
    rdir = os.path.join(work_dir, "model_registry")
    registry = ModelRegistry(rdir, keep_last=6)
    w1 = ref_weights()
    w2 = (w1 * 1.5).astype(np.float32)
    golden = rowcodec.encode("features", np.ones((1, FEATURES),
                                                 np.float32))
    v1 = registry.publish(
        {"weights.bin": w1.tobytes()}, golden_body=golden,
        golden_reply_sha256=golden_reply_digest(make_handler(w1), golden),
        extra={"slow_ms": slow_ms}, set_current=True)
    v2 = registry.publish(
        {"weights.bin": w2.tobytes()}, golden_body=golden,
        golden_reply_sha256=golden_reply_digest(make_handler(w2), golden),
        extra={"slow_ms": slow_ms})

    reg = MetricsRegistry()
    prev = set_registry(reg)
    chaos = _build_chaos(seed, error_rate, registry=reg)
    injector = chaos.injectors["gateway_forward"]
    coord = ServingCoordinator(
        heartbeat_timeout_s=2.0, registry=reg,
        forward_transport=injector.wrap(KeepAliveTransport()),
        coalesce_max=8, canary_beats=2,
        rollout_timeout_s=max(15.0, total_s / 6.0)).start()
    chaos.event_log = coord.events   # scripted faults land on the ring
    ctx = mp.get_context("spawn")
    worker_kw = dict(registry_dir=rdir, max_batch_size=64)
    base_procs, base_stops, _ = spawn_workers(ctx, coord.url, workers,
                                              **worker_kw)
    collector, recorder = arm_observability(
        coord, reg, injector, chaos_bundles=True, cooldown_s=5.0,
        out_dir=os.path.join(work_dir, "incidents"))

    # ------------------------------------------------ heartbeat autoscaler
    next_partition = [workers]
    # cost accounting counts PROVISIONED worker processes (what a fleet
    # pays for), not the instantaneous routing table — chaos evictions
    # blip routes for a heartbeat interval without freeing any machine
    provisioned = [workers]

    def spawn():
        procs, stops, retires = spawn_workers(
            ctx, coord.url, 1, first_partition=next_partition[0],
            **worker_kw)
        next_partition[0] += 1
        provisioned[0] += 1
        return (procs[0], stops[0], retires[0])

    def retire(handle):
        proc, _stop, retire_ev = handle
        retire_ev.set()      # deregister -> drain -> stop -> exit
        proc.join(30.0)
        if proc.is_alive():
            proc.terminate()
        provisioned[0] -= 1

    scaler = Autoscaler.for_service(
        coord, SERVICE, spawn, retire,
        min_workers=workers, max_workers=workers + 2,
        high_queue_depth=float(os.environ.get("PRODUCTION_DAY_HIGH", "6")),
        low_queue_depth=float(os.environ.get("PRODUCTION_DAY_LOW", "1")),
        up_after=2, down_after=6,
        cooldown_s=max(3.0, total_s / 30.0), interval_s=0.25,
        registry=reg).start()

    # ------------------------------------------------- phased client pool
    bodies = make_bodies([w1, w2])   # both versions' payloads accepted
    parsed = urllib.parse.urlsplit(coord.url)
    all_clients = []
    groups = []                      # [(stop_event, clients)] — a stack

    def set_level(n):
        n = int(n)
        cur = sum(len(cs) for _, cs in groups)
        while cur > n and groups:
            ev, cs = groups.pop()
            ev.set()
            for c in cs:
                c.join(10.0)
            cur -= len(cs)
        if cur < n:
            ev = threading.Event()
            cs = [LoadClient(parsed.hostname, parsed.port,
                             f"/gateway/{SERVICE}", bodies, None,
                             DEADLINE_MS / 1000.0, ev)
                  for _ in range(n - cur)]
            for c in cs:
                c.start()
            groups.append((ev, cs))
            all_clients.extend(cs)

    # ---------------------------------------------- the scripted timeline
    phases = diurnal_phases(total_s)
    ph = {p.name: p for p in phases}
    phase_samples = {p.name: [] for p in phases}
    fleet_series = []
    timeline = ScenarioTimeline()
    rollout_info = {}
    learner_summary = {}

    def _start_rollout_with_retry(version, previous=None):
        # under chaos the routing table can be transiently empty (an
        # injected fault just evicted everyone; heartbeats re-register
        # within a beat) — retry like an operator would
        for _ in range(100):
            try:
                return coord.start_rollout(SERVICE, version,
                                           previous=previous)
            except ValueError:
                time.sleep(0.1)
        return None

    def canary_rollout():
        ro = _start_rollout_with_retry(v2, previous=v1)
        rollout_info["canary_rollout_started"] = bool(ro)

    def worker_kill():
        chaos.record_scripted("worker_kill", phase="peak")
        base_procs[-1].terminate()   # a base worker dies mid-rollout
        provisioned[0] -= 1

    def corrupt_artifact():
        chaos.record_scripted("corrupt_artifact", phase="burst")
        v3 = registry.publish({"weights.bin": w2.tobytes()},
                              golden_body=golden,
                              extra={"slow_ms": slow_ms})
        TrainingFaultInjector.corrupt_version_payload(registry, v3)
        rollout_info["corrupt_target"] = v3
        ro = _start_rollout_with_retry(v3)
        rollout_info["corrupt_rollout_started"] = bool(ro)

    def learner_preempt():
        learner_summary.update(_learner_leg(chaos, work_dir))

    timeline.at(ph["peak"].start_s + 0.2 * ph["peak"].duration_s,
                "canary_rollout", canary_rollout)
    timeline.at(ph["peak"].start_s + 0.2 * ph["peak"].duration_s + 2.0,
                "worker_kill", worker_kill)
    timeline.at(ph["burst"].start_s + 1.0, "corrupt_artifact",
                corrupt_artifact)
    timeline.at(ph["trough"].start_s + 2.0, "learner_preempt",
                learner_preempt)

    def on_phase(phase):
        level = max(1, round(phase.traffic * n_clients))
        print(f"== phase {phase.name}: traffic {phase.traffic:.2f}x "
              f"({level} clients) for {phase.duration_s:.0f}s",
              flush=True)
        set_level(level)

    def on_tick(phase):
        try:
            phase_samples[phase.name].append(
                (coord.health() or {}).get("slo"))
        except Exception:  # noqa: BLE001
            pass
        fleet_series.append({"t": round(engine.now(), 1),
                             "workers": provisioned[0],
                             "routed": len(coord.routes(SERVICE))})

    t0 = time.perf_counter()
    engine = ScenarioEngine(phases, timeline, clock=time.monotonic,
                            sleep=time.sleep, tick_s=1.0, registry=reg,
                            on_phase=on_phase, on_tick=on_tick)
    engine.run()
    for ev, cs in groups:
        ev.set()
    for c in all_clients:
        c.join(15.0)
    wall = time.perf_counter() - t0

    # ---------------------------------------------------- the judgment
    tallies = client_tallies(all_clients, wall)
    phase_slo = {name: judge_slo(s) for name, s in phase_samples.items()}
    baseline = max((s["workers"] for s in fleet_series), default=workers)
    cost = cost_proxy(fleet_series, total_s, baseline)
    fleet_snap = collect_fleet(coord.url)
    health_problems = assert_healthy(fleet_snap,
                                     stuck_after_s=total_s / 2.0)

    summary = {
        "mode": "full", "seed": seed, "total_s": total_s,
        "clients_at_peak": n_clients, "base_workers": workers,
        "error_rate": error_rate,
        "phases": engine.phase_log,
        "timeline": engine.timeline.fired,
        "rollouts": rollout_info,
        "learner": learner_summary,
        "autoscaler_actions": len(scaler.actions),
        "fleet_series": fleet_series,
        "cost_proxy": cost,
        "phase_slo": phase_slo,
        "chaos": {
            "master_seed": seed,
            "schedule_digest": chaos.schedule_digest(),
            "planned_digest": planned_digest,
            "injected": {name: dict(inj.counts)
                         for name, inj in chaos.injectors.items()},
            "scripted": dict(chaos.scripted),
        },
        "fleet_health_problems": health_problems,
        **tallies,
    }
    # final bundle pass + fleet snapshot + embedded incidents (stops the
    # recorder/collector; workers must still be up)
    harvest_observability(summary, coord, collector, recorder)
    summary["reconciliation"] = reconcile_chaos(chaos, reg)
    incidents = _incident_reasons(recorder)
    scorecard = build_scorecard(
        registry=reg, phases=phases, phase_slo=phase_slo,
        tallies=tallies,
        incident_reasons=[i["reason"] for i in incidents],
        chaos=chaos, cost=cost, schedule_digest=planned_digest)
    scorecard.check("fleet_healthy_at_day_end", not health_problems,
                    detail="; ".join(health_problems) or
                           "fleet_status --assert-healthy clean")
    summary["scorecard"] = scorecard.as_dict()

    scaler.stop(retire_spawned=True)
    stop_workers(base_procs, base_stops)
    coord.stop()
    set_registry(prev)

    if out:
        os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
        with open(out, "w") as f:
            json.dump(summary, f, indent=1, default=str)
        print(f"wrote {out}")
    return summary


# ------------------------------------------------------------------- CLI

def _print_scorecard(summary):
    sc = summary["scorecard"]
    verdict = "PASS" if sc["passed"] else "FAIL"
    print(f"\n== production-day scorecard: {verdict} "
          f"({sc['checks_total']} checks, {sc['checks_failed']} gating "
          f"failures)")
    for c in sc["checks"]:
        mark = "ok  " if c["ok"] else ("ex  " if c["exempt"] else "FAIL")
        print(f"  [{mark}] {c['check']}: {c['detail']}")


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", choices=("full", "mini"), default="full")
    ap.add_argument("--seed", type=int, default=None)
    ap.add_argument("--duration-s", type=float, default=None)
    ap.add_argument("--clients", type=int, default=None)
    ap.add_argument("--out", default=None)
    args = ap.parse_args()
    if args.out is None:
        args.out = ("docs/PRODUCTION_DAY.json" if args.mode == "full"
                    else "docs/PRODUCTION_DAY_mini.json")
    if args.mode == "mini":
        summary = run_mini(seed=args.seed if args.seed is not None else 20,
                           total_s=args.duration_s or 120.0,
                           out=args.out)
    else:
        summary = run_full(seed=args.seed, total_s=args.duration_s,
                           n_clients=args.clients, out=args.out)
    _print_scorecard(summary)
    return 0 if summary["scorecard"]["passed"] else 1


if __name__ == "__main__":
    sys.exit(main())
