"""VW hot-path batch-size ladder: the measurement that decides fusedTables=auto
and fills the VW row in docs/PERF.md (ISSUE 16).

Grid: minibatch B in {256..16384} x {dense row-invariant, sparse hashed}
features x {fused packed table, unpacked} x {ahead-dispatched ring,
per-step sync baseline}. Each rung streams the same examples through the
online ring (models/vw/online.py) and reports retired examples per wall
second; the sync baseline blocks after every step — the per-example
overhead the ring exists to remove. A digest gate asserts ring and sync
runs of the same configuration land bit-identical weight tables (they
execute the same step sequence; the ring only changes WHEN the host
waits).

Runs on CPU today (the numbers feed the CPU column of docs/PERF.md and
the fusedTables=auto backend rule); the same script is armed on chip via
scripts/tpu_recovery_watch.sh with --out docs/VW_THROUGHPUT_chip.json.
`run_ladder` is importable with an injectable clock so the tier-1 suite
runs a seeded mini-ladder without timing flakiness
(tests/test_vw_fused.py).
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: the pre-overhaul chip measurement this ladder is graded against
#: (docs/PERF.md "VW training throughput", 2026-08-01 TPU v5e run)
BASELINE_EXAMPLES_PER_S = 0.18e6


def make_dataset(rows: int, features: int, num_bits: int, layout: str,
                 seed: int = 0):
    """A VW-shaped stream: [rows, features] values with either
    row-invariant indices (the dense-column fast path: every row hits the
    same slots, shared_indices applies) or per-row hashed indices (the
    sparse path: collisions everywhere, general scatter)."""
    rng = np.random.default_rng(seed)
    nf = 1 << num_bits
    val = rng.normal(size=(rows, features)).astype(np.float32)
    y = np.sign(val @ rng.normal(size=features).astype(np.float32)
                ).astype(np.float32)
    if layout == "dense":
        idx = np.broadcast_to(
            np.arange(features, dtype=np.int32), (rows, features)).copy()
    elif layout == "sparse":
        idx = rng.integers(0, nf, size=(rows, features)).astype(np.int32)
    else:
        raise ValueError(f"layout must be dense|sparse, got {layout!r}")
    w = np.ones(rows, np.float32)
    return idx, val, y, w


def _build_config(num_bits: int, batch: int, fused: bool, layout: str):
    from mmlspark_tpu.models.vw.sgd import VWConfig

    return VWConfig(num_features=1 << num_bits, loss="logistic",
                    minibatch=batch, fused=fused,
                    shared_indices=(layout == "dense"))


def _run_ring(cfg, idx, val, y, w, depth, clock):
    """One warm ring pass over the whole stream; returns (wall_s, state)."""
    import jax

    from mmlspark_tpu.models.vw.online import VWOnlineRing
    from mmlspark_tpu.models.vw.sgd import init_state

    nb = len(y) // cfg.minibatch
    # compile warm-up on a throwaway ring (shared cached_jit executable),
    # so the measured ring starts from a fresh state with a hot cache
    warm = VWOnlineRing(cfg, init_state(cfg.num_features), depth=depth,
                        metrics_every=max(nb, 1), clock=clock)
    b = cfg.minibatch
    warm.submit(idx[:b], val[:b], y[:b], w[:b])
    warm.flush()
    ring = VWOnlineRing(cfg, init_state(cfg.num_features), depth=depth,
                        metrics_every=max(nb, 1), clock=clock)
    t0 = clock()
    ring.submit(idx, val, y, w)
    ring.flush()
    wall = max(clock() - t0, 1e-9)
    state = ring.state()
    jax.block_until_ready(state.w)
    return wall, state


def _run_sync(cfg, idx, val, y, w, clock):
    """The per-step host-sync baseline: identical step sequence, but the
    host blocks after every dispatch (the pre-ring online loop)."""
    import jax
    import jax.numpy as jnp

    from mmlspark_tpu.compile import cache as compilecache
    from mmlspark_tpu.models.vw.sgd import (init_state, make_step_fn,
                                            pack_state, unpack_state)

    b = cfg.minibatch
    nb = len(y) // b
    step = compilecache.cached_jit(make_step_fn(cfg),
                                   key=("vw_online_step", cfg, ()),
                                   name="vw_online_step")
    template = init_state(cfg.num_features)
    carry = pack_state(cfg, template) if cfg.fused else template
    carry, loss = step(carry, (jnp.asarray(idx[:b]), jnp.asarray(val[:b]),
                               jnp.asarray(y[:b]), jnp.asarray(w[:b])))
    jax.block_until_ready(loss)  # compile warm-up
    carry = pack_state(cfg, template) if cfg.fused else template
    t0 = clock()
    for i in range(nb):
        sl = slice(i * b, (i + 1) * b)
        batch = (jnp.asarray(idx[sl]), jnp.asarray(val[sl]),
                 jnp.asarray(y[sl]), jnp.asarray(w[sl]))
        carry, loss = step(carry, batch)
        jax.block_until_ready(loss)   # the per-step sync the ring removes
    wall = max(clock() - t0, 1e-9)
    state = unpack_state(cfg, carry, template) if cfg.fused else carry
    return wall, state


def run_ladder(batch_sizes=(256, 1024, 4096, 16384), rows=1 << 19,
               features=30, num_bits=18, layouts=("dense", "sparse"),
               fused_modes=(False, True), ring_depth=2, seed=0,
               clock=time.perf_counter, include_sync=True,
               max_steps_per_rung=128):
    """Measure every rung; returns the summary dict (JSON-serializable).

    Each rung streams min(rows, batch * max_steps_per_rung) examples —
    enough steps to amortize dispatch, bounded so the sparse/fused slow
    rungs do not dominate the wall clock. The digest gate compares ring
    vs sync final weights per configuration at the largest batch."""
    import jax

    rungs = []
    digest_parity = {}
    for layout in layouts:
        idx, val, y, w = make_dataset(rows, features, num_bits, layout, seed)
        for fused in fused_modes:
            for b in batch_sizes:
                n_use = min(rows, b * max_steps_per_rung)
                n_use -= n_use % b
                if n_use < b:
                    continue
                cfg = _build_config(num_bits, b, fused, layout)
                cut = (idx[:n_use], val[:n_use], y[:n_use], w[:n_use])
                wall, state = _run_ring(cfg, *cut, depth=ring_depth,
                                        clock=clock)
                rungs.append({
                    "layout": layout, "fused": fused, "batch": b,
                    "mode": "ring", "rows": n_use, "steps": n_use // b,
                    "wall_s": wall, "examples_per_s": n_use / wall,
                })
                if include_sync:
                    wall_s, state_s = _run_sync(cfg, *cut, clock=clock)
                    rungs.append({
                        "layout": layout, "fused": fused, "batch": b,
                        "mode": "sync", "rows": n_use, "steps": n_use // b,
                        "wall_s": wall_s, "examples_per_s": n_use / wall_s,
                    })
                    if b == max(batch_sizes):
                        # digest gate: same steps => identical tables
                        digest_parity[f"{layout}_fused={fused}"] = bool(
                            np.allclose(np.asarray(state.w),
                                        np.asarray(state_s.w),
                                        rtol=1e-6, atol=1e-7))
    ring_rungs = [r for r in rungs if r["mode"] == "ring"]
    best = max(ring_rungs, key=lambda r: r["examples_per_s"])
    backend = jax.default_backend()
    # what the ladder says about the auto rule on THIS backend: does the
    # fused layout win its unpacked twin, rung by rung?
    fused_wins = []
    for r in ring_rungs:
        if not r["fused"]:
            continue
        twin = [u for u in ring_rungs
                if not u["fused"] and u["layout"] == r["layout"]
                and u["batch"] == r["batch"]]
        if twin:
            fused_wins.append(
                r["examples_per_s"] > twin[0]["examples_per_s"])
    from mmlspark_tpu.models.vw.sgd import resolve_auto_fused
    return {
        "platform": backend,
        "device": str(jax.devices()[0]),
        "rows": rows, "features": features, "num_bits": num_bits,
        "ring_depth": ring_depth,
        "rungs": rungs,
        "best": dict(best),
        "baseline_examples_per_s": BASELINE_EXAMPLES_PER_S,
        "speedup_vs_baseline":
            best["examples_per_s"] / BASELINE_EXAMPLES_PER_S,
        "auto_decision": {
            "backend": backend,
            "fused_rungs_won": int(sum(fused_wins)),
            "fused_rungs_total": len(fused_wins),
            "auto_resolves_fused": resolve_auto_fused(True, True, backend),
            "rule": "pack on non-cpu backends when adaptive or normalized "
                    "adds a second table; never on cpu (sgd."
                    "resolve_auto_fused)",
        },
        "digest_parity": digest_parity,
        "generated_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default=None,
                    help="write the summary JSON here (e.g. "
                         "docs/VW_THROUGHPUT.json)")
    ap.add_argument("--rows", type=int, default=1 << 19)
    ap.add_argument("--features", type=int, default=30)
    ap.add_argument("--bits", type=int, default=18)
    ap.add_argument("--batches", default="256,1024,4096,16384")
    ap.add_argument("--layouts", default="dense,sparse")
    ap.add_argument("--depth", type=int, default=2)
    ap.add_argument("--no-sync", action="store_true",
                    help="skip the per-step sync baselines")
    args = ap.parse_args()

    batches = tuple(int(b) for b in args.batches.split(","))
    layouts = tuple(args.layouts.split(","))
    summary = run_ladder(batch_sizes=batches, rows=args.rows,
                         features=args.features, num_bits=args.bits,
                         layouts=layouts, ring_depth=args.depth,
                         include_sync=not args.no_sync)
    for r in summary["rungs"]:
        print(f"{r['layout']:>6} fused={str(r['fused']):>5} "
              f"b={r['batch']:>5} {r['mode']:>4}: "
              f"{r['examples_per_s'] / 1e6:6.2f}M ex/s "
              f"({r['steps']} steps)", flush=True)
    best = summary["best"]
    print(f"best: {best['layout']} fused={best['fused']} b={best['batch']} "
          f"{best['examples_per_s'] / 1e6:.2f}M ex/s = "
          f"{summary['speedup_vs_baseline']:.1f}x the "
          f"{BASELINE_EXAMPLES_PER_S / 1e6:.2f}M ex/s chip baseline "
          f"[{summary['platform']}]")
    print(f"digest parity: {summary['digest_parity']}")
    bad = [k for k, v in summary["digest_parity"].items() if not v]
    if args.out:
        with open(args.out, "w") as f:
            json.dump(summary, f, indent=1)
        print(f"wrote {args.out}")
    if bad:
        print(f"DIGEST MISMATCH in {bad}")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
