"""mmlspark_tpu — a TPU-native ML framework with the capabilities of MMLSpark.

The reference (anusharamesh/mmlspark) composes SparkML estimators/transformers over
DataFrames with JNI-wrapped C++ engines per executor; this framework keeps the
pipeline-composition surface but runs every heavy path as JAX/XLA/Pallas programs over a
`jax.sharding.Mesh` of TPU chips. See SURVEY.md for the layer-by-layer mapping.
"""

__version__ = "0.3.0"

from .core.dataframe import DataFrame
from .core.params import Param, Params
from .core.pipeline import (Estimator, Evaluator, Model, Pipeline,
                            PipelineModel, PipelineStage, Transformer)
