"""Hyperparameter distributions + search spaces.

Reference: automl/HyperparamBuilder.scala:11-100 (`DiscreteHyperParam`,
`RangeHyperParam`, `HyperparamBuilder`), automl/ParamSpace.scala (GridSpace /
RandomSpace), automl/DefaultHyperparams.scala:13 (canonical per-learner ranges).
"""

from __future__ import annotations

import itertools
from typing import Any, Dict, Iterator, List, Sequence, Tuple

import numpy as np


class HyperParam:
    def values_for_grid(self, n: int) -> List[Any]:
        raise NotImplementedError

    def sample(self, rng: np.random.Generator) -> Any:
        raise NotImplementedError


class DiscreteHyperParam(HyperParam):
    def __init__(self, values: Sequence[Any]):
        self.values = list(values)

    def values_for_grid(self, n: int) -> List[Any]:
        return list(self.values)

    def sample(self, rng: np.random.Generator) -> Any:
        return self.values[int(rng.integers(len(self.values)))]


class RangeHyperParam(HyperParam):
    def __init__(self, low, high, is_log: bool = False):
        self.low, self.high, self.is_log = low, high, is_log
        self.is_int = isinstance(low, (int, np.integer)) and isinstance(
            high, (int, np.integer))

    def values_for_grid(self, n: int) -> List[Any]:
        if self.is_log:
            vals = np.logspace(np.log10(self.low), np.log10(self.high), n)
        else:
            vals = np.linspace(self.low, self.high, n)
        if self.is_int:
            vals = sorted(set(int(round(v)) for v in vals))
        return [v.item() if hasattr(v, "item") else v for v in vals]

    def sample(self, rng: np.random.Generator) -> Any:
        if self.is_log:
            v = 10 ** rng.uniform(np.log10(self.low), np.log10(self.high))
        else:
            v = rng.uniform(self.low, self.high)
        return int(round(v)) if self.is_int else float(v)


class HyperparamBuilder:
    """Accumulate (estimator, paramName) -> HyperParam entries
    (HyperparamBuilder.scala:97)."""

    def __init__(self):
        self._entries: List[Tuple[Any, str, HyperParam]] = []

    def add_hyperparam(self, est, param_name: str,
                       dist: HyperParam) -> "HyperparamBuilder":
        self._entries.append((est, param_name, dist))
        return self

    addHyperparam = add_hyperparam

    def build(self) -> List[Tuple[Any, str, HyperParam]]:
        return list(self._entries)


class ParamSpace:
    def param_maps(self) -> Iterator[List[Tuple[Any, str, Any]]]:
        raise NotImplementedError


class GridSpace(ParamSpace):
    """Cartesian product over per-param grids."""

    def __init__(self, entries: List[Tuple[Any, str, HyperParam]],
                 grid_size: int = 5):
        self.entries = entries
        self.grid_size = grid_size

    def param_maps(self):
        grids = [d.values_for_grid(self.grid_size) for _, _, d in self.entries]
        for combo in itertools.product(*grids):
            yield [(est, name, v) for (est, name, _), v in
                   zip(self.entries, combo)]


class RandomSpace(ParamSpace):
    """Random sampling (the reference's default search mode)."""

    def __init__(self, entries: List[Tuple[Any, str, HyperParam]],
                 seed: int = 0):
        self.entries = entries
        self.seed = seed

    def param_maps(self):
        rng = np.random.default_rng(self.seed)
        while True:
            yield [(est, name, d.sample(rng)) for est, name, d in self.entries]


class DefaultHyperparams:
    """Canonical search ranges per learner (DefaultHyperparams.scala:13)."""

    @staticmethod
    def for_learner(est) -> List[Tuple[Any, str, HyperParam]]:
        name = type(est).__name__
        if "LogisticRegression" in name:
            return [(est, "regParam", RangeHyperParam(1e-4, 1.0, is_log=True)),
                    (est, "maxIter", DiscreteHyperParam([100, 200]))]
        if "LightGBM" in name:
            return [(est, "numLeaves", DiscreteHyperParam([15, 31, 63])),
                    (est, "learningRate",
                     RangeHyperParam(0.02, 0.3, is_log=True)),
                    (est, "numIterations", DiscreteHyperParam([50, 100]))]
        if "VowpalWabbit" in name:
            return [(est, "learningRate",
                     RangeHyperParam(0.05, 2.0, is_log=True)),
                    (est, "numPasses", DiscreteHyperParam([1, 5, 10]))]
        if "LinearRegression" in name:
            return [(est, "regParam", RangeHyperParam(1e-4, 1.0, is_log=True))]
        return []
