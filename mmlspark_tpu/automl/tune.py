"""TuneHyperparameters / FindBestModel — model search + selection.

Reference: automl/TuneHyperparameters.scala:37-235 (random/grid search, k-fold
CV, threaded parallelism via HasParallelism futures, best-model refit),
automl/FindBestModel.scala:55-199 (evaluate N fitted models on one dataset),
automl/EvaluationUtils.scala:15 (metric dispatch per estimator type).

Thread-parallel model search survives in the TPU build: independent fits are
dispatched on a thread pool (each fit is its own compiled XLA program; the
runtime serializes device access, threads overlap host-side work) — the
analogue of HasParallelismInjected.getExecutionContextProxy.
"""

from __future__ import annotations

import itertools
from concurrent.futures import ThreadPoolExecutor
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from ..core import params as _p
from ..core.dataframe import DataFrame
from ..core.pipeline import Estimator, Model, Transformer
from ..train.compute_statistics import _detect_scored_cols
from ..train.metrics import (MetricConstants, auc_score,
                             classification_metrics, index_label_pred,
                             multiclass_metrics, regression_metrics)
from .hyperparams import GridSpace, ParamSpace, RandomSpace


class EvaluationUtils:
    """Metric dispatch (EvaluationUtils.scala:15). Larger-is-better unless the
    metric is an error metric."""

    LOWER_IS_BETTER = {MetricConstants.MSE, MetricConstants.RMSE,
                       MetricConstants.MAE, "l2"}

    @staticmethod
    def default_metric(est) -> str:
        name = type(est).__name__
        # classifier signals take precedence: "LogisticRegression" contains
        # "Regress" but is a classifier (it declares a probability column)
        if ("Classif" in name or "Logistic" in name
                or (hasattr(est, "has_param")
                    and est.has_param("probabilityCol"))):
            return MetricConstants.ACCURACY
        if "Regress" in name:
            return MetricConstants.RMSE
        return MetricConstants.ACCURACY

    @staticmethod
    def compute(metric: str, df: DataFrame, label_col: str) -> float:
        pred_col, prob_col = _detect_scored_cols(df)
        if metric in (MetricConstants.MSE, MetricConstants.RMSE,
                      MetricConstants.R2, MetricConstants.MAE, "l2"):
            labels = np.asarray(df[label_col], np.float64)
            preds = np.asarray(df[pred_col if pred_col else "scores"],
                               np.float64)
            r = regression_metrics(labels, preds)
            return r["mse" if metric == "l2" else metric]
        labels, preds = index_label_pred(df[label_col], df[pred_col])
        num_class = int(max(labels.max(), preds.max())) + 1
        if metric == MetricConstants.AUC:
            probs = np.asarray(df[prob_col], np.float64)
            scores = probs[:, 1] if probs.ndim == 2 else probs
            return auc_score(labels, scores)
        if num_class <= 2:
            return classification_metrics(labels, preds)[metric]
        return multiclass_metrics(labels, preds, num_class)[metric]


def _best_index(metrics: Sequence[float], larger_better: bool) -> int:
    """Index of the best FINITE metric (NaN candidates — e.g. AUC on a
    single-class fold — are never selected)."""
    vals = np.asarray(metrics, np.float64)
    finite = np.isfinite(vals)
    if not finite.any():
        raise ValueError(f"all candidate metrics are non-finite: {metrics}")
    vals = np.where(finite, vals, -np.inf if larger_better else np.inf)
    return int(vals.argmax() if larger_better else vals.argmin())


def _kfold_indices(n: int, k: int, seed: int) -> List[Tuple[np.ndarray,
                                                            np.ndarray]]:
    rng = np.random.default_rng(seed)
    perm = rng.permutation(n)
    folds = np.array_split(perm, k)
    out = []
    for i in range(k):
        test = np.sort(folds[i])
        train = np.sort(np.concatenate([folds[j] for j in range(k) if j != i]))
        out.append((train, test))
    return out


class TuneHyperparameters(Estimator, _p.HasLabelCol, _p.HasSeed):
    """Search (estimator x paramMap) candidates by k-fold CV; refit the best.

    Reference: automl/TuneHyperparameters.scala:37-235."""

    models = _p.Param("models", "candidate estimators", None, complex=True)
    paramSpace = _p.Param("paramSpace", "ParamSpace of hyperparam maps", None,
                          complex=True)
    evaluationMetric = _p.Param("evaluationMetric",
                                "metric name (EvaluationUtils)", None)
    numFolds = _p.Param("numFolds", "cross-validation folds", 3, int)
    numRuns = _p.Param("numRuns", "candidates drawn from the space", 10, int)
    parallelism = _p.Param("parallelism", "concurrent fits", 4, int)

    def __init__(self, models: Optional[Sequence[Estimator]] = None, **kw):
        super().__init__(**kw)
        if models is not None:
            self.set("models", list(models))

    def _fit(self, df: DataFrame) -> "TuneHyperparametersModel":
        models: List[Estimator] = self.get("models")
        space: Optional[ParamSpace] = self.get("paramSpace")
        label_col = self.get("labelCol")
        metric = (self.get("evaluationMetric")
                  or EvaluationUtils.default_metric(models[0]))
        larger_better = metric not in EvaluationUtils.LOWER_IS_BETTER
        k = self.get("numFolds")
        folds = _kfold_indices(len(df), k, self.get("seed"))

        # candidate list: estimator x paramMap
        candidates: List[Tuple[Estimator, dict]] = []
        if space is None:
            candidates = [(m, {}) for m in models]
        else:
            # a grid is finite: enumerate it fully; numRuns bounds only
            # infinite (random) spaces, as in the reference where numRuns is
            # the random-search draw count (TuneHyperparameters.scala)
            maps = (space.param_maps() if isinstance(space, GridSpace)
                    else itertools.islice(space.param_maps(),
                                          self.get("numRuns")))
            for pm in maps:
                by_est: dict = {}
                for est, name, value in pm:
                    by_est.setdefault(id(est), (est, {}))[1][name] = value
                for est, overrides in by_est.values():
                    candidates.append((est, overrides))

        def evaluate(cand: Tuple[Estimator, dict]) -> float:
            est, overrides = cand
            vals = []
            for train_idx, test_idx in folds:
                model = est.copy(overrides).fit(df.take(train_idx))
                scored = model.transform(df.take(test_idx))
                vals.append(EvaluationUtils.compute(
                    metric, scored, label_col))
            return float(np.mean(vals))

        single_est = len({id(e) for e, _ in candidates}) == 1
        all_keys = set().union(*[set(ov) for _, ov in candidates]) \
            if candidates else set()
        batchable = (single_est and hasattr(candidates[0][0],
                                            "fit_param_maps")
                     and all_keys <= set(getattr(candidates[0][0],
                                                 "_VMAP_PARAM_FIELDS", ())))
        if batchable:
            # batched path: one fit(df, paramMaps) per fold — continuous-only
            # sweeps train every candidate in ONE vmapped XLA program
            # (fit_param_maps falls back to sequential fits otherwise)
            est0 = candidates[0][0]
            maps_all = [dict(ov) for _, ov in candidates]
            per_cand = np.zeros((len(candidates), len(folds)))
            for fi, (train_idx, test_idx) in enumerate(folds):
                fold_models = est0.fit(df.take(train_idx), maps_all)
                test = df.take(test_idx)
                for ci, model in enumerate(fold_models):
                    per_cand[ci, fi] = EvaluationUtils.compute(
                        metric, model.transform(test), label_col)
            metrics = [float(v) for v in per_cand.mean(axis=1)]
        else:
            with ThreadPoolExecutor(max_workers=self.get("parallelism")) as ex:
                metrics = list(ex.map(evaluate, candidates))

        best_i = _best_index(metrics, larger_better)
        best_est, best_overrides = candidates[best_i]
        best_model = best_est.copy(best_overrides).fit(df)
        out = TuneHyperparametersModel(best_model=best_model,
                                       best_metric=float(metrics[best_i]))
        out._all_metrics = [float(m) for m in metrics]
        out._best_params = dict(best_overrides)
        out.set("labelCol", label_col)
        return out


class TuneHyperparametersModel(Model, _p.HasLabelCol):
    bestModel = _p.Param("bestModel", "refit best model", None, complex=True)
    bestMetric = _p.Param("bestMetric", "CV metric of the best candidate",
                          0.0, float)

    def __init__(self, best_model: Optional[Transformer] = None,
                 best_metric: float = 0.0, **kw):
        super().__init__(**kw)
        self._all_metrics: List[float] = []
        self._best_params: dict = {}
        if best_model is not None:
            self._set(bestModel=best_model, bestMetric=best_metric)

    def get_best_model_info(self) -> str:
        return f"params={self._best_params} metric={self.get('bestMetric')}"

    getBestModelInfo = get_best_model_info

    def transform(self, df: DataFrame) -> DataFrame:
        return self.get("bestModel").transform(df)


class FindBestModel(Estimator, _p.HasLabelCol):
    """Evaluate already-fitted models on one dataset; keep the best.

    Reference: automl/FindBestModel.scala:55-199."""

    models = _p.Param("models", "fitted candidate models", None, complex=True)
    evaluationMetric = _p.Param("evaluationMetric", "metric name", None)

    def __init__(self, models: Optional[Sequence[Transformer]] = None, **kw):
        super().__init__(**kw)
        if models is not None:
            self.set("models", list(models))

    def _fit(self, df: DataFrame) -> "FindBestModelModel":
        models: List[Transformer] = self.get("models")
        metric = (self.get("evaluationMetric")
                  or EvaluationUtils.default_metric(models[0]))
        larger_better = metric not in EvaluationUtils.LOWER_IS_BETTER
        label_col = self.get("labelCol")
        vals = []
        for m in models:
            scored = m.transform(df)
            vals.append(EvaluationUtils.compute(metric, scored, label_col))
        best_i = _best_index(vals, larger_better)
        out = FindBestModelModel(best_model=models[best_i],
                                 best_metric=float(vals[best_i]))
        out._all_metrics = [float(v) for v in vals]
        out.set("labelCol", label_col)
        return out


class FindBestModelModel(Model, _p.HasLabelCol):
    bestModel = _p.Param("bestModel", "winning model", None, complex=True)
    bestMetric = _p.Param("bestMetric", "its metric", 0.0, float)

    def __init__(self, best_model: Optional[Transformer] = None,
                 best_metric: float = 0.0, **kw):
        super().__init__(**kw)
        self._all_metrics: List[float] = []
        if best_model is not None:
            self._set(bestModel=best_model, bestMetric=best_metric)

    def transform(self, df: DataFrame) -> DataFrame:
        return self.get("bestModel").transform(df)
