"""AutoML layer (reference: automl/, 6 files, 758 LoC)."""

from .hyperparams import (DefaultHyperparams, DiscreteHyperParam, GridSpace,
                          HyperparamBuilder, RandomSpace, RangeHyperParam)
from .tune import (EvaluationUtils, FindBestModel, FindBestModelModel,
                   TuneHyperparameters, TuneHyperparametersModel)

__all__ = [
    "DiscreteHyperParam", "RangeHyperParam", "HyperparamBuilder",
    "GridSpace", "RandomSpace", "DefaultHyperparams",
    "TuneHyperparameters", "TuneHyperparametersModel",
    "FindBestModel", "FindBestModelModel", "EvaluationUtils",
]
