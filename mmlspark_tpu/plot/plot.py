"""Plotting helpers: confusion matrix + ROC curve.

Reference: src/main/python/mmlspark/plot/plot.py (confusionMatrix :17, roc
:45) — small matplotlib conveniences over scored DataFrames. Rebuilt over the
columnar DataFrame: metrics are computed in numpy here (no Spark collect
round-trip) and rendering degrades gracefully to returning the computed
arrays when matplotlib is absent.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np


def _counts(y, y_hat, labels: Sequence) -> np.ndarray:
    idx = {v: i for i, v in enumerate(labels)}
    cm = np.zeros((len(labels), len(labels)), np.int64)
    for t, p in zip(np.asarray(y).tolist(), np.asarray(y_hat).tolist()):
        if t in idx and p in idx:
            cm[idx[t], idx[p]] += 1
    return cm


def confusion_matrix(df, y_col: str, y_hat_col: str,
                     labels: Optional[Sequence] = None, ax=None):
    """Render (or return) the confusion matrix of scored labels.

    Returns (cm [K,K] int64, ax-or-None). With matplotlib available a heatmap
    with count annotations is drawn; without it, only the matrix is returned.
    """
    y = np.asarray(df[y_col])
    y_hat = np.asarray(df[y_hat_col])
    if labels is None:
        labels = sorted(set(y.tolist()) | set(y_hat.tolist()))
    cm = _counts(y, y_hat, labels)
    try:
        import matplotlib.pyplot as plt
    except ImportError:
        return cm, None
    if ax is None:
        _, ax = plt.subplots()
    ax.imshow(cm, cmap="Blues")
    ax.set_xticks(range(len(labels)), [str(l) for l in labels])
    ax.set_yticks(range(len(labels)), [str(l) for l in labels])
    ax.set_xlabel(y_hat_col)
    ax.set_ylabel(y_col)
    for i in range(len(labels)):
        for j in range(len(labels)):
            ax.text(j, i, str(cm[i, j]), ha="center", va="center",
                    color="white" if cm[i, j] > cm.max() / 2 else "black")
    return cm, ax


# reference-casing alias (plot.py:17)
confusionMatrix = confusion_matrix


def roc_points(y, scores) -> tuple:
    """(fpr, tpr, thresholds) without sklearn: sort by score descending and
    sweep the threshold across unique scores."""
    y = np.asarray(y).astype(bool)
    s = np.asarray(scores, np.float64)
    order = np.argsort(-s)
    y, s = y[order], s[order]
    distinct = np.r_[np.flatnonzero(np.diff(s)), y.size - 1]
    tps = np.cumsum(y)[distinct].astype(np.float64)
    fps = (distinct + 1) - tps
    tpr = np.r_[0.0, tps / max(tps[-1], 1.0)]
    fpr = np.r_[0.0, fps / max(fps[-1], 1.0)]
    return fpr, tpr, np.r_[np.inf, s[distinct]]


def roc(df, y_col: str, y_hat_col: str, ax=None):
    """Render (or return) the ROC curve for a score column. Returns
    ((fpr, tpr), ax-or-None)."""
    fpr, tpr, _ = roc_points(df[y_col], df[y_hat_col])
    try:
        import matplotlib.pyplot as plt
    except ImportError:
        return (fpr, tpr), None
    if ax is None:
        _, ax = plt.subplots()
    ax.plot(fpr, tpr)
    ax.plot([0, 1], [0, 1], linestyle="--")
    ax.set_xlabel("false positive rate")
    ax.set_ylabel("true positive rate")
    return (fpr, tpr), ax
