from .plot import confusionMatrix, confusion_matrix, roc, roc_points

__all__ = ["confusionMatrix", "confusion_matrix", "roc", "roc_points"]
