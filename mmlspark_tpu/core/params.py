"""Param / Params system — single source of truth for every stage's configuration.

Reference analogue: SparkML ``Params`` extended by the MMLSpark param-trait library
(core/contracts/Params.scala:15-216 — `Wrappable`, `Has*Col` traits) and the 19 custom
ComplexParam types (org/apache/spark/ml/param/*). As in the reference, the same Param registry
drives (a) runtime configuration, (b) save/load serialization, and (c) API-surface generation
(mmlspark_tpu.utils.codegen), so there is exactly one place a knob is declared.
"""

from __future__ import annotations

import copy
import numpy as np
from typing import Any, Callable, Dict, List, Optional, Type


class Param:
    """A named, documented, typed parameter declared on a Params class."""

    def __init__(self, name: str, doc: str = "", default: Any = None,
                 converter: Optional[Callable[[Any], Any]] = None,
                 complex: bool = False):
        self.name = name
        self.doc = doc
        self.default = default
        self.converter = converter
        # complex params hold values that can't be JSON-serialized (arrays, models,
        # nested stages) — analogue of ComplexParam (core/serialize/ComplexParam.scala:13)
        self.complex = complex

    def __repr__(self):
        return f"Param({self.name!r})"


class Params:
    """Base for every pipeline stage; holds the param registry and value maps.

    Subclasses declare params as class attributes of type Param. Instances get
    camelCase set/get accessors synthesized automatically (setFoo/getFoo), mirroring
    the codegen'd wrapper surface of the reference (codegen/PySparkWrapper.scala).
    """

    _uid_counter = 0

    def __init__(self, **kwargs):
        cls = type(self)
        Params._uid_counter += 1
        self.uid = f"{cls.__name__}_{Params._uid_counter:08x}"
        self._paramMap: Dict[str, Any] = {}
        self._set(**kwargs)

    # ------------------------------------------------------------ registry
    @classmethod
    def params(cls) -> Dict[str, Param]:
        out: Dict[str, Param] = {}
        for klass in reversed(cls.__mro__):
            for k, v in vars(klass).items():
                if isinstance(v, Param):
                    out[v.name] = v
        return out

    @classmethod
    def has_param(cls, name: str) -> bool:
        return name in cls.params()

    # ------------------------------------------------------------ get / set
    def _set(self, **kwargs) -> "Params":
        registry = self.params()
        for name, value in kwargs.items():
            if value is None and name not in registry:
                continue
            if name not in registry:
                raise ValueError(
                    f"{type(self).__name__} has no param {name!r}; "
                    f"known: {sorted(registry)}")
            p = registry[name]
            if p.converter is not None and value is not None:
                value = p.converter(value)
            self._paramMap[name] = value
        return self

    def set(self, name: str, value: Any) -> "Params":
        return self._set(**{name: value})

    def get(self, name: str) -> Any:
        registry = self.params()
        if name not in registry:
            raise ValueError(f"{type(self).__name__} has no param {name!r}")
        if name in self._paramMap:
            return self._paramMap[name]
        return registry[name].default

    def get_or_default(self, name: str) -> Any:
        return self.get(name)

    def is_set(self, name: str) -> bool:
        return name in self._paramMap

    def explain_params(self) -> str:
        lines = []
        for name, p in sorted(self.params().items()):
            cur = self._paramMap.get(name, p.default)
            lines.append(f"{name}: {p.doc} (default: {p.default!r}, current: {cur!r})")
        return "\n".join(lines)

    def copy(self, extra: Optional[Dict[str, Any]] = None) -> "Params":
        out = copy.copy(self)
        out._paramMap = dict(self._paramMap)
        Params._uid_counter += 1
        out.uid = f"{type(self).__name__}_{Params._uid_counter:08x}"
        if extra:
            out._set(**extra)
        return out

    # ------------------------------------------------- camelCase accessors
    def __getattr__(self, attr: str):
        # synthesized setX/getX accessors (wrapper-surface parity with reference codegen)
        if attr.startswith("set") and len(attr) > 3:
            name = attr[3].lower() + attr[4:]
            if self.has_param(name):
                def setter(value, _name=name):
                    self._set(**{_name: value})
                    return self
                return setter
        if attr.startswith("get") and len(attr) > 3:
            name = attr[3].lower() + attr[4:]
            if self.has_param(name):
                return lambda _name=name: self.get(_name)
        raise AttributeError(
            f"{type(self).__name__!r} object has no attribute {attr!r}")

    def __repr__(self):
        set_params = {k: v for k, v in self._paramMap.items()
                      if not isinstance(v, (np.ndarray,))}
        return f"{type(self).__name__}(uid={self.uid}, {set_params})"


# --------------------------------------------------------------------------
# Shared param traits (reference: core/contracts/Params.scala Has*Col traits)
# --------------------------------------------------------------------------

class HasInputCol(Params):
    inputCol = Param("inputCol", "name of the input column", "input")


class HasInputCols(Params):
    inputCols = Param("inputCols", "names of the input columns", None)


class HasOutputCol(Params):
    outputCol = Param("outputCol", "name of the output column", "output")


class HasOutputCols(Params):
    outputCols = Param("outputCols", "names of the output columns", None)


class HasLabelCol(Params):
    labelCol = Param("labelCol", "name of the label column", "label")


class HasFeaturesCol(Params):
    featuresCol = Param("featuresCol", "name of the features column", "features")


class HasPredictionCol(Params):
    predictionCol = Param("predictionCol", "name of the prediction column", "prediction")


class HasRawPredictionCol(Params):
    rawPredictionCol = Param("rawPredictionCol",
                             "raw (margin) prediction column", "rawPrediction")


class HasProbabilityCol(Params):
    probabilityCol = Param("probabilityCol", "class-probability column", "probability")


class HasWeightCol(Params):
    weightCol = Param("weightCol", "instance weight column", None)


class HasValidationIndicatorCol(Params):
    validationIndicatorCol = Param(
        "validationIndicatorCol",
        "boolean column marking rows held out for early-stopping validation", None)


class HasInitScoreCol(Params):
    initScoreCol = Param("initScoreCol", "initial (warm-start) margin column", None)


class HasGroupCol(Params):
    groupCol = Param("groupCol", "query-group column for ranking", None)


class HasSeed(Params):
    seed = Param("seed", "random seed", 0, int)


class HasBatchSize(Params):
    batchSize = Param("batchSize", "mini-batch size", 1024, int)
