"""Estimator / Transformer / Pipeline — the unit of composition.

Reference analogue: SparkML ``Estimator``/``Transformer``/``PipelineModel`` as used by every
MMLSpark stage (SURVEY.md §0: "The unit of composition everywhere is the SparkML
Estimator/Transformer over a DataFrame"). Save/load mirrors ComplexParamsWritable
(core/serialize/ComplexParam.scala, ConstructorWriter.scala:90): simple params go to JSON,
complex params (arrays, nested stages, fitted state) to sidecar files.
"""

from __future__ import annotations

import importlib
import json
import os
import pickle
import numpy as np
from typing import Any, Dict, List, Optional, Sequence

from .dataframe import DataFrame
from .params import Param, Params


class PipelineStage(Params):
    """Base of every stage. Provides save/load; subclasses implement fit/transform."""

    # ------------------------------------------------------------ save/load
    def save(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)
        simple: Dict[str, Any] = {}
        arrays: Dict[str, np.ndarray] = {}
        complex_meta: Dict[str, Any] = {}
        for name, value in self._paramMap.items():
            kind, payload = _encode_value(value, name, path)
            if kind == "json":
                simple[name] = payload
            elif kind == "array":
                arrays[name] = payload
                complex_meta[name] = {"kind": "array"}
            else:
                complex_meta[name] = payload
        meta = {
            "class": f"{type(self).__module__}.{type(self).__name__}",
            "uid": self.uid,
            "params": simple,
            "complex": complex_meta,
            "format_version": 1,
        }
        extra = self._save_extra(path)
        if extra:
            meta["extra"] = extra
        with open(os.path.join(path, "metadata.json"), "w") as f:
            json.dump(meta, f, indent=2, default=str)
        if arrays:
            np.savez(os.path.join(path, "params.npz"), **arrays)

    def _save_extra(self, path: str) -> Optional[Dict[str, Any]]:
        """Hook for subclasses to persist non-param fitted state."""
        return None

    def _load_extra(self, path: str, extra: Dict[str, Any]) -> None:
        pass

    @staticmethod
    def load(path: str) -> "PipelineStage":
        with open(os.path.join(path, "metadata.json")) as f:
            meta = json.load(f)
        module, _, clsname = meta["class"].rpartition(".")
        cls = getattr(importlib.import_module(module), clsname)
        stage = cls.__new__(cls)
        Params.__init__(stage)
        stage.uid = meta["uid"]
        registry = cls.params()
        for name, value in meta["params"].items():
            if name in registry:
                stage._paramMap[name] = _decode_json_value(value)
        arrays = None
        npz_path = os.path.join(path, "params.npz")
        if os.path.exists(npz_path):
            arrays = np.load(npz_path, allow_pickle=False)
        for name, info in meta.get("complex", {}).items():
            stage._paramMap[name] = _decode_complex(info, name, path, arrays)
        stage._load_extra(path, meta.get("extra") or {})
        return stage

    write = save  # SparkML-surface aliases


class Transformer(PipelineStage):
    def transform(self, df: DataFrame) -> DataFrame:
        raise NotImplementedError

    def __call__(self, df: DataFrame) -> DataFrame:
        return self.transform(df)


class Estimator(PipelineStage):
    def fit(self, df: DataFrame, params: Optional[Dict[str, Any]] = None
            ) -> "Transformer":
        """SparkML Estimator.fit: `params` may be one param override dict or
        a LIST of param maps, returning one fitted model per map (the
        `fit(dataset, paramMaps)` surface TuneHyperparameters sweeps).
        Subclasses may batch the list form (the GBDT trains continuous-only
        maps in one vmapped program); the default is sequential fits."""
        if isinstance(params, (list, tuple)):
            return [self.copy(dict(pm))._fit(df) for pm in params]
        if params:
            return self.copy(params)._fit(df)
        return self._fit(df)

    def _fit(self, df: DataFrame) -> "Transformer":
        raise NotImplementedError


class Model(Transformer):
    """A fitted Transformer produced by an Estimator."""


class Evaluator(Params):
    """Reference analogue: org.apache.spark.ml.evaluation.Evaluator (used by AutoML)."""

    def evaluate(self, df: DataFrame) -> float:
        raise NotImplementedError

    def is_larger_better(self) -> bool:
        return True


class Pipeline(Estimator):
    """Chain of stages; fitting fits estimators in order, threading transforms through.

    Reference analogue: org.apache.spark.ml.Pipeline + NamespaceInjections.pipelineModel
    (org/apache/spark/ml/NamespaceInjections.scala:15-21).
    """

    stages = Param("stages", "ordered pipeline stages", None, complex=True)

    def __init__(self, stages: Optional[Sequence[PipelineStage]] = None, **kw):
        super().__init__(**kw)
        if stages is not None:
            self._set(stages=list(stages))

    def _fit(self, df: DataFrame) -> "PipelineModel":
        fitted: List[Transformer] = []
        cur = df
        for stage in self.get("stages") or []:
            if isinstance(stage, Estimator):
                model = stage.fit(cur)
                fitted.append(model)
                cur = model.transform(cur)
            else:
                fitted.append(stage)
                cur = stage.transform(cur)
        return PipelineModel(stages=fitted)


class PipelineModel(Model):
    stages = Param("stages", "fitted pipeline stages", None, complex=True)

    def __init__(self, stages: Optional[Sequence[Transformer]] = None, **kw):
        super().__init__(**kw)
        if stages is not None:
            self._set(stages=list(stages))

    def transform(self, df: DataFrame) -> DataFrame:
        cur = df
        for stage in self.get("stages") or []:
            cur = stage.transform(cur)
        return cur


# --------------------------------------------------------------------------
# Complex-value codecs (reference: ComplexParam serialization, Serializer.scala)
# --------------------------------------------------------------------------

_JSON_TYPES = (bool, int, float, str, type(None))


def _is_jsonable(v: Any) -> bool:
    if isinstance(v, _JSON_TYPES):
        return True
    if isinstance(v, (list, tuple)):
        return all(_is_jsonable(x) for x in v)
    if isinstance(v, dict):
        return all(isinstance(k, str) and _is_jsonable(x) for k, x in v.items())
    return False


def _encode_value(value: Any, name: str, path: str):
    if isinstance(value, np.integer):
        return "json", int(value)
    if isinstance(value, np.floating):
        return "json", float(value)
    if _is_jsonable(value):
        return "json", list(value) if isinstance(value, tuple) else value
    if isinstance(value, np.ndarray) and value.dtype != object:
        return "array", value
    if isinstance(value, PipelineStage):
        sub = os.path.join(path, f"param_{name}")
        value.save(sub)
        return "complex", {"kind": "stage", "dir": f"param_{name}"}
    if isinstance(value, (list, tuple)) and value and all(
            isinstance(s, PipelineStage) for s in value):
        dirs = []
        for i, s in enumerate(value):
            d = f"param_{name}_{i}"
            s.save(os.path.join(path, d))
            dirs.append(d)
        return "complex", {"kind": "stage_list", "dirs": dirs}
    # fallback: pickle (python-side UDFs, custom objects) — analogue of UDFParam
    fname = f"param_{name}.pkl"
    with open(os.path.join(path, fname), "wb") as f:
        pickle.dump(value, f)
    return "complex", {"kind": "pickle", "file": fname}


def _decode_json_value(v: Any) -> Any:
    return v


def _decode_complex(info: Dict[str, Any], name: str, path: str, arrays) -> Any:
    kind = info["kind"]
    if kind == "array":
        return arrays[name]
    if kind == "stage":
        return PipelineStage.load(os.path.join(path, info["dir"]))
    if kind == "stage_list":
        return [PipelineStage.load(os.path.join(path, d)) for d in info["dirs"]]
    if kind == "pickle":
        with open(os.path.join(path, info["file"]), "rb") as f:
            return pickle.load(f)
    raise ValueError(f"unknown complex param kind {kind!r}")
