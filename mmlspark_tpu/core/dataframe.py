"""Columnar DataFrame — the host-side data plane.

The reference framework composes everything over Spark ``DataFrame``s (SURVEY.md §0); its unit
of distribution is the Spark partition. In the TPU-native design the host data plane is a plain
columnar table (numpy-backed, Arrow-convertible) and *device sharding via jax.sharding replaces
partitioning* — so this class is deliberately single-host and simple. Heavy compute never happens
here; estimators move columns into HBM as jax arrays and shard them over the mesh
(see mmlspark_tpu.parallel).

Reference analogue: org.apache.spark.sql.DataFrame as used by
src/main/scala/com/microsoft/ml/spark/** (e.g. lightgbm/LightGBMBase.scala:70-132 column
casting / repartitioning — here replaced by `cast_column` and device sharding).
"""

from __future__ import annotations

import numpy as np
from typing import Any, Callable, Dict, Iterable, List, Mapping, Optional, Sequence, Tuple


def _is_sparse(values: Any) -> bool:
    return hasattr(values, "toarray") and hasattr(values, "tocsr")


def _col_len(values: Any) -> int:
    return values.shape[0] if _is_sparse(values) else len(values)


def dense_matrix(col: Any, dtype=np.float32) -> np.ndarray:
    """Densify a (possibly sparse) feature column at a consumer boundary.

    Wide sparse columns (kept sparse by ingestion) raise instead of silently
    materializing gigabytes — route those through
    featurize.SparseFeatureBundler first."""
    if _is_sparse(col):
        if col.shape[1] > SPARSE_KEEP_WIDTH:
            raise ValueError(
                f"refusing to densify a {col.shape[1]}-wide sparse column "
                f"(> {SPARSE_KEEP_WIDTH}); pack it with "
                "featurize.SparseFeatureBundler (or densify explicitly "
                "upstream if you really have the memory)")
        return np.asarray(col.toarray(), dtype)
    return np.asarray(col, dtype)


#: sparse columns at or below this width densify at ingestion (every stage
#: consumed dense sparse input historically); wider ones stay CSR for the
#: SparseFeatureBundler / sparse-TextFeaturizer path
SPARSE_KEEP_WIDTH = 4096


def _as_column(values: Any) -> np.ndarray:
    """Coerce arbitrary input into a numpy column (1-D scalars or 2-D vectors).

    scipy.sparse matrices up to SPARSE_KEEP_WIDTH columns densify at
    ingestion (the CSR marshalling boundary of the reference,
    LightGBMUtils.scala:201-265 — every estimator consumes them as dense);
    WIDER sparse matrices stay CSR (row-sliceable) so a 2^18-wide
    hashed-text matrix never materializes — feed those through
    `featurize.SparseFeatureBundler`, which packs them into narrow dense
    bundles (dense-only estimators raise on a wide sparse column)."""
    if _is_sparse(values):
        if values.shape[1] <= SPARSE_KEEP_WIDTH:
            return np.asarray(values.toarray())
        return values.tocsr()
    if isinstance(values, np.ndarray):
        if values.dtype.kind == "U":  # normalize strings to object dtype
            return values.astype(object)
        return values
    if isinstance(values, (list, tuple)):
        if len(values) > 0 and isinstance(values[0], (list, tuple, np.ndarray)):
            try:
                arr = np.asarray(values)
                if arr.dtype != object:
                    return arr
            except ValueError:
                pass
            out = np.empty(len(values), dtype=object)
            for i, v in enumerate(values):
                out[i] = v
            return out
        arr = np.asarray(values)
        if arr.dtype.kind == "U":
            return arr.astype(object)
        return arr
    # jax arrays and other array-likes
    return np.asarray(values)


class DataFrame:
    """An ordered, named collection of equal-length columns.

    Columns are numpy arrays: 1-D for scalar columns, 2-D for dense vector columns,
    object-dtype for strings / ragged values. Per-column ``metadata`` carries schema
    annotations (categorical levels, ML attribute names) the way Spark ML metadata does
    (reference: core/schema/SparkSchema.scala, core/schema/Categoricals.scala).
    """

    def __init__(self, data: Optional[Mapping[str, Any]] = None,
                 metadata: Optional[Dict[str, Dict[str, Any]]] = None):
        self._cols: Dict[str, np.ndarray] = {}
        self._meta: Dict[str, Dict[str, Any]] = dict(metadata or {})
        if data:
            n = None
            for name, values in data.items():
                col = _as_column(values)
                if n is None:
                    n = _col_len(col)
                elif _col_len(col) != n:
                    raise ValueError(
                        f"column {name!r} has length {_col_len(col)}, "
                        f"expected {n}")
                self._cols[name] = col

    # ---------------------------------------------------------------- basics
    @property
    def columns(self) -> List[str]:
        return list(self._cols.keys())

    def __len__(self) -> int:
        if not self._cols:
            return 0
        return _col_len(next(iter(self._cols.values())))

    count = __len__

    def __contains__(self, name: str) -> bool:
        return name in self._cols

    def __getitem__(self, name: str) -> np.ndarray:
        if name not in self._cols:
            raise KeyError(f"no column {name!r}; have {self.columns}")
        return self._cols[name]

    def column(self, name: str) -> np.ndarray:
        return self[name]

    def metadata(self, name: str) -> Dict[str, Any]:
        return self._meta.get(name, {})

    def with_metadata(self, name: str, meta: Dict[str, Any]) -> "DataFrame":
        out = self._shallow_copy()
        out._meta[name] = dict(meta)
        return out

    def schema(self) -> Dict[str, str]:
        return {k: str(v.dtype) + ("" if v.ndim == 1 else f"[{v.shape[1]}]")
                for k, v in self._cols.items()}

    def _shallow_copy(self) -> "DataFrame":
        out = DataFrame()
        out._cols = dict(self._cols)
        out._meta = {k: dict(v) for k, v in self._meta.items()}
        return out

    # ------------------------------------------------------------ transforms
    def select(self, *names: str) -> "DataFrame":
        flat: List[str] = []
        for n in names:
            flat.extend(n if isinstance(n, (list, tuple)) else [n])
        out = DataFrame()
        for n in flat:
            out._cols[n] = self[n]
            if n in self._meta:
                out._meta[n] = dict(self._meta[n])
        return out

    def drop(self, *names: str) -> "DataFrame":
        dropset = set(names)
        out = self._shallow_copy()
        for n in dropset:
            out._cols.pop(n, None)
            out._meta.pop(n, None)
        return out

    def with_column(self, name: str, values: Any,
                    metadata: Optional[Dict[str, Any]] = None) -> "DataFrame":
        col = _as_column(values)
        if self._cols and _col_len(col) != len(self):
            raise ValueError(
                f"new column {name!r} has length {_col_len(col)}, "
                f"expected {len(self)}")
        out = self._shallow_copy()
        out._cols[name] = col
        if metadata is not None:
            out._meta[name] = dict(metadata)
        return out

    withColumn = with_column

    def with_column_renamed(self, old: str, new: str) -> "DataFrame":
        out = DataFrame()
        for n, c in self._cols.items():
            key = new if n == old else n
            out._cols[key] = c
            if n in self._meta:
                out._meta[key] = dict(self._meta[n])
        return out

    def filter(self, mask_or_fn) -> "DataFrame":
        if callable(mask_or_fn):
            mask = np.fromiter((bool(mask_or_fn(r)) for r in self.rows()),
                               dtype=bool, count=len(self))
        else:
            mask = np.asarray(mask_or_fn, dtype=bool)
        return self.take(np.nonzero(mask)[0])

    def take(self, indices) -> "DataFrame":
        idx = np.asarray(indices)
        out = DataFrame()
        for n, c in self._cols.items():
            out._cols[n] = c[idx]
        out._meta = {k: dict(v) for k, v in self._meta.items()}
        return out

    def head(self, n: int = 5) -> "DataFrame":
        return self.take(np.arange(min(n, len(self))))

    def sort(self, *names: str, ascending: bool = True) -> "DataFrame":
        keys = [self[n] for n in reversed(names)]
        order = np.lexsort(keys)
        if not ascending:
            order = order[::-1]
        return self.take(order)

    def union(self, other: "DataFrame") -> "DataFrame":
        if set(self.columns) != set(other.columns):
            raise ValueError("union requires identical column sets")
        out = DataFrame()
        for n in self.columns:
            a, b = self._cols[n], other._cols[n]
            if _is_sparse(a) or _is_sparse(b):
                import scipy.sparse as sp
                out._cols[n] = sp.vstack([a, b]).tocsr()
            else:
                out._cols[n] = np.concatenate([a, b], axis=0)
        out._meta = {k: dict(v) for k, v in self._meta.items()}
        return out

    def group_by(self, *keys: str) -> "GroupedDataFrame":
        """Spark-style df.groupBy(keys).agg(...): returns a grouped view
        whose .agg accepts out_name=(column, fn) pairs with fn in
        count/sum/mean/min/max/first."""
        for k in keys:
            if k not in self._cols:
                raise KeyError(f"unknown group key {k!r}")
        return GroupedDataFrame(self, keys)

    def join(self, other: "DataFrame", on, how: str = "inner"
             ) -> "DataFrame":
        """Hash join on key column(s). how: inner | left. Right-side name
        collisions (other than the keys) get a '_right' suffix, the Spark
        disambiguation users apply manually."""
        if how not in ("inner", "left"):
            raise ValueError(f"how must be 'inner' or 'left', got {how!r}")
        keys = [on] if isinstance(on, str) else list(on)
        lcols, rcols = [], []
        for k in keys:
            if k not in self._cols or k not in other._cols:
                raise KeyError(f"join key {k!r} missing from a side")
            lc, rc = _as_column(self[k]), _as_column(other[k])
            if lc.dtype.kind in "biuf" and rc.dtype.kind in "biuf":
                # joint numeric promotion: int-vs-float sides must compare
                # by VALUE, not by per-side string form
                t = np.result_type(lc.dtype, rc.dtype)
                lc, rc = lc.astype(t), rc.astype(t)
            lcols.append(lc)
            rcols.append(rc)
        lk = _encode_keys(lcols)
        rk = _encode_keys(rcols)
        # Spark join semantics: null (None) keys never match — a None would
        # otherwise string-encode as 'None' and both join with each other
        # and collide with a literal "None" key. NaN keys DO match each
        # other (Spark's NaN semantics: NaN = NaN is true in joins), which
        # searchsorted/string-encoding already provide.
        rvalid = np.flatnonzero(~_null_key_mask(rcols))
        if len(other) == 0 or len(rvalid) == 0:
            counts = np.zeros(len(lk), np.int64)
            order = np.zeros(0, np.int64)
            starts = np.zeros(len(lk), np.int64)
        else:
            order = rvalid[np.argsort(rk[rvalid], kind="stable")]
            rk_sorted = rk[order]
            starts = np.searchsorted(rk_sorted, lk, side="left")
            counts = np.searchsorted(rk_sorted, lk, side="right") - starts
        counts[_null_key_mask(lcols)] = 0
        matched = counts > 0
        cm = counts[matched]
        # within-block offsets 0..c-1 for every matched left row, fully
        # vectorized (no per-row arrays)
        cum = np.cumsum(cm)
        total_m = int(cum[-1]) if len(cum) else 0
        offs = np.arange(total_m) - np.repeat(cum - cm, cm)
        src = np.repeat(starts[matched], cm) + offs
        if how == "inner":
            li = np.repeat(np.arange(len(lk))[matched], cm)
            ri = order[src] if total_m else np.zeros(0, np.int64)
        else:  # left: unmatched rows keep one output row with fill values
            counts_l = np.maximum(counts, 1)
            li = np.repeat(np.arange(len(lk)), counts_l)
            out_start = np.concatenate([[0], np.cumsum(counts_l)[:-1]]) \
                if len(counts_l) else np.zeros(0, np.int64)
            ri = np.full(int(counts_l.sum()), -1, np.int64)
            if total_m:
                pos = np.repeat(out_start[matched], cm) + offs
                ri[pos] = order[src]
        out = self.take(li)
        rvalid = ri >= 0
        ri_safe = np.where(rvalid, ri, 0)
        for n in other.columns:
            if n in keys:
                continue
            name = n if n not in out._cols else f"{n}_right"
            rc = _as_column(other[n])
            if len(rc) == 0:
                col = np.full(len(li), np.nan if rc.dtype.kind == "f"
                              else None,
                              rc.dtype if rc.dtype.kind == "f" else object)
            else:
                col = rc[ri_safe]
                if not rvalid.all():
                    col = col.astype(np.float64) \
                        if col.dtype.kind in "if" else col.astype(object)
                    col[~rvalid] = (np.nan if col.dtype.kind == "f"
                                    else None)
            out._cols[name] = col
            if n in other._meta:
                out._meta[name] = dict(other._meta[n])
        return out

    def random_split(self, weights: Sequence[float], seed: int = 0
                     ) -> List["DataFrame"]:
        """Reference: Dataset.randomSplit used by LightGBMBase.scala:29-50 batch split."""
        w = np.asarray(weights, dtype=np.float64)
        w = w / w.sum()
        rng = np.random.default_rng(seed)
        n = len(self)
        perm = rng.permutation(n)
        bounds = np.floor(np.cumsum(w) * n).astype(int)
        bounds[-1] = n  # fp cumsum can land below 1.0 and silently drop rows
        out, start = [], 0
        for b in bounds:
            out.append(self.take(np.sort(perm[start:b])))
            start = b
        return out

    randomSplit = random_split

    def sample(self, fraction: float, seed: int = 0) -> "DataFrame":
        rng = np.random.default_rng(seed)
        mask = rng.random(len(self)) < fraction
        return self.take(np.nonzero(mask)[0])

    def cast_column(self, name: str, dtype) -> "DataFrame":
        return self.with_column(name, self[name].astype(dtype),
                                metadata=self.metadata(name) or None)

    # -------------------------------------------------------------- row view
    def rows(self) -> Iterable[Dict[str, Any]]:
        cols = self._cols
        for i in range(len(self)):
            yield {n: c[i] for n, c in cols.items()}

    def collect(self) -> List[Dict[str, Any]]:
        return list(self.rows())

    # ------------------------------------------------------------ fluent API
    def ml_transform(self, *stages) -> "DataFrame":
        """Apply transformers (or fitted models) in sequence — the FluentAPI
        sugar `df.mlTransform(t1, t2, ...)` (core/spark/FluentAPI.scala:14-18)."""
        out = self
        for stage in stages:
            out = stage.transform(out)
        return out

    def ml_fit(self, estimator):
        """`df.mlFit(e)` == `e.fit(df)` (core/spark/FluentAPI.scala:20)."""
        return estimator.fit(self)

    mlTransform = ml_transform  # reference casing
    mlFit = ml_fit

    def to_pandas(self):
        import pandas as pd
        data = {}
        for n, c in self._cols.items():
            data[n] = list(c) if c.ndim > 1 else c
        return pd.DataFrame(data)

    toPandas = to_pandas

    @staticmethod
    def from_pandas(pdf, vector_cols: Sequence[str] = ()) -> "DataFrame":
        data = {}
        for n in pdf.columns:
            v = pdf[n].to_numpy()
            if n in vector_cols or (len(v) and isinstance(v[0], (list, np.ndarray))):
                v = np.stack([np.asarray(x) for x in v])
            data[n] = v
        return DataFrame(data)

    def __repr__(self) -> str:
        return f"DataFrame[{len(self)} rows x {len(self._cols)} cols: {self.schema()}]"

    def show(self, n: int = 10) -> None:
        print(self.head(n).to_pandas().to_string())


def concat_dataframes(dfs: Sequence[DataFrame]) -> DataFrame:
    out = dfs[0]
    for d in dfs[1:]:
        out = out.union(d)
    return out


def _null_key_mask(cols: Sequence[np.ndarray]) -> np.ndarray:
    """Rows where any key column holds a null (None in an object column).
    Join never matches these rows — Spark null-key semantics."""
    mask = np.zeros(len(cols[0]), bool)
    for c in cols:
        if c.dtype.kind == "O":
            mask |= np.fromiter((v is None for v in c), bool, len(c))
    return mask


def _encode_keys(cols: Sequence[np.ndarray]) -> np.ndarray:
    """Composite join/group keys -> one sortable 1-D array: single numeric
    keys pass through; anything else string-encodes per column and joins
    with an unlikely separator."""
    cols = [_as_column(c) for c in cols]
    if len(cols) == 1 and cols[0].dtype.kind in "biuf":
        return cols[0]
    parts = [c.astype(str) for c in cols]
    key = parts[0]
    for p in parts[1:]:
        key = np.char.add(np.char.add(key, "\x1f"), p)
    return key


class GroupedDataFrame:
    """df.group_by(keys) result; .agg(out=(col, fn)) mirrors Spark's
    groupBy().agg() for the reductions pipelines actually use."""

    _FNS = {
        "count": lambda v, idx, nb: np.bincount(idx, minlength=nb),
        "sum": lambda v, idx, nb: np.bincount(idx, weights=v, minlength=nb),
        "mean": lambda v, idx, nb: (
            np.bincount(idx, weights=v, minlength=nb)
            / np.maximum(np.bincount(idx, minlength=nb), 1)),
    }

    def __init__(self, df: DataFrame, keys: Sequence[str]):
        self._df = df
        self._keys = tuple(keys)

    def agg(self, **aggs) -> DataFrame:
        df = self._df
        enc = _encode_keys([df[k] for k in self._keys])
        uniq, first_pos, idx = np.unique(enc, return_index=True,
                                         return_inverse=True)
        out = DataFrame()
        for k in self._keys:
            out._cols[k] = _as_column(df[k])[first_pos]
        for name, spec in aggs.items():
            col, fn = spec
            v = _as_column(df[col])
            if fn in self._FNS:
                out._cols[name] = self._FNS[fn](
                    np.asarray(v, np.float64) if fn != "count" else v, idx,
                    len(uniq))
            elif fn in ("min", "max", "first"):
                order = np.argsort(idx, kind="stable")
                bounds = np.searchsorted(idx[order], np.arange(len(uniq)))
                if fn == "first":
                    out._cols[name] = v[order[bounds]]
                else:
                    red = np.minimum if fn == "min" else np.maximum
                    acc = np.empty(len(uniq), v.dtype)
                    sorted_v = v[order]
                    ends = np.append(bounds[1:], len(v))
                    for g in range(len(uniq)):
                        acc[g] = red.reduce(sorted_v[bounds[g]:ends[g]])
                    out._cols[name] = acc
            else:
                raise ValueError(
                    f"unknown aggregation {fn!r}; have count/sum/mean/"
                    f"min/max/first")
        return out

    def count(self) -> DataFrame:
        return self.agg(count=(self._keys[0], "count"))
