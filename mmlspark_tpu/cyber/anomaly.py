"""CyberML access-anomaly detection.

Reference: src/main/python/mmlspark/cyber/anomaly/collaborative_filtering.py:
44-988 — `AccessAnomaly`: per-tenant ALS factorization of the (user, resource)
access matrix; anomaly score = standardized negative affinity (-u.v), so
accesses unlike anything the factorization explains score high. Plus
anomaly/complement_access.py:148 (`ComplementAccessTransformer` — sample
(user, resource) pairs NOT present, for evaluation) and `ConnectedComponents`
(:415 — used to group users/resources sharing access structure).

TPU design: ALS alternating ridge solves are batched einsums + a vmapped
Cholesky solve over all users (then all resources) at once — no per-user
Python loops, one jit per alternation.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import params as _p
from ..core.dataframe import DataFrame
from ..core.pipeline import Estimator, Model


@partial(jax.jit, static_argnames=("rank", "n_out"))
def _als_step(other_factors, rows, cols, vals, n_out, reg, rank: int):
    """One explicit-feedback ALS half-step: solve factors for every `row` id
    given the other side's factors. Normal equations accumulated by
    segment-sum, solved by a vmapped linear solve."""
    f = other_factors[cols]                              # [nnz, r]
    ata = jnp.einsum("ni,nj->nij", f, f)                 # [nnz, r, r]
    atb = f * vals[:, None]                              # [nnz, r]
    gram = jax.ops.segment_sum(ata, rows, n_out)         # [n, r, r]
    rhs = jax.ops.segment_sum(atb, rows, n_out)          # [n, r]
    gram = gram + reg * jnp.eye(rank)[None]
    return jax.vmap(jnp.linalg.solve)(gram, rhs)


@partial(jax.jit, static_argnames=("rank", "n_out"))
def _als_step_implicit(other_factors, rows, cols, conf, n_out, reg,
                       rank: int):
    """One implicit-feedback ALS half-step (Hu/Koren/Volinsky, the
    reference's applyImplicitCf=True default — Spark ALS implicitPrefs):
    minimize sum_ui c_ui (p_ui - x_u . y_i)^2 + reg ||x||^2 with preference
    p=1 for observed pairs (0 elsewhere) and confidence c = 1 + alpha * r
    for observed (1 elsewhere). Normal equations per user:
    (Y^T Y + Y_obs^T diag(c-1) Y_obs + reg I) x = Y_obs^T c — the dense
    all-items Y^T Y background term is one [r, r] matmul, the observed
    correction a segment-sum over nnz."""
    f = other_factors[cols]                              # [nnz, r]
    gram_bg = other_factors.T @ other_factors            # [r, r]
    cm1 = conf - 1.0
    ata = jnp.einsum("n,ni,nj->nij", cm1, f, f)          # [nnz, r, r]
    gram = (jax.ops.segment_sum(ata, rows, n_out)
            + gram_bg[None] + reg * jnp.eye(rank)[None])
    rhs = jax.ops.segment_sum(f * conf[:, None], rows, n_out)
    return jax.vmap(jnp.linalg.solve)(gram, rhs)


@jax.jit
def _pair_scores(user_f, res_f, users, resources):
    return (user_f[users] * res_f[resources]).sum(axis=1)


class AccessAnomaly(Estimator):
    """Per-tenant ALS access model -> standardized anomaly scores."""

    tenantCol = _p.Param("tenantCol", "tenant column", "tenant")
    userCol = _p.Param("userCol", "user index column (int)", "user")
    resCol = _p.Param("resCol", "resource index column (int)", "res")
    likelihoodCol = _p.Param("likelihoodCol",
                             "access strength column (count); None = 1",
                             None)
    outputCol = _p.Param("outputCol", "anomaly score column",
                         "anomaly_score")
    rankParam = _p.Param("rankParam", "latent dimension", 10, int)
    maxIter = _p.Param("maxIter", "ALS sweeps", 25, int)
    regParam = _p.Param("regParam", "ridge regularization", 1.0, float)
    seed = _p.Param("seed", "init seed", 0, int)
    lowValue = _p.Param(
        "lowValue", "per-tenant linear rescale of likelihoodCol to "
        "[lowValue, highValue] (reference LinearScalarScaler; None with "
        "highValue=None disables scaling)", 5.0, float)
    highValue = _p.Param("highValue", "upper end of the likelihood rescale",
                         10.0, float)
    applyImplicitCf = _p.Param(
        "applyImplicitCf", "True (default) = implicit-feedback ALS "
        "(Hu/Koren/Volinsky confidence weights, Spark ALS implicitPrefs); "
        "False = explicit ridge ALS over the accesses plus sampled "
        "complement negatives at negScore", True, bool)
    alphaParam = _p.Param("alphaParam", "implicit-CF confidence slope "
                          "(c = 1 + alpha * likelihood)", 1.0, float)
    complementsetFactor = _p.Param(
        "complementsetFactor", "explicit mode: complement negatives per "
        "positive (ComplementAccessTransformer)", 2, int)
    negScore = _p.Param("negScore", "explicit mode: target value for "
                        "complement negatives", 1.0, float)
    historyAccessDf = _p.Param(
        "historyAccessDf", "optional DataFrame of known (tenant, user, res) "
        "pairs to score 0.0 at transform; None = the training accesses",
        None, complex=True)
    separateTenants = _p.Param(
        "separateTenants", "API-parity flag (reference trains one ALS over "
        "offset id spaces when False): tenants here ALWAYS train in "
        "isolation — the variant the reference documents as more accurate; "
        "ids are per-tenant index spaces either way", False, bool)
    numBlocks = _p.Param(
        "numBlocks", "API-parity flag: Spark ALS partition count; the "
        "batched einsum/Cholesky solves have no block concept", None)

    def _fit(self, df: DataFrame) -> "AccessAnomalyModel":
        tenants = df[self.get("tenantCol")]
        users = np.asarray(df[self.get("userCol")], np.int64)
        resources = np.asarray(df[self.get("resCol")], np.int64)
        lik_col = self.get("likelihoodCol")
        vals = (np.asarray(df[lik_col], np.float64) if lik_col and
                lik_col in df else np.ones(len(df)))
        rank = self.get("rankParam")
        reg = self.get("regParam")
        implicit = self.get("applyImplicitCf")
        alpha = self.get("alphaParam")
        lo, hi = self.get("lowValue"), self.get("highValue")
        hist = self.get("historyAccessDf")
        rng = np.random.default_rng(self.get("seed"))

        factors: Dict[object, Tuple[np.ndarray, np.ndarray]] = {}
        norm: Dict[object, Tuple[float, float]] = {}
        seen: Dict[object, set] = {}
        comps: Dict[object, Tuple[np.ndarray, np.ndarray]] = {}
        for t in sorted(set(tenants.tolist()), key=str):
            mask = np.array([x == t for x in tenants])
            u, r, v = users[mask], resources[mask], vals[mask]
            if lo is not None and hi is not None:
                # per-tenant linear rescale to [lo, hi] (LinearScalarScaler)
                vmin, vmax = float(v.min()), float(v.max())
                v = (lo + (v - vmin) * (hi - lo) / (vmax - vmin)
                     if vmax > vmin else np.full_like(v, (lo + hi) / 2.0))
            nu, nr = int(u.max()) + 1, int(r.max()) + 1
            if not implicit:
                # explicit feedback trains on accesses UNION complement
                # negatives at negScore (reference _enrich_and_normalize)
                neg = ComplementAccessTransformer(
                    tenantCol=self.get("tenantCol"),
                    indexedColNames=[self.get("userCol"),
                                     self.get("resCol")],
                    complementsetFactor=self.get("complementsetFactor"),
                    seed=self.get("seed")).transform(
                        DataFrame({self.get("tenantCol"):
                                   np.array([t] * len(u), dtype=object),
                                   self.get("userCol"): u,
                                   self.get("resCol"): r}))
                nu_ = np.asarray(neg[self.get("userCol")], np.int64)
                nr_ = np.asarray(neg[self.get("resCol")], np.int64)
                u_t = np.concatenate([u, nu_])
                r_t = np.concatenate([r, nr_])
                v_t = np.concatenate(
                    [v, np.full(len(nu_), self.get("negScore"))])
            else:
                u_t, r_t, v_t = u, r, v
            uf = rng.normal(scale=0.1, size=(nu, rank)).astype(np.float32)
            rf = rng.normal(scale=0.1, size=(nr, rank)).astype(np.float32)
            uj, rj = jnp.asarray(u_t), jnp.asarray(r_t)
            vj = jnp.asarray(v_t, jnp.float32)
            uf, rf = jnp.asarray(uf), jnp.asarray(rf)
            step = _als_step_implicit if implicit else _als_step
            kw = {"reg": reg, "rank": rank}
            if implicit:
                vj = 1.0 + alpha * vj                 # confidence weights
            for _ in range(self.get("maxIter")):
                uf = step(rf, uj, rj, vj, n_out=nu, **kw)
                rf = step(uf, rj, uj, vj, n_out=nr, **kw)
            uf, rf = np.asarray(uf), np.asarray(rf)
            # per-tenant standardization of the TRAINING scores over the
            # enriched pairs (ModelNormalizeTransformer: mean 0 / std 1 on
            # the fit data, so scores are comparable across tenants)
            fit_scores = -(uf[np.asarray(u_t)]
                           * rf[np.asarray(r_t)]).sum(axis=1)
            norm[t] = (float(fit_scores.mean()),
                       float(fit_scores.std()) or 1.0)
            factors[t] = (uf, rf)
            # access structure for transform-time semantics: seen pairs
            # score 0.0; user/resource in different connected components
            # score +inf (never co-accessed structures — reference
            # value_calc)
            ucomp, rcomp = _component_maps(u, r, nu, nr)
            comps[t] = (ucomp, rcomp)
            if hist is None:
                seen[t] = set(zip(u.tolist(), r.tolist()))

        if hist is not None:
            h_t = hist[self.get("tenantCol")]
            h_u = np.asarray(hist[self.get("userCol")], np.int64)
            h_r = np.asarray(hist[self.get("resCol")], np.int64)
            for t in set(h_t.tolist()):
                m = np.array([x == t for x in h_t])
                seen[t] = set(zip(h_u[m].tolist(), h_r[m].tolist()))
        model = AccessAnomalyModel(factors=factors, norm=norm, seen=seen,
                                   comps=comps)
        for p in ("tenantCol", "userCol", "resCol", "outputCol"):
            model.set(p, self.get(p))
        return model


def _component_maps(u: np.ndarray, r: np.ndarray, nu: int, nr: int
                    ) -> Tuple[np.ndarray, np.ndarray]:
    """Per-entity connected-component ids over the bipartite access graph
    (reference ConnectedComponents :415-470, which label-propagates to the
    min user index; ids here are canonical component labels — equality is
    the only contract). Unobserved ids get -1 (distinct from every real
    component)."""
    parent = np.arange(nu + nr)

    def find(a):
        root = a
        while parent[root] != root:
            root = parent[root]
        while parent[a] != root:
            parent[a], a = root, parent[a]
        return root

    for a, b in zip(u, r):
        ra, rb = find(int(a)), find(int(b) + nu)
        if ra != rb:
            parent[rb] = ra
    ucomp = np.full(nu, -1, np.int64)
    rcomp = np.full(nr, -1, np.int64)
    for a in set(u.tolist()):
        ucomp[a] = find(int(a))
    for b in set(r.tolist()):
        rcomp[b] = find(int(b) + nu)
    return ucomp, rcomp


class AccessAnomalyModel(Model):
    """Fitted per-tenant access model. Transform semantics, in PRECEDENCE
    order (reference AccessAnomalyModel._transform value_calc :366-413 —
    the seen-pair test is its outermost `when`, so a known access scores
    0.0 even when ids have no factor vectors):

    - (user, res) in the history/training access set -> 0.0 (known access,
      `preserveHistory`);
    - unknown user or resource (no factor vector) -> NaN (null);
    - user and resource in DIFFERENT connected components of the access
      graph -> +inf (no path of shared accesses links them);
    - otherwise the per-tenant standardized negative affinity
      (mean - u.v)/std — mean 0 / std 1 on the fit data.
    """

    tenantCol = _p.Param("tenantCol", "tenant column", "tenant")
    userCol = _p.Param("userCol", "user index column", "user")
    resCol = _p.Param("resCol", "resource index column", "res")
    outputCol = _p.Param("outputCol", "anomaly score column", "anomaly_score")
    factors = _p.Param("factors", "tenant -> (user_f, res_f)", None,
                       complex=True)
    norm = _p.Param("norm", "tenant -> (mean, std)", None, complex=True)
    seenPairs = _p.Param("seenPairs", "tenant -> {(user, res)} known "
                         "accesses (score 0)", None, complex=True)
    comps = _p.Param("comps", "tenant -> (user_comp, res_comp) component "
                     "ids", None, complex=True)
    preserveHistory = _p.Param(
        "preserveHistory", "score known accesses 0.0 instead of their "
        "affinity score (reference preserve_history)", True, bool)

    def __init__(self, factors=None, norm=None, seen=None, comps=None, **kw):
        super().__init__(**kw)
        if factors is not None:
            self._set(factors=factors, norm=norm, seenPairs=seen or {},
                      comps=comps or {})

    def transform(self, df: DataFrame) -> DataFrame:
        tenants = df[self.get("tenantCol")]
        users = np.asarray(df[self.get("userCol")], np.int64)
        resources = np.asarray(df[self.get("resCol")], np.int64)
        factors = self.get("factors")
        norm = self.get("norm")
        seen = self.get("seenPairs") or {}
        comps = self.get("comps") or {}
        preserve = self.get("preserveHistory")
        out = np.full(len(df), np.nan)
        for t in set(tenants.tolist()):
            if t not in factors:
                continue
            uf, rf = factors[t]
            mean, std = norm[t]
            mask = np.array([x == t for x in tenants])
            u, r = users[mask], resources[mask]
            ok = (u >= 0) & (u < len(uf)) & (r >= 0) & (r < len(rf))
            scores = np.full(len(u), np.nan)
            if ok.any():
                raw = -np.asarray(_pair_scores(
                    jnp.asarray(uf), jnp.asarray(rf),
                    jnp.asarray(u[ok]), jnp.asarray(r[ok])))
                scores[ok] = (raw - mean) / std
            if t in comps:
                ucomp, rcomp = comps[t]
                uc = np.where(ok, ucomp[np.clip(u, 0, len(ucomp) - 1)], -2)
                rc = np.where(ok, rcomp[np.clip(r, 0, len(rcomp) - 1)], -2)
                cross = ok & ((uc != rc) | (uc == -1) | (rc == -1))
                scores[cross] = np.inf
            if preserve and t in seen:
                st = seen[t]
                known = np.fromiter(
                    ((int(a), int(b)) in st for a, b in zip(u, r)),
                    bool, len(u))
                scores[known] = 0.0
            out[mask] = scores
        return df.with_column(self.get("outputCol"), out)


class ComplementAccessTransformer(_p.Params):
    """Sample (tenant, user, resource) triples NOT present in the input —
    evaluation negatives (cyber/anomaly/complement_access.py:148)."""

    tenantCol = _p.Param("tenantCol", "tenant column", "tenant")
    indexedColNames = _p.Param("indexedColNames", "columns forming the pair",
                               None)
    complementsetFactor = _p.Param("complementsetFactor",
                                   "negatives per positive", 2, int)
    seed = _p.Param("seed", "sampling seed", 0, int)

    def __init__(self, **kw):
        super().__init__(**kw)
        if not self.is_set("indexedColNames"):
            self.set("indexedColNames", ["user", "res"])

    def transform(self, df: DataFrame) -> DataFrame:
        tcol = self.get("tenantCol")
        ucol, rcol = self.get("indexedColNames")
        tenants = df[tcol]
        users = np.asarray(df[ucol], np.int64)
        resources = np.asarray(df[rcol], np.int64)
        rng = np.random.default_rng(self.get("seed"))
        factor = self.get("complementsetFactor")
        out_t: List = []
        out_u: List[int] = []
        out_r: List[int] = []
        for t in sorted(set(tenants.tolist()), key=str):
            mask = np.array([x == t for x in tenants])
            u, r = users[mask], resources[mask]
            seen = set(zip(u.tolist(), r.tolist()))
            n_want = len(u) * factor
            hi_u, hi_r = int(u.max()) + 1, int(r.max()) + 1
            cap = hi_u * hi_r - len(seen)
            n_want = min(n_want, max(cap, 0))
            tries = 0
            got = set()
            while len(got) < n_want and tries < 50 * max(n_want, 1):
                cu = int(rng.integers(hi_u))
                cr = int(rng.integers(hi_r))
                tries += 1
                if (cu, cr) not in seen and (cu, cr) not in got:
                    got.add((cu, cr))
            for cu, cr in sorted(got):
                out_t.append(t)
                out_u.append(cu)
                out_r.append(cr)
        return DataFrame({tcol: np.array(out_t, dtype=object),
                          ucol: np.array(out_u, np.int64),
                          rcol: np.array(out_r, np.int64)})


def connected_components(edges_u: np.ndarray, edges_v: np.ndarray
                         ) -> np.ndarray:
    """Component id of each bipartite edge, ids densely renumbered in
    first-seen order (reference: collaborative_filtering.py
    ConnectedComponents :415). Vertex spaces are disjoint (u and v are
    separate id spaces). Built on the same union-find as the model's
    per-entity maps (_component_maps)."""
    if not len(edges_u):
        return np.empty(0, np.int64)
    nu = int(edges_u.max()) + 1
    nv = int(edges_v.max()) + 1
    ucomp, _ = _component_maps(np.asarray(edges_u, np.int64),
                               np.asarray(edges_v, np.int64), nu, nv)
    comp: Dict[int, int] = {}
    out = np.empty(len(edges_u), np.int64)
    for i, u in enumerate(edges_u):
        out[i] = comp.setdefault(int(ucomp[int(u)]), len(comp))
    return out
