"""CyberML access-anomaly detection.

Reference: src/main/python/mmlspark/cyber/anomaly/collaborative_filtering.py:
44-988 — `AccessAnomaly`: per-tenant ALS factorization of the (user, resource)
access matrix; anomaly score = standardized negative affinity (-u.v), so
accesses unlike anything the factorization explains score high. Plus
anomaly/complement_access.py:148 (`ComplementAccessTransformer` — sample
(user, resource) pairs NOT present, for evaluation) and `ConnectedComponents`
(:415 — used to group users/resources sharing access structure).

TPU design: ALS alternating ridge solves are batched einsums + a vmapped
Cholesky solve over all users (then all resources) at once — no per-user
Python loops, one jit per alternation.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import params as _p
from ..core.dataframe import DataFrame
from ..core.pipeline import Estimator, Model


@partial(jax.jit, static_argnames=("rank", "n_out"))
def _als_step(other_factors, rows, cols, vals, n_out, reg, rank: int):
    """One ALS half-step: solve factors for every `row` id given the other
    side's factors. Normal equations accumulated by segment-sum, solved by a
    vmapped linear solve."""
    f = other_factors[cols]                              # [nnz, r]
    ata = jnp.einsum("ni,nj->nij", f, f)                 # [nnz, r, r]
    atb = f * vals[:, None]                              # [nnz, r]
    gram = jax.ops.segment_sum(ata, rows, n_out)         # [n, r, r]
    rhs = jax.ops.segment_sum(atb, rows, n_out)          # [n, r]
    gram = gram + reg * jnp.eye(rank)[None]
    return jax.vmap(jnp.linalg.solve)(gram, rhs)


@jax.jit
def _pair_scores(user_f, res_f, users, resources):
    return (user_f[users] * res_f[resources]).sum(axis=1)


class AccessAnomaly(Estimator):
    """Per-tenant ALS access model -> standardized anomaly scores."""

    tenantCol = _p.Param("tenantCol", "tenant column", "tenant")
    userCol = _p.Param("userCol", "user index column (int)", "user")
    resCol = _p.Param("resCol", "resource index column (int)", "res")
    likelihoodCol = _p.Param("likelihoodCol",
                             "access strength column (count); None = 1",
                             None)
    outputCol = _p.Param("outputCol", "anomaly score column",
                         "anomaly_score")
    rankParam = _p.Param("rankParam", "latent dimension", 10, int)
    maxIter = _p.Param("maxIter", "ALS sweeps", 10, int)
    regParam = _p.Param("regParam", "ridge regularization", 0.1, float)
    seed = _p.Param("seed", "init seed", 0, int)

    def _fit(self, df: DataFrame) -> "AccessAnomalyModel":
        tenants = df[self.get("tenantCol")]
        users = np.asarray(df[self.get("userCol")], np.int64)
        resources = np.asarray(df[self.get("resCol")], np.int64)
        lik_col = self.get("likelihoodCol")
        vals = (np.asarray(df[lik_col], np.float64) if lik_col and
                lik_col in df else np.ones(len(df)))
        vals = np.log1p(vals)  # dampen heavy hitters (reference scales counts)
        rank = self.get("rankParam")
        reg = self.get("regParam")
        rng = np.random.default_rng(self.get("seed"))

        factors: Dict[object, Tuple[np.ndarray, np.ndarray]] = {}
        norm: Dict[object, Tuple[float, float]] = {}
        for t in sorted(set(tenants.tolist()), key=str):
            mask = np.array([x == t for x in tenants])
            u, r, v = users[mask], resources[mask], vals[mask]
            nu, nr = int(u.max()) + 1, int(r.max()) + 1
            uf = rng.normal(scale=0.1, size=(nu, rank)).astype(np.float32)
            rf = rng.normal(scale=0.1, size=(nr, rank)).astype(np.float32)
            uj, rj = jnp.asarray(u), jnp.asarray(r)
            vj = jnp.asarray(v, jnp.float32)
            uf, rf = jnp.asarray(uf), jnp.asarray(rf)
            for _ in range(self.get("maxIter")):
                uf = _als_step(rf, uj, rj, vj, reg=reg, rank=rank, n_out=nu)
                rf = _als_step(uf, rj, uj, vj, reg=reg, rank=rank, n_out=nr)
            uf, rf = np.asarray(uf), np.asarray(rf)
            # per-tenant standardization of the TRAINING scores
            # (AccessAnomaly scales scores so tenants are comparable)
            fit_scores = -(uf[u] * rf[r]).sum(axis=1)
            norm[t] = (float(fit_scores.mean()),
                       float(fit_scores.std()) or 1.0)
            factors[t] = (uf, rf)
        model = AccessAnomalyModel(factors=factors, norm=norm)
        for p in ("tenantCol", "userCol", "resCol", "outputCol"):
            model.set(p, self.get(p))
        return model


class AccessAnomalyModel(Model):
    tenantCol = _p.Param("tenantCol", "tenant column", "tenant")
    userCol = _p.Param("userCol", "user index column", "user")
    resCol = _p.Param("resCol", "resource index column", "res")
    outputCol = _p.Param("outputCol", "anomaly score column", "anomaly_score")
    factors = _p.Param("factors", "tenant -> (user_f, res_f)", None,
                       complex=True)
    norm = _p.Param("norm", "tenant -> (mean, std)", None, complex=True)

    def __init__(self, factors=None, norm=None, **kw):
        super().__init__(**kw)
        if factors is not None:
            self._set(factors=factors, norm=norm)

    def transform(self, df: DataFrame) -> DataFrame:
        tenants = df[self.get("tenantCol")]
        users = np.asarray(df[self.get("userCol")], np.int64)
        resources = np.asarray(df[self.get("resCol")], np.int64)
        factors = self.get("factors")
        norm = self.get("norm")
        out = np.full(len(df), np.nan)
        for t in set(tenants.tolist()):
            if t not in factors:
                continue
            uf, rf = factors[t]
            mean, std = norm[t]
            mask = np.array([x == t for x in tenants])
            u, r = users[mask], resources[mask]
            ok = (u >= 0) & (u < len(uf)) & (r >= 0) & (r < len(rf))
            scores = np.full(len(u), np.nan)
            if ok.any():
                raw = -np.asarray(_pair_scores(
                    jnp.asarray(uf), jnp.asarray(rf),
                    jnp.asarray(u[ok]), jnp.asarray(r[ok])))
                scores[ok] = (raw - mean) / std
            out[mask] = scores
        return df.with_column(self.get("outputCol"), out)


class ComplementAccessTransformer(_p.Params):
    """Sample (tenant, user, resource) triples NOT present in the input —
    evaluation negatives (cyber/anomaly/complement_access.py:148)."""

    tenantCol = _p.Param("tenantCol", "tenant column", "tenant")
    indexedColNames = _p.Param("indexedColNames", "columns forming the pair",
                               None)
    complementsetFactor = _p.Param("complementsetFactor",
                                   "negatives per positive", 2, int)
    seed = _p.Param("seed", "sampling seed", 0, int)

    def __init__(self, **kw):
        super().__init__(**kw)
        if not self.is_set("indexedColNames"):
            self.set("indexedColNames", ["user", "res"])

    def transform(self, df: DataFrame) -> DataFrame:
        tcol = self.get("tenantCol")
        ucol, rcol = self.get("indexedColNames")
        tenants = df[tcol]
        users = np.asarray(df[ucol], np.int64)
        resources = np.asarray(df[rcol], np.int64)
        rng = np.random.default_rng(self.get("seed"))
        factor = self.get("complementsetFactor")
        out_t: List = []
        out_u: List[int] = []
        out_r: List[int] = []
        for t in sorted(set(tenants.tolist()), key=str):
            mask = np.array([x == t for x in tenants])
            u, r = users[mask], resources[mask]
            seen = set(zip(u.tolist(), r.tolist()))
            n_want = len(u) * factor
            hi_u, hi_r = int(u.max()) + 1, int(r.max()) + 1
            cap = hi_u * hi_r - len(seen)
            n_want = min(n_want, max(cap, 0))
            tries = 0
            got = set()
            while len(got) < n_want and tries < 50 * max(n_want, 1):
                cu = int(rng.integers(hi_u))
                cr = int(rng.integers(hi_r))
                tries += 1
                if (cu, cr) not in seen and (cu, cr) not in got:
                    got.add((cu, cr))
            for cu, cr in sorted(got):
                out_t.append(t)
                out_u.append(cu)
                out_r.append(cr)
        return DataFrame({tcol: np.array(out_t, dtype=object),
                          ucol: np.array(out_u, np.int64),
                          rcol: np.array(out_r, np.int64)})


def connected_components(edges_u: np.ndarray, edges_v: np.ndarray
                         ) -> np.ndarray:
    """Union-find over a bipartite edge list; returns the component id of each
    edge (reference: collaborative_filtering.py ConnectedComponents :415).
    Vertex spaces are disjoint (u and v are separate id spaces)."""
    nu = int(edges_u.max()) + 1 if len(edges_u) else 0
    parent = np.arange(nu + (int(edges_v.max()) + 1 if len(edges_v) else 0))

    def find(a):
        root = a
        while parent[root] != root:
            root = parent[root]
        while parent[a] != root:
            parent[a], a = root, parent[a]
        return root

    for u, v in zip(edges_u, edges_v):
        ra, rb = find(int(u)), find(int(v) + nu)
        if ra != rb:
            parent[rb] = ra
    comp = {}
    out = np.empty(len(edges_u), np.int64)
    for i, u in enumerate(edges_u):
        root = find(int(u))
        out[i] = comp.setdefault(root, len(comp))
    return out
