"""CyberML (reference: src/main/python/mmlspark/cyber/, 1962 LoC pure python)."""

from .anomaly import (AccessAnomaly, AccessAnomalyModel,
                      ComplementAccessTransformer, connected_components)
from .feature import (IdIndexer, IdIndexerModel, LinearScalarScaler,
                      LinearScalarScalerModel, StandardScalarScaler,
                      StandardScalarScalerModel)

__all__ = [
    "AccessAnomaly", "AccessAnomalyModel", "ComplementAccessTransformer",
    "connected_components",
    "IdIndexer", "IdIndexerModel",
    "StandardScalarScaler", "StandardScalarScalerModel",
    "LinearScalarScaler", "LinearScalarScalerModel",
]
