"""CyberML feature utilities: per-tenant indexers and scalers.

Reference: src/main/python/mmlspark/cyber/feature/indexers.py (partitioned id
indexers — contiguous ids per tenant) and feature/scalers.py (standard / linear
per-partition scalers). Pure-python in the reference too; here the grouping is
vectorized numpy over the tenant column.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..core import params as _p
from ..core.dataframe import DataFrame
from ..core.pipeline import Estimator, Model


class IdIndexer(Estimator):
    """String ids -> per-tenant contiguous ints (cyber/feature/indexers.py)."""
    inputCol = _p.Param("inputCol", "raw id column", "id")
    partitionKey = _p.Param("partitionKey", "tenant column", "tenant")
    outputCol = _p.Param("outputCol", "indexed id column", "id_idx")
    resetPerPartition = _p.Param("resetPerPartition",
                                 "ids restart at 1 per tenant", True, bool)

    def _fit(self, df: DataFrame) -> "IdIndexerModel":
        tenants = df[self.get("partitionKey")]
        ids = df[self.get("inputCol")]
        mapping: Dict[Tuple, int] = {}
        per_tenant_next: Dict[object, int] = {}
        reset = self.get("resetPerPartition")
        global_next = [1]
        for t, v in zip(tenants, ids):
            key = (t, v) if reset else (None, v)
            if key not in mapping:
                if reset:
                    nxt = per_tenant_next.get(t, 1)
                    mapping[key] = nxt
                    per_tenant_next[t] = nxt + 1
                else:
                    mapping[key] = global_next[0]
                    global_next[0] += 1
        model = IdIndexerModel(mapping=mapping)
        for p in ("inputCol", "partitionKey", "outputCol",
                  "resetPerPartition"):
            model.set(p, self.get(p))
        return model


class IdIndexerModel(Model):
    inputCol = _p.Param("inputCol", "raw id column", "id")
    partitionKey = _p.Param("partitionKey", "tenant column", "tenant")
    outputCol = _p.Param("outputCol", "indexed id column", "id_idx")
    resetPerPartition = _p.Param("resetPerPartition", "per-tenant ids", True,
                                 bool)
    mapping = _p.Param("mapping", "(tenant, id) -> int", None, complex=True)

    def __init__(self, mapping=None, **kw):
        super().__init__(**kw)
        if mapping is not None:
            self.set("mapping", mapping)

    def transform(self, df: DataFrame) -> DataFrame:
        mapping = self.get("mapping")
        reset = self.get("resetPerPartition")
        tenants = df[self.get("partitionKey")]
        ids = df[self.get("inputCol")]
        out = np.array([mapping.get((t if reset else None, v), 0)
                        for t, v in zip(tenants, ids)], np.int64)
        return df.with_column(self.get("outputCol"), out)


class _PerTenantScalerBase(Estimator):
    inputCol = _p.Param("inputCol", "value column", "value")
    partitionKey = _p.Param("partitionKey", "tenant column", "tenant")
    outputCol = _p.Param("outputCol", "scaled column", "scaled")

    def _tenant_groups(self, df: DataFrame):
        tenants = df[self.get("partitionKey")]
        vals = np.asarray(df[self.get("inputCol")], np.float64)
        groups: Dict[object, np.ndarray] = {}
        for t in set(tenants.tolist()):
            groups[t] = vals[np.array([x == t for x in tenants])]
        return tenants, vals, groups


class StandardScalarScaler(_PerTenantScalerBase):
    """Per-tenant (x - mean) / std (cyber/feature/scalers.py)."""
    coefficientFactor = _p.Param("coefficientFactor", "std multiplier", 1.0,
                                 float)

    def _fit(self, df: DataFrame) -> "StandardScalarScalerModel":
        _, _, groups = self._tenant_groups(df)
        stats = {t: (float(v.mean()), float(v.std()) or 1.0)
                 for t, v in groups.items()}
        model = StandardScalarScalerModel(stats=stats)
        for p in ("inputCol", "partitionKey", "outputCol",
                  "coefficientFactor"):
            model.set(p, self.get(p))
        return model


class StandardScalarScalerModel(Model):
    inputCol = _p.Param("inputCol", "value column", "value")
    partitionKey = _p.Param("partitionKey", "tenant column", "tenant")
    outputCol = _p.Param("outputCol", "scaled column", "scaled")
    coefficientFactor = _p.Param("coefficientFactor", "std multiplier", 1.0,
                                 float)
    stats = _p.Param("stats", "tenant -> (mean, std)", None, complex=True)

    def __init__(self, stats=None, **kw):
        super().__init__(**kw)
        if stats is not None:
            self.set("stats", stats)

    def transform(self, df: DataFrame) -> DataFrame:
        stats = self.get("stats")
        k = self.get("coefficientFactor")
        tenants = df[self.get("partitionKey")]
        vals = np.asarray(df[self.get("inputCol")], np.float64)
        out = np.empty(len(vals))
        for i, (t, v) in enumerate(zip(tenants, vals)):
            mean, std = stats.get(t, (0.0, 1.0))
            out[i] = (v - mean) / (std * k if std else 1.0)
        return df.with_column(self.get("outputCol"), out)


class LinearScalarScaler(_PerTenantScalerBase):
    """Per-tenant min-max to [minRequiredValue, maxRequiredValue]."""
    minRequiredValue = _p.Param("minRequiredValue", "output min", 0.0, float)
    maxRequiredValue = _p.Param("maxRequiredValue", "output max", 1.0, float)

    def _fit(self, df: DataFrame) -> "LinearScalarScalerModel":
        _, _, groups = self._tenant_groups(df)
        stats = {t: (float(v.min()), float(v.max())) for t, v in
                 groups.items()}
        model = LinearScalarScalerModel(stats=stats)
        for p in ("inputCol", "partitionKey", "outputCol", "minRequiredValue",
                  "maxRequiredValue"):
            model.set(p, self.get(p))
        return model


class LinearScalarScalerModel(Model):
    inputCol = _p.Param("inputCol", "value column", "value")
    partitionKey = _p.Param("partitionKey", "tenant column", "tenant")
    outputCol = _p.Param("outputCol", "scaled column", "scaled")
    minRequiredValue = _p.Param("minRequiredValue", "output min", 0.0, float)
    maxRequiredValue = _p.Param("maxRequiredValue", "output max", 1.0, float)
    stats = _p.Param("stats", "tenant -> (min, max)", None, complex=True)

    def __init__(self, stats=None, **kw):
        super().__init__(**kw)
        if stats is not None:
            self.set("stats", stats)

    def transform(self, df: DataFrame) -> DataFrame:
        stats = self.get("stats")
        lo_t, hi_t = self.get("minRequiredValue"), self.get("maxRequiredValue")
        tenants = df[self.get("partitionKey")]
        vals = np.asarray(df[self.get("inputCol")], np.float64)
        out = np.empty(len(vals))
        for i, (t, v) in enumerate(zip(tenants, vals)):
            lo, hi = stats.get(t, (0.0, 1.0))
            frac = (v - lo) / (hi - lo) if hi > lo else 0.5
            out[i] = lo_t + frac * (hi_t - lo_t)
        return df.with_column(self.get("outputCol"), out)
