"""Data plane: HTTP stack, serving, file readers (reference: io/, 16 files +
Spark Serving, 5 files)."""

from .files import (decode_image, read_binary_files, read_csv,
                    read_images, read_libsvm, write_to_powerbi)
from .http import (AsyncClient, CustomInputParser, CustomOutputParser,
                   HTTPRequestData, HTTPResponseData, HTTPTransformer,
                   JSONInputParser, JSONOutputParser, KeepAliveTransport,
                   SimpleHTTPTransformer, StringOutputParser,
                   send_with_retries)
from .rowcodec import BufferPool, ShardReader
from .shardstore import (ShardStore, ShardStoreError, ShardStoreWriter,
                         ShardVerifyError, as_store, fit_bin_mapper,
                         host_rss_bytes, is_store_path, read_column,
                         stream_fit_arrays, write_store)
from .registry import (ModelRegistry, RegistryError, RegistryModelSource,
                       golden_reply_digest, load_aot_callable)
from .serving import (DynamicBatcher, HTTPStreamSource, ServingServer,
                      ServingUDFs, SwapResult, make_reply, parse_request)
from .autoscale import Autoscaler
from .shared import (PartitionConsolidator, RateLimiter, SharedSingleton,
                     SharedVariable)
from .streaming import FileStreamSource, StreamingQuery
from .distributed_serving import (DistributedServingServer, ServiceInfo,
                                  ServingCoordinator, fetch_routes,
                                  register_with_retries)
from .port_forwarding import Forwarder, forward_port_to_remote

__all__ = [
    "HTTPRequestData", "HTTPResponseData", "HTTPTransformer",
    "SimpleHTTPTransformer", "JSONInputParser", "JSONOutputParser",
    "StringOutputParser", "CustomInputParser", "CustomOutputParser",
    "AsyncClient", "send_with_retries", "KeepAliveTransport",
    "ServingServer", "ServingUDFs", "HTTPStreamSource", "parse_request",
    "make_reply", "DynamicBatcher", "BufferPool", "SwapResult",
    "ShardReader", "ShardStore", "ShardStoreError", "ShardStoreWriter",
    "ShardVerifyError", "as_store", "fit_bin_mapper", "host_rss_bytes",
    "is_store_path", "read_column", "stream_fit_arrays", "write_store",
    "ModelRegistry", "RegistryError", "RegistryModelSource",
    "golden_reply_digest", "load_aot_callable", "Autoscaler",
    "SharedSingleton", "SharedVariable", "PartitionConsolidator",
    "RateLimiter",
    "read_binary_files", "read_images", "read_csv", "read_libsvm",
    "decode_image", "write_to_powerbi",
    "FileStreamSource", "StreamingQuery",
    "ServingCoordinator", "DistributedServingServer", "ServiceInfo",
    "fetch_routes", "register_with_retries",
    "Forwarder", "forward_port_to_remote",
]
