"""Process-shared singletons + PartitionConsolidator.

Reference: io/http/SharedVariable.scala:18-65 (`SharedVariable`/
`SharedSingleton` — one cell per JVM keyed by constructor; the trick serving
uses to share servers across tasks) and io/http/PartitionConsolidator.scala:
17-132 (funnel many partitions' work through one per-executor resource, e.g.
one rate-limited connection).

In the single-process host runtime "per-JVM" becomes "per-process": the
registry is a module-level dict; PartitionConsolidator becomes a transformer
that routes all row processing through one shared, optionally rate-limited
worker."""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional

import numpy as np

from ..core import params as _p
from ..core.dataframe import DataFrame
from ..core.pipeline import Transformer

_REGISTRY: Dict[str, Any] = {}
_REGISTRY_LOCK = threading.Lock()


class SharedSingleton:
    """One instance per process per key (SharedVariable.scala:37)."""

    def __init__(self, ctor: Callable[[], Any], key: Optional[str] = None):
        self.key = key or f"{ctor.__module__}.{getattr(ctor, '__qualname__', repr(ctor))}"
        self._ctor = ctor

    def get(self) -> Any:
        with _REGISTRY_LOCK:
            if self.key not in _REGISTRY:
                _REGISTRY[self.key] = self._ctor()
            return _REGISTRY[self.key]

    @staticmethod
    def clear(key: Optional[str] = None) -> None:
        with _REGISTRY_LOCK:
            if key is None:
                _REGISTRY.clear()
            else:
                _REGISTRY.pop(key, None)


SharedVariable = SharedSingleton  # surface alias


class RateLimiter:
    """Token-per-interval limiter shared by all callers."""

    def __init__(self, min_interval_s: float):
        self.min_interval_s = min_interval_s
        self._lock = threading.Lock()
        self._last = 0.0

    def acquire(self) -> None:
        with self._lock:
            now = time.perf_counter()
            wait = self._last + self.min_interval_s - now
            if wait > 0:
                time.sleep(wait)
                now = time.perf_counter()
            self._last = now


class PartitionConsolidator(Transformer, _p.HasInputCol, _p.HasOutputCol):
    """Route every row through ONE shared worker function, optionally rate
    limited (PartitionConsolidator.scala:17-132). The worker is held in the
    process-wide registry so concurrent transforms share it."""

    fn = _p.Param("fn", "value -> value worker function", None, complex=True)
    requestsPerSecond = _p.Param("requestsPerSecond",
                                 "rate cap; 0 = unlimited", 0.0, float)
    sharedKey = _p.Param("sharedKey",
                         "registry key for the shared limiter", None)

    def transform(self, df: DataFrame) -> DataFrame:
        fn: Callable = self.get("fn")
        rps = self.get("requestsPerSecond")
        limiter: Optional[RateLimiter] = None
        if rps and rps > 0:
            key = self.get("sharedKey") or f"consolidator:{self.uid}"
            limiter = SharedSingleton(
                lambda: RateLimiter(1.0 / rps), key=key).get()
        col = df[self.get("inputCol")]
        out = np.empty(len(df), dtype=object)
        for i, v in enumerate(col):
            if limiter is not None:
                limiter.acquire()
            out[i] = fn(v)
        return df.with_column(self.get("outputCol"), out)
