"""Serving — per-host HTTP servers feeding batched model inference.

Reference: Spark Serving (SURVEY.md §2.3 "Spark Serving" + §3.4 request path):
- HTTPSource.scala:1-227 (driver-hosted v1 source/sink, micro-batch offsets)
- DistributedHTTPSource.scala:26-424 (`JVMSharedServer` per-executor servers,
  `MultiChannelMap` round-robin channels, reply-on-owning-JVM routing)
- continuous/HTTPSourceV2.scala:45-715 (continuous mode: long-lived readers,
  epoch markers, driver routing table), HTTPSinkV2.scala, ServingUDFs.scala.

TPU design: Spark's micro-batch tick becomes a continuous dispatcher thread —
requests land in a queue, are grouped into a dynamic batch (up to maxBatchSize
ROWS — one binary request may carry many rows — or the fill budget, whichever
first), run through the pipeline as ONE DataFrame (one jitted device call),
and replies route back to the owning socket by id — the
JVMSharedServer.respond(batchId, uuid, ...) analogue without JVM hops.
Sub-ms p50 needs the compiled program resident: warm it with `warmup()`.

Round 12 (serving data plane): the fixed maxLatencyMs window became a
DEADLINE-DRIVEN fill policy (`DynamicBatcher`, mode "continuous"): a batch
keeps admitting requests while the OLDEST request's threaded X-Deadline-Ms
budget (minus a measured EWMA dispatch-time estimate) allows, bailing to
launch after `idle_grace_ms` without an arrival so sparse traffic keeps the
legacy latency. Reply serialization is offloaded to a writer thread, so the
dispatcher assembles batch k+1 while batch k's replies are still being
written (no dead time between batches). Request decode is vectorized: the
binary row format (io/rowcodec.py) assembles a whole batch into a pooled
device-bound array with ONE host copy; JSON stays as the per-row fallback.

Round 13 (model lifecycle): the handler is no longer fixed at construction.
`hot_swap()` loads + warms the NEXT model version on a background thread
(digest-probing a golden row, io/registry.py) while the old handler keeps
serving, then flips atomically between batches via `_install_handler` —
the ONE designated mutation point for `self.handler` (AST-linted in
tests/test_model_lifecycle.py), so no in-flight batch can ever observe a
torn swap. Any load/warm/digest failure is a counted rollback
(`serving_swap_events_total{outcome}`) — the old version keeps serving,
never a crash. `drain()` is the retire discipline's middle step
(deregister -> drain -> stop) for the autoscaler (io/autoscale.py).
"""

from __future__ import annotations

import asyncio
import itertools
import json
import math
import queue
import threading
import time
import urllib.parse
import uuid as _uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..core.dataframe import DataFrame
from ..core.pipeline import Transformer
from ..observability import (EventLog, TRACE_HEADER, get_registry,
                             mint_trace_id, trace_id_from_headers)
from ..observability.tracing import drain_payload
from ..resilience import Deadline
from . import rowcodec


#: deterministic per-process instance labels (construction order) so
#: concurrent servers sharing the global registry never collide
_INSTANCE_SEQ = itertools.count()


def _since_of(path: str) -> float:
    """`since` cursor of a `GET /trace?since=<ts>` path (0.0 = full ring;
    a malformed cursor must not 500 the drain — it degrades to a full
    drain, which the collector dedups by ts anyway). float() parses
    'nan'/'inf' without raising, and a NaN cursor would make every
    ts > since comparison False — a PERMANENTLY empty drain masquerading
    as a quiet ring — so non-finite values degrade like any other
    malformed cursor."""
    qs = urllib.parse.urlsplit(path).query
    try:
        since = float(urllib.parse.parse_qs(qs).get("since", ["0"])[0])
    except (TypeError, ValueError):
        return 0.0
    return since if math.isfinite(since) else 0.0


class _PendingRequest:
    __slots__ = ("rid", "body", "headers", "path", "event", "response",
                 "deadline", "deadline_from_client", "trace_id", "t_enq",
                 "nrows", "bin", "_loop", "_fut", "_cb")

    def __init__(self, rid, body, headers, path, loop=None, fut=None,
                 on_complete=None):
        self.rid = rid
        self.body = body
        self.headers = headers
        self.path = path
        self.event = threading.Event()
        self.response: Optional[Dict[str, Any]] = None
        # remaining request budget, propagated hop-to-hop via X-Deadline-Ms:
        # an expired request is answered 504 instead of occupying batch slots
        self.deadline: Optional[Deadline] = Deadline.from_headers(headers)
        # budget PROVENANCE: the continuous batcher may only spend a budget
        # the CLIENT declared (its stated latency tolerance). The gateway
        # stamps every forward with a deadline for expiry/retry safety and
        # marks the hop-protection ones X-Deadline-Source: gateway — those
        # must not make the batcher hold a 30 s default open for fill
        src = "client"
        for k, v in (headers or {}).items():
            if k.lower() == "x-deadline-source":
                src = str(v).lower()
                break
        self.deadline_from_client: bool = (self.deadline is not None
                                           and src != "gateway")
        # end-to-end trace identity: accepted from the client/gateway via
        # X-Trace-Id or minted here; every reply carries it back and every
        # hop's EventLog spans key on it
        self.trace_id: str = trace_id_from_headers(headers) or mint_trace_id()
        # span clock origin: queue_wait and the latency histogram both
        # measure from this enqueue stamp
        self.t_enq: float = time.perf_counter()
        # row-aware batching: a binary-format body may carry many rows
        # (rowcodec header parsed at admission, payload untouched); JSON
        # bodies are one row each
        self.nrows: int = 1
        self.bin: Optional[rowcodec.BinaryHeader] = None
        # asyncio completion route: the dispatcher thread resolves the
        # connection coroutine's future via its event loop instead of an
        # Event the socket thread would block on
        self._loop = loop
        self._fut = fut
        # coalesced-pack route: the part's reply feeds an aggregator
        # instead of a socket (gateway coalescing, io/rowcodec.py packs)
        self._cb = on_complete

    def complete(self, response: Dict[str, Any]) -> None:
        """Deliver the reply to whichever listener produced this request
        (threaded: Event; asyncio: future on the listener's loop;
        coalesced part: the pack aggregator's callback)."""
        self.response = response
        if self._cb is not None:
            self._cb(self)
        elif self._loop is not None:
            def _set():
                if not self._fut.done():
                    self._fut.set_result(response)
            try:
                self._loop.call_soon_threadsafe(_set)
            except RuntimeError:
                # listener shut down mid-batch: the client is gone, and the
                # dispatcher must not die delivering to a closed loop
                pass
        else:
            self.event.set()


def _make_http_listener(enqueue: Callable[["_PendingRequest"], None],
                        request_timeout: float, host: str,
                        port: int, health_fn=None,
                        metrics_fn=None, trace_fn=None
                        ) -> ThreadingHTTPServer:
    """Shared HTTP front door for ServingServer and HTTPStreamSource: POST
    bodies become _PendingRequests handed to `enqueue`; the socket thread
    blocks on the request's event until a dispatcher/commit sets the reply
    (JVMSharedServer's handler role, DistributedHTTPSource.scala:151-168).
    GET /health serves `health_fn()` as JSON when provided (queue depth +
    dispatcher liveness — the load-balancer probe endpoint); GET /metrics
    serves `metrics_fn()` as Prometheus text (the scrape endpoint); GET
    /trace?since=<ts> serves `trace_fn(since)` as JSON (the EventLog
    drain the fleet TraceCollector polls — docs/OBSERVABILITY.md).
    Returns the bound (but not yet serving) server; callers start
    `serve_forever` on a daemon thread."""

    class Handler(BaseHTTPRequestHandler):
        # HTTP/1.1: clients (and the keep-alive gateway transport) reuse
        # the connection; every response path below sets Content-Length
        protocol_version = "HTTP/1.1"

        def do_POST(self):
            length = int(self.headers.get("Content-Length") or 0)
            body = self.rfile.read(length) if length else b""
            pend = _PendingRequest(str(_uuid.uuid4()), body,
                                   dict(self.headers), self.path)
            enqueue(pend)
            ok = pend.event.wait(request_timeout)
            if not ok:
                self.send_response(504)
                self.send_header(TRACE_HEADER, pend.trace_id)
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
            resp = pend.response
            self.send_response(resp["status"])
            self.send_header("Content-Type", "application/json")
            self.send_header(TRACE_HEADER, pend.trace_id)
            for k, v in (resp.get("headers") or {}).items():
                self.send_header(k, v)
            self.send_header("Content-Length", str(len(resp["body"])))
            self.end_headers()
            self.wfile.write(resp["body"])

        def do_GET(self):
            if self.path == "/health" and health_fn is not None:
                body = json.dumps(health_fn()).encode()
                ctype = "application/json"
            elif self.path == "/metrics" and metrics_fn is not None:
                body = metrics_fn().encode()
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            elif self.path.startswith("/trace") and trace_fn is not None:
                body = json.dumps(trace_fn(_since_of(self.path))).encode()
                ctype = "application/json"
            else:
                self.send_response(404)
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):  # quiet
            pass

    class Server(ThreadingHTTPServer):
        # burst tolerance: default backlog of 5 resets concurrent connects
        # (the reference uses 100-thread executor pools —
        # DistributedHTTPSource.scala)
        request_queue_size = 128
        daemon_threads = True

    return Server((host, port), Handler)


class _AsyncListener:
    """Persistent-connection asyncio HTTP front door (round-3 verdict #6).

    The threaded listener pays a thread handoff + Event wakeup + a fresh
    TCP connection per request (~1.8 ms p50 through http.server). This one
    keeps HTTP/1.1 connections open, parses requests with two buffered
    reads (header block, then exact body), and parks each request on an
    asyncio future the dispatcher resolves via call_soon_threadsafe — the
    per-executor long-lived server role of the reference's continuous mode
    (DistributedHTTPSource.scala:89-202, continuous/HTTPSourceV2.scala),
    with sub-ms localhost round-trips (tests/test_serving_latency.py).
    """

    def __init__(self, enqueue: Callable[["_PendingRequest"], None],
                 request_timeout: float, host: str, port: int,
                 health_fn=None, metrics_fn=None, trace_fn=None):
        self._enqueue = enqueue
        self._timeout = request_timeout
        self._health_fn = health_fn
        self._metrics_fn = metrics_fn
        self._trace_fn = trace_fn
        self.host, self.port = host, port
        self._loop = None
        self._server = None
        self._thread = None
        self._started = threading.Event()

    async def _handle_conn(self, reader, writer):
        import socket as _socket
        sock = writer.get_extra_info("socket")
        if sock is not None:
            # no Nagle delay on tiny JSON replies
            sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
        loop = self._loop
        reasons = {200: b"OK", 400: b"Bad Request", 404: b"Not Found",
                   500: b"Internal Server Error", 501: b"Not Implemented",
                   503: b"Service Unavailable", 504: b"Gateway Timeout"}

        def status_line(code):
            return b"HTTP/1.1 %d %s\r\n" % (code, reasons.get(code, b"OK"))

        try:
            while True:
                # malformed/truncated/oversized requests close the
                # connection (or reply 4xx) instead of leaking a task
                # exception into the asyncio log
                try:
                    head = await reader.readuntil(b"\r\n\r\n")
                except (asyncio.IncompleteReadError, ConnectionResetError,
                        asyncio.LimitOverrunError):
                    return
                lines = head.decode("latin1").split("\r\n")
                parts = lines[0].split(" ")
                method = parts[0].upper() if parts else ""
                path = parts[1] if len(parts) > 1 else "/"
                length = 0
                keep_alive = True
                headers = {}
                try:
                    for ln in lines[1:]:
                        if not ln:
                            continue
                        k, _, v = ln.partition(":")
                        headers[k.strip()] = v.strip()
                        kl = k.strip().lower()
                        if kl == "content-length":
                            length = int(v)
                        elif kl == "connection" and "close" in v.lower():
                            keep_alive = False
                except ValueError:
                    writer.write(status_line(400)
                                 + b"Content-Length: 0\r\n\r\n")
                    await writer.drain()
                    return
                try:
                    body = (await reader.readexactly(length)
                            if length else b"")
                except (asyncio.IncompleteReadError, ConnectionResetError):
                    return
                if method == "GET" and (
                        (path == "/health" and self._health_fn is not None)
                        or (path == "/metrics"
                            and self._metrics_fn is not None)
                        or (path.startswith("/trace")
                            and self._trace_fn is not None)):
                    if path == "/health":
                        hb = json.dumps(self._health_fn()).encode()
                        ct = b"application/json"
                    elif path == "/metrics":
                        hb = self._metrics_fn().encode()
                        ct = b"text/plain; version=0.0.4; charset=utf-8"
                    else:
                        hb = json.dumps(
                            self._trace_fn(_since_of(path))).encode()
                        ct = b"application/json"
                    writer.write(
                        status_line(200)
                        + b"Content-Type: %s\r\n"
                        b"Content-Length: %d\r\n\r\n%s" % (ct, len(hb), hb))
                    await writer.drain()
                    if not keep_alive:
                        return
                    continue
                if method != "POST":
                    # other non-POST traffic must not reach the inference
                    # batcher (matches the threaded listener's POST-only
                    # handler)
                    writer.write(status_line(501)
                                 + b"Content-Length: 0\r\n\r\n")
                    await writer.drain()
                    if not keep_alive:
                        return
                    continue
                fut = loop.create_future()
                pend = _PendingRequest(str(_uuid.uuid4()), body, headers,
                                       path, loop=loop, fut=fut)
                self._enqueue(pend)
                try:
                    resp = await asyncio.wait_for(fut, self._timeout)
                except asyncio.TimeoutError:
                    writer.write(status_line(504)
                                 + b"%s: %s\r\n" % (
                                     TRACE_HEADER.encode("latin1"),
                                     pend.trace_id.encode("latin1"))
                                 + b"Content-Length: 0\r\n\r\n")
                    await writer.drain()
                    continue
                rb = resp["body"]
                hdrs = {TRACE_HEADER: pend.trace_id,
                        **(resp.get("headers") or {})}
                extra = b"".join(
                    b"%s: %s\r\n" % (k.encode("latin1"), str(v).encode(
                        "latin1"))
                    for k, v in hdrs.items())
                writer.write(
                    status_line(resp["status"])
                    + b"Content-Type: application/json\r\n" + extra
                    + b"Content-Length: %d\r\n\r\n%s" % (len(rb), rb))
                await writer.drain()
                if not keep_alive:
                    return
        finally:
            try:
                writer.close()
            except Exception:
                pass

    def _run(self):
        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)

        async def _serve():
            self._server = await asyncio.start_server(
                self._handle_conn, self.host, self.port)
            self.port = self._server.sockets[0].getsockname()[1]
            self._started.set()
            async with self._server:
                await self._server.serve_forever()

        try:
            self._loop.run_until_complete(_serve())
        except asyncio.CancelledError:
            pass
        finally:
            self._loop.close()

    def start(self) -> "_AsyncListener":
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        if not self._started.wait(10):
            raise RuntimeError("asyncio listener failed to start")
        return self

    def stop(self) -> None:
        if self._loop is not None and self._loop.is_running():
            def _shutdown():
                if self._server is not None:
                    self._server.close()
                for task in asyncio.all_tasks(self._loop):
                    task.cancel()
            self._loop.call_soon_threadsafe(_shutdown)


def parse_request(requests: List[_PendingRequest],
                  vector_cols=()) -> DataFrame:
    """JSON request bodies -> DataFrame (IOImplicits.parseRequest:126+).
    Bodies must be JSON objects with consistent keys; values may be scalars
    or lists (vectors)."""
    rows = []
    for r in requests:
        try:
            rows.append(json.loads(r.body.decode("utf-8")) if r.body else {})
        except ValueError:
            rows.append({})
    keys = sorted({k for row in rows for k in row})
    data: Dict[str, Any] = {"id": np.array([r.rid for r in requests],
                                           dtype=object)}
    for k in keys:
        vals = [row.get(k) for row in rows]
        if vals and isinstance(vals[0], list) or k in vector_cols:
            data[k] = np.stack([np.asarray(v, np.float32) for v in vals])
        else:
            data[k] = np.asarray(vals)
    return DataFrame(data)


def _json_reply(col: str, v) -> bytes:
    """One row's JSON reply body (the make_reply per-row codec)."""
    if isinstance(v, np.ndarray):
        v = v.tolist()
    elif isinstance(v, (np.integer,)):
        v = int(v)
    elif isinstance(v, (np.floating,)):
        v = float(v)
    return json.dumps({col: v}).encode("utf-8")


def make_reply(df: DataFrame, col: str) -> List[bytes]:
    """Serialize one column back to per-row JSON replies
    (IOImplicits.makeReply:176)."""
    return [_json_reply(col, v) for v in df[col]]


class DynamicBatcher:
    """Batch fill policy: legacy fixed window or deadline-driven continuous.

    Pure decision logic with an injectable clock (`clock()` -> seconds) so
    tests drive it against seeded arrival traces deterministically —
    tests/test_serving_dataplane.py proves the continuous mode fills
    strictly more than the fixed window at equal-or-lower p99 on the same
    trace, and that no launched batch ever contains an expired request.

    - mode "fixed": fill while `now < first.t_enq + max_latency_ms`
      (the pre-round-12 window), with the remaining window computed once
      per wait so a near-empty queue no longer burns it in re-armed
      per-request sleeps.
    - mode "continuous": for deadline-carrying requests the fill budget is
      `oldest.deadline.remaining() - dispatch_est_s` — keep admitting
      until launching any later would violate the oldest request's
      threaded X-Deadline-Ms budget (the dispatch estimate is an EWMA of
      measured handler wall time, `observe_dispatch`). Waiting for the
      NEXT arrival is capped at `idle_grace_ms` (default: max_latency_ms)
      so sparse traffic launches at legacy latency instead of sitting on
      a large budget; requests without a deadline keep the fixed window.

    Batches are counted in ROWS (`_PendingRequest.nrows`): one binary
    request may carry a whole client-side batch.
    """

    MODES = ("continuous", "fixed")

    def __init__(self, max_rows: int, max_latency_ms: float,
                 mode: str = "continuous",
                 idle_grace_ms: Optional[float] = None,
                 clock: Callable[[], float] = time.perf_counter,
                 est_alpha: float = 0.25):
        if mode not in self.MODES:
            raise ValueError(f"batching mode must be one of {self.MODES}, "
                             f"got {mode!r}")
        self.max_rows = max_rows
        self.max_latency_ms = max_latency_ms
        self.mode = mode
        self.idle_grace_ms = (max_latency_ms if idle_grace_ms is None
                              else idle_grace_ms)
        self.clock = clock
        self.est_alpha = est_alpha
        #: EWMA of measured handler wall seconds per batch — the dispatch
        #: cost subtracted from the oldest request's remaining budget
        self.dispatch_est_s = 0.0

    def observe_dispatch(self, seconds: float) -> None:
        if self.dispatch_est_s == 0.0:
            self.dispatch_est_s = seconds
        else:
            self.dispatch_est_s += self.est_alpha * (seconds
                                                     - self.dispatch_est_s)

    @staticmethod
    def _deadline_driven(oldest: "_PendingRequest") -> bool:
        """Budget-fill applies only to a budget the CLIENT declared: the
        gateway's hop-protection deadline (X-Deadline-Source: gateway)
        must not hold moderate traffic open toward a 30 s default — those
        requests keep the fixed window."""
        return (oldest.deadline is not None
                and getattr(oldest, "deadline_from_client", True))

    def fill_budget_s(self, oldest: "_PendingRequest", now: float,
                      t_start: float) -> float:
        """Seconds this batch may keep filling before it must launch.
        The fixed window anchors at FILL START (`t_start`) — the legacy
        contract: a backlogged request that already out-waited the window
        still gets a full fill pass; the continuous budget anchors at the
        oldest request's absolute deadline."""
        window = (t_start + self.max_latency_ms / 1000.0) - now
        if self.mode == "fixed" or not self._deadline_driven(oldest):
            return window
        return oldest.deadline.remaining() - self.dispatch_est_s

    def collect(self, first: "_PendingRequest", try_get,
                should_stop=None) -> List["_PendingRequest"]:
        """Assemble one batch starting from `first`.

        `try_get(timeout_s)` returns the next pending request or None
        (timeout 0 = non-blocking drain). The injected clock/try_get pair
        is what makes this testable against a scripted trace.

        The fill budget is the TIGHTEST constraint across everything
        admitted so far — the minimum deadline budget over the batch's
        client-deadline members (not just the oldest: a 50 ms request
        admitted into a 10 s-budget batch must pull the launch forward,
        not expire mid-fill), AND the fixed window whenever any member
        does not budget-fill."""
        batch = [first]
        rows = first.nrows
        t_start = self.clock()

        def driven(p):
            return self.mode == "continuous" and self._deadline_driven(p)

        tight = first if driven(first) else None
        any_window = not driven(first)

        def budget_s(now):
            b = None
            if tight is not None:
                b = tight.deadline.remaining() - self.dispatch_est_s
            if any_window or tight is None:
                w = (t_start + self.max_latency_ms / 1000.0) - now
                b = w if b is None else min(b, w)
            return b

        while rows < self.max_rows:
            if should_stop is not None and should_stop():
                break
            budget = budget_s(self.clock())
            if budget <= 0:
                break
            pend = try_get(0.0)
            if pend is None:
                wait = budget
                if tight is not None:
                    # a large budget must not hold sparse traffic hostage:
                    # give the next arrival one idle grace, then launch
                    wait = min(wait, self.idle_grace_ms / 1000.0)
                if wait <= 0:
                    break
                pend = try_get(wait)
                if pend is None:
                    if tight is not None:
                        break          # idle grace expired: launch now
                    continue           # fixed: re-check remaining window
            batch.append(pend)
            rows += pend.nrows
            if driven(pend):
                if (tight is None or pend.deadline.remaining()
                        < tight.deadline.remaining()):
                    tight = pend
            else:
                any_window = True
        return batch

    @staticmethod
    def split_expired(batch: List["_PendingRequest"]
                      ) -> (List["_PendingRequest"], List["_PendingRequest"]):
        """(live, expired) at launch time — the invariant the dispatcher
        enforces: no launched batch ever contains an expired request."""
        live: List["_PendingRequest"] = []
        expired: List["_PendingRequest"] = []
        for pend in batch:
            if pend.deadline is not None and pend.deadline.expired:
                expired.append(pend)
            else:
                live.append(pend)
        return live, expired


class _PackAggregator:
    """Collects the per-part replies of a coalesced forward (gateway ->
    worker pack, io/rowcodec.py) and completes the outer HTTP request with
    the length-prefixed reply pack once every part has answered."""

    __slots__ = ("outer", "n", "_parts", "_left", "_lock")

    def __init__(self, outer: "_PendingRequest", n: int):
        self.outer = outer
        self.n = n
        self._parts: List[Optional[tuple]] = [None] * n
        self._left = n
        self._lock = threading.Lock()

    def feeder(self, i: int):
        def cb(sub: "_PendingRequest") -> None:
            resp = sub.response
            with self._lock:
                self._parts[i] = (resp["status"], resp["body"])
                self._left -= 1
                done = self._left == 0
            if done:
                body = rowcodec.encode_reply_pack(self._parts)
                self.outer.complete({
                    "status": 200,
                    "headers": {rowcodec.COALESCE_HEADER: str(self.n)},
                    "body": body})
        return cb


class SwapResult:
    """Outcome handle for one `hot_swap` attempt. `done` fires when the
    attempt resolves; `outcome` is one of "success", "rollback_load",
    "rollback_warm", "rollback_digest", "rejected" (a swap was already
    in flight). Rollbacks carry the triggering exception in `error`."""

    __slots__ = ("version", "outcome", "error", "done")

    def __init__(self, version):
        self.version = version
        self.outcome: Optional[str] = None
        self.error: Optional[BaseException] = None
        self.done = threading.Event()

    def _resolve(self, outcome: str,
                 error: Optional[BaseException] = None) -> None:
        self.outcome = outcome
        self.error = error
        self.done.set()


class ServingServer:
    """One host's serving endpoint: HTTP listener + dynamic-batch dispatcher.

    handler: DataFrame -> DataFrame (the user pipeline; e.g. model.transform).
    replyCol: which output column to serialize back.
    maxBatchSize / maxLatencyMs control the dynamic batcher: a batch launches
    when it holds maxBatchSize ROWS, or per the `batching` policy
    ("continuous" default: fill while the oldest request's X-Deadline-Ms
    budget minus the measured dispatch estimate allows, idle-grace bounded;
    "fixed": the legacy maxLatencyMs window — see DynamicBatcher).
    Binary-format bodies (io/rowcodec.py) may carry many rows per request
    and are assembled into a pooled device-bound array with one host copy;
    coalesced packs (X-Coalesced-Count) are split into per-part requests
    whose replies re-pack onto the one gateway connection.
    max_queue bounds the request queue (0 = unbounded): when full, new
    requests are SHED with 503 + Retry-After instead of growing an unbounded
    backlog that times every client out (load shedding under overload).
    Requests carrying an X-Deadline-Ms budget that has expired are answered
    504 without occupying batch slots. GET /health reports queue depth and
    dispatcher liveness; GET /metrics is the Prometheus scrape (request
    latency histogram, queue depth, shed/expired/error counters, batch-size
    and rows/s gauges). Each request's X-Trace-Id (accepted or minted) keys
    per-hop spans (queue_wait -> batch_assembly -> device_dispatch -> reply)
    in `self.events`, and every reply echoes the id back.
    """

    def __init__(self, handler: Callable[[DataFrame], DataFrame],
                 reply_col: str = "prediction", host: str = "127.0.0.1",
                 port: int = 8899, max_batch_size: int = 64,
                 max_latency_ms: float = 5.0, request_timeout: float = 30.0,
                 vector_cols=(), listener: str = "asyncio",
                 max_queue: int = 0, registry=None, event_log=None,
                 metrics_label: Optional[str] = None,
                 batching: str = "continuous",
                 idle_grace_ms: Optional[float] = None,
                 buffer_pool: Optional[rowcodec.BufferPool] = None,
                 clock: Callable[[], float] = time.perf_counter,
                 model_version: Optional[int] = None):
        self.reply_col = reply_col
        self.host, self.port = host, port
        self.max_batch_size = max_batch_size
        self.max_latency_ms = max_latency_ms
        self.request_timeout = request_timeout
        self.vector_cols = tuple(vector_cols)
        if listener not in ("asyncio", "thread"):
            raise ValueError(f"listener must be 'asyncio' or 'thread', "
                             f"got {listener!r}")
        self.listener = listener
        self.max_queue = max_queue
        self._queue: "queue.Queue[_PendingRequest]" = queue.Queue(
            maxsize=max_queue)
        self._clock = clock
        self.batcher = DynamicBatcher(max_batch_size, max_latency_ms,
                                      mode=batching,
                                      idle_grace_ms=idle_grace_ms,
                                      clock=clock)
        self.pool = buffer_pool if buffer_pool is not None \
            else rowcodec.BufferPool()
        # reply writing runs on its own thread so the dispatcher assembles
        # batch k+1 while batch k's replies are still being serialized
        self._reply_q: "queue.Queue" = queue.Queue()
        self._stop = threading.Event()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._alistener: Optional[_AsyncListener] = None
        self._threads: List[threading.Thread] = []
        self._disp_thread: Optional[threading.Thread] = None
        # telemetry: all counters/gauges/histograms live in the registry
        # (process-global by default, so one scrape carries every server
        # plus the fit-side bridge); the instance label keeps concurrent
        # servers' series apart deterministically (construction order)
        self.registry = registry if registry is not None else get_registry()
        self.events = event_log if event_log is not None else EventLog()
        self.metrics_label = (metrics_label if metrics_label is not None
                              else f"serving-{next(_INSTANCE_SEQ)}")
        lbl = {"instance": self.metrics_label}
        self._m = {
            "requests": self.registry.counter(
                "serving_requests_total", "requests dispatched to a batch",
                lbl),
            "batches": self.registry.counter(
                "serving_batches_total", "dynamic batches launched", lbl),
            "errors": self.registry.counter(
                "serving_errors_total", "requests answered 500", lbl),
            "shed": self.registry.counter(
                "serving_shed_total", "requests shed 503 (queue full)", lbl),
            "expired": self.registry.counter(
                "serving_expired_total",
                "requests answered 504 (X-Deadline-Ms spent)", lbl),
        }
        self._lat_hist = self.registry.histogram(
            "serving_request_latency_seconds",
            "enqueue-to-reply latency (p50/p95/p99 derivable)", lbl)
        self._cold_start_gauge = self.registry.gauge(
            "serving_cold_start_seconds",
            "start() to first successful reply (includes any first-request "
            "compile the cache/AOT layers did not absorb)", lbl)
        self._t_started: Optional[float] = None
        self._batch_gauge = self.registry.gauge(
            "serving_last_batch_size", "rows in the last batch", lbl)
        # the last-batch gauge alone cannot prove batching ENGAGES under
        # load: the histogram records every batch's row count (fill
        # distribution) and the fill-ratio gauge tracks rows/max_batch_size
        # of the last batch, so a load test can assert fill >= target
        self._batch_hist = self.registry.histogram(
            "serving_batch_rows", "rows per launched batch",
            lbl, buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512,
                          1024, 2048, 4096))
        self._fill_gauge = self.registry.gauge(
            "serving_batch_fill_ratio",
            "rows/max_batch_size of the last batch", lbl)
        self._est_gauge = self.registry.gauge(
            "serving_dispatch_estimate_s",
            "EWMA handler wall seconds (continuous-batching budget term)",
            lbl)
        self._m["coalesced_packs"] = self.registry.counter(
            "serving_coalesced_packs_total",
            "coalesced forwards split into per-part requests", lbl)
        self._rows_gauge = self.registry.gauge(
            "serving_rows_per_s", "handler throughput of the last batch",
            lbl)
        self._cb_gauges = [
            self.registry.gauge(
                "serving_queue_depth", "requests waiting for a batch slot",
                lbl),
            self.registry.gauge(
                "serving_dispatcher_alive",
                "1 while the dispatcher thread runs", lbl),
        ]
        self._cb_gauges[0].set_function(self._queue.qsize)
        self._cb_gauges[1].set_function(
            lambda: 1.0 if (self._disp_thread
                            and self._disp_thread.is_alive()) else 0.0)
        # ------------------------------------------------ model lifecycle
        # hot-swap state (round 13): the handler is installed ONLY through
        # _install_handler (AST-linted); swaps run on a background thread
        # and roll back counted on any load/warm/digest failure
        self._lbl = lbl
        self.model_version: Optional[int] = None
        self.swap_state: str = "idle"   # idle | loading | warming
        self.last_swap: Optional[Dict[str, Any]] = None
        self._swap_lock = threading.Lock()
        self._m_swaps: Dict[str, Any] = {}
        self._version_gauge = self.registry.gauge(
            "serving_model_version",
            "registry version of the installed handler (-1 = unversioned)",
            lbl)
        self._version_gauge.set(-1.0)
        pool_gauge = self.registry.gauge(
            "serving_pool_bytes",
            "bytes held in the staging BufferPool freelists", lbl)
        pool_gauge.set_function(lambda: float(self.pool.pooled_bytes))
        self._cb_gauges.append(pool_gauge)
        # drain bookkeeping: requests the dispatcher currently holds
        # (collect/inference) and reply jobs not yet fully written — with
        # the admission queue, these three together account for every
        # admitted-but-unanswered request (ServingServer.drain)
        self._work_lock = threading.Lock()
        self._dispatching = 0
        self._replying = 0
        self._install_handler(handler, version=model_version)

    @property
    def stats(self) -> Dict[str, int]:
        """Counter view (registry-backed; kept for the pre-observability
        `stats` dict consumers and the /health payload)."""
        return {k: int(c.value) for k, c in self._m.items()}

    # -------------------------------------------------------- model lifecycle
    def _install_handler(self, handler: Callable[[DataFrame], DataFrame],
                         version: Optional[int] = None) -> None:
        """THE designated handler mutation point (construction included).

        The flip is a single attribute rebind: the dispatcher reads
        `self.handler` exactly once per batch (`_run_batch`), so every
        batch — and therefore every in-flight request — runs entirely on
        one version; there is no torn state to observe. The AST lint in
        tests/test_model_lifecycle.py forbids any other `self.handler`
        assignment in this module, which is what makes that argument
        airtight rather than a convention.

        Installing also clears the staging BufferPool: the old model's
        batch buckets rarely match the new model's, and old-shape buffers
        would otherwise be stranded until the key-LRU happens to evict
        them (io/rowcodec.BufferPool)."""
        self.handler = handler
        if version is not None:
            self.model_version = int(version)
            self._version_gauge.set(float(version))
        self.pool.clear()

    def _swap_counter(self, outcome: str):
        c = self._m_swaps.get(outcome)
        if c is None:
            c = self.registry.counter(
                "serving_swap_events_total",
                "hot-swap attempts by outcome",
                {**self._lbl, "outcome": outcome})
            self._m_swaps[outcome] = c
        return c

    def hot_swap(self, load_fn: Callable[[], Callable],
                 version: Optional[int],
                 golden_body: Optional[bytes] = None,
                 expected_reply_sha256: Optional[str] = None,
                 wait_s: Optional[float] = None) -> SwapResult:
        """Zero-downtime handler swap: load + warm the next version on a
        background thread while the CURRENT handler keeps serving, then
        flip atomically between batches.

        `load_fn()` builds the new handler (for registry versions this
        includes digest verification — io/registry.RegistryModelSource);
        `golden_body` + `expected_reply_sha256` arm the first-batch
        digest probe: the golden row runs through the new handler (which
        also warms its compiled program) and the reply digest must match
        the publish-time digest. ANY failure — load exception, warm
        exception, digest mismatch — is a counted rollback
        (`serving_swap_events_total{outcome}`): the old handler keeps
        serving and the server never crashes.

        Returns a `SwapResult`; pass `wait_s` to block until it resolves
        (tests and synchronous callers)."""
        res = SwapResult(version)
        with self._swap_lock:
            if self.swap_state != "idle":
                # one swap at a time: the coordinator's rollout reissues
                # targets on later beats, so a rejected attempt is retried
                # naturally once the in-flight one resolves
                self._swap_counter("rejected").inc()
                res._resolve("rejected")
                return res
            self.swap_state = "loading"
        t = threading.Thread(
            target=self._do_swap,
            args=(res, load_fn, version, golden_body, expected_reply_sha256),
            daemon=True, name="hot-swap")
        t.start()
        if wait_s is not None:
            res.done.wait(wait_s)
        return res

    def _do_swap(self, res: SwapResult, load_fn, version,
                 golden_body, expected_reply_sha256) -> None:
        t0 = time.perf_counter()
        outcome, err, handler = "success", None, None
        try:
            handler = load_fn()
        except Exception as e:  # noqa: BLE001 - counted rollback, not crash
            outcome, err = "rollback_load", e
        if outcome == "success" and golden_body is not None:
            with self._swap_lock:
                self.swap_state = "warming"
            try:
                from .registry import golden_reply_digest
                digest = golden_reply_digest(handler, golden_body,
                                             self.reply_col)
            except Exception as e:  # noqa: BLE001
                outcome, err = "rollback_warm", e
            else:
                if (expected_reply_sha256 is not None
                        and digest != expected_reply_sha256):
                    outcome = "rollback_digest"
                    err = ValueError(
                        f"golden reply digest {digest[:12]}… != published "
                        f"{expected_reply_sha256[:12]}…")
        if outcome == "success":
            self._install_handler(handler, version=version)
        self._swap_counter(outcome).inc()
        self.events.append("swap", mint_trace_id(), version=version,
                           outcome=outcome,
                           dur_s=time.perf_counter() - t0)
        with self._swap_lock:
            self.last_swap = {"version": version, "outcome": outcome,
                              "error": (f"{type(err).__name__}: {err}"
                                        if err is not None else None)}
            self.swap_state = "idle"
        res._resolve(outcome, err)

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Wait until every admitted request is answered: no queued
        request (the queue's own `unfinished_tasks` — decremented only
        AFTER the dispatcher has counted the dequeue into `_dispatching`,
        so a just-dequeued-not-yet-counted request can never slip between
        the two checks), the dispatcher holding no batch, and no reply
        job pending. The retire discipline's middle step (deregister ->
        DRAIN -> stop, the PR 10 drain order applied to serving) —
        callers stop routing first, so this converges. Entry and outcome
        land as system events in the ring: a drain that TIMED OUT is
        exactly the kind of fact an incident bundle must carry."""
        t0 = time.perf_counter()
        deadline = time.monotonic() + timeout_s
        ok = False
        while time.monotonic() < deadline:
            with self._work_lock:
                busy = self._dispatching or self._replying
            if not busy and self._queue.unfinished_tasks == 0:
                ok = True
                break
            time.sleep(0.005)
        self.events.append("drain", mint_trace_id(),
                           dur_s=time.perf_counter() - t0,
                           outcome="ok" if ok else "timeout")
        return ok

    # ------------------------------------------------------------ admission
    def _accept(self, pend: _PendingRequest) -> None:
        """Listener entry point: route coalesced packs (one gateway forward
        carrying several client requests) into per-part pending requests,
        parse binary headers for row-aware batching, then admit."""
        npack = rowcodec.coalesced_count(pend.headers)
        if npack >= 2:
            try:
                parts = rowcodec.decode_pack(pend.body)
            except rowcodec.BinaryFormatError as e:
                pend.complete({"status": 400,
                               "body": json.dumps(
                                   {"error": f"bad pack: {e}"}).encode()})
                return
            if len(parts) != npack:
                pend.complete({"status": 400,
                               "body": b'{"error": "pack count mismatch"}'})
                return
            if self.max_queue and (self._queue.qsize() + npack
                                   > self.max_queue):
                # the pack does not fit: shed it WHOLE at the HTTP level so
                # the gateway fails the forward over to a less-loaded
                # worker (a partial admit would strand parts)
                self._m["shed"].inc(npack)
                self.events.append("shed", pend.trace_id, status=503,
                                   pack=npack)
                pend.complete({"status": 503,
                               "headers": {"Retry-After": "1"},
                               "body": b'{"error": "overloaded: '
                                       b'request queue full"}'})
                return
            self._m["coalesced_packs"].inc()
            agg = _PackAggregator(pend, npack)
            for i, (tid, pb) in enumerate(parts):
                sub = _PendingRequest(f"{pend.rid}:{i}", pb, pend.headers,
                                      pend.path, on_complete=agg.feeder(i))
                # each part keeps its OWN client trace id (carried in the
                # pack framing) so its worker spans join its end-to-end
                # trace; the pack/lead id is only the fallback
                sub.trace_id = tid or pend.trace_id
                self._submit(sub)
            return
        self._submit(pend)

    def _submit(self, pend: _PendingRequest) -> None:
        """Admission control between the listener and the batcher: expired
        budgets answer 504 immediately, a full queue sheds with 503 +
        Retry-After (the client's signal to back off and retry elsewhere).
        Binary bodies get their header parsed here (row count for the
        batcher's fill math; malformed binary answers 400)."""
        if pend.bin is None:
            try:
                h = rowcodec.peek(pend.body)
            except rowcodec.BinaryFormatError as e:
                pend.complete({"status": 400,
                               "body": json.dumps(
                                   {"error": f"bad binary body: {e}"}
                               ).encode()})
                return
            if h is not None:
                pend.bin = h
                pend.nrows = h.nrows
        if pend.deadline is not None and pend.deadline.expired:
            self._m["expired"].inc()
            self.events.append("expired", pend.trace_id, status=504)
            pend.complete({"status": 504,
                           "body": b'{"error": "deadline exceeded"}'})
            return
        try:
            self._queue.put_nowait(pend)
        except queue.Full:
            self._m["shed"].inc()
            self.events.append("shed", pend.trace_id, status=503)
            pend.complete({"status": 503,
                           "headers": {"Retry-After": "1"},
                           "body": b'{"error": "overloaded: '
                                   b'request queue full"}'})

    def health(self) -> Dict[str, Any]:
        """GET /health payload: queue depth + dispatcher liveness + the
        installed model version and last swap outcome (the rollout
        operator's per-worker view)."""
        return {"queue_depth": self._queue.qsize(),
                "max_queue": self.max_queue,
                "dispatcher_alive": bool(self._disp_thread
                                         and self._disp_thread.is_alive()),
                "listener": self.listener,
                "model_version": self.model_version,
                "swap_state": self.swap_state,
                "last_swap": dict(self.last_swap) if self.last_swap else None,
                "stats": dict(self.stats)}

    def metrics_text(self) -> str:
        """GET /metrics payload (Prometheus text exposition)."""
        return self.registry.render_prometheus()

    def trace_payload(self, since: float = 0.0) -> Dict[str, Any]:
        """GET /trace?since= payload: this hop's EventLog drained from
        the cursor (strictly newer events only) — the one shared drain
        contract (observability.tracing.drain_payload,
        docs/OBSERVABILITY.md)."""
        return drain_payload(self.metrics_label, self.events, since)

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "ServingServer":
        # armed BEFORE the listener accepts: the first reply may land
        # while start() is still returning
        self._t_started = time.perf_counter()
        # arm the persistent XLA compile cache before the first request can
        # trigger a handler compile: a re-scheduled worker deserializes the
        # executable instead of recompiling (no-op when disabled; AOT
        # artifacts are loaded model-side, e.g. Booster.
        # load_serving_artifacts — docs/SERVING.md "Cold start")
        try:
            from ..compile.cache import configure_persistent_cache
            configure_persistent_cache()
        except Exception:
            pass
        if self.listener == "asyncio":
            # persistent-connection listener: the sub-ms HTTP path
            self._alistener = _AsyncListener(
                self._accept, self.request_timeout, self.host, self.port,
                health_fn=self.health,
                metrics_fn=self.metrics_text,
                trace_fn=self.trace_payload).start()
            self.port = self._alistener.port
        else:
            self._httpd = _make_http_listener(self._accept,
                                              self.request_timeout,
                                              self.host, self.port,
                                              health_fn=self.health,
                                              metrics_fn=self.metrics_text,
                                              trace_fn=self.trace_payload)
            self.port = self._httpd.server_address[1]  # resolve port 0
            t_http = threading.Thread(target=self._httpd.serve_forever,
                                      daemon=True)
            t_http.start()
            self._threads.append(t_http)
        t_reply = threading.Thread(target=self._reply_loop, daemon=True)
        t_reply.start()
        self._threads.append(t_reply)
        t_disp = threading.Thread(target=self._dispatch_loop, daemon=True)
        t_disp.start()
        self._disp_thread = t_disp
        self._threads.append(t_disp)
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
        if self._alistener:
            self._alistener.stop()
        # freeze collect-time gauges: the registry outlives this server,
        # and a live callback would pin the stopped server (queue, handler
        # closure, model arrays) in memory forever. The dispatcher exits
        # within its 0.05 s poll of _stop, but the freeze must not race
        # it: a stopped server scrapes as NOT alive, by definition, and
        # its queue holds nothing servable
        for g in self._cb_gauges:
            g.set_function(None)
        self._cb_gauges[0].set(0.0)   # queue depth
        self._cb_gauges[1].set(0.0)   # dispatcher alive

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/"

    def warmup(self, example: Dict[str, Any]) -> None:
        """Run the pipeline once so the compiled program is resident
        (sub-ms latency needs no first-request compile)."""
        fake = _PendingRequest("warmup", json.dumps(example).encode(), {}, "/")
        df = parse_request([fake], self.vector_cols)
        self.handler(df.drop("id"))

    def serve_direct(self, body: bytes) -> bytes:
        """In-process continuous fast path: one request through the resident
        compiled pipeline, bypassing the HTTP socket — the analogue of the
        reference's continuous mode living inside the executor JVM
        (HTTPSourceV2 long-lived readers). This is the path the sub-ms
        latency claim (docs/mmlspark-serving.md:93) is measured on."""
        if rowcodec.is_binary(body):
            name, arr = rowcodec.decode(body)
            df = DataFrame({name: arr.reshape(-1, arr.shape[-1])})
            scored = self.handler(df)
            return rowcodec.encode_reply(self.reply_col,
                                         scored[self.reply_col])
        fake = _PendingRequest("direct", body, {}, "/")
        df = parse_request([fake], self.vector_cols)
        scored = self.handler(df.drop("id"))
        return make_reply(scored, self.reply_col)[0]

    # ------------------------------------------------------------ dispatcher
    def _try_get(self, timeout_s: float) -> Optional[_PendingRequest]:
        try:
            if timeout_s <= 0:
                return self._queue.get_nowait()
            return self._queue.get(timeout=timeout_s)
        except queue.Empty:
            return None

    def _dispatch_loop(self) -> None:
        try:
            self._dispatch_until_stopped()
        finally:
            # the reply-writer exit sentinel comes from HERE, after the
            # final batch's job is enqueued — a stop() racing an in-flight
            # dispatch must not let the sentinel overtake computed replies
            # (clients would wait out their timeout and the staging
            # buffer would leak)
            self._reply_q.put(None)

    def _dispatch_until_stopped(self) -> None:
        while not self._stop.is_set():
            first = self._try_get(0.05)
            if first is None:
                continue
            # drain accounting: from here until every group is dispatched
            # the dispatcher HOLDS requests that are in no queue. The
            # queue's unfinished_tasks stays >0 until the task_done calls
            # BELOW this increment, so drain() can never observe the
            # moment between dequeue and this count (its two checks
            # overlap by construction)
            with self._work_lock:
                self._dispatching += 1
            try:
                batch = self.batcher.collect(first, self._try_get,
                                             should_stop=self._stop.is_set)
                # every dequeued request (first + collected) is now held
                # and counted under _dispatching: retire its queue slot
                for _ in batch:
                    self._queue.task_done()
                # a request whose cross-hop budget expired while queued gets
                # its 504 now — it must not occupy a batch slot a live
                # request could use (the Deadline threading the gateway
                # forwards shrinks)
                live, expired = DynamicBatcher.split_expired(batch)
                for pend in expired:
                    self._m["expired"].inc()
                    self.events.append("expired", pend.trace_id, status=504)
                    pend.complete({"status": 504,
                                   "body": b'{"error": "deadline exceeded"}'})
                # a batch mixing wire formats (or binary schemas) cannot
                # share one staging array: run homogeneous sub-batches;
                # uniform traffic — the only shape the hot path sees —
                # stays one batch
                for group in self._partition(live):
                    self._run_batch(group)
            finally:
                with self._work_lock:
                    self._dispatching -= 1

    @staticmethod
    def _partition(batch: List[_PendingRequest]
                   ) -> List[List[_PendingRequest]]:
        groups: List[List[_PendingRequest]] = []
        keys: Dict[Any, int] = {}
        for pend in batch:
            key = (None if pend.bin is None
                   else (pend.bin.name, pend.bin.dtype.str, pend.bin.ncols))
            i = keys.get(key)
            if i is None:
                keys[key] = len(groups)
                groups.append([pend])
            else:
                groups[i].append(pend)
        return groups

    @staticmethod
    def _pow2_cap(rows: int) -> int:
        """Pad rows to the next power of two (last row repeated) so the
        jitted pipeline sees few distinct shapes — no per-batch-size
        retrace, stable tail latency. ALWAYS a true power of two: batches
        routinely overshoot max_batch_size (a whole multi-row binary
        request is admitted once any rows remain), and clamping there
        would hand the jit a fresh shape per batch — per-batch retrace,
        the exact stall the padding exists to prevent."""
        cap = 1
        while cap < rows:
            cap *= 2
        return cap

    def _run_batch(self, batch: List[_PendingRequest]) -> None:
        n_req = len(batch)
        rows = sum(p.nrows for p in batch)
        self._m["requests"].inc(n_req)
        self._m["batches"].inc()
        t0 = time.perf_counter()
        for pend in batch:
            self.events.append("queue_wait", pend.trace_id,
                               dur_s=t0 - pend.t_enq, rid=pend.rid)
        binh = batch[0].bin
        staging: Optional[np.ndarray] = None
        try:
            if binh is not None:
                # vectorized decode: every payload lands in one pooled
                # [cap, k] buffer — the single host copy between socket
                # bytes and the device-bound array (io/rowcodec.assemble)
                cap = self._pow2_cap(rows)
                staging, total = rowcodec.assemble(
                    [p.body for p in batch], [p.bin for p in batch],
                    self.pool, cap)
                df = DataFrame({binh.name: staging})
            else:
                df = parse_request(batch, self.vector_cols).drop("id")
                cap = self._pow2_cap(rows)
                if cap > rows:
                    idx = np.concatenate([np.arange(rows),
                                          np.full(cap - rows, rows - 1)])
                    df = df.take(idx)
            t_asm = time.perf_counter()
            scored = self.handler(df)
            t_disp = time.perf_counter()
            self.batcher.observe_dispatch(t_disp - t_asm)
            self._est_gauge.set(self.batcher.dispatch_est_s)
            self._batch_gauge.set(rows)
            self._batch_hist.observe(rows)
            self._fill_gauge.set(rows / float(self.max_batch_size))
            if t_disp > t_asm:
                self._rows_gauge.set(rows / (t_disp - t_asm))
            # serialization + socket writes happen on the reply thread —
            # this dispatcher thread immediately assembles the next batch
            # (no dead time between device dispatches). The pending-reply
            # count is incremented by THIS producer so drain() never sees
            # a gap between queue handoff and the writer picking it up
            with self._work_lock:
                self._replying += 1
            self._reply_q.put((batch, scored, rows, staging,
                               t0, t_asm, t_disp))
        except Exception as e:  # reply 500 to the whole batch
            if staging is not None:
                self.pool.release(staging)
            self._m["errors"].inc(n_req)
            body = json.dumps({"error": str(e)}).encode()
            for pend in batch:
                pend.complete({"status": 500, "body": body})
            t_err = time.perf_counter()
            for pend in batch:
                self.events.append("reply", pend.trace_id,
                                   dur_s=t_err - t0, status=500)
                self._lat_hist.observe(t_err - pend.t_enq)

    # ---------------------------------------------------------- reply path
    def _reply_loop(self) -> None:
        """Serialize + deliver replies OFF the dispatcher thread: the
        previous batch's replies are written while the next batch is
        already being assembled/dispatched (the no-dead-time half of
        continuous batching). The staging buffer returns to the pool only
        after every reply body is built from it."""
        while True:
            job = self._reply_q.get()
            if job is None:
                return
            batch, scored, rows, staging, t0, t_asm, t_disp = job
            try:
                self._write_replies(batch, scored, rows, t0, t_asm, t_disp)
            except Exception as e:  # handler output unusable: 500 the batch
                self._m["errors"].inc(len(batch))
                body = json.dumps({"error": str(e)}).encode()
                t_err = time.perf_counter()
                for pend in batch:
                    if pend.response is None:
                        pend.complete({"status": 500, "body": body})
                        self.events.append("reply", pend.trace_id,
                                           dur_s=t_err - t0, status=500)
                        self._lat_hist.observe(t_err - pend.t_enq)
            finally:
                if staging is not None:
                    self.pool.release(staging)
                with self._work_lock:
                    self._replying -= 1

    def _write_replies(self, batch, scored, rows, t0, t_asm, t_disp):
        vals = scored[self.reply_col]
        off = 0
        bodies: List[bytes] = []
        for pend in batch:
            sub = vals[off:off + pend.nrows]
            off += pend.nrows
            if pend.bin is not None:
                bodies.append(rowcodec.encode_reply(self.reply_col, sub))
            else:
                bodies.append(_json_reply(self.reply_col, sub[0]))
        t_done = time.perf_counter()
        if self._t_started is not None:
            # cold-start-to-first-reply: the metric the compile cache /
            # AOT artifacts exist to shrink (scripts/measure_cold_start)
            self._cold_start_gauge.set(t_done - self._t_started)
            self._t_started = None
        # spans land BEFORE the replies release the clients: a caller that
        # queries the event log right after its reply must see the trace
        for pend in batch:
            self.events.append("batch_assembly", pend.trace_id,
                               dur_s=t_asm - t0, batch=rows)
            self.events.append("device_dispatch", pend.trace_id,
                               dur_s=t_disp - t_asm)
            self.events.append("reply", pend.trace_id,
                               dur_s=t_done - t_disp, status=200)
        for pend, body in zip(batch, bodies):
            self._lat_hist.observe(time.perf_counter() - pend.t_enq)
            pend.complete({"status": 200, "body": body})


class HTTPStreamSource:
    """Serving as a REPLAYABLE micro-batch streaming source.

    Reference: DistributedHTTPSource.scala:274-288 (`getBatch` drains held
    requests into rows keyed by request uuid) + :384-403 (`DistributedHTTPSink
    .addBatch` replies per uuid on the owning JVM), with the offset log
    committing AFTER addBatch — a crash between them replays the batch.

    This source exposes the same contract as `FileStreamSource`
    (`read_batch` / `commit` / `rollback` / `batch_id`), so the existing
    `StreamingQuery` loop drives it unchanged:

    - `read_batch()` drains pending HTTP requests into a DataFrame (columns
      `id` + parsed JSON fields) and STAGES them; clients keep blocking.
    - the sink calls `respond(batch_id, rid, body)` per row — replies are
      HELD, not sent.
    - `commit()` releases the staged replies to the clients and retires the
      batch (the offset-log commit).
    - `rollback()` discards staged replies and REQUEUES the requests at the
      front of the queue — the next `read_batch` replays them, so a failed
      pipeline/sink never drops a request (at-least-once, bounded by each
      client's `request_timeout`).
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 max_batch_size: int = 64, request_timeout: float = 30.0,
                 vector_cols=()):
        self.host, self.port = host, port
        self.max_batch_size = max_batch_size
        self.request_timeout = request_timeout
        self.vector_cols = tuple(vector_cols)
        self._queue: "queue.Queue[_PendingRequest]" = queue.Queue()
        # guards enqueue vs rollback's drain-and-requeue: without it a
        # concurrent POST can jump ahead of a replayed batch
        self._qlock = threading.Lock()
        self._staged: List[_PendingRequest] = []
        self._replies: Dict[str, Dict[str, Any]] = {}
        self._batch_id = -1
        self._httpd: Optional[ThreadingHTTPServer] = None

    def _enqueue(self, pend: _PendingRequest) -> None:
        with self._qlock:
            self._queue.put(pend)

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "HTTPStreamSource":
        self._httpd = _make_http_listener(self._enqueue,
                                          self.request_timeout,
                                          self.host, self.port)
        self.port = self._httpd.server_address[1]
        threading.Thread(target=self._httpd.serve_forever,
                         daemon=True).start()
        return self

    def stop(self) -> None:
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/"

    # -------------------------------------------------------------- offsets
    @property
    def batch_id(self) -> int:
        return self._batch_id

    def read_batch(self) -> Optional[DataFrame]:
        if self._staged:
            raise RuntimeError("previous batch neither committed nor "
                               "rolled back")
        batch: List[_PendingRequest] = []
        while len(batch) < self.max_batch_size:
            try:
                batch.append(self._queue.get_nowait())
            except queue.Empty:
                break
        if not batch:
            return None
        self._batch_id += 1
        self._staged = batch
        self._replies = {}
        return parse_request(batch, self.vector_cols)

    def respond(self, batch_id: int, rid: str, body: bytes,
                status: int = 200) -> None:
        """Stage one reply (HTTPSink `respond(batchId, uuid, response)`).
        Held until commit — a rollback discards it and replays the request."""
        if batch_id != self._batch_id:
            raise ValueError(f"respond for batch {batch_id} but current "
                             f"batch is {self._batch_id}")
        self._replies[rid] = {"status": status, "body": body}

    def commit(self) -> None:
        """Release staged replies to their clients and retire the batch.
        Requests with no staged reply get 500 — a sink that commits without
        responding must not leave clients hanging until timeout."""
        err = json.dumps({"error": "no reply produced"}).encode()
        for pend in self._staged:
            pend.complete(self._replies.get(
                pend.rid, {"status": 500, "body": err}))
        self._staged = []
        self._replies = {}

    def rollback(self) -> None:
        """Discard staged replies and requeue the requests (front-of-queue
        order preserved) — the failed-batch replay path. Atomic w.r.t.
        concurrent POSTs: the enqueue lock is held across the drain and
        re-put so no new request can slot in ahead of the replayed batch."""
        requeue = self._staged
        self._staged = []
        self._replies = {}
        with self._qlock:
            old = []
            while True:
                try:
                    old.append(self._queue.get_nowait())
                except queue.Empty:
                    break
            for pend in requeue + old:
                self._queue.put(pend)

    # ------------------------------------------------------- sink utilities
    def reply_sink(self, reply_col: str):
        """foreachBatch-style sink closing over this source: serializes
        `reply_col` per row and stages replies keyed by the id column."""
        def sink(batch_id: int, df: DataFrame) -> None:
            bodies = make_reply(df, reply_col)
            for rid, body in zip(df["id"], bodies):
                self.respond(batch_id, rid, body)
        return sink


class ServingUDFs:
    """Reference: ServingUDFs.scala:1-50 convenience codecs."""

    @staticmethod
    def request_to_string(pend: _PendingRequest) -> str:
        return pend.body.decode("utf-8", "replace")

    @staticmethod
    def string_to_response(s: str, status: int = 200) -> Dict[str, Any]:
        return {"status": status, "body": s.encode("utf-8")}
