"""Binary row format + pooled batch assembly for the serving hot path.

The JSON request path (`serving.parse_request`) decodes every body with
`json.loads`, builds per-request Python lists, and `np.stack`s them —
three copies and a pile of allocations per batch, which caps the host
side of serving far below what the device sustains (docs/SERVING.md:
~1.1M rows/s at batch 1024 on chip vs the JSON decode path's ~tens of
thousands). This module is the vectorized alternative:

- a self-describing binary wire format (magic + dtype + shape header,
  C-order little-endian payload) carrying one named vector column per
  request — one request may carry MANY rows (shape [r, k]), which is how
  "mixed batch sizes" ride through the gateway;
- `peek` parses only the fixed header (no payload touch) so the batcher
  can count rows at admission time;
- `assemble` copies every request's payload straight into a pooled,
  reusable batch buffer — the ONE host copy between socket bytes and the
  device-bound array (the `np.frombuffer` views are zero-copy);
- a length-prefixed request/reply *pack* codec for gateway coalescing
  (one forward hop carrying several client requests).

JSON stays as the compatibility fallback: `is_binary` routes per body,
and mixed batches degrade to the generic path in `serving.py`.

Wire format v1 (little-endian throughout):

    offset 0   4s   magic  b"MT01"
    offset 4   u8   dtype code (see _DTYPES)
    offset 5   u8   ndim (1 = one row of k features; 2 = [rows, k])
    offset 6   u16  column-name length L
    offset 8   u32 * ndim  dims
    then       L bytes of utf-8 column name
    then       C-order array payload

Reply bodies reuse the same format (name = reply column). Packs:

    request pack  = N * ( u32 length | body )
    reply pack    = N * ( u32 length | u16 status | body )
"""

from __future__ import annotations

import mmap
import struct
import threading
from collections import OrderedDict
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

MAGIC = b"MT01"

#: dtype code <-> numpy dtype (little-endian on the wire)
_DTYPES = {1: np.dtype("<f4"), 2: np.dtype("<f8"),
           3: np.dtype("<i4"), 4: np.dtype("u1"), 5: np.dtype("<i8")}
_DTYPE_CODES = {v: k for k, v in _DTYPES.items()}

_HEAD = struct.Struct("<4sBBH")


class BinaryFormatError(ValueError):
    """Body advertised the magic but the header/payload is malformed."""


def is_binary(body: bytes) -> bool:
    return len(body) >= 4 and body[:4] == MAGIC


def encode(name: str, arr: np.ndarray) -> bytes:
    """One request/reply body: header + C-order little-endian payload."""
    a = np.ascontiguousarray(arr)
    code = _DTYPE_CODES.get(a.dtype.newbyteorder("<"))
    if code is None:
        raise BinaryFormatError(f"unsupported dtype {a.dtype}")
    if a.ndim not in (1, 2):
        raise BinaryFormatError(f"ndim must be 1 or 2, got {a.ndim}")
    nb = name.encode("utf-8")
    head = _HEAD.pack(MAGIC, code, a.ndim, len(nb))
    dims = struct.pack("<%dI" % a.ndim, *a.shape)
    return head + dims + nb + (a.astype(a.dtype.newbyteorder("<"),
                                        copy=False).tobytes())


def encode_reply(name: str, arr) -> bytes:
    """`encode` with dtype coercion for handler outputs: a reply column
    in a dtype the wire does not carry (bool predictions, an odd float
    width, object arrays of Python numbers) is cast to float64 rather
    than 500-ing a working model's whole batch."""
    a = np.asarray(arr)
    if a.dtype.newbyteorder("<") not in _DTYPE_CODES:
        a = a.astype(np.float64)
    return encode(name, a)


class BinaryHeader:
    """Parsed header of a binary body (payload untouched until assembly)."""

    __slots__ = ("name", "dtype", "shape", "offset", "nrows", "ncols")

    def __init__(self, name: str, dtype: np.dtype,
                 shape: Tuple[int, ...], offset: int):
        self.name = name
        self.dtype = dtype
        self.shape = shape
        self.offset = offset
        self.nrows = 1 if len(shape) == 1 else int(shape[0])
        self.ncols = int(shape[-1])


def peek(body: bytes) -> Optional[BinaryHeader]:
    """Header-only parse (row count for the batcher's admission math).
    Returns None for non-binary bodies; raises BinaryFormatError when the
    magic is present but the rest does not hold together."""
    if not is_binary(body):
        return None
    if len(body) < _HEAD.size:
        raise BinaryFormatError("truncated header")
    _, code, ndim, name_len = _HEAD.unpack_from(body, 0)
    dtype = _DTYPES.get(code)
    if dtype is None:
        raise BinaryFormatError(f"unknown dtype code {code}")
    if ndim not in (1, 2):
        raise BinaryFormatError(f"bad ndim {ndim}")
    dims_off = _HEAD.size
    payload_off = dims_off + 4 * ndim + name_len
    if len(body) < payload_off:
        raise BinaryFormatError("truncated dims/name")
    shape = struct.unpack_from("<%dI" % ndim, body, dims_off)
    name = body[dims_off + 4 * ndim:payload_off].decode("utf-8")
    expected = int(np.prod(shape)) * dtype.itemsize
    if len(body) != payload_off + expected:
        raise BinaryFormatError(
            f"payload size {len(body) - payload_off} != expected {expected}")
    return BinaryHeader(name, dtype, tuple(int(d) for d in shape),
                        payload_off)


def decode(body: bytes) -> Tuple[str, np.ndarray]:
    """Full decode -> (column name, ZERO-COPY read-only array view)."""
    h = peek(body)
    if h is None:
        raise BinaryFormatError("not a binary body")
    view = np.frombuffer(body, dtype=h.dtype, offset=h.offset)
    return h.name, view.reshape(h.shape)


def rows_view(body: bytes, h: BinaryHeader) -> np.ndarray:
    """[nrows, ncols] zero-copy view of one request's payload."""
    view = np.frombuffer(body, dtype=h.dtype, offset=h.offset)
    return view.reshape(h.nrows, h.ncols)


# ------------------------------------------------- shard files (storage)

def encode_header(name: str, dtype, shape: Tuple[int, ...]) -> bytes:
    """Header bytes alone, no payload. Streaming shard writers
    (io/shardstore.py) emit one column header and then append the payload
    in pieces as ingest blocks arrive — never concatenating the blocks on
    the host — so the header must be constructible before the payload
    bytes exist."""
    dt = np.dtype(dtype).newbyteorder("<")
    code = _DTYPE_CODES.get(dt)
    if code is None:
        raise BinaryFormatError(f"unsupported dtype {dtype}")
    if len(shape) not in (1, 2):
        raise BinaryFormatError(f"ndim must be 1 or 2, got {len(shape)}")
    nb = name.encode("utf-8")
    head = _HEAD.pack(MAGIC, code, len(shape), len(nb))
    dims = struct.pack("<%dI" % len(shape), *[int(d) for d in shape])
    return head + dims + nb


def peek_at(buf, offset: int = 0) -> Tuple[BinaryHeader, int]:
    """Header parse at an offset inside a larger buffer.

    Shard files concatenate many bodies back to back, so unlike `peek`
    this tolerates trailing data: it validates that the payload FITS and
    returns (header, end_offset) with `header.offset` absolute into
    `buf`. Only the fixed header + dims + name bytes are touched — the
    payload is never read, which is what keeps a shard-directory scan
    O(header bytes) even when `buf` is an mmap of a multi-GB file."""
    total = len(buf)
    if offset + _HEAD.size > total:
        raise BinaryFormatError("truncated header")
    magic, code, ndim, name_len = _HEAD.unpack_from(buf, offset)
    if magic != MAGIC:
        raise BinaryFormatError(f"bad magic at offset {offset}")
    dtype = _DTYPES.get(code)
    if dtype is None:
        raise BinaryFormatError(f"unknown dtype code {code}")
    if ndim not in (1, 2):
        raise BinaryFormatError(f"bad ndim {ndim}")
    dims_off = offset + _HEAD.size
    payload_off = dims_off + 4 * ndim + name_len
    if payload_off > total:
        raise BinaryFormatError("truncated dims/name")
    shape = struct.unpack_from("<%dI" % ndim, buf, dims_off)
    name = bytes(buf[dims_off + 4 * ndim:payload_off]).decode("utf-8")
    expected = int(np.prod(shape)) * dtype.itemsize
    end = payload_off + expected
    if end > total:
        raise BinaryFormatError(
            f"truncated payload: need {expected} bytes at {payload_off}, "
            f"have {total - payload_off}")
    h = BinaryHeader(name, dtype, tuple(int(d) for d in shape), payload_off)
    return h, end


class ShardReader:
    """Zero-copy mmap reader over one shard file.

    A shard is a concatenation of rowcodec bodies, one per column, every
    column agreeing on shape[0] (the shard's row count). Construction
    scans ONLY header bytes through bounded `read(size)` calls —
    `header_bytes_read` is the regression-pinned proof that opening a
    shard costs O(columns), not O(file). Payload access goes through a
    single lazily created mmap whose row-range slices are zero-copy
    views; `iter_blocks` yields those views per block so the ingest hot
    path touches `rows_per_block` rows of pages at a time and never
    materializes the shard.

    Callers must drop every view before `close()` (an mmap with live
    exports cannot be unmapped); the ingest ring copies views into its
    reusable staging buffers and releases them immediately, which is how
    consumed shards actually leave RSS.
    """

    def __init__(self, path):
        self.path = str(path)
        self._f = open(self.path, "rb")
        self._mm: Optional[mmap.mmap] = None
        self.header_bytes_read = 0
        self.block_bytes_viewed = 0
        self.headers: "OrderedDict[str, BinaryHeader]" = OrderedDict()
        self._col_rows: Dict[str, int] = {}
        self._f.seek(0, 2)
        total = self._f.tell()
        off = 0
        while off < total:
            head = self._read_at(off, _HEAD.size)
            magic, code, ndim, name_len = _HEAD.unpack_from(head, 0)
            if magic != MAGIC:
                raise BinaryFormatError(
                    f"{self.path}: bad magic at offset {off}")
            dtype = _DTYPES.get(code)
            if dtype is None:
                raise BinaryFormatError(
                    f"{self.path}: unknown dtype code {code}")
            if ndim not in (1, 2):
                raise BinaryFormatError(f"{self.path}: bad ndim {ndim}")
            rest = self._read_at(off + _HEAD.size, 4 * ndim + name_len)
            shape = struct.unpack_from("<%dI" % ndim, rest, 0)
            name = rest[4 * ndim:].decode("utf-8")
            payload_off = off + _HEAD.size + 4 * ndim + name_len
            expected = int(np.prod(shape)) * dtype.itemsize
            if payload_off + expected > total:
                raise BinaryFormatError(
                    f"{self.path}: truncated payload for column {name!r}")
            h = BinaryHeader(name, dtype,
                             tuple(int(d) for d in shape), payload_off)
            self.headers[name] = h
            self._col_rows[name] = int(shape[0])
            off = payload_off + expected
        rows = {r for r in self._col_rows.values()}
        if len(rows) > 1:
            raise BinaryFormatError(
                f"{self.path}: columns disagree on row count {self._col_rows}")
        self.rows = rows.pop() if rows else 0

    def _read_at(self, off: int, size: int) -> bytes:
        """Bounded positioned read during the header scan (never the
        payload — `size` is always a handful of header bytes)."""
        self._f.seek(off)
        data = self._f.read(size)
        if len(data) != size:
            raise BinaryFormatError(
                f"{self.path}: truncated at offset {off}")
        self.header_bytes_read += len(data)
        return data

    def _mmap(self) -> mmap.mmap:
        if self._mm is None:
            self._mm = mmap.mmap(self._f.fileno(), 0, access=mmap.ACCESS_READ)
        return self._mm

    def column_rows(self, name: str, start: int, stop: int) -> np.ndarray:
        """Zero-copy [stop-start, ...] view of one column's row range."""
        h = self.headers[name]
        count = int(np.prod(h.shape))
        full = np.frombuffer(self._mmap(), dtype=h.dtype, count=count,
                             offset=h.offset).reshape(h.shape)
        view = full[start:stop]
        self.block_bytes_viewed += view.nbytes
        return view

    def iter_blocks(self, rows_per_block: int,
                    columns: Optional[Sequence[str]] = None,
                    start: int = 0, stop: Optional[int] = None
                    ) -> Iterator[Tuple[int, Dict[str, np.ndarray]]]:
        """Yield (row_offset, {column: zero-copy view}) in bounded blocks.

        Each yield's views cover at most `rows_per_block` rows — the
        per-block bytes touched are bounded by rows_per_block * row_bytes
        regardless of shard size (regression-pinned via
        `block_bytes_viewed` in tests/test_shardstore.py)."""
        if rows_per_block <= 0:
            raise ValueError("rows_per_block must be positive")
        names = list(columns) if columns is not None else list(self.headers)
        hi = self.rows if stop is None else min(int(stop), self.rows)
        b0 = max(0, int(start))
        while b0 < hi:
            b1 = min(b0 + rows_per_block, hi)
            yield b0, {nm: self.column_rows(nm, b0, b1) for nm in names}
            b0 = b1

    def close(self) -> None:
        if self._mm is not None:
            self._mm.close()  # raises BufferError if views are still live
            self._mm = None
        if self._f is not None:
            self._f.close()
            self._f = None  # type: ignore[assignment]

    def __enter__(self) -> "ShardReader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# ------------------------------------------------------------- buffer pool

class BufferPool:
    """Reusable host-side batch buffers keyed by (dtype, shape).

    The dispatcher acquires the device-bound staging array here instead
    of allocating per batch; `release` returns it once the batch's
    replies are serialized (with reply writing overlapped, the PREVIOUS
    batch's buffer can still be live while the next assembles — distinct
    buffers from the freelist make that safe). `hits`/`misses` are plain
    ints surfaced through the serving metrics, not a stats dict.

    Bounded in BOTH dimensions (round 13): `max_per_key` caps buffers per
    (dtype, shape) key, and `max_keys` is an LRU bound on DISTINCT keys —
    without it, a hot swap to a model with different batch buckets
    strands every old-shape buffer forever (the old keys are never
    acquired again, so per-key caps alone never free them). `clear()` is
    the swap hook (io/serving.py empties the pool at handler install);
    `pooled_bytes` backs the `serving_pool_bytes` gauge.
    """

    def __init__(self, max_per_key: int = 4, max_keys: int = 16):
        self.max_per_key = max_per_key
        self.max_keys = max_keys
        # insertion/touch order IS the LRU order (oldest first)
        self._free: "OrderedDict[Tuple[str, Tuple[int, ...]], " \
                    "List[np.ndarray]]" = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.key_evictions = 0

    def acquire(self, dtype, shape: Tuple[int, ...]) -> np.ndarray:
        key = (np.dtype(dtype).str, tuple(int(d) for d in shape))
        with self._lock:
            lst = self._free.get(key)
            if lst is not None:
                self._free.move_to_end(key)
                if lst:
                    self.hits += 1
                    return lst.pop()
            self.misses += 1
        return np.empty(key[1], dtype=np.dtype(dtype))

    def release(self, arr: np.ndarray) -> None:
        key = (arr.dtype.str, arr.shape)
        with self._lock:
            lst = self._free.get(key)
            if lst is None:
                lst = self._free[key] = []
            self._free.move_to_end(key)
            if len(lst) < self.max_per_key:
                lst.append(arr)
            while len(self._free) > self.max_keys:
                self._free.popitem(last=False)
                self.key_evictions += 1

    def clear(self) -> None:
        """Drop every pooled buffer (all keys). The hot-swap install hook:
        a new model's batch buckets rarely match the old model's, and the
        stranded-shape buffers would otherwise outlive the swap."""
        with self._lock:
            self._free.clear()

    @property
    def pooled_bytes(self) -> int:
        """Total bytes currently held in freelists (the
        `serving_pool_bytes` gauge source)."""
        with self._lock:
            return sum(a.nbytes for lst in self._free.values() for a in lst)

    @property
    def key_count(self) -> int:
        with self._lock:
            return len(self._free)


def assemble(bodies: Sequence[bytes], headers: Sequence[BinaryHeader],
             pool: BufferPool, cap_rows: int) -> Tuple[np.ndarray, int]:
    """Copy every request's rows into one pooled [cap_rows, k] buffer.

    Returns (buffer, total_rows). This is the single host copy: socket
    bytes -> device-bound staging array. Rows beyond total (padding to
    the jit-stable cap) repeat the last row so the compiled program sees
    one shape per power-of-two bucket. All requests must agree on
    (dtype, ncols); the caller groups/falls back otherwise."""
    h0 = headers[0]
    buf = pool.acquire(h0.dtype, (cap_rows, h0.ncols))
    off = 0
    for body, h in zip(bodies, headers):
        buf[off:off + h.nrows] = rows_view(body, h)
        off += h.nrows
    if off < cap_rows and off > 0:
        buf[off:cap_rows] = buf[off - 1]
    return buf, off


# ------------------------------------------------------- coalescing packs

def encode_pack(bodies: Sequence[bytes],
                trace_ids: Optional[Sequence[str]] = None) -> bytes:
    """Gateway -> worker: N client bodies in one forward hop. Each part
    carries its OWN trace id so a coalesced follower's worker-side spans
    join its gateway-side trace (empty when the caller has none)."""
    out = bytearray()
    for i, b in enumerate(bodies):
        tid = (trace_ids[i] if trace_ids is not None else "").encode(
            "latin1", "replace")
        out += struct.pack("<IH", len(b), len(tid))
        out += tid
        out += b
    return bytes(out)


def decode_pack(body: bytes) -> List[Tuple[str, bytes]]:
    """-> [(trace_id_or_empty, part_body), ...]"""
    parts: List[Tuple[str, bytes]] = []
    off = 0
    while off < len(body):
        if off + 6 > len(body):
            raise BinaryFormatError("truncated pack header")
        n, tl = struct.unpack_from("<IH", body, off)
        off += 6
        if off + tl + n > len(body):
            raise BinaryFormatError("truncated pack part")
        tid = body[off:off + tl].decode("latin1")
        off += tl
        parts.append((tid, body[off:off + n]))
        off += n
    return parts


def encode_reply_pack(replies: Sequence[Tuple[int, bytes]]) -> bytes:
    """Worker -> gateway: per-part (status, body)."""
    out = bytearray()
    for status, b in replies:
        out += struct.pack("<IH", len(b), status)
        out += b
    return bytes(out)


def decode_reply_pack(body: bytes) -> List[Tuple[int, bytes]]:
    parts: List[Tuple[int, bytes]] = []
    off = 0
    while off < len(body):
        if off + 6 > len(body):
            raise BinaryFormatError("truncated reply-pack header")
        n, status = struct.unpack_from("<IH", body, off)
        off += 6
        if off + n > len(body):
            raise BinaryFormatError("truncated reply-pack part")
        parts.append((int(status), body[off:off + n]))
        off += n
    return parts


#: header the gateway sets on a coalesced forward (value = part count);
#: echoed on the worker's reply so the gateway knows to unpack it
COALESCE_HEADER = "X-Coalesced-Count"


def coalesced_count(headers: Optional[Dict[str, str]]) -> int:
    """Part count from headers (0 when absent/malformed — treat as a
    plain request; a malformed count must not kill the request)."""
    if not headers:
        return 0
    for k, v in headers.items():
        if k.lower() == COALESCE_HEADER.lower():
            try:
                return max(0, int(v))
            except (TypeError, ValueError):
                return 0
    return 0
