"""Binary / image file readers + PowerBI-style HTTP sink.

Reference: io/binary/BinaryFileFormat.scala:34-245 (binary format with seeded
subsampling), io/binary/BinaryFileReader.scala:1-106 (recursive read),
io/image/ImageUtils.scala (image<->row), IOImplicits `spark.read.image/binary`
(io/IOImplicits.scala:19-212), powerbi/PowerBIWriter.scala:17-114.

OpenCV JNI decode becomes PIL (host C decode) -> numpy HWC; downstream TPU
stages consume stacked float batches.
"""

from __future__ import annotations

import fnmatch
import json
import os
from typing import List, Optional, Sequence

import numpy as np

from ..core.dataframe import DataFrame
from .http import HTTPRequestData, send_with_retries

IMAGE_EXTENSIONS = (".jpg", ".jpeg", ".png", ".bmp", ".gif", ".tif", ".tiff")


def _walk(path: str, recursive: bool, pattern: Optional[str]) -> List[str]:
    out: List[str] = []
    if os.path.isfile(path):
        return [path]
    for root, dirs, files in os.walk(path):
        for f in sorted(files):
            if pattern and not fnmatch.fnmatch(f, pattern):
                continue
            out.append(os.path.join(root, f))
        if not recursive:
            break
    return sorted(out)


def read_binary_files(path: str, recursive: bool = True,
                      sample_ratio: float = 1.0, seed: int = 0,
                      pattern: Optional[str] = None,
                      inspect_zip: bool = False) -> DataFrame:
    """Directory/file -> DataFrame(path, length, bytes). Seeded subsampling
    mirrors BinaryFileFormat's sampleRatio (BinaryFileFormat.scala:34-245)."""
    files = _walk(path, recursive, pattern)
    if sample_ratio < 1.0:
        rng = np.random.default_rng(seed)
        files = [f for f in files if rng.random() < sample_ratio]
    paths, lengths, blobs = [], [], []
    for f in files:
        if inspect_zip and f.endswith(".zip"):
            import zipfile
            with zipfile.ZipFile(f) as z:
                for name in z.namelist():
                    data = z.read(name)
                    paths.append(f + "::" + name)
                    lengths.append(len(data))
                    blobs.append(data)
            continue
        with open(f, "rb") as fh:
            data = fh.read()
        paths.append(f)
        lengths.append(len(data))
        blobs.append(data)
    blob_col = np.empty(len(blobs), dtype=object)
    for i, b in enumerate(blobs):
        blob_col[i] = b
    return DataFrame({"path": np.array(paths, dtype=object),
                      "length": np.array(lengths, dtype=np.int64),
                      "bytes": blob_col})


def decode_image(data: bytes) -> Optional[np.ndarray]:
    """bytes -> HWC uint8 array (PIL host decode; OpenCV imdecode analogue)."""
    import io as _io
    from PIL import Image
    try:
        img = Image.open(_io.BytesIO(data))
        return np.asarray(img.convert("RGB"))
    except Exception:
        return None


def read_images(path: str, recursive: bool = True, sample_ratio: float = 1.0,
                seed: int = 0, drop_invalid: bool = True) -> DataFrame:
    """Directory -> DataFrame(path, image[HWC uint8]) —
    `spark.read.image` equivalent (IOImplicits.scala:19-212)."""
    files = [f for f in _walk(path, recursive, None)
             if f.lower().endswith(IMAGE_EXTENSIONS)]
    if sample_ratio < 1.0:
        rng = np.random.default_rng(seed)
        files = [f for f in files if rng.random() < sample_ratio]
    paths, images = [], []
    for f in files:
        with open(f, "rb") as fh:
            img = decode_image(fh.read())
        if img is None and drop_invalid:
            continue
        paths.append(f)
        images.append(img)
    img_col = np.empty(len(images), dtype=object)
    for i, im in enumerate(images):
        img_col[i] = im
    return DataFrame({"path": np.array(paths, dtype=object),
                      "image": img_col})


def write_to_powerbi(df: DataFrame, url: str, batch_size: int = 1000,
                     concurrency: int = 1) -> int:
    """POST rows as JSON arrays with retry/backoff
    (powerbi/PowerBIWriter.scala:17-114). Returns number of batches sent."""
    rows = df.collect()
    n_batches = 0
    for start in range(0, len(rows), batch_size):
        chunk = rows[start:start + batch_size]
        payload = json.dumps([{k: _plain(v) for k, v in r.items()}
                              for r in chunk]).encode("utf-8")
        resp = send_with_retries(HTTPRequestData(
            url=url, method="POST",
            headers={"Content-Type": "application/json"}, entity=payload))
        if not (200 <= resp.statusCode < 300):
            raise RuntimeError(
                f"PowerBI write failed: {resp.statusCode} {resp.reasonPhrase}")
        n_batches += 1
    return n_batches


def _plain(v):
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, np.integer):
        return int(v)
    if isinstance(v, np.floating):
        return float(v)
    return v
