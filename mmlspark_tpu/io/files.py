"""Binary / image file readers + PowerBI-style HTTP sink.

Reference: io/binary/BinaryFileFormat.scala:34-245 (binary format with seeded
subsampling), io/binary/BinaryFileReader.scala:1-106 (recursive read),
io/image/ImageUtils.scala (image<->row), IOImplicits `spark.read.image/binary`
(io/IOImplicits.scala:19-212), powerbi/PowerBIWriter.scala:17-114.

OpenCV JNI decode becomes PIL (host C decode) -> numpy HWC; downstream TPU
stages consume stacked float batches.
"""

from __future__ import annotations

import fnmatch
import json
import os
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..core.dataframe import DataFrame
from .http import HTTPRequestData, send_with_retries

IMAGE_EXTENSIONS = (".jpg", ".jpeg", ".png", ".bmp", ".gif", ".tif", ".tiff")


def _walk(path: str, recursive: bool, pattern: Optional[str]) -> List[str]:
    out: List[str] = []
    if os.path.isfile(path):
        return [path]
    for root, dirs, files in os.walk(path):
        for f in sorted(files):
            if pattern and not fnmatch.fnmatch(f, pattern):
                continue
            out.append(os.path.join(root, f))
        if not recursive:
            break
    return sorted(out)


def read_binary_files(path: str, recursive: bool = True,
                      sample_ratio: float = 1.0, seed: int = 0,
                      pattern: Optional[str] = None,
                      inspect_zip: bool = False) -> DataFrame:
    """Directory/file -> DataFrame(path, length, bytes). Seeded subsampling
    mirrors BinaryFileFormat's sampleRatio (BinaryFileFormat.scala:34-245)."""
    files = _walk(path, recursive, pattern)
    if sample_ratio < 1.0:
        rng = np.random.default_rng(seed)
        files = [f for f in files if rng.random() < sample_ratio]
    paths, lengths, blobs = [], [], []
    for f in files:
        if inspect_zip and f.endswith(".zip"):
            import zipfile
            with zipfile.ZipFile(f) as z:
                for name in z.namelist():
                    data = z.read(name)
                    paths.append(f + "::" + name)
                    lengths.append(len(data))
                    blobs.append(data)
            continue
        with open(f, "rb") as fh:
            data = fh.read()
        paths.append(f)
        lengths.append(len(data))
        blobs.append(data)
    blob_col = np.empty(len(blobs), dtype=object)
    for i, b in enumerate(blobs):
        blob_col[i] = b
    return DataFrame({"path": np.array(paths, dtype=object),
                      "length": np.array(lengths, dtype=np.int64),
                      "bytes": blob_col})


def decode_image(data: bytes) -> Optional[np.ndarray]:
    """bytes -> HWC uint8 array (PIL host decode; OpenCV imdecode analogue)."""
    import io as _io
    from PIL import Image
    try:
        img = Image.open(_io.BytesIO(data))
        return np.asarray(img.convert("RGB"))
    except Exception:
        return None


def read_images(path: str, recursive: bool = True, sample_ratio: float = 1.0,
                seed: int = 0, drop_invalid: bool = True) -> DataFrame:
    """Directory -> DataFrame(path, image[HWC uint8]) —
    `spark.read.image` equivalent (IOImplicits.scala:19-212)."""
    files = [f for f in _walk(path, recursive, None)
             if f.lower().endswith(IMAGE_EXTENSIONS)]
    if sample_ratio < 1.0:
        rng = np.random.default_rng(seed)
        files = [f for f in files if rng.random() < sample_ratio]
    paths, images = [], []
    for f in files:
        with open(f, "rb") as fh:
            img = decode_image(fh.read())
        if img is None and drop_invalid:
            continue
        paths.append(f)
        images.append(img)
    img_col = np.empty(len(images), dtype=object)
    for i, im in enumerate(images):
        img_col[i] = im
    return DataFrame({"path": np.array(paths, dtype=object),
                      "image": img_col})


def write_to_powerbi(df: DataFrame, url: str, batch_size: int = 1000,
                     concurrency: int = 1) -> int:
    """POST rows as JSON arrays with retry/backoff
    (powerbi/PowerBIWriter.scala:17-114). Returns number of batches sent."""
    rows = df.collect()
    n_batches = 0
    for start in range(0, len(rows), batch_size):
        chunk = rows[start:start + batch_size]
        payload = json.dumps([{k: _plain(v) for k, v in r.items()}
                              for r in chunk]).encode("utf-8")
        resp = send_with_retries(HTTPRequestData(
            url=url, method="POST",
            headers={"Content-Type": "application/json"}, entity=payload))
        if not (200 <= resp.statusCode < 300):
            raise RuntimeError(
                f"PowerBI write failed: {resp.statusCode} {resp.reasonPhrase}")
        n_batches += 1
    return n_batches


def _plain(v):
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, np.integer):
        return int(v)
    if isinstance(v, np.floating):
        return float(v)
    return v


def read_csv(path: str, header: bool = True, sep: str = ",",
             column_names: Optional[List[str]] = None) -> DataFrame:
    """CSV -> DataFrame (the `spark.read.csv` role; reference pipelines load
    every benchmark dataset this way — Benchmarks.scala readCSV).

    Purely numeric files take a C++ fast path (utils/native.parse_csv_f64 —
    the host data-loader role the reference delegates to Spark's reader);
    anything else falls back to python csv with per-column type inference
    (float64 where every non-empty value parses, else object strings;
    empty/na/nan fields become NaN on both paths).
    """
    import csv as _csv

    with open(path, "rb") as fh:
        raw = fh.read()
    if raw.startswith(b"\xef\xbb\xbf"):  # UTF-8 BOM
        raw = raw[3:]
    first_nl = raw.find(b"\n")
    if first_nl < 0:
        first_nl = len(raw)
    header_line = raw[:first_nl].rstrip(b"\r").decode("utf-8")
    # csv-parse the header so quoted fields containing the separator can't
    # misalign columns against the csv.reader fallback
    parsed_header = next(iter(_csv.reader([header_line], delimiter=sep)),
                         [])
    if column_names is not None:
        names = list(column_names)
        # header=True still means the file HAS a header row to skip
        offset = first_nl + 1 if header else 0
    elif header:
        names = [c.strip() for c in parsed_header]
        offset = first_nl + 1
    else:
        names = [f"_c{i}" for i in range(len(parsed_header))]
        offset = 0
    offset = min(offset, len(raw))
    n_rows = raw.count(b"\n", offset) + (
        0 if raw.endswith(b"\n") or offset >= len(raw) else 1)
    from ..utils.native import parse_csv_f64
    mat = parse_csv_f64(raw, n_rows, len(names), sep=sep, offset=offset)
    if mat is not None:
        # contiguous copies: a column VIEW would pin the whole matrix in
        # memory for as long as any single column lives
        return DataFrame({name: np.ascontiguousarray(mat[:, j])
                          for j, name in enumerate(names)})

    def _tofloat(v: str) -> float:
        # keep the fast path's missing-token convention: '', na, nan (any
        # case) are NaN on BOTH paths so dtype never depends on which
        # parser ran
        if v == "" or v.lower() in ("na", "nan"):
            return np.nan
        return float(v)

    rows = [r for r in
            _csv.reader(raw[offset:].decode("utf-8").splitlines(),
                        delimiter=sep) if r]
    cols: Dict[str, Any] = {}
    for j, name in enumerate(names):
        vals = [r[j].strip() if j < len(r) else "" for r in rows]
        try:
            cols[name] = np.asarray([_tofloat(v) for v in vals], np.float64)
        except ValueError:
            cols[name] = np.asarray(
                [v if v != "" else None for v in vals], dtype=object)
    return DataFrame(cols)


def read_libsvm(path: str, n_features: Optional[int] = None,
                features_col: str = "features",
                label_col: str = "label") -> DataFrame:
    """LibSVM/SVMLight text -> DataFrame with a CSR features column (the
    `spark.read.format("libsvm")` role — upstream LightGBM's canonical
    dataset format, LGBM_DatasetCreateFromCSRSpark ingestion analogue).

    Lines: `<label> [qid:<q>] <index>:<value> ...`. Indices may be 1-based
    (the LibSVM convention) or 0-based — detected from the file minimum.
    `qid:` tokens (the ranking format) become a `group` column. Comments
    after `#` are ignored. The column stays sparse above the ingestion
    densify threshold, dense below it (core/dataframe rules).
    """
    labels: List[float] = []
    groups: List[int] = []
    indptr = [0]
    indices: List[int] = []
    values: List[float] = []
    with open(path) as fh:
        for line in fh:
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            parts = line.split()
            labels.append(float(parts[0]))
            for tok in parts[1:]:
                idx, val = tok.split(":", 1)
                if idx == "qid":
                    groups.append(int(val))
                    continue
                indices.append(int(idx))
                values.append(float(val))
            indptr.append(len(indices))
    if not labels:
        raise ValueError(f"no rows in {path!r}")
    if groups and len(groups) != len(labels):
        raise ValueError(f"{path!r}: {len(groups)} qid tokens for "
                         f"{len(labels)} rows — ranking files need one per "
                         "row")
    idx_arr = np.asarray(indices, np.int64)
    one_based = bool(len(idx_arr)) and idx_arr.min() >= 1
    if one_based:
        idx_arr = idx_arr - 1
    width = n_features or (int(idx_arr.max()) + 1 if len(idx_arr) else 0)
    from scipy.sparse import csr_matrix
    mat = csr_matrix(
        (np.asarray(values, np.float32), idx_arr,
         np.asarray(indptr, np.int64)),
        shape=(len(labels), width))
    data = {features_col: mat, label_col: np.asarray(labels, np.float64)}
    if groups:
        data["group"] = np.asarray(groups, np.int64)
    return DataFrame(data)
