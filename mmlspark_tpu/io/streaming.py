"""Incremental file-stream ingestion with offset/checkpoint semantics.

Reference: the Structured-Streaming-capable readers the batch layer mirrors —
`spark.readStream.image/binary` (io/IOImplicits.scala:19-212) backed by
PatchedImageFileFormat (org/apache/spark/ml/source/image/
PatchedImageFileFormat.scala) and Spark's file-stream source offset log.

TPU-native restructure: Spark's micro-batch engine shrinks to an explicit
(source -> pipeline -> sink) loop. `FileStreamSource` discovers new files by
(mtime, name) watermark and exposes micro-batches as DataFrames;
`StreamingQuery` drives the loop on a thread with at-least-once commit
semantics — the offset checkpoint is persisted AFTER the sink call returns,
so a crash between sink and commit replays that batch (exactly Spark's
file-source + checkpoint contract). Batches feed one jitted transform per
tick, which is the TPU-friendly shape: few large device calls, not per-file
work.

Round 19 (train-on-traffic loop): replayable sources carry a DURABLE
cursor — offsets persist through the PR 10 atomic-write helper, so a
crash can never leave a torn offset file that silently re-delivers (or
drops) a committed batch at the restart boundary. `JsonlEventSource` is
the loop's record-granular source: an append-only JSONL event log read
incrementally with a byte-offset cursor that supports `seek()` — the
primitive the online loop's preempt-resume proof rewinds (a snapshot
stores the cursor; resume re-reads exactly the events after it).
Replay is deterministic: ordering comes from file position (and, for
FileStreamSource, the (mtime, name) sort), never from the wall clock —
a seeded harness replays the identical sequence.
"""

from __future__ import annotations

import fnmatch
import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..core.dataframe import DataFrame
from ..resilience.elastic import atomic_write_text
from .files import decode_image


class FileStreamSource:
    """Directory-watch incremental source.

    Each `read_batch()` returns a DataFrame of files not seen before (or None
    when nothing new), in (mtime, name) order, at most `max_files_per_batch`
    per call. `formats`: "binary" (path, bytes, length), "image" (path,
    image HWC uint8), "json" (one row per .json file of scalars/lists).

    Ingestion contract (same as Spark's file streaming source): files must
    be PLACED ATOMICALLY into the directory (write elsewhere, then
    rename/move in) — a file written in place can be picked up
    half-written.
    """

    def __init__(self, path: str, format: str = "binary",
                 pattern: Optional[str] = None, recursive: bool = True,
                 max_files_per_batch: int = 64,
                 checkpoint_dir: Optional[str] = None):
        if format not in ("binary", "image", "json"):
            raise ValueError(f"unknown stream format {format!r}")
        self.path = path
        self.format = format
        self.pattern = pattern
        self.recursive = recursive
        self.max_files_per_batch = max_files_per_batch
        self.checkpoint_dir = checkpoint_dir
        self._seen: Dict[str, float] = {}
        self._pending: Dict[str, float] = {}  # in-flight batch's files
        self._batch_id = -1
        if checkpoint_dir:
            self._restore()

    # ------------------------------------------------------------ offsets
    @property
    def batch_id(self) -> int:
        return self._batch_id

    def _offsets_file(self) -> str:
        return os.path.join(self.checkpoint_dir, "offsets.json")

    def _restore(self) -> None:
        os.makedirs(self.checkpoint_dir, exist_ok=True)
        f = self._offsets_file()
        if os.path.exists(f):
            with open(f) as fh:
                state = json.load(fh)
            self._seen = {k: float(v) for k, v in state["seen"].items()}
            self._batch_id = int(state["batch_id"])

    def commit(self) -> None:
        """Mark the in-flight batch's files consumed and persist the offset
        watermark (the Spark offset-log commit). Call AFTER the sink has
        consumed the batch => at-least-once delivery: if the sink raises, the
        files stay un-seen and the next read_batch replays them.

        Ordering matters at the restart boundary (ISSUE 19): the offsets
        file is written BEFORE the in-memory promotion, through the PR 10
        atomic-write helper. The pre-19 code mutated ``_seen`` first and
        wrote a bare temp+rename; a crash between the two left the disk
        watermark BEHIND the in-memory one inside the same process run —
        harmless alone, but combined with an in-process restart
        (re-instantiating the source over the same checkpoint dir, the
        elastic-resume shape) the stale disk state re-delivered committed
        batches. Durable-then-promote makes restart replay exact."""
        if self.checkpoint_dir:
            merged = dict(self._seen)
            merged.update(self._pending)
            atomic_write_text(
                self._offsets_file(),
                json.dumps({"batch_id": self._batch_id, "seen": merged}))
        self._seen.update(self._pending)
        self._pending = {}

    # ------------------------------------------------------------ discovery
    def _discover(self) -> List[str]:
        out = []
        if not os.path.isdir(self.path):
            return out
        if self.recursive:
            for root, _, names in os.walk(self.path):
                out += [os.path.join(root, n) for n in names]
        else:
            out += [os.path.join(self.path, n)
                    for n in os.listdir(self.path)
                    if os.path.isfile(os.path.join(self.path, n))]
        if self.pattern:
            out = [p for p in out
                   if fnmatch.fnmatch(os.path.basename(p), self.pattern)]
        fresh = []
        for p in out:
            try:
                m = os.path.getmtime(p)
            except OSError:
                continue  # raced with a delete
            if p not in self._seen and p not in self._pending:
                fresh.append((m, p))
        fresh.sort()
        return [p for _, p in fresh[:self.max_files_per_batch]]

    def read_batch(self) -> Optional[DataFrame]:
        files = self._discover()
        if not files:
            return None
        self._batch_id += 1
        # stage, don't mark seen: within a run read_batch keeps advancing
        # (Spark's micro-batch engine does the same), but only commit()
        # promotes staged files into the persisted watermark — a crash or an
        # explicit rollback() before commit makes them discoverable again
        for p in files:
            try:
                self._pending[p] = os.path.getmtime(p)
            except OSError:
                self._pending[p] = 0.0
        return self._load(files)

    def rollback(self) -> None:
        """Return all staged (read but uncommitted) files to the discoverable
        pool — the failed-sink path of the at-least-once contract."""
        self._pending = {}

    def _load(self, files: List[str]) -> DataFrame:
        if self.format == "json":
            rows = []
            for p in files:
                with open(p) as fh:
                    rows.append(json.load(fh))
            keys = sorted({k for r in rows for k in r})
            data = {"path": np.array(files, dtype=object)}
            for k in keys:
                vals = [r.get(k) for r in rows]
                if vals and isinstance(vals[0], list):
                    data[k] = np.array([np.asarray(v, np.float32)
                                        for v in vals], dtype=object)
                else:
                    data[k] = np.asarray(vals)
            return DataFrame(data)
        blobs = []
        for p in files:
            with open(p, "rb") as fh:
                blobs.append(fh.read())
        if self.format == "image":
            imgs = np.empty(len(files), dtype=object)
            ok = np.zeros(len(files), bool)
            for i, b in enumerate(blobs):
                img = decode_image(b)
                if img is not None:
                    imgs[i] = img
                    ok[i] = True
            return DataFrame({"path": np.array(files, dtype=object),
                              "image": imgs}).filter(ok)
        data = np.empty(len(files), dtype=object)
        for i, b in enumerate(blobs):
            data[i] = b
        return DataFrame({"path": np.array(files, dtype=object),
                          "content": data,
                          "length": np.array([len(b) for b in blobs],
                                             np.int64)})


class StreamingQuery:
    """The micro-batch driver loop: source -> pipeline -> sink on a thread.

    pipeline: DataFrame -> DataFrame (e.g. model.transform); sink receives
    (batch_id, scored DataFrame) — the foreachBatch analogue. Offsets commit
    after the sink returns (at-least-once)."""

    def __init__(self, source: FileStreamSource,
                 pipeline: Optional[Callable[[DataFrame], DataFrame]],
                 sink: Callable[[int, DataFrame], None],
                 poll_interval_s: float = 0.1):
        self.source = source
        self.pipeline = pipeline
        self.sink = sink
        self.poll_interval_s = poll_interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.batches_processed = 0
        self.rows_processed = 0
        self.last_error: Optional[Exception] = None

    def start(self) -> "StreamingQuery":
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                df = self.source.read_batch()
            except Exception as e:  # noqa: BLE001
                self.last_error = e
                time.sleep(self.poll_interval_s)
                continue
            if df is None:
                self._stop.wait(self.poll_interval_s)
                continue
            try:
                out = self.pipeline(df) if self.pipeline else df
                self.sink(self.source.batch_id, out)
                self.source.commit()
                self.batches_processed += 1
                self.rows_processed += len(df)
            except Exception as e:  # noqa: BLE001
                # return the batch to the pool -> replayed next poll
                # (at-least-once)
                self.last_error = e
                self.source.rollback()
                self._stop.wait(self.poll_interval_s)

    def process_available(self) -> int:
        """Synchronous drain (processAllAvailable analogue): run batches until
        the directory has nothing new; returns rows processed."""
        rows = 0
        while True:
            df = self.source.read_batch()
            if df is None:
                return rows
            try:
                out = self.pipeline(df) if self.pipeline else df
                self.sink(self.source.batch_id, out)
            except Exception:
                self.source.rollback()  # leave the batch replayable
                raise
            self.source.commit()
            self.batches_processed += 1
            rows += len(df)
            self.rows_processed += len(df)

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout)

    def await_rows(self, n: int, timeout: float = 30.0) -> bool:
        """Block until >= n rows processed (test helper)."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            if self.rows_processed >= n:
                return True
            time.sleep(0.02)
        return False


# ------------------------------------------------ replayable event source

class JsonlEventSource:
    """Record-granular replayable source over an append-only JSONL log.

    The train-on-traffic loop's ingest primitive (ISSUE 19): one event
    per line, read incrementally with an explicit BYTE-OFFSET cursor.
    Three properties the loop's exactly-once proof rests on:

    - **Replayable**: ``seek(cursor)`` rewinds to any previously returned
      cursor; re-reading yields the identical record sequence (ordering
      is file position, never wall clock — deterministic under any
      seeded harness clock).
    - **Durable**: ``commit(cursor)`` persists the position through the
      PR 10 atomic-write helper; a new source over the same
      ``checkpoint_dir`` resumes exactly there. A torn cursor file is
      impossible (atomic rename), and an UNREADABLE one degrades to
      offset 0 — replay, never a drop (at-least-once posture; the
      consumer's dedup makes it exactly-once).
    - **Torn-tail safe**: a partially appended last line (no trailing
      newline yet, or mid-write JSON) is left un-consumed — the cursor
      never advances past it, so the writer finishing the line makes it
      readable, and a crashed writer's torn tail is skipped once a later
      complete line follows (counted ``online_events_total{kind=torn}``
      via the consumer's refusal vocabulary is NOT used here: a torn
      line is an ingest artifact, surfaced on ``torn_lines``).

    Writers append whole lines (``append_jsonl`` below or any
    line-buffered appender); multi-writer interleaving is out of scope —
    one log per producing process, like a Kafka partition.
    """

    def __init__(self, path: str, checkpoint_dir: Optional[str] = None):
        self.path = path
        self.checkpoint_dir = checkpoint_dir
        self._offset = 0
        self.records_read = 0
        self.torn_lines = 0
        if checkpoint_dir:
            self._restore()

    # ------------------------------------------------------------- cursor
    def _cursor_file(self) -> str:
        return os.path.join(self.checkpoint_dir, "cursor.json")

    def _restore(self) -> None:
        os.makedirs(self.checkpoint_dir, exist_ok=True)
        try:
            with open(self._cursor_file(), encoding="utf-8") as fh:
                self._offset = int(json.load(fh)["offset"])
        except (OSError, ValueError, KeyError, TypeError):
            self._offset = 0  # unreadable cursor => replay, never drop

    def cursor(self) -> Dict[str, Any]:
        """Opaque-but-JSON position token: everything consumed so far."""
        return {"offset": self._offset}

    def seek(self, cursor: Dict[str, Any]) -> None:
        """Rewind/advance to a cursor previously returned by `cursor()`
        (the online loop's resume: its snapshot stores the cursor its
        learner state corresponds to)."""
        off = int(cursor["offset"])
        if off < 0:
            raise ValueError(f"cursor offset must be >= 0, got {off}")
        self._offset = off

    def commit(self, cursor: Optional[Dict[str, Any]] = None) -> None:
        """Persist the cursor (default: current position) durably."""
        if not self.checkpoint_dir:
            return
        off = self._offset if cursor is None else int(cursor["offset"])
        atomic_write_text(self._cursor_file(),
                          json.dumps({"offset": off}))

    # --------------------------------------------------------------- read
    def read(self, max_records: int = 1024) -> List[Dict[str, Any]]:
        """Up to `max_records` complete records after the cursor; advances
        the in-memory cursor past exactly the records returned (plus any
        torn line that a later complete line proves abandoned)."""
        out: List[Dict[str, Any]] = []
        try:
            fh = open(self.path, "rb")
        except OSError:
            return out
        with fh:
            fh.seek(self._offset)
            while len(out) < max_records:
                line_start = fh.tell()
                line = fh.readline()
                if not line:
                    break
                if not line.endswith(b"\n"):
                    # torn tail: writer mid-append — do not consume
                    break
                try:
                    rec = json.loads(line)
                except ValueError:
                    # a torn line the writer abandoned (crash mid-append,
                    # then a later append started a fresh line): skip it,
                    # counted — never silently re-deliver forever
                    self.torn_lines += 1
                    self._offset = fh.tell()
                    continue
                if not isinstance(rec, dict):
                    self.torn_lines += 1
                    self._offset = fh.tell()
                    continue
                rec["_offset"] = line_start
                # the cursor a consumer must store to mark THIS record
                # consumed: the loop snapshots mid-read-batch, so the
                # batch-level `cursor()` is too coarse for exactly-once
                rec["_next_offset"] = fh.tell()
                out.append(rec)
                self._offset = fh.tell()
        self.records_read += len(out)
        return out


def append_jsonl(path: str, record: Dict[str, Any]) -> None:
    """Append one event as a single line (the producing side of
    `JsonlEventSource`). O_APPEND single-write keeps lines atomic for
    same-filesystem readers up to PIPE_BUF-scale records."""
    line = (json.dumps(record, sort_keys=True) + "\n").encode("utf-8")
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
    try:
        os.write(fd, line)
    finally:
        os.close(fd)
