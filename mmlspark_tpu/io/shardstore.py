"""Out-of-core training data plane: rowcodec shards on disk + streaming
bounded-RAM ingest into the device-resident binned dataset.

HIGGS-11M fits in host RAM; production traffic logs don't. A shard store
is a directory of binary rowcodec shard files (io/rowcodec.py wire
format promoted to a storage format: one self-describing body per column
per shard) plus an atomic ``MANIFEST.json`` carrying per-shard
sha256/row-count, the column schema, and the exact full-pass feature
stats the streaming BinMapper fit needs. Everything the in-memory fit
computes from the raw matrix is either recomputed from a bounded sample
(quantile edges) or read from the manifest (min/max/missing — combined
per append block at WRITE time, so no extra full pass at fit time).

The ingest hot path is the PR 6 ahead-dispatch discipline applied to
disk I/O:

- shards are mmapped and read through zero-copy ``ShardReader`` views,
  copied once into a bounded ring of reusable staging buffers by a
  producer thread (page-in + memcpy release the GIL) while the consumer
  bins block k and dispatches its async ``device_put`` — read, bin, and
  transfer overlap;
- blocks land in donated ``dynamic_update_slice`` device buffers exactly
  like the in-memory pipelined fit (`models/lightgbm/base.py`
  _binned_to_device and the sharded/multi-host variants), so the hot
  path has NO host sync (sync-point lint, tests/test_fit_pipeline.py)
  and peak HBM stays ~1x the binned matrix + one block;
- peak host RSS is bounded by the ring: ``ring_depth`` staging block
  sets plus the shards currently mapped (readers are closed — munmapped
  — as soon as no later block needs them), regardless of dataset size
  (bounded-memory lint + RSS-asserted harness, docs/DATA.md).

Digest parity with the in-memory fit is a hard contract, pinned by
tests/test_shardstore.py: same bin edges (ops/binning.BinMapper
.fit_sampled — same rng sample, exact stats), same device values (same
casts, same padding/masking as mesh.shard_rows), bit-identical boosters.

Multi-host fits give each host ownership of only its shards: the rows a
host's devices own (parallel/multihost.local_row_slices) are mapped back
to shard row ranges, and rows another host owns are never read, binned,
or transferred here — host ingest cost divides by the host count.
"""

from __future__ import annotations

import hashlib
import json
import os
import queue
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import rowcodec

MANIFEST_NAME = "MANIFEST.json"
STORE_FORMAT = "mmlspark-tpu-shardstore"
STORE_SCHEMA_VERSION = 1

#: canonical column names (fixed vocabulary — the fit route keys on them)
FEATURES = "features"
LABEL = "label"
WEIGHT = "weight"
GROUP = "group"


class ShardStoreError(ValueError):
    """Store directory/manifest/shard is malformed or inconsistent."""


class ShardVerifyError(ShardStoreError):
    """A shard's bytes do not match the manifest sha256/row count."""


def _publish_verify_failure() -> None:
    try:
        from ..observability.bridge import publish_ingest_verify_failure
        publish_ingest_verify_failure()
    except Exception:  # noqa: BLE001 - metrics must never mask the error
        pass


def host_rss_bytes(peak: bool = False) -> Optional[int]:
    """Current (VmRSS) or peak (VmHWM) resident set of this process in
    bytes, from /proc/self/status; None where that interface is absent.
    The `ingest_rss_bytes` gauge source and the measure_ingest harness's
    bound probe."""
    key = "VmHWM:" if peak else "VmRSS:"
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith(key):
                    return int(line.split()[1]) * 1024
    except OSError:
        return None
    return None


# ---------------------------------------------------------------- writer

class ShardStoreWriter:
    """Streaming shard-store writer: bounded by the append block size.

    ``append`` buffers row blocks (views are fine — they are consumed at
    the next flush) and cuts a shard file every ``rows_per_shard`` rows;
    the shard is written column by column — header first
    (rowcodec.encode_header), then each buffered block's payload bytes —
    so no block concatenation ever materializes a whole shard in RAM.
    sha256 is folded in while writing. ``close`` writes ``MANIFEST.json``
    through the atomic-write helper (resilience/elastic.py): the manifest
    commit IS the store's existence — shard files without a manifest are
    invisible garbage, never a torn dataset.

    Exact full-pass stats are accumulated per block at write time
    (np.fmin/np.fmax of per-block nanmin/nanmax == whole-matrix
    nanmin/nanmax; OR of per-block isnan-any) — the inputs
    ops/binning.BinMapper.fit_sampled needs for bit-parity with the
    in-memory fit, paid here where the rows are already in hand.
    """

    def __init__(self, path: str, rows_per_shard: int = 1_000_000):
        if rows_per_shard <= 0:
            raise ValueError("rows_per_shard must be positive")
        self.path = str(path)
        self.rows_per_shard = int(rows_per_shard)
        os.makedirs(self.path, exist_ok=True)
        self._buf: List[Dict[str, np.ndarray]] = []
        self._buf_rows = 0
        self._shards: List[Dict[str, Any]] = []
        self._rows = 0
        self._columns: Optional[List[str]] = None
        self._dtypes: Dict[str, np.dtype] = {}
        self._ncols = 0
        self._fmin: Optional[np.ndarray] = None
        self._fmax: Optional[np.ndarray] = None
        self._missing: Optional[np.ndarray] = None
        self._any_nan = False
        self._label_min = np.inf
        self._label_max = -np.inf
        self._closed = False

    def append(self, features: np.ndarray, label: np.ndarray,
               weight: Optional[np.ndarray] = None,
               group: Optional[np.ndarray] = None) -> None:
        if self._closed:
            raise ShardStoreError("writer already closed")
        features = np.ascontiguousarray(features)
        label = np.ascontiguousarray(label)
        if features.ndim != 2:
            raise ShardStoreError("features must be 2-D [rows, F]")
        r = features.shape[0]
        if label.shape != (r,):
            raise ShardStoreError(
                f"label shape {label.shape} != ({r},)")
        block = {FEATURES: features, LABEL: label}
        if weight is not None:
            weight = np.ascontiguousarray(weight, np.float32)
            if weight.shape != (r,):
                raise ShardStoreError(
                    f"weight shape {weight.shape} != ({r},)")
            block[WEIGHT] = weight
        if group is not None:
            group = np.ascontiguousarray(group)
            if group.shape != (r,):
                raise ShardStoreError(
                    f"group shape {group.shape} != ({r},)")
            block[GROUP] = group
        if self._columns is None:
            self._columns = list(block)
            self._dtypes = {nm: a.dtype for nm, a in block.items()}
            self._ncols = features.shape[1]
            for nm, dt in self._dtypes.items():
                if dt.newbyteorder("<") not in rowcodec._DTYPE_CODES:
                    raise ShardStoreError(
                        f"column {nm!r}: unsupported dtype {dt}")
        else:
            if list(block) != self._columns:
                raise ShardStoreError(
                    f"append columns {list(block)} != first append's "
                    f"{self._columns}")
            if features.shape[1] != self._ncols:
                raise ShardStoreError(
                    f"features has {features.shape[1]} cols, store has "
                    f"{self._ncols}")
            for nm, a in block.items():
                if a.dtype != self._dtypes[nm]:
                    raise ShardStoreError(
                        f"column {nm!r} dtype {a.dtype} != {self._dtypes[nm]}")
        if r == 0:
            return
        self._update_stats(features, label)
        self._buf.append(block)
        self._buf_rows += r
        self._rows += r
        while self._buf_rows >= self.rows_per_shard:
            self._flush(self.rows_per_shard)

    def _update_stats(self, features: np.ndarray, label: np.ndarray) -> None:
        # np.fmin/np.fmax ignore the NaN side of a pair, so the per-block
        # reduce chain equals whole-matrix nanmin/nanmax — and equals
        # plain min/max when NaN-free — matching BinMapper.fit's stats in
        # every case (and never emitting the all-NaN-slice warning).
        bmin = np.fmin.reduce(features, axis=0)
        bmax = np.fmax.reduce(features, axis=0)
        if self._fmin is None:
            self._fmin, self._fmax = bmin, bmax
        else:
            self._fmin = np.fmin(self._fmin, bmin)
            self._fmax = np.fmax(self._fmax, bmax)
        if features.dtype.kind == "f":
            nanmask = np.isnan(features)
            if self._missing is None:
                self._missing = nanmask.any(axis=0)
            else:
                self._missing |= nanmask.any(axis=0)
            self._any_nan = bool(self._any_nan or nanmask.any())
        elif self._missing is None:
            self._missing = np.zeros(features.shape[1], bool)
        self._label_min = float(np.fmin(self._label_min,
                                        np.fmin.reduce(label)))
        self._label_max = float(np.fmax(self._label_max,
                                        np.fmax.reduce(label)))

    def _flush(self, rows: int) -> None:
        """Cut one shard of exactly ``rows`` rows from the buffer head."""
        rows = int(min(rows, self._buf_rows))
        if rows <= 0:
            return
        head: List[Dict[str, np.ndarray]] = []
        taken = 0
        while taken < rows:
            block = self._buf[0]
            r = block[FEATURES].shape[0]
            if taken + r <= rows:
                head.append(self._buf.pop(0))
                taken += r
            else:
                cut = rows - taken
                head.append({nm: a[:cut] for nm, a in block.items()})
                self._buf[0] = {nm: a[cut:] for nm, a in block.items()}
                taken = rows
        self._buf_rows -= rows
        fname = f"shard-{len(self._shards):05d}.mt"
        fpath = os.path.join(self.path, fname)
        digest = hashlib.sha256()
        nbytes = 0
        with open(fpath, "wb") as f:
            for nm in self._columns or []:
                dt = self._dtypes[nm].newbyteorder("<")
                shape = ((rows, self._ncols) if nm == FEATURES else (rows,))
                hb = rowcodec.encode_header(nm, dt, shape)
                f.write(hb)
                digest.update(hb)
                nbytes += len(hb)
                for block in head:
                    payload = np.ascontiguousarray(
                        block[nm]).astype(dt, copy=False).tobytes()
                    f.write(payload)
                    digest.update(payload)
                    nbytes += len(payload)
            f.flush()
            os.fsync(f.fileno())
        self._shards.append({"file": fname, "rows": rows,
                             "bytes": nbytes,
                             "sha256": digest.hexdigest()})

    def close(self) -> "ShardStore":
        if self._closed:
            return ShardStore(self.path)
        if self._buf_rows:
            self._flush(self._buf_rows)
        self._closed = True
        col_stats: Optional[Dict[str, Any]] = None
        if self._rows:
            col_stats = {
                "feature_min": [float(v) for v in self._fmin],
                "feature_max": [float(v) for v in self._fmax],
                "missing": [bool(v) for v in (
                    self._missing if self._missing is not None
                    else np.zeros(self._ncols, bool))],
                "any_nan": bool(self._any_nan),
                "label_min": float(self._label_min),
                "label_max": float(self._label_max),
            }
        manifest = {
            "format": STORE_FORMAT,
            "schema_version": STORE_SCHEMA_VERSION,
            "rows": int(self._rows),
            "num_features": int(self._ncols),
            "columns": {nm: {"dtype": self._dtypes[nm].newbyteorder("<").str,
                             **({"cols": int(self._ncols)}
                                if nm == FEATURES else {})}
                        for nm in (self._columns or [])},
            "shards": self._shards,
            "stats": col_stats,
        }
        from ..resilience.elastic import atomic_write_text
        atomic_write_text(os.path.join(self.path, MANIFEST_NAME),
                          json.dumps(manifest, indent=2, sort_keys=True))
        return ShardStore(self.path)

    def __enter__(self) -> "ShardStoreWriter":
        return self

    def __exit__(self, exc_type, *exc) -> None:
        if exc_type is None:
            self.close()


def write_store(path: str, features: np.ndarray, label: np.ndarray,
                weight: Optional[np.ndarray] = None,
                group: Optional[np.ndarray] = None,
                rows_per_shard: int = 1_000_000,
                block_rows: int = 262_144) -> "ShardStore":
    """In-RAM arrays -> shard store (tests/small datasets; the real
    out-of-core route streams ShardStoreWriter.append from a generator)."""
    with ShardStoreWriter(path, rows_per_shard) as w:
        n = features.shape[0]
        for i0 in range(0, n, block_rows):
            i1 = min(i0 + block_rows, n)
            w.append(features[i0:i1], label[i0:i1],
                     None if weight is None else weight[i0:i1],
                     None if group is None else group[i0:i1])
    return ShardStore(path)


# ----------------------------------------------------------------- store

class ShardStore:
    """An opened shard-store directory: manifest + shard access.

    ``shape`` mirrors a 2-D matrix ((rows, num_features)) so fit-path
    bookkeeping (`n, f = x.shape`) reads the same for both routes;
    everything row-payload goes through per-shard ``ShardReader``s.
    ``manifest_digest`` is the dataset identity the checkpoint
    shard-cursor records (resilience/elastic.py schema v2) — resume
    against a different/rewritten store is a counted refusal, not a
    silent wrong-data continuation.
    """

    def __init__(self, path: str):
        self.path = str(path)
        mpath = os.path.join(self.path, MANIFEST_NAME)
        try:
            with open(mpath) as f:
                manifest = json.load(f)
        except OSError as e:
            raise ShardStoreError(f"cannot read {mpath}: {e}") from e
        except ValueError as e:
            raise ShardStoreError(f"malformed manifest {mpath}: {e}") from e
        if manifest.get("format") != STORE_FORMAT:
            raise ShardStoreError(
                f"{mpath}: format {manifest.get('format')!r} is not "
                f"{STORE_FORMAT!r}")
        ver = int(manifest.get("schema_version", -1))
        if ver > STORE_SCHEMA_VERSION:
            raise ShardStoreError(
                f"{mpath}: schema_version {ver} is newer than this reader "
                f"({STORE_SCHEMA_VERSION})")
        self.manifest = manifest
        self.rows = int(manifest["rows"])
        self.num_features = int(manifest["num_features"])
        self.columns: Dict[str, Dict[str, Any]] = manifest["columns"]
        self.shards: List[Dict[str, Any]] = list(manifest["shards"])
        self.stats: Optional[Dict[str, Any]] = manifest.get("stats")
        self.manifest_digest = hashlib.sha256(
            json.dumps(manifest, sort_keys=True).encode()).hexdigest()
        if sum(int(s["rows"]) for s in self.shards) != self.rows:
            raise ShardStoreError(
                f"{mpath}: shard row counts do not sum to rows={self.rows}")

    @property
    def shape(self) -> Tuple[int, int]:
        return (self.rows, self.num_features)

    def __len__(self) -> int:
        return self.rows

    def column_dtype(self, name: str) -> np.dtype:
        return np.dtype(self.columns[name]["dtype"])

    def shard_path(self, i: int) -> str:
        return os.path.join(self.path, self.shards[i]["file"])

    def open_shard(self, i: int) -> rowcodec.ShardReader:
        return rowcodec.ShardReader(self.shard_path(i))

    def shard_row_ranges(self) -> List[Tuple[int, int]]:
        """Global [start, stop) row range of each shard — global row
        order IS shard concatenation order."""
        out, base = [], 0
        for s in self.shards:
            out.append((base, base + int(s["rows"])))
            base += int(s["rows"])
        return out

    def cursor(self) -> Dict[str, Any]:
        """The shard-cursor fields a checkpoint manifest records
        (resilience/elastic.py schema v2): enough to validate at resume
        time that the store on disk is byte-for-byte the dataset the
        snapshot was trained on."""
        return {"store": self.path,
                "manifest_digest": self.manifest_digest,
                "shards": len(self.shards),
                "rows": int(self.rows)}

    def verify(self, shard: Optional[int] = None,
               chunk_bytes: int = 1 << 20) -> int:
        """Recompute shard sha256s in bounded chunks against the
        manifest. Returns the number of shards verified; a mismatch
        counts `ingest_verify_failures_total` and raises
        ShardVerifyError naming the shard."""
        idxs = range(len(self.shards)) if shard is None else [int(shard)]
        for i in idxs:
            entry = self.shards[i]
            digest = hashlib.sha256()
            with open(self.shard_path(i), "rb") as f:
                while True:
                    chunk = f.read(chunk_bytes)
                    if not chunk:
                        break
                    digest.update(chunk)
            if digest.hexdigest() != entry["sha256"]:
                _publish_verify_failure()
                raise ShardVerifyError(
                    f"{self.shard_path(i)}: sha256 mismatch (manifest "
                    f"{entry['sha256'][:12]}…, file "
                    f"{digest.hexdigest()[:12]}…)")
        return len(list(idxs))


def is_store_path(obj: Any) -> bool:
    """True when ``obj`` names a shard-store directory on disk."""
    if not isinstance(obj, (str, os.PathLike)):
        return False
    return os.path.isfile(os.path.join(str(obj), MANIFEST_NAME))


def as_store(obj: Any) -> Optional[ShardStore]:
    """ShardStore | store-directory path -> ShardStore; anything else ->
    None (the fit-entry routing probe in models/lightgbm/base.py)."""
    if isinstance(obj, ShardStore):
        return obj
    if is_store_path(obj):
        return ShardStore(str(obj))
    return None


# -------------------------------------------------- streamed BinMapper fit

def _gather_sample(store: ShardStore,
                   idx: Optional[np.ndarray]) -> np.ndarray:
    """DESIGNATED block-assembly point (bounded-memory lint,
    tests/test_shardstore.py): the ONE place a multi-shard feature gather
    materializes, and it is bounded by the bin sample count (or the full
    store when the store is smaller), never the dataset."""
    total = store.rows if idx is None else int(len(idx))
    out = np.empty((total, store.num_features), np.float64)
    pos = 0
    for i, (g0, g1) in enumerate(store.shard_row_ranges()):
        if idx is None:
            local = None
            take = g1 - g0
        else:
            lo = int(np.searchsorted(idx, g0))
            hi = int(np.searchsorted(idx, g1))
            if hi == lo:
                continue
            local = idx[lo:hi] - g0
            take = hi - lo
        rd = store.open_shard(i)
        try:
            view = rd.column_rows(FEATURES, 0, rd.rows)
            out[pos:pos + take] = view if local is None else view[local]
            del view
        finally:
            rd.close()
        pos += take
    return out


def fit_bin_mapper(store: ShardStore, max_bins: int = 255,
                   sample_count: int = 200_000, seed: int = 0,
                   categorical: Optional[Tuple[int, ...]] = None,
                   max_bins_by_feature: Optional[np.ndarray] = None,
                   use_missing: bool = True):
    """BinMapper from a shard store with BIT-PARITY to the in-memory
    ``BinMapper.fit(X)``: the same rng draw picks the sample rows (drawn
    against the same n with the same seed; row order is irrelevant —
    compute_bin_edges sorts per column), which are gathered from the
    shards, and the full-pass min/max/missing stats come from the
    manifest (accumulated exactly at write time). Cost: O(sample) reads
    + O(columns) manifest, never a full-data pass."""
    from ..ops.binning import BinMapper
    n = store.rows
    if n == 0:
        raise ShardStoreError("cannot fit a BinMapper on an empty store")
    if store.stats is None:
        raise ShardStoreError("store manifest carries no stats")
    if n > sample_count:
        rng = np.random.default_rng(seed)
        idx = np.sort(rng.choice(n, sample_count, replace=False))
    else:
        idx = None
    sample = _gather_sample(store, idx)
    st = store.stats
    return BinMapper.fit_sampled(
        sample, n,
        feature_min=np.asarray(st["feature_min"], np.float64),
        feature_max=np.asarray(st["feature_max"], np.float64),
        missing_any=np.asarray(st["missing"], bool),
        float_data=store.column_dtype(FEATURES).kind == "f",
        max_bins=max_bins, sample_count=sample_count, seed=seed,
        categorical=categorical, max_bins_by_feature=max_bins_by_feature,
        use_missing=use_missing)


def read_column(store: ShardStore, name: str) -> np.ndarray:
    """DESIGNATED block-assembly point (bounded-memory lint): full
    materialization of ONE auxiliary 1-D column. The lambdarank group-id
    column rides this — a single int column is the documented exception
    to the RSS bound (docs/DATA.md), ~1/(4·F) of the feature payload."""
    if name not in store.columns:
        raise ShardStoreError(f"store has no column {name!r}")
    parts = []
    for i in range(len(store.shards)):
        rd = store.open_shard(i)
        try:
            view = rd.column_rows(name, 0, rd.rows)
            parts.append(np.array(view))
            del view
        finally:
            rd.close()
    return (np.concatenate(parts) if parts
            else np.empty(0, store.column_dtype(name)))


# ------------------------------------------------------- prefetch ring

#: column source spec: ("store", column_name) reads shard payloads,
#: ("const", value) fills real rows with value — pad rows are always 0
_DONE = object()


class _PrefetchRing:
    """Bounded ring of reusable staging buffer sets filled ahead by a
    producer thread.

    ``requests`` is the exact consumption order: (tag, segments) where
    each segment (dest_row, g0, g1) copies padded-global rows [g0, g1)
    of every column into the buffer at dest_row. Rows at/after the
    store's real row count are PADDING and fill as 0. The producer walks
    shard mmaps through zero-copy views (page-in + memcpy release the
    GIL under the consumer's binning), recycles at most ``depth`` buffer
    sets, and closes each shard reader after its last-use request — so
    resident staging is depth block sets and resident file pages are the
    handful of shards the in-flight requests span. That is the RSS bound
    (docs/DATA.md); nothing here scales with dataset size."""

    def __init__(self, store: ShardStore,
                 columns: Dict[str, Tuple],
                 requests: Sequence[Tuple[Any, List[Tuple[int, int, int]]]],
                 rows_cap: int, depth: int = 2):
        self._store = store
        self._columns = columns
        self._requests = list(requests)
        self._ranges = store.shard_row_ranges()
        self._free: "queue.Queue" = queue.Queue()
        self._ready: "queue.Queue" = queue.Queue(
            maxsize=max(2, int(depth)) + 1)
        self._abort = False
        self._err: Optional[BaseException] = None
        self.bytes_filled = 0
        fdim = store.num_features
        for _ in range(max(2, int(depth))):
            bufset = {}
            for nm, spec in columns.items():
                dt = spec[2]
                shape = ((rows_cap, fdim) if nm == FEATURES
                         else (rows_cap,))
                bufset[nm] = np.zeros(shape, dt)
            self._free.put(bufset)
        # last request index touching each shard -> close (munmap) there
        self._last_use: Dict[int, int] = {}
        for ri, (_tag, segs) in enumerate(self._requests):
            for _dst, g0, g1 in segs:
                for si, (s0, s1) in enumerate(self._ranges):
                    if g0 < min(s1, store.rows) and s0 < g1:
                        self._last_use[si] = ri
        self._thread = threading.Thread(target=self._produce, daemon=True,
                                        name="shardstore-prefetch")
        self._thread.start()

    def _fill(self, bufset: Dict[str, np.ndarray], dst: int, g0: int,
              g1: int, readers: Dict[int, rowcodec.ShardReader]) -> None:
        rows = self._store.rows
        real1 = min(g1, rows)
        for nm, spec in self._columns.items():
            buf = bufset[nm]
            if spec[0] == "const":
                if real1 > g0:
                    buf[dst:dst + (real1 - g0)] = spec[1]
            if g1 > real1:  # padding rows (beyond the store) are zero
                buf[dst + max(0, real1 - g0):dst + (g1 - g0)] = 0
        if real1 <= g0:
            return
        for si, (s0, s1) in enumerate(self._ranges):
            a = max(g0, s0)
            b = min(real1, s1)
            if b <= a:
                continue
            rd = readers.get(si)
            if rd is None:
                rd = readers[si] = self._store.open_shard(si)
            for nm, spec in self._columns.items():
                if spec[0] != "store":
                    continue
                view = rd.column_rows(spec[1], a - s0, b - s0)
                np.copyto(bufset[nm][dst + (a - g0):dst + (b - g0)], view,
                          casting="same_kind")
                self.bytes_filled += view.nbytes
                del view

    def _produce(self) -> None:
        readers: Dict[int, rowcodec.ShardReader] = {}
        try:
            for ri, (tag, segs) in enumerate(self._requests):
                bufset = None
                while bufset is None:
                    if self._abort:
                        return
                    try:
                        bufset = self._free.get(timeout=0.2)
                    except queue.Empty:
                        continue
                for dst, g0, g1 in segs:
                    self._fill(bufset, dst, g0, g1, readers)
                # munmap shards no later request touches — this is what
                # actually returns their file-backed pages
                for si in [s for s, last in self._last_use.items()
                           if last == ri and s in readers]:
                    readers.pop(si).close()
                self._ready.put((tag, bufset))
            self._ready.put((_DONE, None))
        except BaseException as e:  # noqa: BLE001 - surfaced to consumer
            self._err = e
            try:
                self._ready.put_nowait((_DONE, None))
            except queue.Full:
                pass
        finally:
            for rd in readers.values():
                try:
                    rd.close()
                except Exception:  # noqa: BLE001
                    pass

    def __iter__(self):
        while True:
            tag, bufset = self._ready.get()
            if tag is _DONE:
                if self._err is not None:
                    raise self._err
                return
            yield tag, bufset

    def recycle(self, bufset: Dict[str, np.ndarray]) -> None:
        self._free.put(bufset)

    def close(self) -> None:
        self._abort = True
        try:
            while True:
                self._ready.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=10.0)


# ------------------------------------------------------ streaming ingest

def _block_plan(extent: int, blk: int) -> List[int]:
    """Shift-back block starts: every window is full-size (ONE compiled
    write shape); the final window's overlap rows rewrite identical
    values — same discipline as the in-memory pipelined fit."""
    starts = [0]
    for i0 in range(blk, extent, blk):
        starts.append(min(i0, extent - blk))
    return starts


def _zero_pad_rows(arr: np.ndarray, segs: List[Tuple[int, int, int]],
                   n_real: int) -> None:
    """Zero computed values (margins) on padding rows so the streamed
    arrays match shard_rows' zero-padded in-memory layout bit for bit."""
    for dst, g0, g1 in segs:
        if g1 > n_real:
            arr[dst + max(0, n_real - g0):dst + (g1 - g0)] = 0


def _publish_stream_metrics(rows: int, seconds: float) -> None:
    try:
        from ..observability.bridge import publish_ingest_metrics
        publish_ingest_metrics(rows=rows, seconds=seconds,
                               rss_bytes=host_rss_bytes())
    except Exception:  # noqa: BLE001 - metrics must never fail ingest
        pass


def _observe_block_seconds(seconds: float) -> None:
    """Per-block hot-path sample, observed straight into the registry's
    `ingest_block_seconds` histogram — the telemetry lint
    (tests/test_observability.py) forbids latency-sample LISTS in io/,
    and a histogram is the right home anyway."""
    try:
        from ..observability.bridge import publish_ingest_metrics
        publish_ingest_metrics(rows=0, seconds=0.0,
                               block_seconds=[seconds])
    except Exception:  # noqa: BLE001 - metrics must never fail ingest
        pass


def stream_fit_arrays(bm, store: ShardStore, *, k: int = 1, mesh=None,
                      margin_fn: Optional[Callable] = None,
                      blk: Optional[int] = None, ring_depth: int = 2,
                      timeline=None):
    """The out-of-core twin of base._pipelined_device_data: shards ->
    (binned_device, (y_d, w_d, t_d, mg_d, gidx)) with gidx always None
    (group ids ride read_column, serial fits only).

    Routing mirrors the in-memory fit exactly: serial (mesh None),
    sharded single-process ([ndev, rows_per_dev, F] super-blocks,
    donated writes at (0, j0, 0), communication-free flatten), and
    multi-host (per-device buffers on LOCAL devices only, assembled via
    jax.make_array_from_single_device_arrays — each host reads only the
    shards its rows live in). No host sync anywhere (sync-point lint,
    tests/test_fit_pipeline.py); ``margin_fn`` (resume/init-score
    streaming: raw features block -> [rows, k] float32 margin) is the
    one documented stall, confined to warm-start fits.

    Value parity with the in-memory route (pinned bit-identical by the
    digest tests): y casts through the same dtype chain (float64 ->
    canonical on sharded paths, stored-dtype -> canonical serial), pad
    rows are zero everywhere shard_rows zero-pads, absent weights are
    ones on real rows / zero on padding, and the binned matrix bins the
    same raw values blockwise (BinMapper.transform is blockwise-exact).
    """
    from ..utils.profiling import NULL_TIMELINE
    tl = timeline if timeline is not None else NULL_TIMELINE
    n, fdim = store.shape
    if n == 0:
        raise ShardStoreError("cannot stream an empty store")
    if mesh is None:
        return _stream_serial(bm, store, k, margin_fn, blk, ring_depth, tl)
    from ..parallel import mesh as meshlib
    if meshlib.process_count() > 1:
        return _stream_multihost(bm, store, k, margin_fn, blk, ring_depth,
                                 tl, mesh)
    return _stream_sharded(bm, store, k, margin_fn, blk, ring_depth, tl,
                           mesh)


def _ring_columns(store: ShardStore, need_weight_stream: bool,
                  y_staging_dtype) -> Dict[str, Tuple]:
    cols: Dict[str, Tuple] = {
        FEATURES: ("store", FEATURES, store.column_dtype(FEATURES)),
        LABEL: ("store", LABEL, y_staging_dtype),
    }
    if need_weight_stream:
        if WEIGHT in store.columns:
            cols[WEIGHT] = ("store", WEIGHT, np.float32)
        else:
            # absent weights are ones on real rows, zero on padding —
            # exactly shard_rows' weights*mask fold
            cols[WEIGHT] = ("const", np.float32(1.0), np.float32)
    return cols


def _stream_serial(bm, store, k, margin_fn, blk, ring_depth, tl):
    import jax
    import jax.numpy as jnp
    from ..compile import cache as compilecache
    n, fdim = store.shape
    if blk is None:
        blk = max(1_000_000, -(-n // 8))
    blk = max(1, min(int(blk), n))
    starts = _block_plan(n, blk)
    tl.meta["blk"] = int(blk)
    tl.meta["n_blocks"] = len(starts)
    y_dt = jax.dtypes.canonicalize_dtype(store.column_dtype(LABEL))
    has_w = WEIGHT in store.columns
    cols = _ring_columns(store, has_w, store.column_dtype(LABEL))
    requests = [(j0, [(0, j0, j0 + blk)]) for j0 in starts]
    bdt = jnp.uint8 if bm.max_bins <= 256 else jnp.int32
    write2 = compilecache.cached_jit(
        lambda buf, block, i0: jax.lax.dynamic_update_slice(
            buf, block, (i0, 0)),
        key="binned_write2d", name="gbdt_binned_write", donate_argnums=0)
    write1 = compilecache.cached_jit(
        lambda buf, block, i0: jax.lax.dynamic_update_slice(
            buf, block, (i0,)),
        key="ingest_write1d", name="ingest_aux_write", donate_argnums=0)
    binned = jnp.zeros((n, fdim), bdt)
    y_d = jnp.zeros((n,), y_dt)
    w_d = jnp.zeros((n,), jnp.float32) if has_w else jnp.ones(
        (n,), jnp.float32)
    mg_d = (jnp.zeros((n, k), jnp.float32) if margin_fn is not None
            else None)
    ring = _PrefetchRing(store, cols, requests, blk, ring_depth)
    t_start = time.perf_counter()
    try:
        for j0, bufset in ring:
            t0 = time.perf_counter()
            feats = bufset[FEATURES]
            i0 = jnp.int32(j0)
            if margin_fn is not None:
                with tl.span(f"margin[{j0}]"):
                    mg = margin_fn(feats).astype(
                        np.float32, copy=False).reshape(blk, k)
                mg_d = write2(mg_d, jax.device_put(mg), i0)
            with tl.span(f"bin[{j0}]"):
                bk = bm.transform(feats)
            with tl.span(f"put[{j0}]"):
                binned = write2(binned, jax.device_put(bk), i0)
                y_d = write1(y_d, jax.device_put(
                    bufset[LABEL].astype(y_dt)), i0)
                if has_w:
                    w_d = write1(w_d, jax.device_put(
                        bufset[WEIGHT].astype(np.float32)), i0)
            ring.recycle(bufset)
            _observe_block_seconds(time.perf_counter() - t0)
    finally:
        ring.close()
    t_d = jnp.ones((n,), jnp.float32)
    if mg_d is None:
        mg_d = jnp.zeros((n, k), jnp.float32)
    _publish_stream_metrics(n, time.perf_counter() - t_start)
    return binned, (y_d, w_d, t_d, mg_d, None)


def _stream_sharded(bm, store, k, margin_fn, blk, ring_depth, tl, mesh):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from ..compile import cache as compilecache
    from ..parallel import mesh as meshlib
    n, fdim = store.shape
    nd = mesh.shape[meshlib.DATA_AXIS]
    n_pad = n + ((-n) % nd)
    ppd = n_pad // nd
    if blk is None:
        blk = max(1_000_000 // nd, -(-ppd // 8))
    blk = max(1, min(int(blk), ppd))
    starts = _block_plan(ppd, blk)
    tl.meta["blk"] = int(blk * nd)
    tl.meta["n_blocks"] = len(starts)
    tl.meta["ndev"] = int(nd)
    # sharded fits cast y through float64 (the serial-path parity cast)
    cols = _ring_columns(store, True, np.float64)
    requests = [(j0, [(d * blk, d * ppd + j0, d * ppd + j0 + blk)
                      for d in range(nd)]) for j0 in starts]
    sh3 = NamedSharding(mesh, P(meshlib.DATA_AXIS, None, None))
    sh2 = NamedSharding(mesh, P(meshlib.DATA_AXIS, None))
    bdt = jnp.uint8 if bm.max_bins <= 256 else jnp.int32
    write3 = compilecache.cached_jit(
        lambda buf, block, j0: jax.lax.dynamic_update_slice(
            buf, block, (0, j0, 0)),
        key="binned_write3d", name="gbdt_binned_write", donate_argnums=0)
    write2 = compilecache.cached_jit(
        lambda buf, block, j0: jax.lax.dynamic_update_slice(
            buf, block, (0, j0)),
        key="ingest_write2d", name="ingest_aux_write", donate_argnums=0)
    binned = jnp.zeros((nd, ppd, fdim), bdt, device=sh3)
    y_d = jnp.zeros((nd, ppd), jnp.float32, device=sh2)
    w_d = jnp.zeros((nd, ppd), jnp.float32, device=sh2)
    t_d = jnp.zeros((nd, ppd), jnp.float32, device=sh2)
    mg_d = (jnp.zeros((nd, ppd, k), jnp.float32, device=sh3)
            if margin_fn is not None else None)
    ring = _PrefetchRing(store, cols, requests, nd * blk, ring_depth)
    t_start = time.perf_counter()
    try:
        for j0, bufset in ring:
            t0 = time.perf_counter()
            feats = bufset[FEATURES]
            segs = [(d * blk, d * ppd + j0, d * ppd + j0 + blk)
                    for d in range(nd)]
            i0 = jnp.int32(j0)
            if margin_fn is not None:
                with tl.span(f"margin[{j0}]"):
                    mg = margin_fn(feats).astype(
                        np.float32, copy=False).reshape(nd * blk, k)
                    _zero_pad_rows(mg, segs, n)
                mg_d = write3(mg_d, jax.device_put(
                    mg.reshape(nd, blk, k), sh3), i0)
            with tl.span(f"bin[{j0}]"):
                bk = bm.transform(feats).reshape(nd, blk, fdim)
            with tl.span(f"put[{j0}]"):
                binned = write3(binned, jax.device_put(bk, sh3), i0)
                y_d = write2(y_d, jax.device_put(
                    bufset[LABEL].astype(np.float32).reshape(nd, blk),
                    sh2), i0)
                w_d = write2(w_d, jax.device_put(
                    bufset[WEIGHT].astype(np.float32).reshape(nd, blk),
                    sh2), i0)
                # is_train is 1 on real rows, 0 on padding — exactly
                # shard_rows' padded (~is_valid) mask
                t_d = write2(t_d, jax.device_put(
                    _train_mask(segs, n, nd, blk), sh2), i0)
            ring.recycle(bufset)
            _observe_block_seconds(time.perf_counter() - t0)
    finally:
        ring.close()
    flat2 = compilecache.cached_jit(
        lambda b: b.reshape(b.shape[0] * b.shape[1], b.shape[2]),
        key=("binned_flat", nd), name="gbdt_binned_flat",
        out_shardings=meshlib.data_sharding(mesh, 2))
    flat1 = compilecache.cached_jit(
        lambda b: b.reshape(b.shape[0] * b.shape[1]),
        key=("ingest_flat1", nd), name="ingest_aux_flat",
        out_shardings=meshlib.data_sharding(mesh, 1))
    out_mg = (flat2(mg_d) if mg_d is not None
              else jnp.zeros((n_pad, k), jnp.float32))
    _publish_stream_metrics(n, time.perf_counter() - t_start)
    return flat2(binned), (flat1(y_d), flat1(w_d), flat1(t_d), out_mg,
                           None)


def _train_mask(segs: List[Tuple[int, int, int]], n_real: int, nd: int,
                blk: int) -> np.ndarray:
    """Host [nd, blk] is_train block: 1.0 real rows, 0.0 padding — what
    shard_rows produces for (~is_valid) when no validation column rides
    the store."""
    out = np.ones((nd * blk,), np.float32)
    _zero_pad_rows(out, segs, n_real)
    return out.reshape(nd, blk)


def _stream_multihost(bm, store, k, margin_fn, blk, ring_depth, tl, mesh):
    import jax
    import jax.numpy as jnp
    from ..compile import cache as compilecache
    from ..parallel import mesh as meshlib
    from ..parallel import multihost as mhlib
    n, fdim = store.shape
    nd = mesh.shape[meshlib.DATA_AXIS]
    n_pad = n + ((-n) % nd)
    ppd = n_pad // nd
    spans = mhlib.local_row_slices(mesh, n_pad)
    if blk is None:
        blk = max(1_000_000 // nd, -(-ppd // 8))
    blk = max(1, min(int(blk), ppd))
    starts = _block_plan(ppd, blk)
    tl.meta["blk"] = int(blk * len(spans))
    tl.meta["n_blocks"] = len(starts)
    tl.meta["ndev"] = int(nd)
    tl.meta["local_devices"] = len(spans)
    cols = _ring_columns(store, True, np.float64)
    # per-host shard ownership: requests touch ONLY this host's spans,
    # so the ring opens only the shards this host's rows live in
    requests = [((di, j0), [(0, r0 + j0, r0 + j0 + blk)])
                for j0 in starts
                for di, (_dev, r0, _r1) in enumerate(spans)]
    bdt = jnp.uint8 if bm.max_bins <= 256 else jnp.int32
    write2 = compilecache.cached_jit(
        lambda buf, block, i0: jax.lax.dynamic_update_slice(
            buf, block, (i0, 0)),
        key="binned_write2d", name="gbdt_binned_write", donate_argnums=0)
    write1 = compilecache.cached_jit(
        lambda buf, block, i0: jax.lax.dynamic_update_slice(
            buf, block, (i0,)),
        key="ingest_write1d", name="ingest_aux_write", donate_argnums=0)
    b_bufs = [jax.device_put(jnp.zeros((ppd, fdim), bdt), dev)
              for dev, _r0, _r1 in spans]
    y_bufs = [jax.device_put(jnp.zeros((ppd,), jnp.float32), dev)
              for dev, _r0, _r1 in spans]
    w_bufs = [jax.device_put(jnp.zeros((ppd,), jnp.float32), dev)
              for dev, _r0, _r1 in spans]
    t_bufs = [jax.device_put(jnp.zeros((ppd,), jnp.float32), dev)
              for dev, _r0, _r1 in spans]
    mg_bufs = ([jax.device_put(jnp.zeros((ppd, k), jnp.float32), dev)
                for dev, _r0, _r1 in spans]
               if margin_fn is not None else None)
    ring = _PrefetchRing(store, cols, requests, blk, ring_depth)
    t_start = time.perf_counter()
    rows_local = 0
    try:
        for (di, j0), bufset in ring:
            t0 = time.perf_counter()
            dev, r0, _r1 = spans[di]
            segs = [(0, r0 + j0, r0 + j0 + blk)]
            rows_local += blk
            feats = bufset[FEATURES]
            i0 = jnp.int32(j0)
            if margin_fn is not None:
                with tl.span(f"margin[{r0 + j0}]"):
                    mg = margin_fn(feats).astype(
                        np.float32, copy=False).reshape(blk, k)
                    _zero_pad_rows(mg, segs, n)
                mg_bufs[di] = write2(mg_bufs[di],
                                     jax.device_put(mg, dev), i0)
            with tl.span(f"bin[{r0 + j0}]"):
                bk = bm.transform(feats)
            with tl.span(f"put[{r0 + j0}]"):
                b_bufs[di] = write2(b_bufs[di], jax.device_put(bk, dev), i0)
                y_bufs[di] = write1(y_bufs[di], jax.device_put(
                    bufset[LABEL].astype(np.float32), dev), i0)
                w_bufs[di] = write1(w_bufs[di], jax.device_put(
                    bufset[WEIGHT].astype(np.float32), dev), i0)
                t_bufs[di] = write1(t_bufs[di], jax.device_put(
                    _train_mask(segs, n, 1, blk).reshape(blk), dev), i0)
            ring.recycle(bufset)
            _observe_block_seconds(time.perf_counter() - t0)
    finally:
        ring.close()
    sh2 = meshlib.data_sharding(mesh, 2)
    sh1 = meshlib.data_sharding(mesh, 1)
    binned = jax.make_array_from_single_device_arrays((n_pad, fdim), sh2,
                                                      b_bufs)
    y_d = jax.make_array_from_single_device_arrays((n_pad,), sh1, y_bufs)
    w_d = jax.make_array_from_single_device_arrays((n_pad,), sh1, w_bufs)
    t_d = jax.make_array_from_single_device_arrays((n_pad,), sh1, t_bufs)
    mg_d = (jax.make_array_from_single_device_arrays(
                (n_pad, k), sh2, mg_bufs) if mg_bufs is not None
            else mhlib.zeros_row_sharded(mesh, (n_pad, k)))
    _publish_stream_metrics(rows_local, time.perf_counter() - t_start)
    return binned, (y_d, w_d, t_d, mg_d, None)


__all__ = [
    "MANIFEST_NAME", "STORE_FORMAT", "STORE_SCHEMA_VERSION",
    "FEATURES", "LABEL", "WEIGHT", "GROUP",
    "ShardStoreError", "ShardVerifyError", "ShardStore",
    "ShardStoreWriter", "write_store", "is_store_path", "as_store",
    "fit_bin_mapper", "read_column", "stream_fit_arrays",
    "host_rss_bytes",
]
