"""Distributed serving: per-host servers + driver registration/routing.

Reference: the two distributed thirds of Spark Serving —
- DistributedHTTPSource.scala:26-424: per-executor `JVMSharedServer`s, a
  `MultiChannelMap` handing requests round-robin to partition channels, and
  reply-on-owning-JVM routing (`respond(batchId, uuid, response)` :396-402);
- continuous/HTTPSourceV2.scala:45-715: `WorkerServer`s POST a `ServiceInfo`
  to a driver service (:113-173) which keeps a `machine:partition` routing
  table; continuous mode replaces micro-batch ticks with long-lived readers.

TPU-native restructure: each host runs a `ServingServer` (io/serving.py) with
the compiled model resident; a `ServingCoordinator` plays the driver service —
workers register `ServiceInfo`, clients either fetch the routing table and
talk to workers directly (the reference's usual path: the load balancer
forwards to executor servers) or POST through the coordinator's forwarding
gateway, which round-robins across workers (MultiChannelMap.addToNextList
semantics). Replies always come back on the connection that owns the request —
there is no cross-host respond hop to re-create because each worker owns its
own sockets.

Failure handling (resilience layer):
- workers HEARTBEAT to the coordinator (`POST /heartbeat`); a monitor thread
  evicts heartbeat-capable workers silent for `heartbeat_timeout_s` — a
  dead worker cannot stay in the routing table forever (manual
  registrations without a heartbeat loop keep the old contract: evicted
  only by gateway failure detection);
- the gateway retries a failed forward on the next healthy worker under a
  shared `RetryPolicy`, deregistering unreachable workers immediately;
- an evicted-but-alive worker's next heartbeat gets 410 Gone and the worker
  RE-REGISTERS itself — transient eviction (a chaos-injected forward
  failure, a network blip) heals without operator action;
- request budgets ride the `X-Deadline-Ms` header: the gateway answers 504
  when the budget is spent and re-encodes only the REMAINING budget on each
  forward hop, so a retry can never exceed the client's patience.
"""

from __future__ import annotations

import dataclasses
import json
import socket
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

from ..observability import (EventLog, TRACE_HEADER, get_registry,
                             mint_trace_id, trace_id_from_headers)
from ..resilience import Deadline, RetryError, RetryPolicy
from .serving import _INSTANCE_SEQ, ServingServer


class ServiceInfo:
    """Worker registration record (HTTPSourceV2.scala ServiceInfo :126-152).

    `heartbeating=True` declares at REGISTRATION time that this worker runs
    a heartbeat loop, making it subject to silence-based eviction from the
    moment it registers — inferring capability from the first received beat
    would leave a worker that dies (or is GIL-starved by a jit compile)
    before ever beating in the routing table forever."""

    __slots__ = ("name", "host", "port", "machine", "partition",
                 "heartbeating")

    def __init__(self, name: str, host: str, port: int,
                 machine: str = "localhost", partition: int = 0,
                 heartbeating: bool = False):
        self.name = name
        self.host = host
        self.port = port
        self.machine = machine
        self.partition = partition
        self.heartbeating = heartbeating

    def to_dict(self) -> Dict:
        return {"name": self.name, "host": self.host, "port": self.port,
                "machine": self.machine, "partition": self.partition,
                "heartbeating": self.heartbeating}

    @staticmethod
    def from_dict(d: Dict) -> "ServiceInfo":
        return ServiceInfo(d["name"], d["host"], int(d["port"]),
                           d.get("machine", "localhost"),
                           int(d.get("partition", 0)),
                           bool(d.get("heartbeating", False)))

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/"


def _default_transport(url: str, body: bytes, headers: Dict[str, str],
                       timeout: float) -> Tuple[int, bytes]:
    """One forward hop. Raises urllib.error.HTTPError for alive-but-erroring
    workers and other exceptions for unreachable ones — the gateway treats
    the two differently. Injectable for chaos testing (FaultInjector.wrap)."""
    req = urllib.request.Request(url, data=body, headers=headers)
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, r.read()


class ServingCoordinator:
    """Driver-role registration + routing service with worker health.

    Endpoints:
      POST /register   body = ServiceInfo JSON           (worker -> driver)
      POST /heartbeat  body = ServiceInfo JSON; 410 Gone => re-register
      GET  /routes/<service>                             routing table JSON
      GET  /health     worker counts + eviction stats
      GET  /metrics    Prometheus text (forward latency + gateway counters)
      POST /gateway/<service>  forward to a healthy worker (retry + evict)

    Workers silent for `heartbeat_timeout_s` are evicted by a monitor
    thread (the driver-side failure detector the reference lacks — its
    routing table only ever grows, HTTPSourceV2.scala:113-173).
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 forward_timeout: float = 30.0,
                 heartbeat_timeout_s: float = 10.0,
                 forward_transport=None,
                 forward_retry: Optional[RetryPolicy] = None,
                 registry=None, event_log=None,
                 metrics_label: Optional[str] = None):
        self.host, self.port = host, port
        self.forward_timeout = forward_timeout
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self._routes: Dict[str, List[ServiceInfo]] = {}
        self._rr: Dict[str, int] = {}
        self._last_seen: Dict[Tuple[str, str, int], float] = {}
        self._known: set = set()  # services that have EVER had a worker
        # workers subject to silence-based eviction: declared heartbeating
        # at registration, or actually heartbeat at least once — a plain
        # register()/register_with_retries worker with no heartbeat loop
        # keeps the pre-resilience contract (evicted only by gateway
        # failure detection)
        self._hb_seen: set = set()
        self._lock = threading.Lock()
        self._stopev = threading.Event()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._transport = forward_transport or _default_transport
        # bounded fail-fast: ~8 attempts spanning ~1.5 s rides out a
        # transient all-evicted dip (heartbeat re-registration is sub-second)
        # without hanging a doomed request for the full forward_timeout
        self.forward_retry = forward_retry or RetryPolicy(
            attempts=8, backoff_s=0.05, multiplier=1.5, max_backoff_s=0.4,
            jitter=0.1)
        # telemetry: gateway counters + forward-latency histogram in the
        # (default: process-global) registry, per-hop forward spans in the
        # coordinator's own event log (the gateway side of a trace)
        self.registry = registry if registry is not None else get_registry()
        self.events = event_log if event_log is not None else EventLog()
        self.metrics_label = (metrics_label if metrics_label is not None
                              else f"gateway-{next(_INSTANCE_SEQ)}")
        lbl = {"instance": self.metrics_label}
        self._m = {
            "forwards": self.registry.counter(
                "gateway_forwards_total", "gateway requests forwarded", lbl),
            "forward_retries": self.registry.counter(
                "gateway_forward_retries_total",
                "failover/retry forward attempts past the first", lbl),
            "evictions": self.registry.counter(
                "gateway_evictions_total",
                "workers dropped from the routing table", lbl),
            "heartbeats": self.registry.counter(
                "gateway_heartbeats_total", "worker heartbeats recorded",
                lbl),
        }
        self._m_failures = self.registry.counter(
            "gateway_forward_failures_total",
            "forward transport failures (worker unreachable/dropped)", lbl)
        self._m_expired = self.registry.counter(
            "gateway_expired_total", "gateway replies with 504 (budget "
            "spent)", lbl)
        self._m_shed = self.registry.counter(
            "gateway_shed_total", "gateway replies with 503 (workers "
            "shedding or none registered)", lbl)
        self._lat_hist = self.registry.histogram(
            "gateway_request_latency_seconds",
            "gateway receive-to-reply latency", lbl)
        self._workers_gauge = self.registry.gauge(
            "gateway_registered_workers",
            "workers currently routable (all services)", lbl)
        self._workers_gauge.set_function(self._worker_count)

    def _worker_count(self) -> int:
        with self._lock:
            return sum(len(v) for v in self._routes.values())

    @property
    def stats(self) -> Dict[str, int]:
        """Counter view (registry-backed; the pre-observability dict)."""
        return {k: int(c.value) for k, c in self._m.items()}

    # -------------------------------------------------------------- registry
    def register(self, info: ServiceInfo) -> None:
        with self._lock:
            lst = self._routes.setdefault(info.name, [])
            # a worker identity is (machine, partition) — re-registration
            # (e.g. a restarted worker on a new port) replaces its stale
            # entry. Workers must carry unique identities; the
            # DistributedServingServer defaults derive them from hostname +
            # bound port so unconfigured workers on any topology never
            # collide. Same-endpoint re-posts are also collapsed.
            for s in lst:
                if (s.machine, s.partition) == (info.machine,
                                                info.partition) \
                        or (s.host, s.port) == (info.host, info.port):
                    self._last_seen.pop((info.name, s.host, s.port), None)
                    self._hb_seen.discard((info.name, s.host, s.port))
            lst[:] = [s for s in lst
                      if (s.machine, s.partition) != (info.machine,
                                                      info.partition)
                      and (s.host, s.port) != (info.host, info.port)]
            lst.append(info)
            self._known.add(info.name)
            key = (info.name, info.host, info.port)
            self._last_seen[key] = time.monotonic()
            if info.heartbeating:
                # eviction-eligible from registration: a worker that dies
                # before its first beat must not stay routable forever
                self._hb_seen.add(key)

    def routes(self, name: str) -> List[ServiceInfo]:
        with self._lock:
            return list(self._routes.get(name, []))

    def deregister(self, name: str, info: ServiceInfo) -> None:
        """Drop a worker from the routing table (gateway failure detection:
        a worker whose forward errored is evicted until it re-registers —
        an alive worker's next heartbeat gets 410 and re-registers it)."""
        with self._lock:
            lst = self._routes.get(name)
            if lst:
                before = len(lst)
                lst[:] = [s for s in lst
                          if (s.host, s.port) != (info.host, info.port)]
                if len(lst) < before:
                    self._m["evictions"].inc()
            self._last_seen.pop((name, info.host, info.port), None)
            self._hb_seen.discard((name, info.host, info.port))

    def heartbeat(self, info: ServiceInfo) -> str:
        """Record a worker heartbeat. Returns:
        "ok"         — worker is routable, beat recorded;
        "gone"       — worker is not in the table and its (machine,
                       partition) slot is free: re-register (HTTP 410);
        "superseded" — a DIFFERENT endpoint now owns this worker's
                       (machine, partition) identity (HTTP 409): do NOT
                       re-register — doing so would collapse the successor
                       out of the table and the two incarnations would evict
                       each other in a permanent flap. Stand down; if the
                       successor dies the slot frees up and the next beat
                       gets "gone" again."""
        with self._lock:
            lst = self._routes.get(info.name, [])
            if any((s.host, s.port) == (info.host, info.port) for s in lst):
                key = (info.name, info.host, info.port)
                self._last_seen[key] = time.monotonic()
                self._hb_seen.add(key)
                self._m["heartbeats"].inc()
                return "ok"
            if any((s.machine, s.partition) == (info.machine, info.partition)
                   for s in lst):
                return "superseded"
            return "gone"

    def _next_worker(self, name: str) -> Optional[ServiceInfo]:
        """Round-robin channel selection (MultiChannelMap.addToNextList,
        DistributedHTTPSource.scala:81-83)."""
        with self._lock:
            lst = self._routes.get(name)
            if not lst:
                return None
            i = self._rr.get(name, 0) % len(lst)
            self._rr[name] = i + 1
            return lst[i]

    # --------------------------------------------------------------- health
    def _monitor_loop(self) -> None:
        """Evict HEARTBEATING workers whose last beat is older than
        heartbeat_timeout_s. Workers that never heartbeat (plain
        register()/register_with_retries, no DistributedServingServer loop)
        are exempt — for them only gateway failure detection evicts, the
        pre-resilience contract."""
        interval = max(0.02, self.heartbeat_timeout_s / 4.0)
        while not self._stopev.wait(interval):
            cutoff = time.monotonic() - self.heartbeat_timeout_s
            with self._lock:
                for name, lst in self._routes.items():
                    stale = [s for s in lst
                             if (name, s.host, s.port) in self._hb_seen
                             and self._last_seen.get(
                                 (name, s.host, s.port), 0.0) < cutoff]
                    if stale:
                        lst[:] = [s for s in lst if s not in stale]
                        for s in stale:
                            self._last_seen.pop((name, s.host, s.port),
                                                None)
                            self._hb_seen.discard((name, s.host, s.port))
                            self._m["evictions"].inc()

    def health(self) -> Dict:
        with self._lock:
            services = {name: len(lst) for name, lst in self._routes.items()}
        return {"services": services,
                "heartbeat_timeout_s": self.heartbeat_timeout_s,
                "stats": dict(self.stats)}

    # -------------------------------------------------------------- gateway
    def _handle_gateway(self, reply, name: str, body: bytes,
                        headers: Dict[str, str]) -> None:
        """Forward with bounded retry + eviction + deadline propagation.
        `reply(status, body)` writes the client response. The trace id
        (client-sent X-Trace-Id or minted here) rides every forward hop —
        retries and failovers included — and comes back on the reply, so
        the gateway's per-attempt spans and the worker's dispatch spans
        join on one id."""
        trace_id = trace_id_from_headers(headers) or mint_trace_id()
        t_recv = time.perf_counter()
        raw_reply = reply

        def reply(status: int, rbody: bytes, rheaders=None) -> None:
            dur = time.perf_counter() - t_recv
            self._lat_hist.observe(dur)
            if status == 504:
                self._m_expired.inc()
            elif status == 503:
                self._m_shed.inc()
            self.events.append("reply", trace_id, dur_s=dur, status=status)
            raw_reply(status, rbody,
                      {TRACE_HEADER: trace_id, **(rheaders or {})})

        if name not in self._known:
            reply(503, json.dumps(
                {"error": f"no workers for {name!r}: never registered"}
            ).encode())
            return
        client_deadline = Deadline.from_headers(headers)
        deadline = (client_deadline
                    or Deadline.after(self.forward_timeout))
        if deadline.expired:
            reply(504, b'{"error": "deadline exceeded"}')
            return
        policy = self.forward_retry
        if client_deadline is not None:
            # an explicit client budget makes the DEADLINE the retry
            # contract: keep failing over for as long as the client is
            # still waiting (rides out transient all-evicted churn), not
            # just for the fail-fast attempt count
            policy = dataclasses.replace(policy, attempts=None)
        elif policy.attempts is not None:
            # bounded fail-fast must still be able to try EVERY registered
            # worker once (the pre-resilience per-worker bound): a
            # correlated failure of N-1 workers out of many should reach
            # the survivor, not give up at a fixed count
            policy = dataclasses.replace(
                policy, attempts=max(policy.attempts,
                                     len(self.routes(name)) + 1))
        self._m["forwards"].inc()
        last_err = "routing table empty (all workers evicted)"
        last_shed = None  # most recent worker 503 (queue-full) response
        for attempt in policy.attempts_iter(deadline=deadline):
            if attempt.index:
                self._m["forward_retries"].inc()
            worker = self._next_worker(name)
            if worker is None:
                # all evicted: the backoff sleep gives heartbeat
                # re-registration a chance to repopulate the table
                self.events.append("forward_attempt", trace_id,
                                   attempt=attempt.index,
                                   outcome="no_worker")
                continue
            remaining = deadline.remaining()
            if remaining <= 0:
                break
            fwd_headers = {"Content-Type": "application/json",
                           TRACE_HEADER: trace_id,
                           Deadline.HEADER: deadline.to_header()}
            w_id = f"{worker.host}:{worker.port}"
            t_fwd = time.perf_counter()
            try:
                status, rbody = self._transport(
                    worker.url, body, fwd_headers,
                    min(self.forward_timeout, remaining))
            except urllib.error.HTTPError as e:
                if e.code == 503:
                    # worker SHED the request (bounded queue full): it is
                    # alive — don't evict — but another worker may have
                    # room, so keep failing over; remember the shed reply
                    # (incl. Retry-After) in case every worker is full
                    last_err = f"worker {worker.host}:{worker.port} shed " \
                               f"(503 queue full)"
                    last_shed = (e.read(),
                                 {k: v for k, v in e.headers.items()
                                  if k.lower() == "retry-after"})
                    self.events.append(
                        "forward_attempt", trace_id, attempt=attempt.index,
                        dur_s=time.perf_counter() - t_fwd, worker=w_id,
                        outcome="shed")
                    continue
                # worker is ALIVE and answered with a non-shed error
                # status — deterministic for this request; surface it
                # (with its headers), don't evict
                self.events.append(
                    "forward_attempt", trace_id, attempt=attempt.index,
                    dur_s=time.perf_counter() - t_fwd, worker=w_id,
                    outcome=f"http_{e.code}")
                reply(e.code, e.read(),
                      {k: v for k, v in e.headers.items()
                       if k.lower() == "retry-after"})
                return
            except Exception as e:  # unreachable: evict + retry next worker
                last_err = str(e)
                self._m_failures.inc()
                self.events.append(
                    "forward_attempt", trace_id, attempt=attempt.index,
                    dur_s=time.perf_counter() - t_fwd, worker=w_id,
                    outcome="unreachable")
                self.deregister(name, worker)
            else:
                self.events.append(
                    "forward_attempt", trace_id, attempt=attempt.index,
                    dur_s=time.perf_counter() - t_fwd, worker=w_id,
                    outcome="ok")
                # reply OUTSIDE the try: a client that disconnects while the
                # response is being written must not be misread as a worker
                # failure (which would evict the healthy worker and re-send
                # the already-processed request — a duplicate inference)
                reply(status, rbody)
                return
        if last_shed is not None and not deadline.expired:
            # every attempt landed on a full queue: propagate the shed
            # (503 + Retry-After) so the client backs off correctly
            reply(503, last_shed[0], last_shed[1])
            return
        # unbounded mode only exits on budget exhaustion -> 504; bounded
        # mode distinguishes attempts-exhausted (502) from expired (504)
        reply(504 if (client_deadline is not None or deadline.expired)
              else 502,
              json.dumps({"error": f"forward failed: {last_err}"}).encode())

    # ------------------------------------------------------------- lifecycle
    def start(self) -> "ServingCoordinator":
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else b""
                if self.path == "/register":
                    try:
                        outer.register(ServiceInfo.from_dict(
                            json.loads(body.decode())))
                        self._reply(200, b'{"ok": true}')
                    except (ValueError, KeyError) as e:
                        self._reply(400, json.dumps(
                            {"error": str(e)}).encode())
                elif self.path == "/heartbeat":
                    try:
                        state = outer.heartbeat(ServiceInfo.from_dict(
                            json.loads(body.decode())))
                    except (ValueError, KeyError) as e:
                        self._reply(400, json.dumps(
                            {"error": str(e)}).encode())
                        return
                    if state == "ok":
                        self._reply(200, b'{"ok": true}')
                    elif state == "superseded":
                        self._reply(409, b'{"error": "identity taken by a '
                                         b'newer registration; stand down"}')
                    else:
                        self._reply(410, b'{"error": "unknown worker; '
                                         b're-register"}')
                elif self.path.startswith("/gateway/"):
                    name = self.path[len("/gateway/"):].strip("/")
                    outer._handle_gateway(self._reply, name, body,
                                          dict(self.headers))
                else:
                    self._reply(404, b'{"error": "unknown endpoint"}')

            def do_GET(self):
                if self.path.startswith("/routes/"):
                    name = self.path[len("/routes/"):].strip("/")
                    body = json.dumps(
                        [s.to_dict() for s in outer.routes(name)]).encode()
                    self._reply(200, body)
                elif self.path == "/health":
                    self._reply(200, json.dumps(outer.health()).encode())
                elif self.path == "/metrics":
                    self._reply(200,
                                outer.registry.render_prometheus().encode(),
                                ctype="text/plain; version=0.0.4; "
                                      "charset=utf-8")
                else:
                    self._reply(404, b'{"error": "unknown endpoint"}')

            def _reply(self, status: int, body: bytes, headers=None,
                       ctype: str = "application/json"):
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        class Server(ThreadingHTTPServer):
            daemon_threads = True
            request_queue_size = 128

        self._httpd = Server((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        threading.Thread(target=self._httpd.serve_forever,
                         daemon=True).start()
        threading.Thread(target=self._monitor_loop, daemon=True).start()
        return self

    def stop(self) -> None:
        self._stopev.set()
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
        # freeze the collect-time gauge so the registry (which outlives
        # this coordinator) does not pin it in memory via the callback; a
        # stopped coordinator routes to nobody, so it scrapes as 0
        self._workers_gauge.set_function(None)
        self._workers_gauge.set(0.0)

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"


def register_with_retries(coordinator_url: str, info: ServiceInfo,
                          retries: int = 10, delay_s: float = 0.2,
                          policy: Optional[RetryPolicy] = None) -> None:
    """Worker-side registration with bounded retries (the workers' ServiceInfo
    POST, HTTPSourceV2.scala:126-152), routed through the shared
    RetryPolicy (retry discipline mirrors the reference's port-probe/
    rendezvous retry loops, TrainUtils.scala:496-512)."""
    body = json.dumps(info.to_dict()).encode()

    def post_once() -> None:
        req = urllib.request.Request(
            coordinator_url.rstrip("/") + "/register", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=5.0) as r:
            if r.status != 200:
                raise ConnectionError(f"register returned {r.status}")

    pol = policy or RetryPolicy(attempts=retries, backoff_s=delay_s,
                                multiplier=1.5, max_backoff_s=2.0,
                                jitter=0.1)
    try:
        pol.call(post_once)
    except RetryError as e:
        raise ConnectionError(
            f"could not register with coordinator at {coordinator_url}: "
            f"{e.last}") from e


class DistributedServingServer(ServingServer):
    """A per-host worker: ServingServer that announces itself to the
    coordinator on start (WorkerServer + ServiceInfo POST,
    HTTPSourceV2.scala:318-430) and HEARTBEATS for liveness — a worker the
    coordinator evicted (crash suspected, chaos-injected forward failure)
    re-registers itself on the next beat if it is actually alive."""

    def __init__(self, handler, coordinator_url: str, service_name: str,
                 partition: Optional[int] = None,
                 machine: Optional[str] = None,
                 heartbeat_interval_s: float = 1.0, **kw):
        super().__init__(handler, **kw)
        self.coordinator_url = coordinator_url
        self.service_name = service_name
        self.partition = partition
        self.machine = machine
        self.heartbeat_interval_s = heartbeat_interval_s
        self._info: Optional[ServiceInfo] = None
        self._hb_stop = threading.Event()

    def start(self) -> "DistributedServingServer":
        super().start()
        # default identity is (hostname, bound port): unique across hosts AND
        # across multiple unconfigured workers on one host, so defaults never
        # evict each other in the coordinator's (machine, partition) registry
        machine = (self.machine if self.machine is not None
                   else socket.gethostname())
        partition = self.partition if self.partition is not None else self.port
        self._info = ServiceInfo(self.service_name, self.host, self.port,
                                 machine, partition, heartbeating=True)
        register_with_retries(self.coordinator_url, self._info)
        threading.Thread(target=self._heartbeat_loop, daemon=True).start()
        return self

    def _heartbeat_loop(self) -> None:
        url = self.coordinator_url.rstrip("/") + "/heartbeat"
        body = json.dumps(self._info.to_dict()).encode()
        while not self._hb_stop.wait(self.heartbeat_interval_s):
            try:
                req = urllib.request.Request(
                    url, data=body,
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=5.0):
                    pass
            except urllib.error.HTTPError as e:
                # 409 (identity superseded by a newer registration) is a
                # deliberate stand-down: keep beating WITHOUT re-registering,
                # so two live incarnations of one identity cannot evict each
                # other in a flap loop; if the successor dies the next beat
                # gets 410 and heals normally
                if e.code == 410 and not self._hb_stop.is_set():
                    # evicted while alive (gateway failure detection tripped
                    # on a transient fault): heal by re-registering
                    try:
                        register_with_retries(
                            self.coordinator_url, self._info, retries=3,
                            delay_s=max(0.05,
                                        self.heartbeat_interval_s / 4.0))
                    except ConnectionError:
                        pass  # next beat tries again
            except Exception:  # noqa: BLE001 - coordinator briefly
                pass  # unreachable: keep beating; it may come back

    def stop(self) -> None:
        self._hb_stop.set()
        super().stop()


def fetch_routes(coordinator_url: str, name: str) -> List[ServiceInfo]:
    """Client-side routing-table fetch (the reference's load-balancer path:
    clients resolve `machine:partition` workers and talk to them directly)."""
    with urllib.request.urlopen(
            coordinator_url.rstrip("/") + f"/routes/{name}",
            timeout=5.0) as r:
        return [ServiceInfo.from_dict(d) for d in json.loads(r.read())]
