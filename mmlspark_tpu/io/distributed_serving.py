"""Distributed serving: per-host servers + driver registration/routing.

Reference: the two distributed thirds of Spark Serving —
- DistributedHTTPSource.scala:26-424: per-executor `JVMSharedServer`s, a
  `MultiChannelMap` handing requests round-robin to partition channels, and
  reply-on-owning-JVM routing (`respond(batchId, uuid, response)` :396-402);
- continuous/HTTPSourceV2.scala:45-715: `WorkerServer`s POST a `ServiceInfo`
  to a driver service (:113-173) which keeps a `machine:partition` routing
  table; continuous mode replaces micro-batch ticks with long-lived readers.

TPU-native restructure: each host runs a `ServingServer` (io/serving.py) with
the compiled model resident; a `ServingCoordinator` plays the driver service —
workers register `ServiceInfo`, clients either fetch the routing table and
talk to workers directly (the reference's usual path: the load balancer
forwards to executor servers) or POST through the coordinator's forwarding
gateway, which round-robins across workers (MultiChannelMap.addToNextList
semantics). Replies always come back on the connection that owns the request —
there is no cross-host respond hop to re-create because each worker owns its
own sockets. The micro-batch tick does not exist at all: worker dispatchers
are continuous (the HTTPSourceV2-continuous analogue), so "continuous mode"
is the only mode.
"""

from __future__ import annotations

import json
import socket
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

from .serving import ServingServer


class ServiceInfo:
    """Worker registration record (HTTPSourceV2.scala ServiceInfo :126-152)."""

    __slots__ = ("name", "host", "port", "machine", "partition")

    def __init__(self, name: str, host: str, port: int,
                 machine: str = "localhost", partition: int = 0):
        self.name = name
        self.host = host
        self.port = port
        self.machine = machine
        self.partition = partition

    def to_dict(self) -> Dict:
        return {"name": self.name, "host": self.host, "port": self.port,
                "machine": self.machine, "partition": self.partition}

    @staticmethod
    def from_dict(d: Dict) -> "ServiceInfo":
        return ServiceInfo(d["name"], d["host"], int(d["port"]),
                           d.get("machine", "localhost"),
                           int(d.get("partition", 0)))

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/"


class ServingCoordinator:
    """Driver-role registration + routing service.

    Endpoints:
      POST /register   body = ServiceInfo JSON           (worker -> driver)
      GET  /routes/<service>                             routing table JSON
      POST /gateway/<service>  forward round-robin to a registered worker
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 forward_timeout: float = 30.0):
        self.host, self.port = host, port
        self.forward_timeout = forward_timeout
        self._routes: Dict[str, List[ServiceInfo]] = {}
        self._rr: Dict[str, int] = {}
        self._lock = threading.Lock()
        self._httpd: Optional[ThreadingHTTPServer] = None

    # -------------------------------------------------------------- registry
    def register(self, info: ServiceInfo) -> None:
        with self._lock:
            lst = self._routes.setdefault(info.name, [])
            # a worker identity is (machine, partition) — re-registration
            # (e.g. a restarted worker on a new port) replaces its stale
            # entry. Workers must carry unique identities; the
            # DistributedServingServer defaults derive them from hostname +
            # bound port so unconfigured workers on any topology never
            # collide. Same-endpoint re-posts are also collapsed.
            lst[:] = [s for s in lst
                      if (s.machine, s.partition) != (info.machine,
                                                      info.partition)
                      and (s.host, s.port) != (info.host, info.port)]
            lst.append(info)

    def routes(self, name: str) -> List[ServiceInfo]:
        with self._lock:
            return list(self._routes.get(name, []))

    def deregister(self, name: str, info: ServiceInfo) -> None:
        """Drop a worker from the routing table (gateway failure detection:
        a worker whose forward errored is evicted until it re-registers)."""
        with self._lock:
            lst = self._routes.get(name)
            if lst:
                lst[:] = [s for s in lst
                          if (s.host, s.port) != (info.host, info.port)]

    def _next_worker(self, name: str) -> Optional[ServiceInfo]:
        """Round-robin channel selection (MultiChannelMap.addToNextList,
        DistributedHTTPSource.scala:81-83)."""
        with self._lock:
            lst = self._routes.get(name)
            if not lst:
                return None
            i = self._rr.get(name, 0) % len(lst)
            self._rr[name] = i + 1
            return lst[i]

    # ------------------------------------------------------------- lifecycle
    def start(self) -> "ServingCoordinator":
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else b""
                if self.path == "/register":
                    try:
                        outer.register(ServiceInfo.from_dict(
                            json.loads(body.decode())))
                        self._reply(200, b'{"ok": true}')
                    except (ValueError, KeyError) as e:
                        self._reply(400, json.dumps(
                            {"error": str(e)}).encode())
                elif self.path.startswith("/gateway/"):
                    name = self.path[len("/gateway/"):].strip("/")
                    # failure detection: a worker that refuses/errors is
                    # deregistered and the request fails over to the next
                    # one — bounded by the registered worker count
                    last_err = "no workers registered"
                    for _ in range(max(len(outer.routes(name)), 1)):
                        worker = outer._next_worker(name)
                        if worker is None:
                            self._reply(503, json.dumps(
                                {"error":
                                 f"no workers for {name!r}: {last_err}"}
                            ).encode())
                            return
                        try:
                            req = urllib.request.Request(
                                worker.url, data=body,
                                headers={"Content-Type": "application/json"})
                            with urllib.request.urlopen(
                                    req, timeout=outer.forward_timeout) as r:
                                self._reply(r.status, r.read())
                                return
                        except urllib.error.HTTPError as e:
                            # worker is ALIVE and answered with an error
                            # status — surface it, don't evict
                            self._reply(e.code, e.read())
                            return
                        except Exception as e:  # unreachable: evict + retry
                            last_err = str(e)
                            outer.deregister(name, worker)
                    self._reply(502, json.dumps(
                        {"error": f"forward failed: {last_err}"}).encode())
                else:
                    self._reply(404, b'{"error": "unknown endpoint"}')

            def do_GET(self):
                if self.path.startswith("/routes/"):
                    name = self.path[len("/routes/"):].strip("/")
                    body = json.dumps(
                        [s.to_dict() for s in outer.routes(name)]).encode()
                    self._reply(200, body)
                else:
                    self._reply(404, b'{"error": "unknown endpoint"}')

            def _reply(self, status: int, body: bytes):
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        class Server(ThreadingHTTPServer):
            daemon_threads = True
            request_queue_size = 128

        self._httpd = Server((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        threading.Thread(target=self._httpd.serve_forever,
                         daemon=True).start()
        return self

    def stop(self) -> None:
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"


def register_with_retries(coordinator_url: str, info: ServiceInfo,
                          retries: int = 10, delay_s: float = 0.2) -> None:
    """Worker-side registration with bounded retries (the workers' ServiceInfo
    POST, HTTPSourceV2.scala:126-152; retry discipline mirrors the reference's
    port-probe/rendezvous retry loops, TrainUtils.scala:496-512)."""
    body = json.dumps(info.to_dict()).encode()
    last: Optional[Exception] = None
    for attempt in range(retries):
        try:
            req = urllib.request.Request(
                coordinator_url.rstrip("/") + "/register", data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=5.0) as r:
                if r.status == 200:
                    return
        except Exception as e:  # noqa: BLE001
            last = e
        time.sleep(delay_s * (attempt + 1))
    raise ConnectionError(
        f"could not register with coordinator at {coordinator_url}: {last}")


class DistributedServingServer(ServingServer):
    """A per-host worker: ServingServer that announces itself to the
    coordinator on start (WorkerServer + ServiceInfo POST,
    HTTPSourceV2.scala:318-430)."""

    def __init__(self, handler, coordinator_url: str, service_name: str,
                 partition: Optional[int] = None,
                 machine: Optional[str] = None, **kw):
        super().__init__(handler, **kw)
        self.coordinator_url = coordinator_url
        self.service_name = service_name
        self.partition = partition
        self.machine = machine

    def start(self) -> "DistributedServingServer":
        super().start()
        # default identity is (hostname, bound port): unique across hosts AND
        # across multiple unconfigured workers on one host, so defaults never
        # evict each other in the coordinator's (machine, partition) registry
        machine = (self.machine if self.machine is not None
                   else socket.gethostname())
        partition = self.partition if self.partition is not None else self.port
        register_with_retries(
            self.coordinator_url,
            ServiceInfo(self.service_name, self.host, self.port,
                        machine, partition))
        return self


def fetch_routes(coordinator_url: str, name: str) -> List[ServiceInfo]:
    """Client-side routing-table fetch (the reference's load-balancer path:
    clients resolve `machine:partition` workers and talk to them directly)."""
    with urllib.request.urlopen(
            coordinator_url.rstrip("/") + f"/routes/{name}",
            timeout=5.0) as r:
        return [ServiceInfo.from_dict(d) for d in json.loads(r.read())]
