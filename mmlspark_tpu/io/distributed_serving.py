"""Distributed serving: per-host servers + driver registration/routing.

Reference: the two distributed thirds of Spark Serving —
- DistributedHTTPSource.scala:26-424: per-executor `JVMSharedServer`s, a
  `MultiChannelMap` handing requests round-robin to partition channels, and
  reply-on-owning-JVM routing (`respond(batchId, uuid, response)` :396-402);
- continuous/HTTPSourceV2.scala:45-715: `WorkerServer`s POST a `ServiceInfo`
  to a driver service (:113-173) which keeps a `machine:partition` routing
  table; continuous mode replaces micro-batch ticks with long-lived readers.

TPU-native restructure: each host runs a `ServingServer` (io/serving.py) with
the compiled model resident; a `ServingCoordinator` plays the driver service —
workers register `ServiceInfo`, clients either fetch the routing table and
talk to workers directly (the reference's usual path: the load balancer
forwards to executor servers) or POST through the coordinator's forwarding
gateway, which round-robins across workers (MultiChannelMap.addToNextList
semantics). Replies always come back on the connection that owns the request —
there is no cross-host respond hop to re-create because each worker owns its
own sockets.

Failure handling (resilience layer):
- workers HEARTBEAT to the coordinator (`POST /heartbeat`); a monitor thread
  evicts heartbeat-capable workers silent for `heartbeat_timeout_s` — a
  dead worker cannot stay in the routing table forever (manual
  registrations without a heartbeat loop keep the old contract: evicted
  only by gateway failure detection);
- the gateway retries a failed forward on the next healthy worker under a
  shared `RetryPolicy`, deregistering unreachable workers immediately;
- an evicted-but-alive worker's next heartbeat gets 410 Gone and the worker
  RE-REGISTERS itself — transient eviction (a chaos-injected forward
  failure, a network blip) heals without operator action;
- request budgets ride the `X-Deadline-Ms` header: the gateway answers 504
  when the budget is spent and re-encodes only the REMAINING budget on each
  forward hop, so a retry can never exceed the client's patience.

Round 12 (load-aware data plane): the forward path reuses keep-alive
connections per worker (`io.http.KeepAliveTransport`, still injectable for
chaos), routing is LEAST-LOADED by default — scored from the queue-depth
load report each worker now piggybacks on its heartbeat plus the gateway's
own in-flight count (rows/s rides the same beat, surfaced via /health for
operators/autoscalers), round-robin among ties so idle fleets keep the
reference's channel rotation — and concurrent gateway requests to one
service COALESCE: handler threads cooperatively lead, each packing up to
`coalesce_max` queued client bodies into one length-prefixed forward
(io/rowcodec.py packs); the worker splits them into per-part batcher
entries and the reply pack fans back out. Every routing decision is
counted (`gateway_route_decisions_total{decision}`).

Round 13 (model lifecycle): the heartbeat becomes the rollout control
channel. Each beat piggybacks the worker's model_version, last swap
outcome, error/request totals, and p99 beside the PR 12 load report, and
the coordinator's reply carries that worker's `target_version`; a worker
with a `RegistryModelSource` (io/registry.py) hot-swaps toward its target
on its own swap thread. `start_rollout` drives the HEALTH-GATED state
machine: canary (one worker swaps first; its post-swap error-rate delta
and p99 are judged against its pre-rollout baseline over `canary_beats`
beats) -> promoting (every routed worker targets the version) -> done;
any swap failure, health breach, canary eviction, or timeout rolls the
whole fleet back to the previous version — an automatic, counted
transition, never an operator page. State is visible in `/health`
(`rollouts`, `worker_models`) and as `gateway_rollout_state{service}` /
`gateway_rollout_transitions_total{state}`. `retire()` is the scale-down
path: stand down the heartbeat, deregister, drain, stop (io/autoscale.py
actuates it from the same heartbeat load signals the router consumes).
"""

from __future__ import annotations

import collections
import dataclasses
import json
import socket
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional, Tuple

from ..observability import (EventLog, SLOMonitor, TRACE_HEADER,
                             get_registry, mint_trace_id,
                             trace_id_from_headers)
from ..resilience import Deadline, RetryError, RetryPolicy
from . import rowcodec
from .http import KeepAliveTransport
from .serving import _INSTANCE_SEQ, _since_of, ServingServer, SwapResult

#: rollout state machine vocabulary; the index is the
#: `gateway_rollout_state{service}` gauge value
ROLLOUT_STATES = ("idle", "canary", "promoting", "done", "rolled_back")


class ServiceInfo:
    """Worker registration record (HTTPSourceV2.scala ServiceInfo :126-152).

    `heartbeating=True` declares at REGISTRATION time that this worker runs
    a heartbeat loop, making it subject to silence-based eviction from the
    moment it registers — inferring capability from the first received beat
    would leave a worker that dies (or is GIL-starved by a jit compile)
    before ever beating in the routing table forever."""

    __slots__ = ("name", "host", "port", "machine", "partition",
                 "heartbeating")

    def __init__(self, name: str, host: str, port: int,
                 machine: str = "localhost", partition: int = 0,
                 heartbeating: bool = False):
        self.name = name
        self.host = host
        self.port = port
        self.machine = machine
        self.partition = partition
        self.heartbeating = heartbeating

    def to_dict(self) -> Dict:
        return {"name": self.name, "host": self.host, "port": self.port,
                "machine": self.machine, "partition": self.partition,
                "heartbeating": self.heartbeating}

    @staticmethod
    def from_dict(d: Dict) -> "ServiceInfo":
        return ServiceInfo(d["name"], d["host"], int(d["port"]),
                           d.get("machine", "localhost"),
                           int(d.get("partition", 0)),
                           bool(d.get("heartbeating", False)))

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/"


def _default_transport(url: str, body: bytes, headers: Dict[str, str],
                       timeout: float) -> Tuple[int, bytes]:
    """One forward hop. Raises urllib.error.HTTPError for alive-but-erroring
    workers and other exceptions for unreachable ones — the gateway treats
    the two differently. Injectable for chaos testing (FaultInjector.wrap)."""
    req = urllib.request.Request(url, data=body, headers=headers)
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, r.read()


class _GatewayEntry:
    """One client request riding the gateway: its raw reply writer wrapped
    with the coordinator's telemetry (latency histogram, 503/504 counters,
    per-reply span, trace-id echo), an exactly-once guard (a coalescing
    leader and the stall safety net must never double-write a socket), and
    a done event the owning handler thread parks on."""

    __slots__ = ("body", "headers", "trace_id", "client_deadline",
                 "deadline", "done", "_coord", "_raw_reply", "_t_recv",
                 "_lock", "_replied")

    def __init__(self, coord: "ServingCoordinator", raw_reply, body: bytes,
                 headers: Dict[str, str]):
        self.body = body
        self.headers = headers
        self.trace_id = trace_id_from_headers(headers) or mint_trace_id()
        self.client_deadline = Deadline.from_headers(headers)
        self.deadline = (self.client_deadline
                         or Deadline.after(coord.forward_timeout))
        self.done = threading.Event()
        self._coord = coord
        self._raw_reply = raw_reply
        self._t_recv = time.perf_counter()
        self._lock = threading.Lock()
        self._replied = False

    def reply(self, status: int, rbody: bytes, rheaders=None) -> None:
        with self._lock:
            if self._replied:
                return
            self._replied = True
        coord = self._coord
        dur = time.perf_counter() - self._t_recv
        coord._lat_hist.observe(dur)
        if status == 504:
            coord._m_expired.inc()
        elif status == 503:
            coord._m_shed.inc()
        coord.events.append("reply", self.trace_id, dur_s=dur,
                            status=status)
        try:
            self._raw_reply(status, rbody,
                            {TRACE_HEADER: self.trace_id,
                             **(rheaders or {})})
        except Exception:
            # this entry's client hung up: its loss must stay ITS loss — a
            # coalescing leader writing a dead follower's socket must not
            # die mid-distribution and strand the other entries (and a
            # disconnect can never be misread as a worker failure)
            pass
        finally:
            self.done.set()

    def expire_if_due(self) -> bool:
        if self.deadline.expired:
            self.reply(504, b'{"error": "deadline exceeded"}')
            return True
        return False


class _Coalescer:
    """Per-service staging between gateway handler threads and leader
    forwards (the pending deque + active-leader count)."""

    __slots__ = ("lock", "pending", "leaders")

    def __init__(self):
        self.lock = threading.Lock()
        self.pending: "collections.deque[_GatewayEntry]" = \
            collections.deque()
        self.leaders = 0


class ServingCoordinator:
    """Driver-role registration + routing service with worker health.

    Endpoints:
      POST /register   body = ServiceInfo JSON           (worker -> driver)
      POST /heartbeat  body = ServiceInfo JSON; 410 Gone => re-register
      GET  /routes/<service>                             routing table JSON
      GET  /health     worker counts + eviction stats
      GET  /metrics    Prometheus text (forward latency + gateway counters)
      POST /gateway/<service>  forward to a healthy worker (retry + evict)

    Workers silent for `heartbeat_timeout_s` are evicted by a monitor
    thread (the driver-side failure detector the reference lacks — its
    routing table only ever grows, HTTPSourceV2.scala:113-173).
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 forward_timeout: float = 30.0,
                 heartbeat_timeout_s: float = 10.0,
                 forward_transport=None,
                 forward_retry: Optional[RetryPolicy] = None,
                 registry=None, event_log=None,
                 metrics_label: Optional[str] = None,
                 route_policy: str = "least_loaded",
                 coalesce_max: int = 8, coalesce_wait_ms: float = 0.0,
                 coalesce_parallel: int = 4,
                 canary_beats: int = 3,
                 rollout_timeout_s: float = 60.0,
                 canary_max_error_rate: float = 0.05,
                 canary_min_requests: int = 20,
                 canary_max_p99_factor: float = 3.0,
                 canary_p99_floor_ms: float = 5.0,
                 slo_monitor: "Optional[SLOMonitor]" = "default",
                 slo_rollout_gate: bool = False):
        self.host, self.port = host, port
        self.forward_timeout = forward_timeout
        self.heartbeat_timeout_s = heartbeat_timeout_s
        if route_policy not in ("least_loaded", "round_robin"):
            raise ValueError(f"route_policy must be 'least_loaded' or "
                             f"'round_robin', got {route_policy!r}")
        self.route_policy = route_policy
        # gateway-side request coalescing: leaders pack up to coalesce_max
        # queued client bodies into ONE forward; <=1 disables. wait_ms is
        # an optional pre-grab window (0 = pack only what is already
        # queued — the forward round-trip itself is the natural window
        # under load, so the default adds zero idle latency);
        # coalesce_parallel bounds concurrent leader forwards per service
        self.coalesce_max = coalesce_max
        self.coalesce_wait_ms = coalesce_wait_ms
        self.coalesce_parallel = max(1, coalesce_parallel)
        self._coalescers: Dict[str, "_Coalescer"] = {}
        self._routes: Dict[str, List[ServiceInfo]] = {}
        self._rr: Dict[str, int] = {}
        self._last_seen: Dict[Tuple[str, str, int], float] = {}
        # worker load reports (heartbeat-piggybacked queue depth) and the
        # gateway's own in-flight forwards — the least-loaded score
        # inputs; rows/s rides the same beat for /health consumers
        self._load: Dict[Tuple[str, str, int], float] = {}
        self._rates: Dict[Tuple[str, str, int], float] = {}
        self._inflight: Dict[Tuple[str, int], int] = {}
        self._known: set = set()  # services that have EVER had a worker
        # workers subject to silence-based eviction: declared heartbeating
        # at registration, or actually heartbeat at least once — a plain
        # register()/register_with_retries worker with no heartbeat loop
        # keeps the pre-resilience contract (evicted only by gateway
        # failure detection)
        self._hb_seen: set = set()
        # rollout control (round 13): latest heartbeat-piggybacked report
        # per worker (model_version, swap outcome, error/request totals,
        # p99) and the per-service rollout record the state machine runs on
        self.canary_beats = int(canary_beats)
        self.rollout_timeout_s = float(rollout_timeout_s)
        self.canary_max_error_rate = float(canary_max_error_rate)
        self.canary_min_requests = int(canary_min_requests)
        self.canary_max_p99_factor = float(canary_max_p99_factor)
        self.canary_p99_floor_ms = float(canary_p99_floor_ms)
        self._reports: Dict[Tuple[str, str, int], Dict] = {}
        self._rollouts: Dict[str, Dict] = {}
        self._rollout_gauges: Dict[str, object] = {}
        self._rollout_counters: Dict[Tuple[str, str], object] = {}
        self._lock = threading.Lock()
        self._stopev = threading.Event()
        self._httpd: Optional[ThreadingHTTPServer] = None
        # default: keep-alive connection reuse per worker; chaos tests and
        # custom stacks still inject any (url, body, headers, timeout)
        # callable (FaultInjector.wrap composes with either)
        self._owns_transport = forward_transport is None
        self._transport = (KeepAliveTransport() if forward_transport is None
                           else forward_transport)
        # bounded fail-fast: ~8 attempts spanning ~1.5 s rides out a
        # transient all-evicted dip (heartbeat re-registration is sub-second)
        # without hanging a doomed request for the full forward_timeout
        self.forward_retry = forward_retry or RetryPolicy(
            attempts=8, backoff_s=0.05, multiplier=1.5, max_backoff_s=0.4,
            jitter=0.1)
        # telemetry: gateway counters + forward-latency histogram in the
        # (default: process-global) registry, per-hop forward spans in the
        # coordinator's own event log (the gateway side of a trace)
        self.registry = registry if registry is not None else get_registry()
        self.events = event_log if event_log is not None else EventLog()
        self.metrics_label = (metrics_label if metrics_label is not None
                              else f"gateway-{next(_INSTANCE_SEQ)}")
        # SLO burn-rate monitors (ISSUE 14): dual-window burn over the
        # gateway's own error/latency families, ticked on the monitor
        # loop, surfaced in /health and as slo_burn_rate{slo,window}.
        # Breach events land in THIS coordinator's event log so the
        # trace collector / flight recorder see them like any other
        # system event. slo_rollout_gate=True (off by default) also
        # rolls active rollouts back while an SLO is breached.
        # slo_monitor: "default" (sentinel) = the stock gateway pair;
        # None = MONITORING OFF (no per-tick registry sampling).
        if slo_monitor == "default":
            self.slo: Optional[SLOMonitor] = SLOMonitor.gateway_defaults(
                registry=self.registry, event_log=self.events,
                metrics_label=f"slo-{self.metrics_label}")
        else:
            self.slo = slo_monitor
        self.slo_rollout_gate = bool(slo_rollout_gate)
        # pluggable rollout gates (ISSUE 19): callables consulted each
        # rollout_tick; a non-None return is a breach reason that rolls
        # active rollouts back — how the online loop's held-out regret
        # gate (train/online_loop.py HoldoutGate) vetoes a worse model
        # the same way a corrupt artifact or SLO burn does
        self._rollout_monitors: List[Callable[[], Optional[str]]] = []
        lbl = {"instance": self.metrics_label}
        self._m = {
            "forwards": self.registry.counter(
                "gateway_forwards_total", "gateway requests forwarded", lbl),
            "forward_retries": self.registry.counter(
                "gateway_forward_retries_total",
                "failover/retry forward attempts past the first", lbl),
            "evictions": self.registry.counter(
                "gateway_evictions_total",
                "workers dropped from the routing table", lbl),
            "heartbeats": self.registry.counter(
                "gateway_heartbeats_total", "worker heartbeats recorded",
                lbl),
        }
        self._m_failures = self.registry.counter(
            "gateway_forward_failures_total",
            "forward transport failures (worker unreachable/dropped)", lbl)
        self._m_expired = self.registry.counter(
            "gateway_expired_total", "gateway replies with 504 (budget "
            "spent)", lbl)
        self._m_shed = self.registry.counter(
            "gateway_shed_total", "gateway replies with 503 (workers "
            "shedding or none registered)", lbl)
        self._lat_hist = self.registry.histogram(
            "gateway_request_latency_seconds",
            "gateway receive-to-reply latency", lbl)
        self._workers_gauge = self.registry.gauge(
            "gateway_registered_workers",
            "workers currently routable (all services)", lbl)
        self._workers_gauge.set_function(self._worker_count)
        # routing + coalescing telemetry (round 12): which policy branch
        # picked the worker, and how many client requests shared a forward
        self._m_route: Dict[str, object] = {}
        self._route_lbl = lbl
        self._m_coal_fwd = self.registry.counter(
            "gateway_coalesced_forwards_total",
            "forwards carrying >= 2 coalesced client requests", lbl)
        self._m_coal_reqs = self.registry.counter(
            "gateway_coalesced_requests_total",
            "client requests that rode a shared forward", lbl)

    def _route_counter(self, decision: str):
        c = self._m_route.get(decision)
        if c is None:
            c = self.registry.counter(
                "gateway_route_decisions_total",
                "worker-selection outcomes by policy branch",
                {**self._route_lbl, "decision": decision})
            self._m_route[decision] = c
        return c

    def _worker_count(self) -> int:
        with self._lock:
            return sum(len(v) for v in self._routes.values())

    @property
    def stats(self) -> Dict[str, int]:
        """Counter view (registry-backed; the pre-observability dict)."""
        return {k: int(c.value) for k, c in self._m.items()}

    # -------------------------------------------------------------- registry
    def register(self, info: ServiceInfo) -> None:
        with self._lock:
            lst = self._routes.setdefault(info.name, [])
            # a worker identity is (machine, partition) — re-registration
            # (e.g. a restarted worker on a new port) replaces its stale
            # entry. Workers must carry unique identities; the
            # DistributedServingServer defaults derive them from hostname +
            # bound port so unconfigured workers on any topology never
            # collide. Same-endpoint re-posts are also collapsed.
            for s in lst:
                if (s.machine, s.partition) == (info.machine,
                                                info.partition) \
                        or (s.host, s.port) == (info.host, info.port):
                    self._last_seen.pop((info.name, s.host, s.port), None)
                    self._hb_seen.discard((info.name, s.host, s.port))
            lst[:] = [s for s in lst
                      if (s.machine, s.partition) != (info.machine,
                                                      info.partition)
                      and (s.host, s.port) != (info.host, info.port)]
            lst.append(info)
            self._known.add(info.name)
            key = (info.name, info.host, info.port)
            self._last_seen[key] = time.monotonic()
            if info.heartbeating:
                # eviction-eligible from registration: a worker that dies
                # before its first beat must not stay routable forever
                self._hb_seen.add(key)

    def routes(self, name: str) -> List[ServiceInfo]:
        with self._lock:
            return list(self._routes.get(name, []))

    def deregister(self, name: str, info: ServiceInfo) -> None:
        """Drop a worker from the routing table (gateway failure detection:
        a worker whose forward errored is evicted until it re-registers —
        an alive worker's next heartbeat gets 410 and re-registers it)."""
        with self._lock:
            lst = self._routes.get(name)
            if lst:
                before = len(lst)
                lst[:] = [s for s in lst
                          if (s.host, s.port) != (info.host, info.port)]
                if len(lst) < before:
                    self._m["evictions"].inc()
            self._last_seen.pop((name, info.host, info.port), None)
            self._load.pop((name, info.host, info.port), None)
            self._rates.pop((name, info.host, info.port), None)
            self._reports.pop((name, info.host, info.port), None)
            self._hb_seen.discard((name, info.host, info.port))

    def heartbeat(self, info: ServiceInfo, load: Optional[float] = None,
                  rate: Optional[float] = None,
                  report: Optional[Dict] = None) -> str:
        """Record a worker heartbeat. Returns:
        "ok"         — worker is routable, beat recorded;
        "gone"       — worker is not in the table and its (machine,
                       partition) slot is free: re-register (HTTP 410);
        "superseded" — a DIFFERENT endpoint now owns this worker's
                       (machine, partition) identity (HTTP 409): do NOT
                       re-register — doing so would collapse the successor
                       out of the table and the two incarnations would evict
                       each other in a permanent flap. Stand down; if the
                       successor dies the slot frees up and the next beat
                       gets "gone" again."""
        with self._lock:
            lst = self._routes.get(info.name, [])
            if any((s.host, s.port) == (info.host, info.port) for s in lst):
                key = (info.name, info.host, info.port)
                self._last_seen[key] = time.monotonic()
                self._hb_seen.add(key)
                self._m["heartbeats"].inc()
                if load is not None:
                    # heartbeat-piggybacked load report (worker queue
                    # depth): the least-loaded router's freshest signal
                    try:
                        self._load[key] = float(load)
                    except (TypeError, ValueError):
                        pass
                if rate is not None:
                    # throughput rides the same beat: surfaced via
                    # /health for operators/autoscalers (routing scores
                    # on queue depth; a momentary rows/s says little
                    # about REMAINING capacity)
                    try:
                        self._rates[key] = float(rate)
                    except (TypeError, ValueError):
                        pass
                if report is not None:
                    # the rollout control channel: model_version / swap
                    # outcome / error totals / p99 ride the same beat,
                    # and every beat advances the rollout state machine
                    self._reports[key] = dict(report)
                    self._observe_rollout_locked(info, report)
                return "ok"
            if any((s.machine, s.partition) == (info.machine, info.partition)
                   for s in lst):
                return "superseded"
            return "gone"

    # -------------------------------------------------------------- rollout
    def _rollout_gauge(self, name: str):
        g = self._rollout_gauges.get(name)
        if g is None:
            g = self.registry.gauge(
                "gateway_rollout_state",
                "rollout state machine position "
                "(0 idle, 1 canary, 2 promoting, 3 done, 4 rolled_back)",
                {**self._route_lbl, "service": name})
            self._rollout_gauges[name] = g
        return g

    def _rollout_transition(self, name: str, state: str):
        c = self._rollout_counters.get((name, state))
        if c is None:
            c = self.registry.counter(
                "gateway_rollout_transitions_total",
                "rollout state transitions by destination state",
                {**self._route_lbl, "service": name, "state": state})
            self._rollout_counters[(name, state)] = c
        return c

    def _set_rollout_state_locked(self, name: str, ro: Dict, state: str,
                                  reason: Optional[str]) -> None:
        ro["state"] = state
        ro["reason"] = reason
        self._rollout_gauge(name).set(float(ROLLOUT_STATES.index(state)))
        self._rollout_transition(name, state).inc()
        self.events.append("rollout", mint_trace_id(), service=name,
                           state=state, target=ro["target"],
                           reason=reason)

    def start_rollout(self, name: str, version: int,
                      previous: Optional[int] = None,
                      canary: Optional[Tuple[str, int]] = None) -> Dict:
        """Begin a health-gated rollout of `version` for one service.

        One worker — the explicit `canary` (host, port) or the first in
        stable (machine, partition) order — is targeted first; its
        post-swap error-rate delta and p99, judged against the baseline
        captured HERE from its last heartbeat report, must stay clean for
        `canary_beats` beats before the target goes fleet-wide. Any swap
        failure, health breach, canary eviction, or `rollout_timeout_s`
        expiry rolls every worker back to `previous` (defaulted from the
        canary's reported model_version). Returns the rollout record."""
        with self._lock:
            lst = list(self._routes.get(name, []))
            if not lst:
                raise ValueError(f"no workers registered for {name!r}")
            active = self._rollouts.get(name)
            if active and active["state"] in ("canary", "promoting"):
                raise ValueError(
                    f"rollout already active for {name!r} "
                    f"(state {active['state']})")
            cw = None
            if canary is not None:
                host, port = canary[0], int(canary[1])
                for s in lst:
                    if (s.host, s.port) == (host, port):
                        cw = s
                        break
                if cw is None:
                    raise ValueError(
                        f"canary {host}:{port} not in routing table")
            else:
                cw = sorted(lst,
                            key=lambda s: (s.machine, s.partition))[0]
            if previous is None:
                # default rollback target: the canary's reported version,
                # else ANY worker's (a rollout started before the first
                # beat landed must still know where "back" is)
                rep = self._reports.get((name, cw.host, cw.port)) or {}
                previous = rep.get("model_version")
                if previous is None:
                    for s in lst:
                        rep = self._reports.get((name, s.host, s.port)) or {}
                        if rep.get("model_version") is not None:
                            previous = rep.get("model_version")
                            break
            baseline = {}
            for s in lst:
                rep = self._reports.get((name, s.host, s.port)) or {}
                baseline[f"{s.host}:{s.port}"] = {
                    "errors": int(rep.get("errors_total") or 0),
                    "requests": int(rep.get("requests_total") or 0),
                    "p99_ms": rep.get("p99_ms")}
            ro = {"service": name, "target": int(version),
                  "previous": previous,
                  "state": "idle", "reason": None,
                  "canary": [cw.host, cw.port],
                  "started_s": time.monotonic(),
                  "canary_ok_beats": 0,
                  "baseline": baseline}
            self._rollouts[name] = ro
            self._set_rollout_state_locked(name, ro, "canary", None)
            return dict(ro)

    def _target_for_locked(self, name: str, host: str,
                           port: int) -> Optional[int]:
        """The version this worker should run, per the rollout state (None
        = no opinion, worker keeps what it has). Canary phase targets only
        the canary — every other worker is pinned to `previous`, which is
        also what makes rollback an ordinary re-target."""
        ro = self._rollouts.get(name)
        if ro is None:
            return None
        state = ro["state"]
        if state == "canary":
            if [host, port] == ro["canary"]:
                return ro["target"]
            return ro["previous"]
        if state in ("promoting", "done"):
            return ro["target"]
        if state == "rolled_back":
            return ro["previous"]
        return None

    def heartbeat_target(self, info: ServiceInfo) -> Optional[int]:
        """The `target_version` the heartbeat reply carries for this
        worker (the rollout actuation channel)."""
        with self._lock:
            return self._target_for_locked(info.name, info.host, info.port)

    def _report_breach_locked(self, ro: Dict, key_str: str,
                              rep: Dict) -> Optional[str]:
        """Health gate for a worker ALREADY reporting the target version.

        Error-rate deltas are judged against the worker's POST-SWAP
        baseline — captured from its first target-version beat — so
        traffic it served on the old version (long for late-promoting
        workers) is never misattributed to the new one; a pre-swap error
        blip cannot roll the fleet back, and a bad new version's errors
        are not diluted by the pre-swap window. p99 compares against the
        PRE-ROLLOUT baseline * factor (it is a distribution snapshot,
        not a cumulative counter), floored so sub-ms noise can't trip
        the ratio. Requires `canary_min_requests` post-swap requests
        before judging — a 1-error-in-2-requests blip must not roll a
        fleet."""
        swap_base = ro.setdefault("swap_base", {})
        base = swap_base.get(key_str)
        if base is None:
            # first beat on the target version: this IS the post-swap
            # origin; nothing to judge yet
            swap_base[key_str] = {
                "errors": int(rep.get("errors_total") or 0),
                "requests": int(rep.get("requests_total") or 0)}
            base = None
        else:
            err_d = int(rep.get("errors_total") or 0) - base["errors"]
            req_d = int(rep.get("requests_total") or 0) - base["requests"]
            if req_d >= self.canary_min_requests \
                    and err_d / req_d > self.canary_max_error_rate:
                return f"error_rate {err_d}/{req_d}"
        b99 = (ro["baseline"].get(key_str) or {}).get("p99_ms")
        p99 = rep.get("p99_ms")
        if p99 and b99 and p99 > max(b99 * self.canary_max_p99_factor,
                                     self.canary_p99_floor_ms):
            return f"p99 {p99}ms vs baseline {b99}ms"
        return None

    def _observe_rollout_locked(self, info: ServiceInfo,
                                rep: Dict) -> None:
        """Advance the rollout state machine on one heartbeat report
        (called under self._lock from `heartbeat`)."""
        name = info.name
        ro = self._rollouts.get(name)
        if ro is None or ro["state"] not in ("canary", "promoting"):
            return
        target = ro["target"]
        key_str = f"{info.host}:{info.port}"
        # a swap attempt at the target that failed ANYWHERE = rollback
        # ("rejected" means a concurrent swap was in flight — retried on a
        # later beat, not a failure)
        if rep.get("swap_version") == target and \
                rep.get("swap_outcome") not in (None, "success", "rejected"):
            self._set_rollout_state_locked(
                name, ro, "rolled_back",
                f"{key_str}: swap {rep['swap_outcome']}")
            return
        mv = rep.get("model_version")
        if mv == target:
            breach = self._report_breach_locked(ro, key_str, rep)
            if breach:
                self._set_rollout_state_locked(name, ro, "rolled_back",
                                               f"{key_str}: {breach}")
                return
        if ro["state"] == "canary":
            if [info.host, info.port] == ro["canary"] and mv == target:
                ro["canary_ok_beats"] += 1
                if ro["canary_ok_beats"] >= self.canary_beats:
                    self._set_rollout_state_locked(name, ro, "promoting",
                                                   None)
        if ro["state"] == "promoting":
            lst = self._routes.get(name, [])
            if lst and all(
                    (self._reports.get((name, s.host, s.port)) or {}
                     ).get("model_version") == target for s in lst):
                self._set_rollout_state_locked(name, ro, "done", None)

    def add_rollout_monitor(
            self, fn: "Callable[[], Optional[str]]") -> None:
        """Register an external rollout gate: ``fn()`` is consulted on
        every `rollout_tick` (outside the coordinator lock — monitors may
        hold their own) and a non-None return is a breach reason that
        rolls every active rollout back. The online loop's held-out
        regression gate plugs in here."""
        with self._lock:
            self._rollout_monitors.append(fn)

    def rollout_tick(self) -> None:
        """Clock-driven rollout checks the beat-driven observer cannot
        make: overall timeout, canary loss (killed mid-swap and evicted
        by the heartbeat monitor), an SLO burning on both windows (when
        `slo_rollout_gate` is on), and any registered rollout monitor
        reporting a breach. Runs on the monitor loop's cadence; tests
        call it directly."""
        now = time.monotonic()
        slo_breach = (self.slo_rollout_gate and self.slo is not None
                      and self.slo.breached())
        monitor_breach: Optional[str] = None
        with self._lock:
            monitors = list(self._rollout_monitors)
            active = any(ro["state"] in ("canary", "promoting")
                         for ro in self._rollouts.values())
        if active:
            for mon in monitors:
                try:
                    monitor_breach = mon()
                except Exception as exc:  # noqa: BLE001 - a crashing gate
                    # must fail SAFE (veto), never wedge the rollout loop
                    monitor_breach = f"rollout monitor error: {exc!r}"
                if monitor_breach:
                    break
        with self._lock:
            for name, ro in self._rollouts.items():
                if ro["state"] not in ("canary", "promoting"):
                    continue
                if slo_breach:
                    # the additional (off-by-default) gate: a fleet
                    # burning its error budget must not keep promoting
                    self._set_rollout_state_locked(
                        name, ro, "rolled_back",
                        "slo burn-rate breach (slo_rollout_gate)")
                    continue
                if monitor_breach:
                    self._set_rollout_state_locked(
                        name, ro, "rolled_back", monitor_breach)
                    continue
                if now - ro["started_s"] > self.rollout_timeout_s:
                    self._set_rollout_state_locked(
                        name, ro, "rolled_back",
                        f"timeout after {self.rollout_timeout_s:.0f}s")
                    continue
                if ro["state"] == "canary":
                    ch, cp = ro["canary"]
                    if not any((s.host, s.port) == (ch, cp)
                               for s in self._routes.get(name, [])):
                        # hysteresis: a chaos-blip eviction heals on the
                        # next beat (410 -> re-register); only a canary
                        # missing for 3 consecutive ticks — actually dead
                        # (e.g. killed mid-swap) — rolls the fleet back
                        ro["canary_lost_ticks"] = \
                            ro.get("canary_lost_ticks", 0) + 1
                        if ro["canary_lost_ticks"] >= 3:
                            self._set_rollout_state_locked(
                                name, ro, "rolled_back",
                                f"canary {ch}:{cp} lost (evicted)")
                    else:
                        ro["canary_lost_ticks"] = 0

    def rollout_status(self, name: str) -> Optional[Dict]:
        with self._lock:
            ro = self._rollouts.get(name)
            return dict(ro) if ro else None

    def worker_loads(self, name: str) -> Dict[str, Dict[str, float]]:
        """Per-ROUTED-worker load signals for one service (queue depth +
        rows/s from the latest beat; a worker yet to report counts as 0).
        The autoscaler's signal set — the same numbers the least-loaded
        router scores on (io/autoscale.py)."""
        with self._lock:
            out = {}
            for s in self._routes.get(name, []):
                key = (name, s.host, s.port)
                out[f"{s.host}:{s.port}"] = {
                    "queue_depth": float(self._load.get(key, 0.0)),
                    "rows_per_s": float(self._rates.get(key, 0.0))}
            return out

    def _next_worker(self, name: str) -> Optional[ServiceInfo]:
        """Worker selection. Policy "least_loaded" (default) scores each
        worker as (heartbeat-reported queue depth) + (this gateway's
        in-flight forwards to it) and picks the minimum, rotating
        round-robin among ties — an idle fleet therefore keeps the exact
        reference channel rotation (MultiChannelMap.addToNextList,
        DistributedHTTPSource.scala:81-83), while a hot or slow worker
        sheds new routes until its queue drains. The chosen worker's
        in-flight count is bumped here; `_release_worker` undoes it."""
        with self._lock:
            lst = self._routes.get(name)
            if not lst:
                return None
            i0 = self._rr.get(name, 0) % len(lst)
            decision = "round_robin"
            pick = i0
            if self.route_policy == "least_loaded":
                scores = [self._load.get((name, s.host, s.port), 0.0)
                          + self._inflight.get((s.host, s.port), 0)
                          for s in lst]
                best = min(scores)
                for k in range(len(lst)):
                    i = (i0 + k) % len(lst)
                    if scores[i] == best:
                        pick = i
                        break
                decision = ("rr_tie" if best == max(scores)
                            else "least_loaded")
            self._rr[name] = pick + 1
            worker = lst[pick]
            wkey = (worker.host, worker.port)
            self._inflight[wkey] = self._inflight.get(wkey, 0) + 1
        self._route_counter(decision).inc()
        return worker

    def _release_worker(self, worker: ServiceInfo) -> None:
        with self._lock:
            wkey = (worker.host, worker.port)
            n = self._inflight.get(wkey, 0) - 1
            if n > 0:
                self._inflight[wkey] = n
            else:
                self._inflight.pop(wkey, None)

    # --------------------------------------------------------------- health
    def _monitor_loop(self) -> None:
        """Evict HEARTBEATING workers whose last beat is older than
        heartbeat_timeout_s. Workers that never heartbeat (plain
        register()/register_with_retries, no DistributedServingServer loop)
        are exempt — for them only gateway failure detection evicts, the
        pre-resilience contract."""
        interval = max(0.02, self.heartbeat_timeout_s / 4.0)
        while not self._stopev.wait(interval):
            cutoff = time.monotonic() - self.heartbeat_timeout_s
            with self._lock:
                for name, lst in self._routes.items():
                    stale = [s for s in lst
                             if (name, s.host, s.port) in self._hb_seen
                             and self._last_seen.get(
                                 (name, s.host, s.port), 0.0) < cutoff]
                    if stale:
                        lst[:] = [s for s in lst if s not in stale]
                        for s in stale:
                            self._last_seen.pop((name, s.host, s.port),
                                                None)
                            self._load.pop((name, s.host, s.port), None)
                            self._rates.pop((name, s.host, s.port), None)
                            self._reports.pop((name, s.host, s.port), None)
                            self._hb_seen.discard((name, s.host, s.port))
                            self._m["evictions"].inc()
            # SLO sampling + clock-driven rollout checks (timeout, canary
            # eviction, optional SLO gate) ride the same monitor cadence
            if self.slo is not None:
                try:
                    self.slo.tick()
                except Exception:  # noqa: BLE001 - a bad SLO sample must
                    pass           # not kill eviction monitoring
            self.rollout_tick()

    def health(self) -> Dict:
        with self._lock:
            services = {name: len(lst) for name, lst in self._routes.items()}
            loads = {f"{n}:{h}:{p}": {"queue_depth": v,
                                      "rows_per_s": self._rates.get(
                                          (n, h, p), 0.0)}
                     for (n, h, p), v in self._load.items()}
            rollouts = {name: {k: v for k, v in ro.items()
                               if k not in ("baseline", "swap_base")}
                        for name, ro in self._rollouts.items()}
            models = {f"{n}:{h}:{p}": {
                          "model_version": rep.get("model_version"),
                          "swap_state": rep.get("swap_state"),
                          "swap_outcome": rep.get("swap_outcome"),
                          "trace_events_total":
                              rep.get("trace_events_total")}
                      for (n, h, p), rep in self._reports.items()}
        return {"services": services,
                "heartbeat_timeout_s": self.heartbeat_timeout_s,
                "route_policy": self.route_policy,
                "worker_loads": loads,
                "rollouts": rollouts,
                "worker_models": models,
                "slo": (self.slo.status() if self.slo is not None
                        else None),
                "stats": dict(self.stats)}

    def trace_payload(self, since: float = 0.0) -> Dict:
        """GET /trace?since= drain of the gateway's own EventLog (the
        shared contract — observability.tracing.drain_payload)."""
        from ..observability.tracing import drain_payload
        return drain_payload(self.metrics_label, self.events, since)

    def rollouts_status(self) -> Dict[str, Dict]:
        """Locked snapshot of every rollout record (minus the bulky
        baselines) — what the flight recorder embeds in bundles; direct
        iteration of `_rollouts` would race the heartbeat/monitor
        threads that mutate the records."""
        with self._lock:
            return {n: {k: v for k, v in ro.items()
                        if k not in ("baseline", "swap_base")}
                    for n, ro in self._rollouts.items()}

    # -------------------------------------------------------------- gateway
    def _coalescer(self, name: str) -> "_Coalescer":
        with self._lock:
            co = self._coalescers.get(name)
            if co is None:
                co = _Coalescer()
                self._coalescers[name] = co
            return co

    def _handle_gateway(self, reply, name: str, body: bytes,
                        headers: Dict[str, str]) -> None:
        """Gateway entry: wrap the client's reply with telemetry, then
        either forward directly or ride the per-service coalescer — a
        LEADER thread packs queued client bodies into one forward
        (io/rowcodec packs) while followers park on their reply event.
        A single-entry group forwards the raw body, bit-identical to the
        pre-coalescing wire path."""
        entry = _GatewayEntry(self, reply, body, headers)
        if self.coalesce_max <= 1:
            self._forward_entries(name, [entry])
            return
        co = self._coalescer(name)
        with co.lock:
            co.pending.append(entry)
        # cooperative leadership: every handler thread whose entry is
        # still pending competes to drive ONE group at a time (up to
        # coalesce_parallel concurrently), then re-checks its own entry.
        # A thread never drains the deque past its own reply — a leader
        # that kept forwarding other clients' groups would starve its OWN
        # connection's next pipelined request (observed as client
        # timeouts under chaos churn) — and every entry has a live thread
        # pushing, so work is conserved and FIFO groups bound the wait.
        while not entry.done.is_set():
            with co.lock:
                lead = bool(co.pending) and \
                    co.leaders < self.coalesce_parallel
                if lead:
                    co.leaders += 1
            if lead:
                if self.coalesce_wait_ms > 0:
                    time.sleep(self.coalesce_wait_ms / 1000.0)
                with co.lock:
                    group = [co.pending.popleft()
                             for _ in range(min(len(co.pending),
                                                self.coalesce_max))]
                try:
                    if group:
                        self._forward_entries(name, group)
                finally:
                    with co.lock:
                        co.leaders -= 1
                continue
            if entry.deadline.expired:
                # stuck in the deque past the budget (all leader slots
                # pinned in deadline-length chaos retries): answer the
                # 504 NOW; the exactly-once guard turns the eventual
                # dequeue's expire_if_due into a silent drop
                entry.reply(504, b'{"error": "deadline exceeded '
                                 b'waiting for a forward slot"}')
                return
            entry.done.wait(0.005)

    def _forward_entries(self, name: str,
                         entries: List["_GatewayEntry"]) -> None:
        """Forward one group (1 = plain body, >=2 = coalesced pack) with
        bounded retry + eviction + deadline propagation. Each entry's
        trace id rides its own reply; the forward hop itself carries the
        lead entry's id so gateway attempt spans and worker dispatch
        spans join on one id."""
        if name not in self._known:
            for e in entries:
                e.reply(503, json.dumps(
                    {"error": f"no workers for {name!r}: never registered"}
                ).encode())
            return
        entries = [e for e in entries if not e.expire_if_due()]
        if not entries:
            return
        n = len(entries)
        trace_id = entries[0].trace_id
        if n == 1:
            body = entries[0].body
            extra_headers = {}
        else:
            body = rowcodec.encode_pack([e.body for e in entries],
                                        [e.trace_id for e in entries])
            extra_headers = {rowcodec.COALESCE_HEADER: str(n)}
            self._m_coal_fwd.inc()
            self._m_coal_reqs.inc(n)
        # the pack's budget is the TIGHTEST member's; with every entry
        # carrying an explicit client budget the deadline (not the attempt
        # count) is the retry contract, as in the single-request path
        all_client = all(e.client_deadline is not None for e in entries)
        deadline = min((e.deadline for e in entries),
                       key=lambda d: d.expires_at)
        policy = self.forward_retry
        if all_client:
            policy = dataclasses.replace(policy, attempts=None)
        elif policy.attempts is not None:
            # bounded fail-fast must still be able to try EVERY registered
            # worker once: a correlated failure of N-1 workers out of many
            # should reach the survivor, not give up at a fixed count
            policy = dataclasses.replace(
                policy, attempts=max(policy.attempts,
                                     len(self.routes(name)) + 1))
        self._m["forwards"].inc(n)
        last_err = "routing table empty (all workers evicted)"
        last_shed = None  # most recent worker 503 (queue-full) response
        for attempt in policy.attempts_iter(deadline=deadline):
            if attempt.index:
                self._m["forward_retries"].inc()
            worker = self._next_worker(name)
            if worker is None:
                # all evicted: the backoff sleep gives heartbeat
                # re-registration a chance to repopulate the table
                self.events.append("forward_attempt", trace_id,
                                   attempt=attempt.index,
                                   outcome="no_worker")
                continue
            remaining = deadline.remaining()
            if remaining <= 0:
                self._release_worker(worker)
                break
            fwd_headers = {"Content-Type": "application/json",
                           TRACE_HEADER: trace_id,
                           Deadline.HEADER: deadline.to_header(),
                           # provenance: a client-declared budget may drive
                           # the worker's continuous batch fill; the
                           # gateway's own hop-protection default must not
                           "X-Deadline-Source": ("client" if all_client
                                                 else "gateway"),
                           **extra_headers}
            w_id = f"{worker.host}:{worker.port}"
            t_fwd = time.perf_counter()
            try:
                status, rbody = self._transport(
                    worker.url, body, fwd_headers,
                    min(self.forward_timeout, remaining))
            except urllib.error.HTTPError as e:
                if e.code == 503:
                    # worker SHED the request (bounded queue full): it is
                    # alive — don't evict — but another worker may have
                    # room, so keep failing over; remember the shed reply
                    # (incl. Retry-After) in case every worker is full
                    last_err = f"worker {worker.host}:{worker.port} shed " \
                               f"(503 queue full)"
                    last_shed = (e.read(),
                                 {k: v for k, v in e.headers.items()
                                  if k.lower() == "retry-after"})
                    self.events.append(
                        "forward_attempt", trace_id, attempt=attempt.index,
                        dur_s=time.perf_counter() - t_fwd, worker=w_id,
                        outcome="shed")
                    continue
                # worker is ALIVE and answered with a non-shed error
                # status — deterministic for this request; surface it
                # (with its headers), don't evict
                self.events.append(
                    "forward_attempt", trace_id, attempt=attempt.index,
                    dur_s=time.perf_counter() - t_fwd, worker=w_id,
                    outcome=f"http_{e.code}")
                eh = {k: v for k, v in e.headers.items()
                      if k.lower() == "retry-after"}
                ebody = e.read()
                for en in entries:
                    en.reply(e.code, ebody, eh)
                return
            except Exception as e:  # unreachable: evict + retry next worker
                last_err = str(e)
                self._m_failures.inc()
                self.events.append(
                    "forward_attempt", trace_id, attempt=attempt.index,
                    dur_s=time.perf_counter() - t_fwd, worker=w_id,
                    outcome="unreachable")
                self.deregister(name, worker)
            else:
                self.events.append(
                    "forward_attempt", trace_id, attempt=attempt.index,
                    dur_s=time.perf_counter() - t_fwd, worker=w_id,
                    outcome="ok")
                # replies OUTSIDE the try: a client that disconnects while
                # the response is being written must not be misread as a
                # worker failure (which would evict the healthy worker and
                # re-send the already-processed request — a duplicate
                # inference)
                if n == 1:
                    entries[0].reply(status, rbody)
                else:
                    self._distribute_pack(entries, status, rbody)
                return
            finally:
                self._release_worker(worker)
        if last_shed is not None and not deadline.expired:
            # every attempt landed on a full queue: propagate the shed
            # (503 + Retry-After) so the client backs off correctly
            for en in entries:
                en.reply(503, last_shed[0], last_shed[1])
            return
        # unbounded mode only exits on budget exhaustion -> 504; bounded
        # mode distinguishes attempts-exhausted (502) from expired (504)
        status = 504 if (all_client or deadline.expired) else 502
        ebody = json.dumps({"error": f"forward failed: {last_err}"}).encode()
        for en in entries:
            en.reply(status, ebody)

    @staticmethod
    def _distribute_pack(entries: List["_GatewayEntry"], status: int,
                         rbody: bytes) -> None:
        """Fan a reply pack back out to its client entries; an undecodable
        pack answers 502 (the worker is alive — no eviction — but this
        forward produced nothing usable)."""
        try:
            parts = rowcodec.decode_reply_pack(rbody)
            if len(parts) != len(entries):
                raise rowcodec.BinaryFormatError(
                    f"{len(parts)} parts for {len(entries)} entries")
        except rowcodec.BinaryFormatError as e:
            ebody = json.dumps({"error": f"bad reply pack: {e}"}).encode()
            for en in entries:
                en.reply(502, ebody)
            return
        for en, (pstatus, pbody) in zip(entries, parts):
            # the reply-pack framing carries no headers: restore the
            # back-off contract for a part-level shed (a part only sheds
            # on the rare admit race past the whole-pack capacity check;
            # the worker's shed replies always say Retry-After: 1)
            en.reply(pstatus, pbody,
                     {"Retry-After": "1"} if pstatus == 503 else None)

    # ------------------------------------------------------------- lifecycle
    def start(self) -> "ServingCoordinator":
        outer = self

        class Handler(BaseHTTPRequestHandler):
            # HTTP/1.1 keep-alive: load-test clients and forwarding proxies
            # reuse gateway connections (every reply sets Content-Length)
            protocol_version = "HTTP/1.1"

            def do_POST(self):
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else b""
                if self.path == "/register":
                    try:
                        outer.register(ServiceInfo.from_dict(
                            json.loads(body.decode())))
                        self._reply(200, b'{"ok": true}')
                    except (ValueError, KeyError) as e:
                        self._reply(400, json.dumps(
                            {"error": str(e)}).encode())
                elif self.path == "/heartbeat":
                    try:
                        d = json.loads(body.decode())
                        info = ServiceInfo.from_dict(d)
                        state = outer.heartbeat(info,
                                                load=d.get("queue_depth"),
                                                rate=d.get("rows_per_s"),
                                                report=d)
                    except (ValueError, KeyError) as e:
                        self._reply(400, json.dumps(
                            {"error": str(e)}).encode())
                        return
                    if state == "ok":
                        # the rollout actuation channel: the beat's reply
                        # tells the worker which version it should run
                        self._reply(200, json.dumps(
                            {"ok": True,
                             "target_version":
                                 outer.heartbeat_target(info)}).encode())
                    elif state == "superseded":
                        self._reply(409, b'{"error": "identity taken by a '
                                         b'newer registration; stand down"}')
                    else:
                        self._reply(410, b'{"error": "unknown worker; '
                                         b're-register"}')
                elif self.path == "/deregister":
                    # the retire discipline's first step: stop routing to
                    # a worker that is about to drain (autoscaler
                    # scale-down); in-flight forwards still complete
                    try:
                        info = ServiceInfo.from_dict(json.loads(
                            body.decode()))
                        outer.deregister(info.name, info)
                        self._reply(200, b'{"ok": true}')
                    except (ValueError, KeyError) as e:
                        self._reply(400, json.dumps(
                            {"error": str(e)}).encode())
                elif self.path.startswith("/rollout/"):
                    name = self.path[len("/rollout/"):].strip("/")
                    try:
                        d = json.loads(body.decode()) if body else {}
                        ro = outer.start_rollout(
                            name, int(d["version"]),
                            previous=d.get("previous"),
                            canary=(tuple(d["canary"])
                                    if d.get("canary") else None))
                        self._reply(200, json.dumps(
                            {k: v for k, v in ro.items()
                             if k != "baseline"}).encode())
                    except (ValueError, KeyError, TypeError) as e:
                        self._reply(400, json.dumps(
                            {"error": str(e)}).encode())
                elif self.path.startswith("/gateway/"):
                    name = self.path[len("/gateway/"):].strip("/")
                    outer._handle_gateway(self._reply, name, body,
                                          dict(self.headers))
                else:
                    self._reply(404, b'{"error": "unknown endpoint"}')

            def do_GET(self):
                if self.path.startswith("/routes/"):
                    name = self.path[len("/routes/"):].strip("/")
                    body = json.dumps(
                        [s.to_dict() for s in outer.routes(name)]).encode()
                    self._reply(200, body)
                elif self.path == "/health":
                    self._reply(200, json.dumps(outer.health()).encode())
                elif self.path == "/metrics":
                    self._reply(200,
                                outer.registry.render_prometheus().encode(),
                                ctype="text/plain; version=0.0.4; "
                                      "charset=utf-8")
                elif self.path.startswith("/trace"):
                    self._reply(200, json.dumps(outer.trace_payload(
                        _since_of(self.path))).encode())
                else:
                    self._reply(404, b'{"error": "unknown endpoint"}')

            def _reply(self, status: int, body: bytes, headers=None,
                       ctype: str = "application/json"):
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        class Server(ThreadingHTTPServer):
            daemon_threads = True
            request_queue_size = 128

        self._httpd = Server((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        threading.Thread(target=self._httpd.serve_forever,
                         daemon=True).start()
        threading.Thread(target=self._monitor_loop, daemon=True).start()
        return self

    def stop(self) -> None:
        self._stopev.set()
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
        if self._owns_transport:
            try:
                self._transport.close()
            except Exception:
                pass
        # freeze the collect-time gauge so the registry (which outlives
        # this coordinator) does not pin it in memory via the callback; a
        # stopped coordinator routes to nobody, so it scrapes as 0
        self._workers_gauge.set_function(None)
        self._workers_gauge.set(0.0)

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"


def register_with_retries(coordinator_url: str, info: ServiceInfo,
                          retries: int = 10, delay_s: float = 0.2,
                          policy: Optional[RetryPolicy] = None) -> None:
    """Worker-side registration with bounded retries (the workers' ServiceInfo
    POST, HTTPSourceV2.scala:126-152), routed through the shared
    RetryPolicy (retry discipline mirrors the reference's port-probe/
    rendezvous retry loops, TrainUtils.scala:496-512)."""
    body = json.dumps(info.to_dict()).encode()

    def post_once() -> None:
        req = urllib.request.Request(
            coordinator_url.rstrip("/") + "/register", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=5.0) as r:
            if r.status != 200:
                raise ConnectionError(f"register returned {r.status}")

    pol = policy or RetryPolicy(attempts=retries, backoff_s=delay_s,
                                multiplier=1.5, max_backoff_s=2.0,
                                jitter=0.1)
    try:
        pol.call(post_once)
    except RetryError as e:
        raise ConnectionError(
            f"could not register with coordinator at {coordinator_url}: "
            f"{e.last}") from e


class DistributedServingServer(ServingServer):
    """A per-host worker: ServingServer that announces itself to the
    coordinator on start (WorkerServer + ServiceInfo POST,
    HTTPSourceV2.scala:318-430) and HEARTBEATS for liveness — a worker the
    coordinator evicted (crash suspected, chaos-injected forward failure)
    re-registers itself on the next beat if it is actually alive.

    With a `model_source` (io/registry.RegistryModelSource) the worker is
    REGISTRY-BACKED: `handler=None` loads the registry's CURRENT version
    at construction, every beat reports the installed model_version +
    last swap outcome, and a `target_version` in the beat's reply triggers
    a hot swap toward it on the swap thread (the coordinator's rollout
    actuation). `retire()` leaves the fleet without dropping a request."""

    def __init__(self, handler, coordinator_url: str, service_name: str,
                 partition: Optional[int] = None,
                 machine: Optional[str] = None,
                 heartbeat_interval_s: float = 1.0,
                 model_source=None, **kw):
        self.model_source = model_source
        if handler is None:
            if model_source is None:
                raise ValueError("handler=None requires a model_source")
            handler, version = model_source.load_current()
            kw.setdefault("model_version", version)
        super().__init__(handler, **kw)
        self.coordinator_url = coordinator_url
        self.service_name = service_name
        self.partition = partition
        self.machine = machine
        self.heartbeat_interval_s = heartbeat_interval_s
        self._info: Optional[ServiceInfo] = None
        self._hb_stop = threading.Event()
        #: last target this worker LAUNCHED a swap for: a failed target is
        #: attempted once — the coordinator sees the failure report and
        #: re-targets (rollback); only a CHANGED target re-triggers
        self._attempted_target: Optional[int] = None
        self._swap_res: Optional[SwapResult] = None

    def start(self) -> "DistributedServingServer":
        super().start()
        # default identity is (hostname, bound port): unique across hosts AND
        # across multiple unconfigured workers on one host, so defaults never
        # evict each other in the coordinator's (machine, partition) registry
        machine = (self.machine if self.machine is not None
                   else socket.gethostname())
        partition = self.partition if self.partition is not None else self.port
        self._info = ServiceInfo(self.service_name, self.host, self.port,
                                 machine, partition, heartbeating=True)
        register_with_retries(self.coordinator_url, self._info)
        threading.Thread(target=self._heartbeat_loop, daemon=True).start()
        return self

    def _heartbeat_report(self) -> Dict:
        """One beat's payload: the PR 12 load report plus the rollout
        control fields (installed version, swap outcome, error/request
        totals, p99) the coordinator's health gate judges on."""
        d = self._info.to_dict()
        d["queue_depth"] = self._queue.qsize()
        d["rows_per_s"] = self._rows_gauge.value
        d["model_version"] = self.model_version
        d["swap_state"] = self.swap_state
        last = self.last_swap or {}
        d["swap_version"] = last.get("version")
        d["swap_outcome"] = last.get("outcome")
        d["requests_total"] = int(self._m["requests"].value)
        d["errors_total"] = int(self._m["errors"].value)
        # span-count piggyback (ISSUE 14): lets the trace collector tell
        # a quiet ring from one that overflowed between drains, and the
        # fleet snapshot report per-worker trace volume without a scrape
        d["trace_events_total"] = self.events.total_appended
        try:
            p99 = self.registry.quantile(
                "serving_request_latency_seconds", 0.99,
                {"instance": self.metrics_label})
        except Exception:  # noqa: BLE001 - telemetry never breaks the beat
            p99 = None
        d["p99_ms"] = round(p99 * 1e3, 3) if p99 else None
        return d

    def _maybe_swap(self, target) -> None:
        """Act on the beat reply's target_version: launch at most one swap
        per DISTINCT target (a failed attempt is reported back and the
        coordinator re-targets; a 'rejected' attempt — another swap was in
        flight — re-arms so a later beat retries)."""
        if target is None or self.model_source is None:
            return
        target = int(target)
        if self._swap_res is not None and self._swap_res.done.is_set() \
                and self._swap_res.outcome == "rejected" \
                and self._attempted_target == target:
            self._attempted_target = None
        if target == self.model_version or target == self._attempted_target:
            return
        if self.swap_state != "idle":
            return  # a swap is in flight; re-check on the next beat
        self._attempted_target = target
        self.request_swap(target)

    def request_swap(self, version: int) -> SwapResult:
        """Resolve `version` through the model source and launch the hot
        swap. A source that cannot even DESCRIBE the version (manifest
        missing/unreadable) resolves immediately as a counted
        rollback_load — the same funnel as a load failure."""
        try:
            load_fn, golden, expected = self.model_source.describe(version)
        except Exception as e:  # noqa: BLE001 - counted rollback
            self._swap_counter("rollback_load").inc()
            res = SwapResult(version)
            with self._swap_lock:
                self.last_swap = {"version": version,
                                  "outcome": "rollback_load",
                                  "error": f"{type(e).__name__}: {e}"}
            res._resolve("rollback_load", e)
            self._swap_res = res
            return res
        res = self.hot_swap(load_fn, version, golden_body=golden,
                            expected_reply_sha256=expected)
        self._swap_res = res
        return res

    def retire(self, drain_timeout_s: float = 30.0) -> bool:
        """Leave the fleet without dropping a request (the autoscaler's
        scale-down path): stand the heartbeat down FIRST (so the
        410-heal cannot re-register a retiring worker), DEREGISTER (no
        new routes; in-flight forwards still complete on the live
        sockets), DRAIN every admitted request, then stop — the PR 10
        deregister -> drain -> stop discipline applied to serving. The
        retirement is a system event in this worker's ring (drained by
        the trace collector BEFORE stop() — the collector's poll races
        the teardown, which is why the event lands first)."""
        t0 = time.perf_counter()
        self.events.append("retire", mint_trace_id(),
                           worker=f"{self.host}:{self.port}",
                           service=self.service_name, phase="begin")
        self._hb_stop.set()
        try:
            req = urllib.request.Request(
                self.coordinator_url.rstrip("/") + "/deregister",
                data=json.dumps(self._info.to_dict()).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=5.0):
                pass
        except Exception:  # noqa: BLE001 - coordinator gone: the
            pass           # heartbeat-timeout monitor evicts us anyway
        ok = self.drain(drain_timeout_s)
        self.events.append("retire", mint_trace_id(),
                           worker=f"{self.host}:{self.port}",
                           service=self.service_name, phase="done",
                           outcome="ok" if ok else "drain_timeout",
                           dur_s=time.perf_counter() - t0)
        self.stop()
        return ok

    def _heartbeat_loop(self) -> None:
        url = self.coordinator_url.rstrip("/") + "/heartbeat"
        wait_s = self.heartbeat_interval_s
        while not self._hb_stop.wait(wait_s):
            wait_s = self.heartbeat_interval_s
            # each beat piggybacks a load report: queue depth (the
            # least-loaded router's score input) + last-batch throughput —
            # the "autoscaling hooks" gauges used as control inputs — plus
            # the round-13 rollout fields (_heartbeat_report)
            body = json.dumps(self._heartbeat_report()).encode()
            try:
                req = urllib.request.Request(
                    url, data=body,
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=5.0) as r:
                    try:
                        rep = json.loads(r.read() or b"{}")
                    except ValueError:
                        rep = {}
                self._maybe_swap(rep.get("target_version"))
            except urllib.error.HTTPError as e:
                # 409 (identity superseded by a newer registration) is a
                # deliberate stand-down: keep beating WITHOUT re-registering,
                # so two live incarnations of one identity cannot evict each
                # other in a flap loop; if the successor dies the next beat
                # gets 410 and heals normally
                if e.code == 410 and not self._hb_stop.is_set():
                    # evicted while alive (gateway failure detection tripped
                    # on a transient fault): heal by re-registering
                    try:
                        register_with_retries(
                            self.coordinator_url, self._info, retries=3,
                            delay_s=max(0.05,
                                        self.heartbeat_interval_s / 4.0))
                        # beat again NOW: under eviction churn (chaos
                        # forward faults) the healed registration must
                        # deliver its report and receive its rollout
                        # target before the next fault can evict it —
                        # waiting a full interval loses that race
                        wait_s = 0.01
                    except ConnectionError:
                        pass  # next beat tries again
            except Exception:  # noqa: BLE001 - coordinator briefly
                pass  # unreachable: keep beating; it may come back

    def stop(self) -> None:
        self._hb_stop.set()
        super().stop()


def fetch_routes(coordinator_url: str, name: str) -> List[ServiceInfo]:
    """Client-side routing-table fetch (the reference's load-balancer path:
    clients resolve `machine:partition` workers and talk to them directly)."""
    with urllib.request.urlopen(
            coordinator_url.rstrip("/") + f"/routes/{name}",
            timeout=5.0) as r:
        return [ServiceInfo.from_dict(d) for d in json.loads(r.read())]
