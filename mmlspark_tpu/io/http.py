"""HTTP data plane: schemas, clients, transformers, parsers.

Reference files replaced here:
- io/http/HTTPSchema.scala:36-348 — `HTTPRequestData`/`HTTPResponseData`
  case classes + row codecs -> python dataclasses with to/from dict
- io/http/HTTPClients.scala:26-167 — pooled client, `sendWithRetries`
  (backoff array, 429 Retry-After handling)
- io/http/Clients.scala:12-63 — `AsyncClient` bounded-concurrency ordered
  future pipeline -> ThreadPoolExecutor map (order-preserving)
- io/http/HTTPTransformer.scala:79-129, SimpleHTTPTransformer.scala:64-166,
  Parsers.scala:24-230 — request-column -> response-column stages
"""

from __future__ import annotations

import http.client
import io
import json
import threading
import urllib.error
import urllib.parse
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..core import params as _p
from ..core.dataframe import DataFrame
from ..core.pipeline import Transformer
from ..resilience import RetryPolicy, parse_retry_after


@dataclass
class HTTPRequestData:
    """Reference: HTTPSchema.scala HTTPRequestData."""
    url: str
    method: str = "GET"
    headers: Dict[str, str] = field(default_factory=dict)
    entity: Optional[bytes] = None

    def to_dict(self) -> Dict[str, Any]:
        return {"url": self.url, "method": self.method,
                "headers": dict(self.headers),
                "entity": self.entity.decode("utf-8", "replace")
                if self.entity else None}


@dataclass
class HTTPResponseData:
    """Reference: HTTPSchema.scala HTTPResponseData."""
    statusCode: int
    entity: Optional[bytes] = None
    headers: Dict[str, str] = field(default_factory=dict)
    reasonPhrase: str = ""

    def to_dict(self) -> Dict[str, Any]:
        return {"statusCode": self.statusCode,
                "reasonPhrase": self.reasonPhrase,
                "headers": dict(self.headers),
                "entity": self.entity.decode("utf-8", "replace")
                if self.entity else None}


RETRY_BACKOFFS_MS = (100, 500, 1000)  # HTTPClients.scala retry array


#: per-outcome counter handles, built lazily then reused — the registry's
#: own hot-path contract is "keep the handle, hit only the series lock"
#: (a set_registry() swap after first use keeps counting on the old
#: registry; acceptable for the data plane, tests pass explicit registries)
_HTTP_CLIENT_COUNTERS: Dict[str, Any] = {}


def _count_http_client(outcome: str) -> None:
    """Client-side data-plane telemetry: per-attempt outcomes by class
    (2xx/4xx/5xx/429/send_failed) — the HTTPTransformer/cognitive request
    path lands in the same registry as serving and fit
    (docs/OBSERVABILITY.md). Fully guarded: a telemetry failure (import,
    metric-kind collision) must never fail the actual HTTP request."""
    c = _HTTP_CLIENT_COUNTERS.get(outcome)
    if c is None:
        try:
            from ..observability import get_registry
            c = get_registry().counter(
                "http_client_attempts_total",
                "send_with_retries attempts by outcome class",
                labels={"outcome": outcome})
        except Exception:  # noqa: BLE001 - telemetry never fails the send
            return
        _HTTP_CLIENT_COUNTERS[outcome] = c
    c.inc()


class KeepAliveTransport:
    """Per-endpoint HTTP/1.1 connection pool for the gateway forward path.

    The gateway used to open a fresh TCP connection per forward ATTEMPT
    (`urllib.request.urlopen`), paying connect latency and a socket churn
    tax on every hop at high request rates. This transport keeps a small
    freelist of `http.client.HTTPConnection`s per (host, port), reusing
    them across forwards to the same worker; a stale pooled connection
    (worker restarted, idle timeout) is retried ONCE on a fresh connect
    before the failure propagates, so reuse can never turn a healthy
    worker into a false eviction.

    Signature-compatible with `_default_transport(url, body, headers,
    timeout) -> (status, bytes)` — raises `urllib.error.HTTPError` for
    alive-but-erroring workers (status >= 400, headers preserved for
    Retry-After propagation) and connection errors for unreachable ones,
    so `FaultInjector.wrap` and the gateway's failover logic apply
    unchanged. Reuse vs fresh connects land in the shared client-attempt
    counter family (`http_client_attempts_total{outcome=conn_reused|
    conn_fresh}`) and on the `reused`/`fresh` int attributes.
    """

    def __init__(self, max_per_host: int = 8):
        self.max_per_host = max_per_host
        self._free: Dict[Tuple[str, int], List[http.client.HTTPConnection]] \
            = {}
        self._lock = threading.Lock()
        self.reused = 0
        self.fresh = 0

    def _acquire(self, key: Tuple[str, int], timeout: float):
        with self._lock:
            lst = self._free.get(key)
            if lst:
                conn = lst.pop()
                self.reused += 1
                reused = True
            else:
                conn = None
        if conn is None:
            conn = http.client.HTTPConnection(key[0], key[1],
                                              timeout=timeout)
            with self._lock:
                self.fresh += 1
            reused = False
        elif conn.sock is not None:
            conn.sock.settimeout(timeout)
        _count_http_client("conn_reused" if reused else "conn_fresh")
        return conn, reused

    def _release(self, key: Tuple[str, int],
                 conn: http.client.HTTPConnection) -> None:
        with self._lock:
            lst = self._free.setdefault(key, [])
            if len(lst) < self.max_per_host:
                lst.append(conn)
                return
        conn.close()

    def close(self) -> None:
        with self._lock:
            conns = [c for lst in self._free.values() for c in lst]
            self._free.clear()
        for c in conns:
            c.close()

    def __call__(self, url: str, body: bytes, headers: Dict[str, str],
                 timeout: float) -> Tuple[int, bytes]:
        parsed = urllib.parse.urlsplit(url)
        key = (parsed.hostname or "127.0.0.1", parsed.port or 80)
        path = parsed.path or "/"
        if parsed.query:
            path += "?" + parsed.query
        conn, was_reused = self._acquire(key, timeout)
        try:
            status, data, resp_headers, will_close = self._round_trip(
                conn, path, body, headers)
        except (http.client.HTTPException, OSError) as e:
            conn.close()
            # a TIMEOUT proves nothing about delivery — the worker may be
            # mid-inference; re-sending would duplicate the request AND
            # block past the deadline loop's reaction time. Only a
            # connection-level failure on a REUSED socket earns the one
            # fresh retry ("idle pooled socket died" vs "worker died").
            if not was_reused or isinstance(e, TimeoutError):
                raise
            # every other pooled socket to this worker predates the same
            # restart: drop them, and retry on a GUARANTEED-fresh connect
            # (re-acquiring from the pool could hand back another stale
            # socket and turn a healthy restarted worker into an eviction)
            with self._lock:
                stale = self._free.pop(key, [])
                self.fresh += 1
            for c in stale:
                c.close()
            _count_http_client("conn_fresh")
            conn = http.client.HTTPConnection(key[0], key[1],
                                              timeout=timeout)
            try:
                status, data, resp_headers, will_close = self._round_trip(
                    conn, path, body, headers)
            except (http.client.HTTPException, OSError):
                conn.close()
                raise
        if will_close:
            conn.close()
        else:
            self._release(key, conn)
        if status >= 400:
            raise urllib.error.HTTPError(url, status, "", resp_headers,
                                         io.BytesIO(data))
        return status, data

    @staticmethod
    def _round_trip(conn, path, body, headers):
        conn.request("POST", path, body=body, headers=headers)
        resp = conn.getresponse()
        data = resp.read()
        return resp.status, data, resp.headers, resp.will_close


def send_with_retries(req: HTTPRequestData,
                      backoffs=RETRY_BACKOFFS_MS,
                      timeout: float = 60.0,
                      session=None,
                      policy: Optional[RetryPolicy] = None
                      ) -> HTTPResponseData:
    """Reference: HandlingUtils.sendWithRetries (HTTPClients.scala:74-110):
    retries on 429 (honoring Retry-After, both delta-seconds and HTTP-date
    forms) and 5xx. The retry schedule is the shared `resilience.RetryPolicy`
    (default: the reference's backoff array); a 429's Retry-After overrides
    the policy's next sleep."""
    import requests
    sess = session or requests
    if policy is None:
        policy = RetryPolicy.from_backoffs_ms(backoffs)
    last = None
    for attempt in policy.attempts_iter():
        try:
            r = sess.request(req.method, req.url, headers=req.headers,
                             data=req.entity, timeout=timeout)
        except Exception as e:  # connection errors retry too
            _count_http_client("send_failed")
            last = HTTPResponseData(0, str(e).encode(), {}, "send failed")
            if attempt.is_last:
                return last
            continue
        resp = HTTPResponseData(r.status_code, r.content,
                                dict(r.headers), r.reason or "")
        _count_http_client("429" if r.status_code == 429
                           else f"{r.status_code // 100}xx")
        if r.status_code == 429 and not attempt.is_last:
            wait = parse_retry_after(r.headers.get("Retry-After"))
            if wait is not None:
                attempt.override_sleep_s = wait
            last = resp
            continue
        if 500 <= r.status_code < 600 and not attempt.is_last:
            last = resp
            continue
        return resp
    return last or HTTPResponseData(0, b"", {}, "exhausted retries")


class AsyncClient:
    """Bounded-concurrency ordered request pipeline (Clients.scala:12-63)."""

    def __init__(self, concurrency: int = 8, timeout: float = 60.0,
                 policy: Optional[RetryPolicy] = None):
        self.concurrency = concurrency
        self.timeout = timeout
        self.policy = policy

    def send_all(self, requests_: List[Optional[HTTPRequestData]]
                 ) -> List[Optional[HTTPResponseData]]:
        import requests as _rq
        with _rq.Session() as sess:
            def one(req):
                if req is None:
                    return None
                return send_with_retries(req, timeout=self.timeout,
                                         session=sess, policy=self.policy)
            with ThreadPoolExecutor(max_workers=self.concurrency) as ex:
                return list(ex.map(one, requests_))  # order preserved


class HTTPTransformer(Transformer, _p.HasInputCol, _p.HasOutputCol):
    """Column of HTTPRequestData -> column of HTTPResponseData
    (HTTPTransformer.scala:79-129)."""
    concurrency = _p.Param("concurrency", "parallel in-flight requests", 8,
                           int)
    timeout = _p.Param("timeout", "per-request timeout seconds", 60.0, float)

    def __init__(self, **kw):
        kw.setdefault("inputCol", "request")
        kw.setdefault("outputCol", "response")
        super().__init__(**kw)

    def transform(self, df: DataFrame) -> DataFrame:
        reqs = list(df[self.get("inputCol")])
        client = AsyncClient(self.get("concurrency"), self.get("timeout"))
        resps = client.send_all(reqs)
        out = np.empty(len(df), dtype=object)
        for i, r in enumerate(resps):
            out[i] = r
        return df.with_column(self.get("outputCol"), out)


# ---------------------------------------------------------------- parsers

class JSONInputParser(Transformer, _p.HasInputCol, _p.HasOutputCol):
    """Row -> HTTPRequestData with JSON entity (Parsers.scala JSONInputParser)."""
    url = _p.Param("url", "target url", None)
    method = _p.Param("method", "HTTP method", "POST")
    headers = _p.Param("headers", "extra headers", None)

    def __init__(self, **kw):
        kw.setdefault("outputCol", "request")
        super().__init__(**kw)

    def transform(self, df: DataFrame) -> DataFrame:
        headers = {"Content-Type": "application/json",
                   **(self.get("headers") or {})}
        col = df[self.get("inputCol")]
        out = np.empty(len(df), dtype=object)
        for i, v in enumerate(col):
            body = v if isinstance(v, (dict, list)) else _jsonable(v)
            out[i] = HTTPRequestData(
                url=self.get("url"), method=self.get("method"),
                headers=dict(headers),
                entity=json.dumps(body).encode("utf-8"))
        return df.with_column(self.get("outputCol"), out)


class JSONOutputParser(Transformer, _p.HasInputCol, _p.HasOutputCol):
    """HTTPResponseData -> parsed JSON (Parsers.scala JSONOutputParser)."""

    def __init__(self, **kw):
        kw.setdefault("inputCol", "response")
        kw.setdefault("outputCol", "parsed")
        super().__init__(**kw)

    def transform(self, df: DataFrame) -> DataFrame:
        col = df[self.get("inputCol")]
        out = np.empty(len(df), dtype=object)
        for i, r in enumerate(col):
            if r is None or r.entity is None:
                out[i] = None
            else:
                try:
                    out[i] = json.loads(r.entity.decode("utf-8"))
                except (ValueError, UnicodeDecodeError):
                    out[i] = None
        return df.with_column(self.get("outputCol"), out)


class StringOutputParser(Transformer, _p.HasInputCol, _p.HasOutputCol):
    def __init__(self, **kw):
        kw.setdefault("inputCol", "response")
        kw.setdefault("outputCol", "parsed")
        super().__init__(**kw)

    def transform(self, df: DataFrame) -> DataFrame:
        col = df[self.get("inputCol")]
        out = np.empty(len(df), dtype=object)
        for i, r in enumerate(col):
            out[i] = (r.entity.decode("utf-8", "replace")
                      if r is not None and r.entity else None)
        return df.with_column(self.get("outputCol"), out)


class CustomInputParser(Transformer, _p.HasInputCol, _p.HasOutputCol):
    udf = _p.Param("udf", "value -> HTTPRequestData function", None,
                   complex=True)

    def __init__(self, **kw):
        kw.setdefault("outputCol", "request")
        super().__init__(**kw)

    def transform(self, df: DataFrame) -> DataFrame:
        fn: Callable = self.get("udf")
        col = df[self.get("inputCol")]
        out = np.empty(len(df), dtype=object)
        for i, v in enumerate(col):
            out[i] = fn(v)
        return df.with_column(self.get("outputCol"), out)


class CustomOutputParser(Transformer, _p.HasInputCol, _p.HasOutputCol):
    udf = _p.Param("udf", "HTTPResponseData -> value function", None,
                   complex=True)

    def __init__(self, **kw):
        kw.setdefault("inputCol", "response")
        kw.setdefault("outputCol", "parsed")
        super().__init__(**kw)

    def transform(self, df: DataFrame) -> DataFrame:
        fn: Callable = self.get("udf")
        col = df[self.get("inputCol")]
        out = np.empty(len(df), dtype=object)
        for i, v in enumerate(col):
            out[i] = fn(v)
        return df.with_column(self.get("outputCol"), out)


class SimpleHTTPTransformer(Transformer, _p.HasInputCol, _p.HasOutputCol):
    """JSONInputParser -> HTTPTransformer -> output parser, with errorCol
    (SimpleHTTPTransformer.scala:64-166)."""

    url = _p.Param("url", "target url", None)
    method = _p.Param("method", "HTTP method", "POST")
    headers = _p.Param("headers", "extra headers", None)
    concurrency = _p.Param("concurrency", "parallel requests", 8, int)
    timeout = _p.Param("timeout", "request timeout seconds", 60.0, float)
    errorCol = _p.Param("errorCol", "column receiving error info", "error")
    outputParser = _p.Param("outputParser", "custom output parser stage", None,
                            complex=True)
    flattenOutputBatches = _p.Param("flattenOutputBatches",
                                    "API parity; no-op here", False, bool)

    def __init__(self, **kw):
        kw.setdefault("outputCol", "parsed")
        super().__init__(**kw)

    def transform(self, df: DataFrame) -> DataFrame:
        inp = JSONInputParser(
            inputCol=self.get("inputCol"), outputCol="__http_req",
            url=self.get("url"), method=self.get("method"),
            headers=self.get("headers"))
        http = HTTPTransformer(inputCol="__http_req",
                               outputCol="__http_resp",
                               concurrency=self.get("concurrency"),
                               timeout=self.get("timeout"))
        parser = (self.get("outputParser")
                  or JSONOutputParser()).copy(
                      {"inputCol": "__http_resp",
                       "outputCol": self.get("outputCol")})
        mid = http.transform(inp.transform(df))
        out = parser.transform(mid)
        errors = np.empty(len(df), dtype=object)
        for i, r in enumerate(mid["__http_resp"]):
            if r is None:
                errors[i] = "no response"
            elif not (200 <= r.statusCode < 300):
                errors[i] = f"{r.statusCode} {r.reasonPhrase}"
            else:
                errors[i] = None
        return (out.drop("__http_req", "__http_resp")
                   .with_column(self.get("errorCol"), errors))


def _jsonable(v):
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    return v
