"""Versioned model registry: the durable side of zero-downtime serving.

Reference: the ModelDownloader / Spark Serving lifecycle (SURVEY §0) —
models are published as immutable, integrity-checked artifacts and
serving processes move between them without restarting. Rebuilt here on
the repo's own substrate:

- every byte is written through the PR 10 atomic-write helper
  (``resilience.elastic.atomic_write_bytes``): a preempted publish can
  never leave a torn version;
- each version is a numbered payload directory (``v_NNNNNNNN/``) plus a
  JSON manifest (``version_NNNNNNNN.json``) carrying a sha256 digest per
  payload file — the manifest commits the version (same
  manifest-commits-the-snapshot ordering as ``CheckpointStore``), and
  ``resolve()`` verifies every digest before a worker may load it;
- ``CURRENT``/``CANARY`` pointer files pin versions for rollout: retention
  (keep-last-K) never evicts a pinned version;
- a version may carry a **golden probe**: one binary rowcodec request body
  plus the sha256 of the reply the model must produce for it. The hot-swap
  warm step (io/serving.py ``hot_swap``) replays the golden row through
  the freshly loaded handler and rolls back on digest mismatch — a wrong
  or stale artifact can never take over a worker.

Loading is the caller's ``loader(version_dir, manifest) -> handler``;
AOT-backed versions route through ``load_aot_callable`` below, which
reuses the compiled -> exported -> fresh-JIT resolver from
``compile/aot.py`` verbatim (the version directory IS an ``AOTStore``).

Every verification failure is a counted, logged event
(``model_registry_verify_failures_total{reason}``) — never a crash on the
serving path; the swap layer converts it into a counted rollback.
"""

from __future__ import annotations

import base64
import hashlib
import json
import logging
import os
import re
import shutil
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..resilience.elastic import atomic_write_bytes, atomic_write_text
from ..core.dataframe import DataFrame
from . import rowcodec

__all__ = [
    "REGISTRY_SCHEMA_VERSION", "RegistryError", "ModelRegistry",
    "RegistryModelSource", "golden_reply_digest", "load_aot_callable",
]

log = logging.getLogger(__name__)

REGISTRY_SCHEMA_VERSION = 1

_VERSION_RE = re.compile(r"^version_(\d{8})\.json$")
CURRENT_POINTER = "CURRENT.json"
CANARY_POINTER = "CANARY.json"


class RegistryError(RuntimeError):
    """A version could not be verified/resolved (missing, digest mismatch,
    schema skew). The hot-swap layer treats this as a counted rollback —
    it must never crash a serving worker."""


def _sha256(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _count_verify_failure(reason: str, version: Any) -> None:
    log.warning("model registry version %s unusable (%s)", version, reason)
    try:
        from ..observability import get_registry
        get_registry().counter(
            "model_registry_verify_failures_total",
            "registry version loads that failed verification, by reason",
            {"reason": reason}).inc()
    except Exception:  # noqa: BLE001 - telemetry never fails resolution
        pass


def golden_reply_digest(handler: Callable[[DataFrame], DataFrame],
                        golden_body: bytes,
                        reply_col: str = "prediction") -> str:
    """Run one binary rowcodec golden request through ``handler`` and
    digest the reply bytes — computed once at publish time (the expected
    digest stored in the manifest) and again by the swap warm probe (the
    first-batch digest gate). Byte-identical replies <=> equal digests."""
    name, arr = rowcodec.decode(golden_body)
    if arr.ndim == 1:
        arr = arr.reshape(1, -1)
    scored = handler(DataFrame({name: np.ascontiguousarray(arr)}))
    return _sha256(rowcodec.encode_reply(reply_col, scored[reply_col]))


class ModelRegistry:
    """A directory of numbered, digest-verified model versions.

    Layout::

        <dir>/v_00000001/...payload files...   (weights, AOT artifacts)
        <dir>/version_00000001.json            (manifest — commits the version)
        <dir>/CURRENT.json                     ({"version": N} pointer)
        <dir>/CANARY.json                      (optional canary pointer)

    Manifest schema::

        {"schema_version": 1, "version": 1,
         "files": {"<relpath>": {"sha256": "...", "bytes": 123}, ...},
         "golden": {"body_b64": "...", "reply_sha256": "...",
                    "reply_col": "prediction"} | null,
         "extra": {...publisher metadata...}}

    Retention: ``keep_last`` most recent versions survive ``publish``;
    versions pinned by the CURRENT or CANARY pointer are never evicted
    (a rollback target must still exist when the rollback fires).
    """

    def __init__(self, directory: str, keep_last: int = 4):
        if keep_last < 2:
            # a failed swap rolls back to the PREVIOUS version; retention
            # must never leave only the version being rolled away from
            raise ValueError(f"keep_last must be >= 2, got {keep_last}")
        self.directory = os.path.abspath(directory)
        self.keep_last = int(keep_last)

    # -------------------------------------------------------------- listing
    def versions(self) -> List[int]:
        """Committed (manifest-bearing) version numbers, oldest first.
        In-progress payload directories without a manifest are invisible."""
        try:
            names = os.listdir(self.directory)
        except FileNotFoundError:
            return []
        return sorted(int(m.group(1)) for n in names
                      if (m := _VERSION_RE.match(n)))

    def version_dir(self, version: int) -> str:
        return os.path.join(self.directory, f"v_{version:08d}")

    def _manifest_path(self, version: int) -> str:
        return os.path.join(self.directory, f"version_{version:08d}.json")

    def manifest(self, version: int) -> Optional[Dict[str, Any]]:
        try:
            with open(self._manifest_path(version), encoding="utf-8") as fh:
                doc = json.load(fh)
        except (OSError, ValueError):
            return None
        return doc if isinstance(doc, dict) else None

    # -------------------------------------------------------------- publish
    def publish(self, files: Optional[Dict[str, bytes]] = None,
                source_dir: Optional[str] = None, *,
                golden_body: Optional[bytes] = None,
                golden_reply_sha256: Optional[str] = None,
                reply_col: str = "prediction",
                extra: Optional[Dict[str, Any]] = None,
                set_current: bool = False) -> int:
        """Write one new version (payload files, then the manifest that
        commits them — both through the atomic helper), apply retention,
        and return the version number.

        ``files`` maps relative paths to bytes; ``source_dir`` copies an
        existing artifact directory (e.g. an ``AOTStore``) instead. The
        optional golden probe (one binary rowcodec body + the sha256 of
        the reply the model must produce) is what the swap warm step
        replays before any flip."""
        if (files is None) == (source_dir is None):
            raise ValueError("publish needs exactly one of files/source_dir")
        if files is None:
            files = {}
            for root, _, names in os.walk(source_dir):
                for n in names:
                    p = os.path.join(root, n)
                    rel = os.path.relpath(p, source_dir)
                    with open(p, "rb") as fh:
                        files[rel] = fh.read()
        if not files:
            raise ValueError("a version must carry at least one file")
        versions = self.versions()
        version = (versions[-1] + 1) if versions else 1
        vdir = self.version_dir(version)
        entries: Dict[str, Dict[str, Any]] = {}
        for rel, data in sorted(files.items()):
            atomic_write_bytes(os.path.join(vdir, rel), data)
            entries[rel] = {"sha256": _sha256(data), "bytes": len(data)}
        golden = None
        if golden_body is not None:
            golden = {"body_b64": base64.b64encode(golden_body).decode(),
                      "reply_sha256": golden_reply_sha256,
                      "reply_col": reply_col}
        manifest = {
            "schema_version": REGISTRY_SCHEMA_VERSION,
            "version": version,
            "files": entries,
            "golden": golden,
            "extra": dict(extra or {}),
        }
        atomic_write_text(self._manifest_path(version),
                          json.dumps(manifest, indent=1, sort_keys=True))
        try:
            from ..observability import get_registry
            get_registry().counter(
                "model_registry_publish_total",
                "model versions published").inc()
        except Exception:  # noqa: BLE001
            pass
        if set_current:
            self.set_current(version)
        self._gc()
        return version

    def _gc(self) -> None:
        """Keep-last-K retention, never evicting a pointer-pinned version."""
        pinned = {v for v in (self.current(), self.canary()) if v is not None}
        vs = self.versions()
        for v in vs[:-self.keep_last] if self.keep_last else []:
            if v in pinned:
                continue
            try:
                os.remove(self._manifest_path(v))
            except OSError:
                pass
            shutil.rmtree(self.version_dir(v), ignore_errors=True)

    # ------------------------------------------------------------- pointers
    def _read_pointer(self, name: str) -> Optional[int]:
        try:
            with open(os.path.join(self.directory, name),
                      encoding="utf-8") as fh:
                v = json.load(fh).get("version")
            return int(v) if v is not None else None
        except (OSError, ValueError, AttributeError, TypeError):
            return None

    def _write_pointer(self, name: str, version: Optional[int]) -> None:
        atomic_write_text(os.path.join(self.directory, name),
                          json.dumps({"version": version}))

    def current(self) -> Optional[int]:
        return self._read_pointer(CURRENT_POINTER)

    def set_current(self, version: Optional[int]) -> None:
        if version is not None and self.manifest(version) is None:
            raise RegistryError(f"cannot pin CURRENT to unknown "
                                f"version {version}")
        self._write_pointer(CURRENT_POINTER, version)

    def canary(self) -> Optional[int]:
        return self._read_pointer(CANARY_POINTER)

    def set_canary(self, version: Optional[int]) -> None:
        if version is not None and self.manifest(version) is None:
            raise RegistryError(f"cannot pin CANARY to unknown "
                                f"version {version}")
        self._write_pointer(CANARY_POINTER, version)

    # -------------------------------------------------------------- resolve
    def verify(self, version: int) -> Tuple[bool, str]:
        """Digest-check every payload file against the manifest. Returns
        (ok, reason) without raising — ``resolve`` is the raising form."""
        man = self.manifest(version)
        if man is None:
            return False, "missing_manifest"
        if int(man.get("schema_version", -1)) > REGISTRY_SCHEMA_VERSION:
            return False, "schema_newer_than_reader"
        vdir = self.version_dir(version)
        for rel, ent in man.get("files", {}).items():
            try:
                with open(os.path.join(vdir, rel), "rb") as fh:
                    data = fh.read()
            except OSError:
                return False, "payload_missing"
            if _sha256(data) != ent.get("sha256"):
                return False, "digest_mismatch"
        return True, "ok"

    def resolve(self, version: int) -> Tuple[str, Dict[str, Any]]:
        """Verified (payload_dir, manifest) for one version, or
        ``RegistryError`` with a counted
        ``model_registry_verify_failures_total{reason}``. Workers call
        this inside the swap load step, so a corrupt artifact becomes a
        counted rollback, never a crash or a silently-wrong model."""
        ok, reason = self.verify(version)
        if not ok:
            _count_verify_failure(reason, version)
            raise RegistryError(
                f"model version {version} failed verification: {reason}")
        return self.version_dir(version), self.manifest(version)

    def golden(self, version: int
               ) -> Tuple[Optional[bytes], Optional[str], str]:
        """(golden_body, expected_reply_sha256, reply_col) for one version
        (Nones when the publisher attached no probe)."""
        man = self.manifest(version) or {}
        g = man.get("golden") or {}
        body = (base64.b64decode(g["body_b64"])
                if g.get("body_b64") else None)
        return body, g.get("reply_sha256"), g.get("reply_col", "prediction")


def load_aot_callable(version_dir: str, name: str, args,
                      expect_nr_devices: int = 1):
    """Resolve an AOT-backed version's entry to the fastest usable
    callable — the version directory is an ``AOTStore``, and this is the
    PR 11 compiled -> exported -> fresh-JIT resolver applied to it
    (``compile/aot.load_serving_callable``; returns None on a counted
    fallback, in which case the caller's loader supplies the fresh JIT)."""
    from ..compile.aot import AOTStore, load_serving_callable
    return load_serving_callable(AOTStore(version_dir), name, args,
                                 expect_nr_devices=expect_nr_devices)


class RegistryModelSource:
    """Worker-side bridge from a registry to the hot-swap machinery.

    ``loader(version_dir, manifest) -> handler`` builds the serving
    callable (an AOT-backed loader routes through ``load_aot_callable``).
    ``describe(version)`` returns the ``(load_fn, golden_body,
    expected_reply_sha256)`` triple ``ServingServer.hot_swap`` consumes:
    ``load_fn`` performs digest verification + loading ON THE SWAP
    THREAD, so every failure lands in the counted-rollback funnel while
    the old handler keeps serving."""

    def __init__(self, directory: str,
                 loader: Callable[[str, Dict[str, Any]], Callable],
                 keep_last: int = 4):
        self.registry = ModelRegistry(directory, keep_last=keep_last)
        self.loader = loader

    def current_version(self) -> Optional[int]:
        return self.registry.current()

    def describe(self, version: int):
        golden_body, expected, _reply_col = self.registry.golden(version)

        def load_fn():
            vdir, manifest = self.registry.resolve(version)
            return self.loader(vdir, manifest)

        return load_fn, golden_body, expected

    def load_current(self):
        """(handler, version) for the CURRENT pointer — the worker's
        start-of-life model. Raises when there is no usable current
        version (a worker with nothing to serve must not start)."""
        version = self.registry.current()
        if version is None:
            raise RegistryError("registry has no CURRENT version")
        vdir, manifest = self.registry.resolve(version)
        return self.loader(vdir, manifest), version
