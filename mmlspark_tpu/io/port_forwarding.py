"""TCP port forwarding with the reference's probe/retry contract.

Reference: io/http/PortForwarding.scala:12-86 — `forwardPortToRemote` builds a
jsch SSH session and probes `remotePortStart + attempt` until a reverse
forwarding binds, exposing a local service on a remote bind address.

TPU restructure: the JVM/SSH dependency disappears; what the reference
actually provides the stack is "make service A reachable at address B with
port probing + bounded retries", which a plain threaded socket relay does
natively (and testably, with zero credentials). The options-map API keeps the
reference's `forwarding.*` key names so configs port over unchanged. When a
true encrypted tunnel is required, point the relay at an `ssh -R` endpoint —
transport and relay compose instead of being welded together.
"""

from __future__ import annotations

import socket
import threading
from typing import Dict, Tuple

from ..resilience import RetryError, RetryPolicy


class Forwarder:
    """A running TCP relay: (bind_address, port) -> (target_host, target_port).

    The jsch `Session` analogue: hold it to keep the tunnel alive, `stop()`
    to tear it down (session.disconnect)."""

    def __init__(self, bind_address: str, port: int, target_host: str,
                 target_port: int, backlog: int = 32):
        self.target = (target_host, target_port)
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((bind_address, port))
        self._srv.listen(backlog)
        self.port = self._srv.getsockname()[1]
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._thread.start()

    # ------------------------------------------------------------- relaying
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                client, _ = self._srv.accept()
            except OSError:
                return  # listener closed
            threading.Thread(target=self._relay, args=(client,),
                             daemon=True).start()

    def _relay(self, client: socket.socket) -> None:
        try:
            upstream = socket.create_connection(self.target, timeout=10.0)
        except OSError:
            client.close()
            return

        def pump(src: socket.socket, dst: socket.socket) -> None:
            try:
                while True:
                    data = src.recv(1 << 16)
                    if not data:
                        break
                    dst.sendall(data)
            except OSError:
                pass
            finally:
                for s in (src, dst):
                    try:
                        s.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                    s.close()

        threading.Thread(target=pump, args=(client, upstream),
                         daemon=True).start()
        threading.Thread(target=pump, args=(upstream, client),
                         daemon=True).start()

    def stop(self) -> None:
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass


def forward_port_to_remote(bind_address: str, remote_port_start: int,
                           local_host: str, local_port: int,
                           max_retries: int = 50
                           ) -> Tuple[Forwarder, int]:
    """Probe ports [remote_port_start, remote_port_start + max_retries] until
    one binds, exactly the reference's retry loop
    (PortForwarding.scala:50-66), expressed over the shared RetryPolicy
    (attempt index = port offset; zero backoff — a bound port won't free
    itself for waiting). Returns (forwarder, bound_port)."""
    probe = {"port": remote_port_start}

    def bind_next() -> Forwarder:
        port = probe["port"]
        probe["port"] += 1
        return Forwarder(bind_address, port, local_host, local_port)

    policy = RetryPolicy(attempts=max_retries + 1, backoff_s=0.0,
                         jitter=0.0, timeout_s=None,
                         retryable=lambda e: isinstance(e, OSError))
    try:
        fwd = policy.call(bind_next)
    except RetryError as e:
        raise RuntimeError(
            f"Could not find open port between {remote_port_start} and "
            f"{remote_port_start + max_retries}") from e.last
    return fwd, fwd.port


def forward_port_to_remote_options(options: Dict[str, str]
                                   ) -> Tuple[Forwarder, int]:
    """Options-map entry with the reference's key names
    (PortForwarding.scala:71-86). SSH-credential keys (username/sshhost/
    keydir/keysas) are accepted and ignored — transport is composed
    separately (see module docstring)."""
    start = options.get("forwarding.remoteportstart",
                        options.get("forwarding.localport"))
    if start is None:
        raise KeyError("forwarding.remoteportstart or forwarding.localport "
                       "is required")
    return forward_port_to_remote(
        options.get("forwarding.bindaddress", "127.0.0.1"),
        int(start),
        options.get("forwarding.localhost", "127.0.0.1"),
        int(options["forwarding.localport"]),
        int(options.get("forwarding.maxretires",  # sic — reference key name
                        options.get("forwarding.maxretries", "50"))),
    )
