"""Reusable serving-load harness: fleet setup, traffic, observability.

Extracted from scripts/measure_serving_load.py (ISSUE 20 satellite): the
sustained-load / hot-swap / autoscale legs were 1039 lines of
copy-adjacent scenario code living in a script, so the production-day
scenario engine (resilience/scenario.py + scripts/run_production_day.py)
could only have composed them by duplicating fleet setup/teardown. This
module is the importable library: spawn-context worker processes
(`worker_main` stays module-level so RegistryModelSource pickles by
module path), the keep-alive verifying client (`LoadClient`), the
observability arm/harvest pair, the Prometheus scrape helpers, and the
three measured legs (`run_load_variant`, `run_swap_variant`,
`run_autoscale_variant`) byte-compatible with the script's historical
`--scenario load|swap|autoscale` JSON output — the script is now a thin
CLI over these functions and the old private names remain importable
there.

The legs' contracts (docs/SERVING.md):
- load: >= 100k mixed-size row-requests/s through the gateway; chaos
  variant adds 30% injected forward faults + one worker kill with ZERO
  accepted (HTTP 200) requests carrying a wrong/missing payload.
- swap: registry-backed fleet, mid-run canary -> promote rollout with
  zero lost/shed accepted requests; chaos variant corrupts the target
  artifact (digest gate must fail the swap) + kills a worker mid-rollout
  + 30% forward faults — the rollout must auto-roll-back, zero loss.
- autoscale: ramped load against a 2-worker base fleet; the Autoscaler
  must grow 2 -> 4 under the ramp and retire back to 2 after it
  (deregister -> drain -> stop), zero lost requests.
"""

import json
import os
import re
import socket
import sys
import threading
import time
import urllib.request

import numpy as np

FEATURES = 16
BATCH_MIX = (1, 8, 64, 256)
DEADLINE_MS = 10_000
SERVICE = "load"


def ref_weights() -> np.ndarray:
    return (np.arange(FEATURES, dtype=np.float32) + 1.0) / FEATURES


def make_handler(w: np.ndarray, slow_ms: float = 0.0):
    def handler(df):
        if slow_ms:
            # models a heavier per-batch device cost (the autoscale
            # scenario needs queues to actually build under the ramp)
            time.sleep(slow_ms / 1000.0)
        x = np.asarray(df["features"], np.float32)
        return df.with_column("prediction", (x @ w).astype(np.float32))
    return handler


def registry_loader(vdir: str, manifest: dict):
    """Version loader for registry-backed workers: weights.bin -> linear
    scorer (module-level so spawn-context worker processes can pickle a
    RegistryModelSource built around it)."""
    with open(os.path.join(vdir, "weights.bin"), "rb") as fh:
        w = np.frombuffer(fh.read(), np.float32).copy()
    slow_ms = float(manifest.get("extra", {}).get("slow_ms", 0.0))
    return make_handler(w, slow_ms)


def worker_main(coord_url: str, partition: int, ready, stop,
                retire=None, registry_dir: str = None,
                slow_ms: float = 0.0, max_batch_size: int = 1024) -> None:
    """One serving worker in its own process (own GIL): numpy linear
    scorer — the host-path cost model; the chip handler swaps in the
    jitted booster (scripts/measure_serving_tpu.py). With `registry_dir`
    the worker is registry-backed (serves CURRENT, hot-swaps on rollout
    targets); with `retire` set it leaves via deregister -> drain -> stop
    (the autoscaler's zero-loss scale-down)."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    from mmlspark_tpu.io.distributed_serving import DistributedServingServer

    kw = {}
    if registry_dir is not None:
        from mmlspark_tpu.io.registry import RegistryModelSource
        handler = None
        kw["model_source"] = RegistryModelSource(registry_dir,
                                                 registry_loader)
    else:
        handler = make_handler(ref_weights(), slow_ms)

    server = DistributedServingServer(
        handler, coord_url, SERVICE, partition=partition,
        machine=f"load-{partition}", port=0,
        max_batch_size=max_batch_size, max_latency_ms=0.5,
        heartbeat_interval_s=0.25, max_queue=4096, **kw).start()
    ready.set()
    while not stop.wait(0.1):
        if retire is not None and retire.is_set():
            server.retire(drain_timeout_s=30.0)
            return
    server.stop()


class LoadClient(threading.Thread):
    """Keep-alive HTTP/1.1 client hammering the gateway with binary
    bodies of mixed row counts; verifies EVERY 200 payload exactly.
    `expected_first` per body may be a tuple of acceptable values — the
    swap scenario accepts BOTH versions' outputs for the whole run (any
    other value is a torn/corrupt reply) and tallies which version
    answered in `value_counts`."""

    def __init__(self, host, port, path, bodies, expected, deadline_s,
                 stop_ev):
        super().__init__(daemon=True)
        self.addr = (host, port)
        self.path = path.encode()
        # [(nrows, body, expected_first | (v1, v2, ...))] — normalized
        self.bodies = [(n, b, e if isinstance(e, tuple) else (e,))
                       for n, b, e in bodies]
        self.deadline_s = deadline_s
        self.stop_ev = stop_ev
        self.expected = expected
        self.sent = 0
        self.ok_requests = 0
        self.ok_rows = 0
        self.shed = 0
        self.expired = 0
        self.errors = 0
        self.bad_payload = 0
        self.lost = 0
        self.value_counts = {}        # matched expected index -> replies

    def _connect(self):
        s = socket.create_connection(self.addr, timeout=30.0)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return s

    def run(self):
        from mmlspark_tpu.io import rowcodec
        sock = self._connect()
        buf = b""
        i = 0
        head_tpl = (b"POST %s HTTP/1.1\r\nHost: x\r\n"
                    b"Content-Type: application/octet-stream\r\n"
                    b"X-Deadline-Ms: %d\r\n"
                    b"Content-Length: %%d\r\n\r\n"
                    % (self.path, DEADLINE_MS))
        while not self.stop_ev.is_set():
            nrows, body, exp_first = self.bodies[i % len(self.bodies)]
            i += 1
            try:
                sock.sendall(head_tpl % len(body) + body)
                self.sent += 1
                # read one response
                while b"\r\n\r\n" not in buf:
                    chunk = sock.recv(262144)
                    if not chunk:
                        raise ConnectionError("closed")
                    buf += chunk
                head, _, rest = buf.partition(b"\r\n\r\n")
                status = int(head.split(b" ", 2)[1])
                length = 0
                for ln in head.split(b"\r\n"):
                    if ln.lower().startswith(b"content-length:"):
                        length = int(ln.split(b":", 1)[1])
                while len(rest) < length:
                    chunk = sock.recv(262144)
                    if not chunk:
                        raise ConnectionError("closed")
                    rest += chunk
                payload, buf = rest[:length], rest[length:]
                if status == 200:
                    _, preds = rowcodec.decode(payload)
                    match = None
                    if preds.shape[0] == nrows:
                        for k, e in enumerate(exp_first):
                            if abs(float(preds[0]) - e) <= 1e-4:
                                match = k
                                break
                    if match is None:
                        self.bad_payload += 1
                    else:
                        self.ok_requests += 1
                        self.ok_rows += nrows
                        self.value_counts[match] = \
                            self.value_counts.get(match, 0) + 1
                elif status == 503:
                    self.shed += 1
                elif status == 504:
                    self.expired += 1
                else:
                    self.errors += 1
            except Exception:
                # connection died mid-request (gateway restart, teardown
                # race): the in-flight request got NO reply
                self.lost += 1
                try:
                    sock.close()
                except Exception:
                    pass
                if self.stop_ev.is_set():
                    return
                try:
                    sock = self._connect()
                    buf = b""
                except Exception:
                    time.sleep(0.05)
        try:
            sock.close()
        except Exception:
            pass


def make_bodies(weight_sets, rng_seed: int = 5):
    """Binary bodies for the mixed-size schedule. `weight_sets`: one or
    more weight vectors; each body's expected first value covers every
    set (the swap legs accept both versions' outputs for the whole
    run)."""
    from mmlspark_tpu.io import rowcodec
    rng = np.random.default_rng(rng_seed)
    bodies = []
    for nrows in BATCH_MIX:
        x = rng.normal(size=(nrows, FEATURES)).astype(np.float32)
        exp = tuple(float(x[0] @ w) for w in weight_sets)
        bodies.append((nrows, rowcodec.encode("features", x),
                       exp if len(exp) > 1 else exp[0]))
    return bodies


def scrape(url: str) -> str:
    with urllib.request.urlopen(url, timeout=10.0) as r:
        return r.read().decode()


# ------------------------------------------- fleet observability (PR 14)

def arm_observability(coord, reg, injector=None, **recorder_kw):
    """TraceCollector + FlightRecorder over one coordinator's fleet: the
    collector drains every ring (gateway in-process, workers over
    /trace), the recorder watches the anomaly triggers and dumps atomic
    incident bundles. The chaos injector's decisions are bridged onto
    the gateway ring so injections appear in bundles beside the failures
    they caused. Extra kwargs reach the FlightRecorder (the
    production-day run arms `chaos_bundles=True` this way)."""
    import tempfile
    from mmlspark_tpu.observability import FlightRecorder, TraceCollector

    collector = TraceCollector.for_coordinator(coord, SERVICE,
                                               registry=reg).start(0.5)
    inc_dir = recorder_kw.pop("out_dir", None) \
        or tempfile.mkdtemp(prefix="mmlspark_incidents_")
    recorder_kw.setdefault("window_s", 30.0)
    recorder_kw.setdefault("cooldown_s", 10.0)
    recorder_kw.setdefault("shed_spike", 500.0)
    recorder_kw.setdefault("slowest_k", 8)
    recorder_kw.setdefault("failed_k", 20)
    recorder = FlightRecorder.for_coordinator(
        coord, collector, inc_dir, SERVICE, registry=reg,
        **recorder_kw).start(1.0)
    if injector is not None:
        injector.event_log = coord.events
    return collector, recorder


def harvest_observability(summary, coord, collector, recorder):
    """Final drain + fleet snapshot INTO the summary (workers must still
    be up: the bundle's /health walk and the fleet snapshot need them)."""
    if collector is None:
        return
    recorder.stop()
    collector.stop()
    try:
        recorder.tick()   # one synchronous final pass
    except Exception:
        pass
    try:
        scripts_dir = os.path.join(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))), "scripts")
        sys.path.insert(0, scripts_dir)
        from fleet_status import collect_fleet
        summary["fleet"] = collect_fleet(coord.url)
    except Exception as e:  # noqa: BLE001 - snapshot must not fail the run
        summary["fleet_error"] = str(e)[:200]
    bundles, seen = [], set()
    for p in recorder.incidents:
        try:
            with open(p) as f:
                b = json.load(f)
        except Exception:  # noqa: BLE001
            continue
        # embed the FIRST bundle of each distinct reason (bundles carry
        # full registry snapshots — a flat cap could crowd the rollback
        # bundle out behind repeated SLO/p99 firings)
        if b["reason"] in seen:
            continue
        seen.add(b["reason"])
        bundles.append(b)
        if len(bundles) >= 5:
            break
    summary["incidents"] = bundles
    summary["incident_paths"] = list(recorder.incidents)


def prom_value(text: str, name: str) -> float:
    total = 0.0
    for m in re.finditer(rf"^{name}(?:{{[^}}]*}})? ([0-9.e+-]+)$", text,
                         re.M):
        total += float(m.group(1))
    return total


def prom_by_label(text: str, name: str, label: str) -> dict:
    """Sum a counter family per value of one label."""
    out = {}
    for m in re.finditer(rf'^{name}{{([^}}]*)}} ([0-9.e+-]+)$', text, re.M):
        lm = re.search(rf'{label}="([^"]*)"', m.group(1))
        if lm:
            out[lm.group(1)] = out.get(lm.group(1), 0.0) + float(m.group(2))
    return out


def spawn_workers(ctx, coord_url, n, registry_dir=None, slow_ms=0.0,
                  max_batch_size=1024, first_partition=0):
    """Each worker gets its OWN stop/retire events: terminate()-ing a
    worker that shares an Event can kill it while it holds the event's
    internal lock, deadlocking the parent's later set() (observed on the
    chaos path)."""
    procs, readies, stops, retires = [], [], [], []
    for p in range(first_partition, first_partition + n):
        ready = ctx.Event()
        stop = ctx.Event()
        retire = ctx.Event()
        proc = ctx.Process(target=worker_main,
                           args=(coord_url, p, ready, stop, retire,
                                 registry_dir, slow_ms, max_batch_size),
                           daemon=True)
        proc.start()
        procs.append(proc)
        readies.append(ready)
        stops.append(stop)
        retires.append(retire)
    for r in readies:
        if not r.wait(60):
            raise RuntimeError("worker failed to start/register")
    return procs, stops, retires


def stop_workers(procs, stops):
    """Signal stops, join, terminate stragglers — the shared teardown."""
    for p, st in zip(procs, stops):
        if p.is_alive():
            st.set()
    for p in procs:
        p.join(10.0)
        if p.is_alive():
            p.terminate()


def client_tallies(clients, wall) -> dict:
    sent = sum(c.sent for c in clients)
    ok_rows = sum(c.ok_rows for c in clients)
    values = {}
    for c in clients:
        for k, v in c.value_counts.items():
            values[k] = values.get(k, 0) + v
    return {
        "client_requests": sent,
        "ok_requests": sum(c.ok_requests for c in clients),
        "ok_rows": ok_rows,
        "row_requests_per_s": round(ok_rows / wall, 1),
        "shed": sum(c.shed for c in clients),
        "expired": sum(c.expired for c in clients),
        "errors": sum(c.errors for c in clients),
        "bad_payload_on_200": sum(c.bad_payload for c in clients),
        "no_reply_lost": sum(c.lost for c in clients),
        "replies_by_version_index": values,
    }


# ------------------------------------------------------------ the legs

def run_load_variant(chaos: bool, duration_s: float, n_workers: int,
                     n_clients: int, collect: bool = True) -> dict:
    import multiprocessing as mp
    import urllib.parse
    from mmlspark_tpu.io.distributed_serving import ServingCoordinator
    from mmlspark_tpu.io.http import KeepAliveTransport
    from mmlspark_tpu.observability import MetricsRegistry, set_registry
    from mmlspark_tpu.resilience import FaultInjector

    # fresh process-global registry per variant: worker processes have
    # their own; the gateway's series live here
    reg = MetricsRegistry()
    prev = set_registry(reg)
    injector = None
    transport = None
    if chaos:
        transport = KeepAliveTransport()
        injector = FaultInjector(seed=12, error_rate=0.3)
    coord = ServingCoordinator(
        heartbeat_timeout_s=2.0, registry=reg,
        forward_transport=(injector.wrap(transport) if chaos else None),
        coalesce_max=8).start()
    ctx = mp.get_context("spawn")
    procs, worker_stops, _ = spawn_workers(ctx, coord.url, n_workers)
    collector = recorder = None
    if collect:
        collector, recorder = arm_observability(coord, reg, injector)

    w = ref_weights()
    bodies = make_bodies([w])

    stop_clients = threading.Event()
    parsed = urllib.parse.urlsplit(coord.url)
    clients = [LoadClient(parsed.hostname, parsed.port,
                          f"/gateway/{SERVICE}", bodies, w,
                          DEADLINE_MS / 1000.0, stop_clients)
               for _ in range(n_clients)]
    t0 = time.perf_counter()
    for c in clients:
        c.start()
    killed_at = None
    if chaos:
        # kill one worker a third of the way in: it must be evicted and
        # the fleet rebalanced with zero accepted-request loss
        time.sleep(max(duration_s / 3.0, 1.0))
        if recorder is not None:
            # the p99-breach trigger compares against the healthy phase
            recorder.arm_baseline()
        procs[0].terminate()
        killed_at = time.perf_counter() - t0
        time.sleep(max(duration_s * 2.0 / 3.0, 1.0))
    else:
        time.sleep(duration_s)
    stop_clients.set()
    for c in clients:
        c.join(15.0)
    wall = time.perf_counter() - t0

    # worker-side scrape BEFORE teardown: batch fill + request accounting
    worker_stats = []
    for s in coord.routes(SERVICE):
        try:
            text = scrape(f"http://{s.host}:{s.port}/metrics")
            cnt = prom_value(text, "serving_batch_rows_count")
            tot = prom_value(text, "serving_batch_rows_sum")
            worker_stats.append({
                "worker": f"{s.machine}:{s.partition}",
                "batches": cnt,
                "mean_batch_rows": round(tot / cnt, 2) if cnt else 0.0,
                "requests": prom_value(text, "serving_requests_total"),
                "shed": prom_value(text, "serving_shed_total"),
                "coalesced_packs": prom_value(
                    text, "serving_coalesced_packs_total"),
            })
        except Exception as e:
            worker_stats.append({"worker": f"{s.machine}:{s.partition}",
                                 "scrape_error": str(e)[:100]})

    # trace exemplars: a few gateway traces with their per-attempt spans
    exemplars = []
    seen = set()
    for ev in list(coord.events.events())[-400:]:
        tid = ev.get("trace_id")
        if tid and tid not in seen:
            seen.add(tid)
            spans = [{k: v for k, v in e.items() if k != "trace_id"}
                     for e in coord.events.events(tid)]
            exemplars.append({"trace_id": tid, "spans": spans[:8]})
        if len(exemplars) >= 3:
            break

    lbl = {"instance": coord.metrics_label}
    p50 = reg.quantile("gateway_request_latency_seconds", 0.5, lbl)
    p99 = reg.quantile("gateway_request_latency_seconds", 0.99, lbl)
    sent = sum(c.sent for c in clients)
    ok_req = sum(c.ok_requests for c in clients)
    ok_rows = sum(c.ok_rows for c in clients)
    shed = sum(c.shed for c in clients)
    expired = sum(c.expired for c in clients)
    errors = sum(c.errors for c in clients)
    bad = sum(c.bad_payload for c in clients)
    lost = sum(c.lost for c in clients)
    mean_fill_rows = [ws["mean_batch_rows"] for ws in worker_stats
                      if ws.get("batches")]
    summary = {
        "variant": "chaos" if chaos else "baseline",
        "duration_s": round(wall, 1),
        "workers": n_workers,
        "clients": n_clients,
        "batch_mix_rows": list(BATCH_MIX),
        "client_requests": sent,
        "ok_requests": ok_req,
        "ok_rows": ok_rows,
        "row_requests_per_s": round(ok_rows / wall, 1),
        "client_requests_per_s": round(sent / wall, 1),
        "shed": shed,
        "expired": expired,
        "errors": errors,
        "bad_payload_on_200": bad,
        "no_reply_lost": lost,
        "shed_rate": round(shed / sent, 5) if sent else 0.0,
        "gateway_p50_ms": round(p50 * 1e3, 3) if p50 else None,
        "gateway_p99_ms": round(p99 * 1e3, 3) if p99 else None,
        "coalesced_forwards": reg.total("gateway_coalesced_forwards_total"),
        "coalesced_requests": reg.total("gateway_coalesced_requests_total"),
        "route_decisions": reg.total("gateway_route_decisions_total"),
        "forward_failures": reg.total("gateway_forward_failures_total"),
        "evictions": reg.total("gateway_evictions_total"),
        "worker_stats": worker_stats,
        "mean_batch_rows": (round(float(np.mean(mean_fill_rows)), 1)
                            if mean_fill_rows else 0.0),
        "trace_exemplars": exemplars,
    }
    if chaos:
        summary["injected"] = dict(injector.counts)
        summary["worker_killed_at_s"] = round(killed_at, 1)
    summary["collect"] = bool(collect)
    harvest_observability(summary, coord, collector, recorder)

    stop_workers(procs, worker_stops)
    coord.stop()
    set_registry(prev)
    return summary


def run_swap_variant(chaos: bool, duration_s: float, n_workers: int,
                     n_clients: int, collect: bool = True) -> dict:
    """Sustained load with a mid-run version rollout. Baseline: canary ->
    promote to v2 completes with zero lost/shed accepted requests, every
    200 payload exact against {v1, v2}. Chaos: the target version's
    artifact is CORRUPT (digest gate must fail the swap), a worker is
    killed mid-rollout, and 30% of gateway forwards fail — the rollout
    must auto-roll-back with zero accepted-request loss."""
    import multiprocessing as mp
    import tempfile
    import urllib.parse
    from mmlspark_tpu.io import rowcodec
    from mmlspark_tpu.io.distributed_serving import ServingCoordinator
    from mmlspark_tpu.io.http import KeepAliveTransport
    from mmlspark_tpu.io.registry import ModelRegistry, golden_reply_digest
    from mmlspark_tpu.observability import MetricsRegistry, set_registry
    from mmlspark_tpu.resilience import FaultInjector
    from mmlspark_tpu.resilience.chaos import TrainingFaultInjector

    w1 = ref_weights()
    w2 = (w1 * 1.5).astype(np.float32)
    rdir = tempfile.mkdtemp(prefix="model_registry_")
    registry = ModelRegistry(rdir, keep_last=4)
    golden = rowcodec.encode("features",
                             np.ones((1, FEATURES), np.float32))
    v1 = registry.publish(
        {"weights.bin": w1.tobytes()}, golden_body=golden,
        golden_reply_sha256=golden_reply_digest(make_handler(w1), golden),
        set_current=True)
    v2 = registry.publish(
        {"weights.bin": w2.tobytes()}, golden_body=golden,
        golden_reply_sha256=golden_reply_digest(make_handler(w2), golden))
    target = v2
    if chaos:
        # the corrupt-artifact swap fault: the digest gate must fail the
        # canary's swap and the rollout must roll back automatically
        v3 = registry.publish({"weights.bin": w2.tobytes()},
                              golden_body=golden)
        TrainingFaultInjector.corrupt_version_payload(registry, v3)
        target = v3

    reg = MetricsRegistry()
    prev = set_registry(reg)
    injector = None
    transport = None
    if chaos:
        transport = KeepAliveTransport()
        injector = FaultInjector(seed=12, error_rate=0.3)
    coord = ServingCoordinator(
        heartbeat_timeout_s=2.0, registry=reg,
        forward_transport=(injector.wrap(transport) if chaos else None),
        coalesce_max=8, canary_beats=2,
        rollout_timeout_s=max(10.0, duration_s / 3.0)).start()
    ctx = mp.get_context("spawn")
    procs, worker_stops, _ = spawn_workers(ctx, coord.url, n_workers,
                                           registry_dir=rdir)
    collector = recorder = None
    if collect:
        collector, recorder = arm_observability(coord, reg, injector)

    bodies = make_bodies([w1, w2])

    stop_clients = threading.Event()
    parsed = urllib.parse.urlsplit(coord.url)
    clients = [LoadClient(parsed.hostname, parsed.port,
                          f"/gateway/{SERVICE}", bodies, None,
                          DEADLINE_MS / 1000.0, stop_clients)
               for _ in range(n_clients)]
    t0 = time.perf_counter()
    for c in clients:
        c.start()

    # phase 1: steady pre-swap traffic (beats deliver model_version
    # reports, baselines settle)
    time.sleep(max(duration_s / 3.0, 2.0))
    if recorder is not None:
        recorder.arm_baseline()  # p99 judged against pre-swap steady
    # under chaos the routing table can be transiently EMPTY (an injected
    # forward fault just evicted everyone; heartbeats re-register within
    # a beat) — retry like an operator would
    ro = None
    for _ in range(100):
        try:
            ro = coord.start_rollout(SERVICE, target, previous=v1)
            break
        except ValueError:
            time.sleep(0.1)
    if ro is None:
        raise RuntimeError("could not start rollout: no workers stayed "
                           "registered")
    rollout_started_at = time.perf_counter() - t0
    print(f"  rollout -> v{target} started at {rollout_started_at:.1f}s "
          f"(canary {ro['canary'][0]}:{ro['canary'][1]})", flush=True)
    killed_at = None
    if chaos:
        # worker kill mid-swap: terminate a NON-canary worker while the
        # rollout is in flight; it must be evicted with zero accepted loss
        time.sleep(0.5)
        procs[-1].terminate()
        killed_at = time.perf_counter() - t0
    # wait for the state machine to resolve, under full load throughout
    state = None
    t_resolve = None
    deadline = time.time() + max(duration_s, 30.0)
    while time.time() < deadline:
        state = (coord.rollout_status(SERVICE) or {}).get("state")
        if state in ("done", "rolled_back"):
            if t_resolve is None:
                t_resolve = time.perf_counter() - t0
            break
        time.sleep(0.1)
    # phase 3: steady post-swap traffic (post-flip payloads verified)
    time.sleep(max(duration_s / 3.0, 2.0))
    stop_clients.set()
    for c in clients:
        c.join(15.0)
    wall = time.perf_counter() - t0

    # per-worker swap telemetry before teardown
    worker_swaps = []
    for s in coord.routes(SERVICE):
        try:
            text = scrape(f"http://{s.host}:{s.port}/metrics")
            worker_swaps.append({
                "worker": f"{s.machine}:{s.partition}",
                "model_version": prom_value(text, "serving_model_version"),
                "swap_events": prom_by_label(
                    text, "serving_swap_events_total", "outcome"),
            })
        except Exception as e:
            worker_swaps.append({"worker": f"{s.machine}:{s.partition}",
                                 "scrape_error": str(e)[:100]})

    lbl = {"instance": coord.metrics_label}
    p50 = reg.quantile("gateway_request_latency_seconds", 0.5, lbl)
    p99 = reg.quantile("gateway_request_latency_seconds", 0.99, lbl)
    summary = {
        "variant": "swap_chaos" if chaos else "swap",
        "duration_s": round(wall, 1),
        "workers": n_workers,
        "clients": n_clients,
        "batch_mix_rows": list(BATCH_MIX),
        "versions": {"previous": v1, "target": target,
                     "target_corrupt": bool(chaos)},
        "rollout_started_at_s": round(rollout_started_at, 1),
        "rollout_resolved_at_s": (round(t_resolve, 1)
                                  if t_resolve else None),
        "rollout_final_state": state,
        "rollout": {k: v for k, v in
                    (coord.rollout_status(SERVICE) or {}).items()
                    if k != "baseline"},
        "worker_killed_at_s": (round(killed_at, 1)
                               if killed_at is not None else None),
        "gateway_p50_ms": round(p50 * 1e3, 3) if p50 else None,
        "gateway_p99_ms": round(p99 * 1e3, 3) if p99 else None,
        "evictions": reg.total("gateway_evictions_total"),
        "forward_failures": reg.total("gateway_forward_failures_total"),
        "worker_swaps": worker_swaps,
        **client_tallies(clients, wall),
    }
    if chaos:
        summary["injected"] = dict(injector.counts)
    summary["collect"] = bool(collect)
    harvest_observability(summary, coord, collector, recorder)

    stop_workers(procs, worker_stops)
    coord.stop()
    set_registry(prev)
    return summary


def run_autoscale_variant(duration_s: float, n_clients: int,
                          collect: bool = True) -> dict:
    """Ramped load against a 2-worker base fleet with the Autoscaler
    acting on heartbeat queue-depth signals: grow 2 -> 4 under the ramp,
    retire back to 2 after it (deregister -> drain -> stop), zero lost
    requests throughout."""
    import multiprocessing as mp
    import urllib.parse
    from mmlspark_tpu.io.autoscale import Autoscaler
    from mmlspark_tpu.io.distributed_serving import ServingCoordinator
    from mmlspark_tpu.observability import MetricsRegistry, set_registry

    reg = MetricsRegistry()
    prev = set_registry(reg)
    coord = ServingCoordinator(heartbeat_timeout_s=2.0, registry=reg,
                               coalesce_max=8).start()
    ctx = mp.get_context("spawn")
    # deliberately heavier per-batch cost + smaller batches so the ramp
    # creates a genuine 2-worker capacity DEFICIT (queues grow until the
    # fleet scales) that 4 workers clear — the autoscaler's signal
    worker_kw = dict(slow_ms=float(os.environ.get("MEASURE_AS_SLOW_MS",
                                                  "7")),
                     max_batch_size=64)
    base_procs, base_stops, _ = spawn_workers(ctx, coord.url, 2,
                                              **worker_kw)
    collector = recorder = None
    if collect:
        collector, recorder = arm_observability(coord, reg)
    next_partition = [2]
    spawned = []   # (proc, stop, retire) the autoscaler manages

    def spawn():
        procs, stops, retires = spawn_workers(
            ctx, coord.url, 1, first_partition=next_partition[0],
            **worker_kw)
        next_partition[0] += 1
        handle = (procs[0], stops[0], retires[0])
        spawned.append(handle)
        return handle

    def retire(handle):
        proc, stop, retire_ev = handle
        retire_ev.set()       # worker: deregister -> drain -> stop -> exit
        proc.join(30.0)
        if proc.is_alive():
            proc.terminate()

    scaler = Autoscaler.for_service(
        coord, SERVICE, spawn, retire,
        min_workers=2, max_workers=4,
        high_queue_depth=float(os.environ.get("MEASURE_AS_HIGH", "6")),
        low_queue_depth=float(os.environ.get("MEASURE_AS_LOW", "1")),
        up_after=2, down_after=8,
        cooldown_s=max(3.0, duration_s / 15.0), interval_s=0.25,
        registry=reg).start()

    w = ref_weights()
    bodies = make_bodies([w])
    parsed = urllib.parse.urlsplit(coord.url)

    def mk_clients(n, stop_ev):
        cs = [LoadClient(parsed.hostname, parsed.port,
                         f"/gateway/{SERVICE}", bodies, None,
                         DEADLINE_MS / 1000.0, stop_ev)
              for _ in range(n)]
        for c in cs:
            c.start()
        return cs

    # load trace: light -> ramp (all clients) -> light again
    t0 = time.perf_counter()
    m0 = time.monotonic()   # the Autoscaler's action clock origin
    stop_all = threading.Event()
    stop_ramp = threading.Event()
    light = mk_clients(max(2, n_clients // 8), stop_all)
    fleet_series = []

    def sample_fleet():
        fleet_series.append(
            {"t": round(time.perf_counter() - t0, 1),
             "workers": len(coord.routes(SERVICE)),
             "mean_queue_depth": round(float(np.mean(
                 [v["queue_depth"] for v in
                  coord.worker_loads(SERVICE).values()] or [0.0])), 2)})

    phase = max(duration_s / 3.0, 4.0)
    end1 = time.perf_counter() + phase
    while time.perf_counter() < end1:
        sample_fleet()
        time.sleep(0.5)
    ramp = mk_clients(n_clients, stop_ramp)
    peak_workers = 0
    end2 = time.perf_counter() + phase
    while time.perf_counter() < end2:
        sample_fleet()
        peak_workers = max(peak_workers, len(coord.routes(SERVICE)))
        time.sleep(0.5)
    stop_ramp.set()
    for c in ramp:
        c.join(15.0)
    end3 = time.perf_counter() + phase
    while time.perf_counter() < end3:
        sample_fleet()
        time.sleep(0.5)
    stop_all.set()
    for c in light:
        c.join(15.0)
    wall = time.perf_counter() - t0
    final_workers = len(coord.routes(SERVICE))

    clients = light + ramp
    lbl = {"instance": coord.metrics_label}
    p50 = reg.quantile("gateway_request_latency_seconds", 0.5, lbl)
    p99 = reg.quantile("gateway_request_latency_seconds", 0.99, lbl)
    summary = {
        "variant": "autoscale",
        "duration_s": round(wall, 1),
        "base_workers": 2,
        "clients_light": len(light), "clients_ramp": len(ramp),
        "batch_mix_rows": list(BATCH_MIX),
        "peak_workers": peak_workers,
        "final_workers": final_workers,
        "actions": [{**a, "t": round(a["t"] - m0, 1)}
                    for a in scaler.actions],
        "scale_ups": sum(1 for a in scaler.actions
                         if a["action"] == "scale_up"),
        "scale_downs": sum(1 for a in scaler.actions
                           if a["action"] == "scale_down"),
        "fleet_series": fleet_series,
        "gateway_p50_ms": round(p50 * 1e3, 3) if p50 else None,
        "gateway_p99_ms": round(p99 * 1e3, 3) if p99 else None,
        "evictions": reg.total("gateway_evictions_total"),
        **client_tallies(clients, wall),
    }
    summary["collect"] = bool(collect)
    harvest_observability(summary, coord, collector, recorder)

    scaler.stop(retire_spawned=True)
    stop_workers(base_procs, base_stops)
    coord.stop()
    set_registry(prev)
    return summary
