"""Autoscaler actuation: spawn/retire serving workers from heartbeat load.

ROADMAP item 2's last open piece: the PR 12 least-loaded router already
consumes per-worker queue-depth reports piggybacked on heartbeats; this
module closes the loop by ACTING on the very same signals — the fleet
grows when the observed mean queue depth says the workers are saturating
and shrinks when it says capacity is idle, with nothing new measured
(`ServingCoordinator.worker_loads` is the one signal source).

Control discipline (the part that keeps chaos from flapping the fleet):

- **smoothing** — the per-beat queue-depth snapshot is spiky (a queue
  drains in milliseconds between beats); decisions compare an EWMA of
  the observed mean (`ewma_alpha`) against the watermarks, so only a
  SUSTAINED deficit or surplus registers;
- **hysteresis** — a scale decision needs `up_after`/`down_after`
  CONSECUTIVE breaching observations; a single chaos-induced blip (one
  slow batch, one killed worker's redistributed queue) resets the streak;
- **cooldown** — after any action, no further action for `cooldown_s`:
  a freshly spawned worker needs time to register and absorb load before
  the controller may judge the new steady state;
- **bounds** — the observed fleet never leaves [min_workers, max_workers],
  and scale-down only retires workers THIS autoscaler spawned (the base
  fleet an operator started is never touched).

Retire = the PR 10 drain discipline applied to serving: the `retire`
callable must deregister (stop routing) -> drain (every admitted request
answered) -> stop — `DistributedServingServer.retire()` is exactly that,
so scale-down loses zero requests (proved by the autoscale scenario of
scripts/measure_serving_load.py and tests/test_model_lifecycle.py).

Everything is injectable (signals, spawn, retire, clock) so the
hysteresis/cooldown logic is tested deterministically without sockets.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ..observability import EventLog, get_registry, mint_trace_id

__all__ = ["Autoscaler"]


class Autoscaler:
    """Grow/shrink a serving fleet from worker queue-depth signals.

    `signals()` returns the current per-worker queue depths (one float
    per ROUTED worker — `ServingCoordinator.worker_loads(service)` values;
    `for_service` builds it). `spawn()` starts one worker and returns an
    opaque handle; `retire(handle)` must deregister -> drain -> stop it.
    `tick()` makes one observation and at most one action; `start()` runs
    ticks on a daemon thread every `interval_s`.
    """

    def __init__(self, signals: Callable[[], List[float]],
                 spawn: Callable[[], Any],
                 retire: Callable[[Any], None], *,
                 min_workers: int = 1, max_workers: int = 8,
                 high_queue_depth: float = 32.0,
                 low_queue_depth: float = 2.0,
                 up_after: int = 2, down_after: int = 5,
                 cooldown_s: float = 10.0, interval_s: float = 0.5,
                 ewma_alpha: float = 0.5,
                 clock: Callable[[], float] = time.monotonic,
                 registry=None, metrics_label: Optional[str] = None,
                 event_log: Optional[EventLog] = None):
        if min_workers < 1 or max_workers < min_workers:
            raise ValueError(f"need 1 <= min_workers <= max_workers, got "
                             f"[{min_workers}, {max_workers}]")
        if low_queue_depth >= high_queue_depth:
            raise ValueError("low_queue_depth must be < high_queue_depth "
                             "(the hysteresis band)")
        self.signals = signals
        self.spawn = spawn
        self.retire = retire
        self.min_workers = int(min_workers)
        self.max_workers = int(max_workers)
        self.high_queue_depth = float(high_queue_depth)
        self.low_queue_depth = float(low_queue_depth)
        self.up_after = int(up_after)
        self.down_after = int(down_after)
        self.cooldown_s = float(cooldown_s)
        self.interval_s = float(interval_s)
        if not (0.0 < ewma_alpha <= 1.0):
            raise ValueError("ewma_alpha must be in (0, 1]")
        self.ewma_alpha = float(ewma_alpha)
        self.smoothed_depth: Optional[float] = None
        self.clock = clock
        #: handles of workers THIS autoscaler spawned (LIFO retire order —
        #: the newest worker has the least affinity to shed)
        self.handles: List[Any] = []
        self.actions: List[Dict[str, Any]] = []   # decision audit trail
        self._hot = 0
        self._cold = 0
        self._last_action_at: Optional[float] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        reg = registry if registry is not None else get_registry()
        lbl = {"instance": metrics_label or "autoscaler"}
        self._m_actions = {
            a: reg.counter("autoscaler_actions_total",
                           "scale actions taken", {**lbl, "action": a})
            for a in ("scale_up", "scale_down")}
        self._g_workers = reg.gauge(
            "autoscaler_workers", "workers observed at the last tick", lbl)
        self._g_depth = reg.gauge(
            "autoscaler_mean_queue_depth",
            "mean per-worker queue depth at the last tick", lbl)
        # system-event bridge (ISSUE 14): every scale action lands in an
        # EventLog the trace collector drains, so autoscale actions show
        # up in incident bundles beside the swaps/evictions they interact
        # with. Pass the coordinator's log (Autoscaler.for_service does)
        # to put them on the ring the fleet collector already polls.
        self.events = event_log if event_log is not None else EventLog(256)

    # ------------------------------------------------------------- decisions
    def tick(self) -> Optional[str]:
        """One observation, at most one action. Returns "scale_up",
        "scale_down", or None."""
        depths = list(self.signals())
        n = len(depths)
        raw = (sum(depths) / n) if n else 0.0
        if self.smoothed_depth is None:
            self.smoothed_depth = raw
        else:
            self.smoothed_depth += self.ewma_alpha * (raw
                                                      - self.smoothed_depth)
        mean = self.smoothed_depth
        self._g_workers.set(float(n))
        self._g_depth.set(mean)
        # hysteresis streaks: any observation inside the band resets both
        if mean > self.high_queue_depth and n < self.max_workers:
            self._hot += 1
            self._cold = 0
        elif mean < self.low_queue_depth and n > self.min_workers \
                and self.handles:
            self._cold += 1
            self._hot = 0
        else:
            self._hot = 0
            self._cold = 0
        now = self.clock()
        if self._last_action_at is not None \
                and now - self._last_action_at < self.cooldown_s:
            return None
        if self._hot >= self.up_after:
            self.handles.append(self.spawn())
            self._after_action("scale_up", now, n, mean)
            return "scale_up"
        if self._cold >= self.down_after:
            # pop only AFTER retire() returns: a retire that raises (HTTP
            # deregister down, process join failed) must leave the worker
            # tracked so stop(retire_spawned=True) / the next cold streak
            # can still reach it
            handle = self.handles[-1]
            self.retire(handle)
            self.handles.pop()
            self._after_action("scale_down", now, n, mean)
            return "scale_down"
        return None

    def _after_action(self, action: str, now: float, n: int,
                      mean: float) -> None:
        self._hot = 0
        self._cold = 0
        self._last_action_at = now
        self._m_actions[action].inc()
        self.actions.append({"t": now, "action": action,
                             "workers_before": n,
                             "mean_queue_depth": round(mean, 2)})
        self.events.append("autoscale", mint_trace_id(), action=action,
                           workers_before=n,
                           mean_queue_depth=round(mean, 2))

    # -------------------------------------------------------------- lifecycle
    def start(self) -> "Autoscaler":
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="autoscaler")
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 - one bad scrape must not
                pass           # kill the control loop

    def stop(self, retire_spawned: bool = False) -> None:
        """Stop ticking; optionally retire every worker this autoscaler
        spawned (clean shutdown of the dynamic pool)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(self.interval_s * 4 + 1.0)
        if retire_spawned:
            while self.handles:
                self.retire(self.handles.pop())

    # ------------------------------------------------------------ conveniences
    @classmethod
    def for_service(cls, coordinator, service: str,
                    spawn: Callable[[], Any],
                    retire: Callable[[Any], None], **kw) -> "Autoscaler":
        """Signals wired to `coordinator.worker_loads(service)` — the same
        heartbeat-piggybacked queue depths the least-loaded router scores
        on; nothing new is measured. Scale actions land in the
        COORDINATOR's event log (the ring the fleet collector polls)."""
        def signals() -> List[float]:
            return [v["queue_depth"]
                    for v in coordinator.worker_loads(service).values()]
        kw.setdefault("event_log", coordinator.events)
        return cls(signals, spawn, retire, **kw)
