"""Device-mesh topology discovery and construction.

Replaces the reference's driver-coordinated cluster topology machinery wholesale:
- ClusterUtil executor/task-count discovery (core/utils/ClusterUtil.scala:13-177)
- LightGBM socket rendezvous + NetworkInit ring (lightgbm/LightGBMUtils.scala:108-185,
  TrainUtils.scala:410-512)
- VW spanning-tree allreduce bootstrap (vw/VowpalWabbitBase.scala:401-429)

In the TPU-native design there are no sockets and no rendezvous protocol: multi-host SPMD
launch is inherently gang-scheduled (the analogue of Spark barrier mode,
lightgbm/LightGBMBase.scala:224-231), `jax.distributed.initialize` + the JAX coordination
service replace the driver ServerSocket, and collectives ride ICI intra-slice / DCN across
slices via named mesh axes.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Patient bounded device bring-up (probe subprocesses + jittered RetryPolicy
# backoff + Deadline wall budget, structured probe records): the resilient
# path to a healthy mesh on a flaky shared pool. Convenience re-export for
# code already working at the mesh layer; launchers that must control the
# backend BEFORE jax is imported (env-var CPU forcing) import it from
# mmlspark_tpu.resilience.bringup instead — this module imports jax at top.
from ..resilience.bringup import backend_bringup  # noqa: F401 (re-export)

DATA_AXIS = "data"    # row/batch sharding (the universal strategy — SURVEY.md §2.2)
MODEL_AXIS = "model"  # tensor/feature sharding for deep models


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
    """Version-portable `jax.shard_map`: newer jax exposes it top-level
    with `check_vma`; older releases (<= 0.4.x) ship
    `jax.experimental.shard_map.shard_map` with the same knob named
    `check_rep`. Every shard_map in this codebase routes through here so
    a jax upgrade/downgrade is a one-line concern. check_vma defaults
    True to match jax's own default — callers that don't opt out keep
    the replication check."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_vma)


#: default bound on jax.distributed.initialize (seconds). The runtime's
#: own default is 300 s of silent blocking; the fabric wants a missing
#: host to become a NAMED error well before a pool's kill grace.
DEFAULT_INIT_TIMEOUT_S = 120.0


def distributed_init(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     initialization_timeout: Optional[float] = None) -> None:
    """Multi-host bootstrap. Replaces driver rendezvous (LightGBMUtils.scala:116-185):
    the JAX coordination service plays the driver's ServerSocket role.

    ``initialization_timeout`` bounds the gather: if the coordinator never
    comes up or a host never arrives, this raises a RuntimeError naming
    the coordinator address and the expected process count (and counts a
    ``multihost_rendezvous_events_total{event=initialize,outcome=timeout}``)
    instead of hanging forever — the ISSUE-15 fix for the unbounded
    8-line wrapper. Prefer the full rendezvous contract in
    parallel/multihost.connect, which also gates THIS call behind the
    coordinator roster barrier."""
    if not (num_processes is not None and num_processes > 1):
        return
    try:
        # the CPU backend refuses cross-process programs ("Multiprocess
        # computations aren't implemented on the CPU backend") unless a
        # collectives implementation is selected BEFORE the backend
        # initializes; gloo ships in jaxlib and makes the virtual
        # multi-host CPU mesh (tests, measure_podslice) real. Best-effort:
        # older/newer jax may not expose the option, TPU pods never
        # consult it, and an operator's explicit choice (e.g.
        # 'mpitrampoline' under mpirun) is NEVER overwritten.
        try:
            current = jax.config.read("jax_cpu_collectives_implementation")
        except Exception:  # noqa: BLE001 - no reader: treat as unset
            current = None
        if current in (None, "", "none"):
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # noqa: BLE001 - option absent: accelerator path
        pass
    timeout_s = (DEFAULT_INIT_TIMEOUT_S if initialization_timeout is None
                 else float(initialization_timeout))
    kw = {"initialization_timeout": max(1, int(round(timeout_s)))}
    bounded = True
    try:
        try:
            jax.distributed.initialize(coordinator_address, num_processes,
                                       process_id, **kw)
        except TypeError:
            # pre-initialization_timeout jax: the knob does not exist —
            # fall back to the runtime's own (300 s) bound rather than
            # refusing to initialize at all
            bounded = False
            jax.distributed.initialize(coordinator_address, num_processes,
                                       process_id)
    except Exception as e:
        # classify for the counted-timeout contract: a gather that ran
        # out of time vs any other failure (port in use, re-init, ...)
        msg = str(e).lower()
        outcome = ("timeout" if ("deadline" in msg or "timeout" in msg
                                 or "timed out" in msg) else "error")
        try:
            from ..observability import publish_rendezvous_event
            publish_rendezvous_event("initialize", outcome)
        except Exception:  # noqa: BLE001 - telemetry never hides the error
            pass
        bound = (f"within {timeout_s:.0f}s" if bounded else
                 "within the runtime's default bound (this jax predates "
                 "initialization_timeout)")
        raise RuntimeError(
            f"jax.distributed.initialize failed for process {process_id}: "
            f"could not gather {num_processes} processes at coordinator "
            f"{coordinator_address} {bound} — check that "
            f"every host launched, can reach the coordinator, and agrees "
            f"on num_processes ({e})") from e


def device_count() -> int:
    return jax.device_count()


def local_device_count() -> int:
    return jax.local_device_count()


def process_count() -> int:
    """Hosts (jax processes) in the mesh — 1 for every single-controller
    run; >1 only after distributed_init/multihost.connect."""
    return jax.process_count()


def get_mesh(n_devices: Optional[int] = None,
             axis_names: Sequence[str] = (DATA_AXIS,),
             shape: Optional[Sequence[int]] = None) -> Mesh:
    """Construct a mesh over available devices.

    Default is a 1-D data mesh (the reference's only strategy is data parallelism over
    partitions — SURVEY.md §2.2). Pass a 2-D ``shape`` + two axis names for data x model
    sharding of deep models.
    """
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    n = len(devs)
    if shape is None:
        shape = (n,) if len(axis_names) == 1 else _factor(n, len(axis_names))
    arr = np.array(devs).reshape(tuple(shape))
    return Mesh(arr, tuple(axis_names))


def _factor(n: int, ndims: int) -> Tuple[int, ...]:
    """Split n devices into ndims mesh dims, biggest dim first."""
    dims = [n] + [1] * (ndims - 1)
    for i in range(1, ndims):
        for f in (2, 3, 5, 7):
            while dims[0] % f == 0 and dims[i] * f <= dims[0] // f:
                dims[0] //= f
                dims[i] *= f
    return tuple(dims)


def place_global(mesh: Mesh, arr, spec) -> jax.Array:
    """Multi-controller-safe device placement of a host array that EVERY
    process holds in full (the test/bootstrap topology: each host computes
    the same host-side prep, then contributes only its addressable shards).

    Single-process: plain ``jnp.asarray`` — jit handles placement. Multi-
    process: ``jax.make_array_from_callback`` builds one GLOBAL jax.Array
    whose shards live on each process's local devices; collectives inside
    shard_map then ride the cross-process (DCN-analogue) channel. A
    committed single-device array (what ``jnp.asarray`` produces) is NOT
    valid input to a global-mesh program, which is why the sharded fit
    paths route through here.
    """
    import jax.numpy as jnp
    if jax.process_count() == 1:
        return jnp.asarray(arr)
    arr = np.asarray(arr)
    sharding = NamedSharding(mesh, spec)
    return jax.make_array_from_callback(arr.shape, sharding,
                                        lambda idx: arr[idx])


def data_sharding(mesh: Mesh, ndim: int = 1) -> NamedSharding:
    """Rows sharded over the data axis, everything else replicated."""
    spec = [None] * ndim
    spec[0] = DATA_AXIS
    return NamedSharding(mesh, P(*spec))


def place_rows(mesh: Mesh, arr) -> jax.Array:
    """Row-shard a host array over the mesh data axis with an explicit
    NamedSharding (row count must already be a multiple of the axis
    size — shard_rows pads). Single-process: one async device_put whose
    per-device pieces ride the host links in parallel (each device
    receives only its shard — the sharded fit paths' transfer plane).
    Multi-process: each process slices out and device_puts ONLY its own
    shards, assembled into one global array via
    jax.make_array_from_single_device_arrays (multihost.assemble_row_sharded
    — the ISSUE-15 process-local data plane)."""
    arr = np.asarray(arr)
    sharding = data_sharding(mesh, arr.ndim)
    if jax.process_count() == 1:
        return jax.device_put(arr, sharding)
    from . import multihost
    return multihost.assemble_row_sharded(mesh, arr, sharding)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def describe_mesh(mesh: Mesh) -> dict:
    """JSON-able mesh identity (ordered axis names + extents) — what the
    checkpoint manifests record so a restore can tell same-mesh from
    needs-reshard without touching orbax internals
    (models/deep/checkpoint.py mesh manifest; resilience/elastic.py
    snapshot `ndev`)."""
    return {"axis_names": [str(a) for a in mesh.axis_names],
            "shape": [int(mesh.shape[a]) for a in mesh.axis_names]}


def pad_to_multiple(arr: np.ndarray, multiple: int, axis: int = 0,
                    fill=0) -> Tuple[np.ndarray, int]:
    """Pad along axis to a multiple; returns (padded, original_length).

    Padding/masking is the TPU-native answer to the reference's empty/skewed-partition
    defenses (empty-partition "ignore" protocol, TrainUtils.scala:463-471): shards are
    always equal-sized, padded rows carry zero weight.
    """
    n = arr.shape[axis]
    rem = (-n) % multiple
    if rem == 0:
        return arr, n
    pad_widths = [(0, 0)] * arr.ndim
    pad_widths[axis] = (0, rem)
    return np.pad(arr, pad_widths, constant_values=fill), n


def shard_rows(mesh: Mesh, *arrays: np.ndarray, weights=None):
    """Pad row dimension to the mesh data-axis size and place with row
    sharding (NamedSharding via place_rows — multi-process safe). The
    DEFAULT data layout of every sharded fit entry point (GBDT/VW).

    Returns ``(*sharded_arrays, valid_mask)`` where valid_mask is 1.0
    for real rows and 0.0 for padding — the masking discipline replacing
    StratifiedRepartition-style partition invariants (SURVEY.md §7 hard
    parts).

    ``weights``: caller-supplied per-row sample weights. The zero-weight
    contract for padded rows is enforced HERE — the returned weights are
    ``weights * mask`` (padding slots zeroed) so no fit site can forget
    the product and let a padded row carry the caller's weight into a
    histogram. With weights the return is
    ``(*sharded_arrays, sharded_weights, valid_mask)``.
    """
    ndev = mesh.shape[DATA_AXIS]
    n = arrays[0].shape[0]
    out = [place_rows(mesh, pad_to_multiple(np.asarray(a), ndev, axis=0)[0])
           for a in arrays]
    mask_host, _ = pad_to_multiple(np.ones(n, np.float32), ndev, axis=0)
    if weights is not None:
        w = np.asarray(weights, np.float32)
        if w.shape[0] != n:
            raise ValueError(
                f"weights rows {w.shape[0]} != data rows {n}")
        w_pad, _ = pad_to_multiple(w, ndev, axis=0)
        # padding slots are zero-filled by the pad AND re-masked: the
        # product is the contract, not an artifact of the fill value
        out.append(place_rows(mesh, w_pad * mask_host))
    mask = place_rows(mesh, mask_host)
    return (*out, mask)
