"""Coordinator-based multi-host rendezvous for the training fabric.

The reference's distribution story is the Spark DRIVER acting as rendezvous
server: LightGBMUtils `NetworkInit` opens a driver ServerSocket, every
executor connects, the driver assigns ring positions and broadcasts the
topology before a single byte of training traffic flows
(LightGBMUtils.scala:108-185, TrainUtils.scala:410-512). This module plays
that role for a multi-process `jax.distributed` mesh:

- ``RendezvousCoordinator`` — a small threaded TCP registration service
  (one JSON line per request/response). It assigns process ids, records
  each host's address, distributes the jax coordination-service address
  (process 0's ``host:jax_port`` unless pinned at construction), and gates
  the barrier: ``wait`` releases only when every expected host has joined,
  and a missing/late host is a COUNTED timeout naming the coordinator
  address and the missing count — never a silent hang.
- ``RendezvousClient`` — join with bounded retries (the ONE
  `resilience.policy.RetryPolicy` implementation; a not-yet-listening
  coordinator is a retryable condition, a duplicate process id is not),
  server-side ``wait`` barrier, heartbeats.
- ``Heartbeater`` — a daemon thread beating every ``interval_s``; the
  coordinator piggybacks the currently-lost process ids on every beat
  reply (the `distributed_serving` heartbeat-piggyback pattern), and the
  first non-empty set fires ``on_host_lost`` exactly once. A lost host
  wedges in-flight collectives, so the fabric's default reaction
  (parallel/multihost.py) is SIGTERM + a hard-exit watchdog, not a drain
  that would itself hang.

Telemetry (PR 8 registry, guarded — a broken observability import must
never fail a rendezvous): ``multihost_rendezvous_events_total{event,
outcome}`` and the ``multihost_hosts_alive`` gauge.
"""

from __future__ import annotations

import json
import socket
import socketserver
import threading
import time
from typing import Callable, Dict, List, Optional

from ..resilience.policy import Deadline, RetryPolicy

__all__ = ["RendezvousError", "RendezvousTimeout", "RendezvousCoordinator",
           "RendezvousClient", "Heartbeater"]


class RendezvousError(RuntimeError):
    """The coordinator rejected a request (duplicate process id, roster
    full, unknown process) or could not start (port in use)."""


class RendezvousTimeout(RendezvousError):
    """A rendezvous deadline expired: the coordinator never came up, or
    the roster never filled (a late/missing host)."""


def _publish(event: str, outcome: str = "ok") -> None:
    try:
        from ..observability import publish_rendezvous_event
        publish_rendezvous_event(event, outcome)
    except Exception:  # noqa: BLE001 - telemetry never fails a rendezvous
        pass


def _set_alive(n: int) -> None:
    try:
        from ..observability import set_hosts_alive
        set_hosts_alive(n)
    except Exception:  # noqa: BLE001 - telemetry never fails a rendezvous
        pass


class _Host:
    __slots__ = ("name", "process_id", "addr", "jax_port", "joined_at",
                 "last_beat", "lost", "left")

    def __init__(self, name: str, process_id: int, addr: str,
                 jax_port: Optional[int]):
        self.name = name
        self.process_id = process_id
        self.addr = addr
        self.jax_port = jax_port
        self.joined_at = time.monotonic()
        self.last_beat: Optional[float] = None
        self.lost = False
        #: a clean departure (``leave``): exempt from silence eviction
        #: and NEVER reported in the lost lists — a host that finished
        #: its work must not trigger its peers' reapers
        self.left = False


class _Handler(socketserver.StreamRequestHandler):
    def handle(self):  # one JSON line in, one JSON line out
        try:
            line = self.rfile.readline(1 << 16)
            req = json.loads(line.decode("utf-8"))
            resp = self.server.coordinator._dispatch(req)
        except Exception as e:  # noqa: BLE001 - a bad request must not kill the server
            resp = {"ok": False, "error": f"bad request: {e}"}
        try:
            self.wfile.write((json.dumps(resp) + "\n").encode("utf-8"))
        except OSError:
            pass  # client gone; its retry policy owns the recovery


class _Server(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = False  # a bound port must FAIL loudly, not share


class RendezvousCoordinator:
    """The driver-rendezvous replacement: assign ids, gate the barrier,
    watch liveness. Run it on the launcher (or host 0) before starting
    the per-host workers."""

    def __init__(self, num_hosts: int, port: int = 0,
                 bind_host: str = "127.0.0.1",
                 jax_coordinator: Optional[str] = None,
                 heartbeat_timeout_s: float = 10.0):
        if num_hosts < 1:
            raise ValueError(f"num_hosts must be >= 1, got {num_hosts}")
        self.num_hosts = int(num_hosts)
        self._port = int(port)
        self._bind_host = bind_host
        #: explicit jax coordination-service address; None = derived from
        #: process 0's (addr, jax_port) join payload at wait time
        self._jax_coordinator = jax_coordinator
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self._cond = threading.Condition()
        self._hosts: Dict[str, _Host] = {}
        self._by_pid: Dict[int, _Host] = {}
        self._server: Optional[_Server] = None
        self._threads: List[threading.Thread] = []
        self._stopping = threading.Event()

    # ---------------------------------------------------------------- server
    def start(self) -> "RendezvousCoordinator":
        try:
            self._server = _Server((self._bind_host, self._port), _Handler)
        except OSError as e:
            _publish("bind", "port_in_use")
            raise RendezvousError(
                f"rendezvous coordinator could not bind "
                f"{self._bind_host}:{self._port}: {e} — the port is in use "
                f"(inject a free port, or let port=0 pick one)") from e
        self._server.coordinator = self
        t = threading.Thread(target=self._server.serve_forever,
                             name="rendezvous-server", daemon=True)
        t.start()
        m = threading.Thread(target=self._monitor,
                             name="rendezvous-monitor", daemon=True)
        m.start()
        self._threads = [t, m]
        _publish("bind")
        _set_alive(0)
        return self

    @property
    def address(self) -> str:
        if self._server is None:
            return f"{self._bind_host}:{self._port}"
        host, port = self._server.server_address[:2]
        return f"{host}:{port}"

    def stop(self) -> None:
        self._stopping.set()
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None

    # ------------------------------------------------------------- liveness
    def _alive_count(self) -> int:
        return sum(1 for h in self._hosts.values()
                   if not h.lost and not h.left)

    def _lost_pids(self) -> List[int]:
        return sorted(h.process_id for h in self._hosts.values()
                      if h.lost and not h.left)

    def _monitor(self) -> None:
        poll = max(0.05, min(1.0, self.heartbeat_timeout_s / 4.0))
        while not self._stopping.wait(poll):
            now = time.monotonic()
            with self._cond:
                for h in self._hosts.values():
                    # only hosts that have ever beaten are subject to
                    # silence-based eviction (the distributed_serving
                    # _hb_seen discipline: a join without a heartbeat
                    # loop must not be reaped for not having one); a
                    # cleanly-departed host is exempt
                    if (not h.lost and not h.left
                            and h.last_beat is not None
                            and now - h.last_beat > self.heartbeat_timeout_s):
                        h.lost = True
                        _publish("heartbeat", "lost")
                _set_alive(self._alive_count())

    # ------------------------------------------------------------- commands
    def _dispatch(self, req: dict) -> dict:
        cmd = req.get("cmd")
        if cmd == "join":
            return self.join(str(req.get("host", "")),
                             addr=str(req.get("addr", "127.0.0.1")),
                             jax_port=req.get("jax_port"),
                             process_id=req.get("process_id"))
        if cmd == "wait":
            return self.wait(float(req.get("timeout_s", 60.0)))
        if cmd == "heartbeat":
            return self.heartbeat(int(req.get("process_id", -1)))
        if cmd == "leave":
            return self.leave(int(req.get("process_id", -1)))
        if cmd == "status":
            return self.status()
        return {"ok": False, "error": f"unknown command {cmd!r}"}

    def join(self, name: str, addr: str = "127.0.0.1",
             jax_port: Optional[int] = None,
             process_id: Optional[int] = None) -> dict:
        """Register one host; assigns the smallest free process id unless
        an explicit one is requested. Re-joining under the same name is
        idempotent (a restarted join retry must not burn a second id)."""
        if not name:
            return {"ok": False, "error": "join requires a host name"}
        with self._cond:
            if name in self._hosts:
                h = self._hosts[name]
                _publish("join", "rejoin")
                return {"ok": True, "process_id": h.process_id,
                        "num_hosts": self.num_hosts, "rejoined": True}
            if process_id is not None and int(process_id) in self._by_pid:
                other = self._by_pid[int(process_id)]
                _publish("join", "duplicate")
                return {"ok": False,
                        "error": f"duplicate process id {process_id}: "
                                 f"already held by host {other.name!r}"}
            if len(self._hosts) >= self.num_hosts:
                _publish("join", "roster_full")
                return {"ok": False,
                        "error": f"rendezvous roster full "
                                 f"({self.num_hosts}/{self.num_hosts} joined)"}
            if process_id is None:
                pid = next(i for i in range(self.num_hosts)
                           if i not in self._by_pid)
            else:
                pid = int(process_id)
                if not 0 <= pid < self.num_hosts:
                    _publish("join", "bad_process_id")
                    return {"ok": False,
                            "error": f"process_id {pid} outside "
                                     f"[0, {self.num_hosts})"}
            h = _Host(name, pid, addr,
                      int(jax_port) if jax_port is not None else None)
            self._hosts[name] = h
            self._by_pid[pid] = h
            _publish("join")
            _set_alive(self._alive_count())
            if len(self._hosts) == self.num_hosts:
                self._cond.notify_all()
            return {"ok": True, "process_id": pid,
                    "num_hosts": self.num_hosts}

    def _resolve_jax_coordinator(self) -> Optional[str]:
        if self._jax_coordinator:
            return self._jax_coordinator
        p0 = self._by_pid.get(0)
        if p0 is not None and p0.jax_port is not None:
            return f"{p0.addr}:{p0.jax_port}"
        return None

    def wait(self, timeout_s: float = 60.0) -> dict:
        """The barrier: block until every expected host joined. A miss is
        a counted timeout naming this coordinator and the missing count —
        the failure the 8-line `distributed_init` could only express as a
        hang."""
        with self._cond:
            full = self._cond.wait_for(
                lambda: len(self._hosts) == self.num_hosts,
                timeout=max(0.0, timeout_s))
            joined = len(self._hosts)
            if not full:
                _publish("wait", "timeout")
                missing = self.num_hosts - joined
                return {"ok": False, "timeout": True, "joined": joined,
                        "expected": self.num_hosts,
                        "error": f"rendezvous timeout at {self.address}: "
                                 f"{joined}/{self.num_hosts} hosts joined "
                                 f"({missing} missing) after {timeout_s:.1f}s"}
            _publish("wait")
            return {"ok": True, "num_hosts": self.num_hosts,
                    "jax_coordinator": self._resolve_jax_coordinator(),
                    "roster": [{"host": h.name, "process_id": h.process_id,
                                "addr": h.addr}
                               for h in sorted(self._hosts.values(),
                                               key=lambda h: h.process_id)]}

    def heartbeat(self, process_id: int) -> dict:
        """Record one beat; the reply piggybacks the currently-lost pids
        so every host learns about a dead peer without a separate poll."""
        with self._cond:
            h = self._by_pid.get(int(process_id))
            if h is None:
                _publish("heartbeat", "unknown")
                return {"ok": False,
                        "error": f"unknown process id {process_id}"}
            healed = h.lost
            h.last_beat = time.monotonic()
            h.lost = False
            _publish("heartbeat", "heal" if healed else "ok")
            _set_alive(self._alive_count())
            return {"ok": True, "lost": self._lost_pids()}

    def leave(self, process_id: int) -> dict:
        """A CLEAN departure (MultihostSession.close): the host stops
        beating but must never appear in the lost lists — finishing
        first is not dying, and peers still measuring/draining must not
        be reaped over it."""
        with self._cond:
            h = self._by_pid.get(int(process_id))
            if h is None:
                _publish("leave", "unknown")
                return {"ok": False,
                        "error": f"unknown process id {process_id}"}
            h.left = True
            h.lost = False
            _publish("leave")
            _set_alive(self._alive_count())
            return {"ok": True}

    def status(self) -> dict:
        with self._cond:
            return {"ok": True, "joined": len(self._hosts),
                    "expected": self.num_hosts,
                    "hosts_alive": self._alive_count(),
                    "lost": self._lost_pids(),
                    "left": sorted(h.process_id
                                   for h in self._hosts.values() if h.left),
                    "jax_coordinator": self._resolve_jax_coordinator()}


# -------------------------------------------------------------------- client

#: join retry shape: a coordinator that is still starting refuses
#: connections — retry with short jittered backoff until the caller's
#: deadline (unbounded attempts REQUIRE a deadline, policy.py contract)
_JOIN_POLICY = RetryPolicy(attempts=None, backoff_s=0.2, multiplier=1.6,
                           max_backoff_s=2.0, jitter=0.1)


class RendezvousClient:
    """One host's view of the coordinator. Every RPC is one short-lived
    connection (no pooled socket to go stale across a host's lifetime)."""

    def __init__(self, address: str, rpc_timeout_s: float = 10.0):
        host, _, port = address.rpartition(":")
        self.host, self.port = host or "127.0.0.1", int(port)
        self.address = f"{self.host}:{self.port}"
        self.rpc_timeout_s = float(rpc_timeout_s)

    def _rpc(self, payload: dict,
             timeout_s: Optional[float] = None) -> dict:
        t = self.rpc_timeout_s if timeout_s is None else timeout_s
        with socket.create_connection((self.host, self.port),
                                      timeout=t) as s:
            s.sendall((json.dumps(payload) + "\n").encode("utf-8"))
            with s.makefile("rb") as fh:
                line = fh.readline(1 << 16)
        if not line:
            raise ConnectionError(
                f"rendezvous coordinator {self.address} closed the "
                f"connection without a reply")
        resp = json.loads(line.decode("utf-8"))
        if not resp.get("ok"):
            if resp.get("timeout"):
                raise RendezvousTimeout(resp.get("error", "timeout"))
            raise RendezvousError(resp.get("error", "rejected"))
        return resp

    def join(self, name: str, addr: str = "127.0.0.1",
             jax_port: Optional[int] = None,
             process_id: Optional[int] = None,
             deadline_s: float = 60.0,
             retry: Optional[RetryPolicy] = None) -> dict:
        """Join with retries: connection failures (coordinator not up yet)
        retry under the deadline; a COORDINATOR REJECTION (duplicate id,
        roster full) raises immediately — retrying it cannot succeed."""
        policy = retry or _JOIN_POLICY
        deadline = Deadline.after(deadline_s)
        last: Optional[BaseException] = None
        for _a in policy.attempts_iter(deadline=deadline):
            try:
                return self._rpc({"cmd": "join", "host": name, "addr": addr,
                                  "jax_port": jax_port,
                                  "process_id": process_id})
            except RendezvousError:
                raise
            except (OSError, ValueError) as e:
                last = e
        _publish("join", "timeout")
        raise RendezvousTimeout(
            f"could not join rendezvous coordinator {self.address} within "
            f"{deadline_s:.1f}s (last error: {last})")

    def wait(self, deadline_s: float = 60.0) -> dict:
        """Block until the roster fills or the deadline passes. The wait
        runs SERVER-side; the socket timeout pads it so a coordinator
        that dies mid-wait surfaces as a connection error, not a hang."""
        return self._rpc({"cmd": "wait", "timeout_s": deadline_s},
                         timeout_s=deadline_s + 5.0)

    def heartbeat(self, process_id: int) -> dict:
        return self._rpc({"cmd": "heartbeat", "process_id": process_id})

    def leave(self, process_id: int) -> dict:
        return self._rpc({"cmd": "leave", "process_id": process_id})

    def status(self) -> dict:
        return self._rpc({"cmd": "status"})


class Heartbeater(threading.Thread):
    """Daemon beat loop + host-loss watch. ``on_host_lost(lost_pids)``
    fires at most once, from this thread — the callback must not assume
    the main thread is responsive (a lost host usually means the main
    thread is wedged inside a collective).

    Hysteresis: the callback fires only after ``confirm_beats``
    CONSECUTIVE beat replies report a loss — a single reply reflecting a
    transient scheduler stall (the coordinator heals a returning host)
    must not trigger the irreversible reaper. Cost: one extra
    ``interval_s`` of detection latency."""

    def __init__(self, client: RendezvousClient, process_id: int,
                 interval_s: float = 2.0,
                 on_host_lost: Optional[Callable[[List[int]], None]] = None,
                 confirm_beats: int = 2):
        super().__init__(name=f"rendezvous-heartbeat-{process_id}",
                         daemon=True)
        self.client = client
        self.process_id = int(process_id)
        self.interval_s = float(interval_s)
        self.on_host_lost = on_host_lost
        self.confirm_beats = max(1, int(confirm_beats))
        self._lost_streak = 0
        self._stop = threading.Event()
        self.fired = False

    def run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                resp = self.client.heartbeat(self.process_id)
            except Exception:  # noqa: BLE001 - a missed beat is not fatal;
                continue       # the coordinator's timeout owns liveness
            lost = [p for p in resp.get("lost", ())
                    if p != self.process_id]
            self._lost_streak = self._lost_streak + 1 if lost else 0
            if (lost and self._lost_streak >= self.confirm_beats
                    and not self.fired and self.on_host_lost is not None):
                self.fired = True
                try:
                    self.on_host_lost(lost)
                except Exception:  # noqa: BLE001 - the watch must keep beating
                    pass

    def stop(self) -> None:
        self._stop.set()
