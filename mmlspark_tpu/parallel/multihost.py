"""Multi-host training fabric: process-local data plane + elastic membership.

Extends the single-controller mesh (parallel/mesh.py) to a multi-process
`jax.distributed` fleet with NO estimator-API change:
`LightGBMClassifier().fit(df)` on a connected fabric shard_maps over the
GLOBAL device mesh, and each host bins and transfers only ITS OWN rows.

Three layers:

- **Bootstrap** — ``connect()`` drives the full rendezvous contract:
  join the coordinator with bounded retries (parallel/rendezvous.py),
  gate on the roster barrier, then ``mesh.distributed_init`` with the
  distributed jax-coordinator address and an initialization timeout, so
  a missing host is a counted, named failure at every stage instead of a
  silent hang.
- **Data plane** — global row-sharded arrays assembled from PROCESS-LOCAL
  pieces via ``jax.make_array_from_single_device_arrays``:
  ``assemble_row_sharded`` (the multi-process route of
  ``mesh.place_rows``, so `shard_rows` composes unchanged),
  ``zeros_row_sharded`` (device-side zeros — a [N, K] zero margin never
  crosses a host link), and ``binned_to_device`` (the multi-host variant
  of the PR 6/9 double-buffered streaming construction: each host bins
  ONLY its row spans, block k's per-device async device_put rides under
  block k+1's host binning, donated per-device dynamic_update_slice
  writes, no host sync anywhere — the sync-point lint covers this module
  too, tests/test_fit_pipeline.py).
- **Elastic membership** — a heartbeat watch whose default host-lost
  action is the REAPER: SIGTERM (a drainable fit drains) plus a hard-exit
  watchdog (``os._exit(75)`` after the grace), because a lost host wedges
  every in-flight collective and a wedged main thread can run neither
  Python signal handlers nor a drain. Recovery is PR 10's elastic
  contract: resume from the last durable snapshot at the SURVIVING device
  count (`shard_rows` re-shards; digest-identical, docs/RESILIENCE.md).
  The chaos fault that proves it is `TrainingFaultInjector(kill_host=)`.

Multi-host checkpoint discipline: snapshots are written by process 0 only
(models/lightgbm/base.py save_ck) — point every host at ONE shared
checkpointDir for resumable pod fits, or accept that only host 0's
directory holds the durable state (docs/MULTIHOST.md).
"""

from __future__ import annotations

import os
import signal
import socket
import threading
import time
from typing import Callable, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import mesh as meshlib
from .rendezvous import Heartbeater, RendezvousClient, _publish

__all__ = ["MultihostTopology", "topology", "local_row_slices",
           "assemble_row_sharded", "zeros_row_sharded", "binned_to_device",
           "store_binned_to_device", "connect", "MultihostSession"]


class MultihostTopology(NamedTuple):
    """The fleet shape the comm model prices (parallel/strategy.py
    hosts/devices_per_host terms) and the bench/podslice rows record."""
    hosts: int
    devices_per_host: int
    devices: int
    process_id: int

    def as_labels(self) -> dict:
        return {"hosts": str(self.hosts),
                "devices_per_host": str(self.devices_per_host)}


def topology() -> MultihostTopology:
    return MultihostTopology(jax.process_count(), jax.local_device_count(),
                             jax.device_count(), jax.process_index())


# ---------------------------------------------------------------- data plane

def local_row_slices(mesh, global_rows: int
                     ) -> List[Tuple[object, int, int]]:
    """This process's (device, row_start, row_stop) spans of a
    row-sharded [global_rows, ...] array — the rows this host (and no
    other) must bin and transfer. ``global_rows`` must already be a
    multiple of the data-axis extent (shard_rows/pad_to_multiple pads)."""
    sharding = meshlib.data_sharding(mesh, 2)
    spans = []
    imap = sharding.addressable_devices_indices_map((global_rows, 1))
    for dev, idx in imap.items():
        rs = idx[0]
        start = 0 if rs.start is None else int(rs.start)
        stop = global_rows if rs.stop is None else int(rs.stop)
        spans.append((dev, start, stop))
    spans.sort(key=lambda t: t[1])
    return spans


def assemble_row_sharded(mesh, arr, sharding=None):
    """Global row-sharded jax.Array from a full host copy, transferring
    ONLY this process's shards: per addressable device, slice the host
    rows the device owns, async device_put to that device, then one
    ``jax.make_array_from_single_device_arrays`` — the multi-process
    route of ``mesh.place_rows`` (single-process keeps the one-dispatch
    NamedSharding device_put)."""
    if sharding is None:
        sharding = meshlib.data_sharding(mesh, arr.ndim)
    imap = sharding.addressable_devices_indices_map(arr.shape)
    pieces = [jax.device_put(arr[idx], dev) for dev, idx in imap.items()]
    return jax.make_array_from_single_device_arrays(arr.shape, sharding,
                                                    pieces)


def zeros_row_sharded(mesh, shape: Sequence[int], dtype=jnp.float32,
                      row_axis: int = 0):
    """Row-sharded global zeros with NO host transfer: per-device
    ``jnp.zeros`` of the shard shape (device-side fill), assembled like
    assemble_row_sharded — the multi-process form of the pipelined fit's
    '[N, K] zeros never cross the host link' contract. ``row_axis``
    places the data axis (dart's [T, N, K] delta carry shards rows on
    axis 1)."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    shape = tuple(int(s) for s in shape)
    spec = [None] * len(shape)
    spec[row_axis] = meshlib.DATA_AXIS
    sharding = NamedSharding(mesh, P(*spec))
    imap = sharding.addressable_devices_indices_map(shape)
    pieces = []
    for dev, idx in imap.items():
        shard_shape = tuple(
            (s.stop or shape[i]) - (s.start or 0) if isinstance(s, slice)
            else 1 for i, s in enumerate(idx))
        pieces.append(jax.device_put(jnp.zeros(shard_shape, dtype), dev))
    return jax.make_array_from_single_device_arrays(shape, sharding, pieces)


def binned_to_device(bm, x: np.ndarray, mesh, blk: Optional[int] = None,
                     timeline=None):
    """Multi-host streaming dataset construction: the PR 6/9
    double-buffered bin->device_put pipeline with each host binning only
    its OWN row spans.

    Per local device d owning global rows [r0, r1): stream blocks of
    ``blk`` rows — bin block j+1 on the host while block j's async
    device_put rides d's host link — into a donated per-device
    dynamic_update_slice buffer, then assemble the per-device [ppd, F]
    buffers into ONE global row-sharded array via
    ``jax.make_array_from_single_device_arrays``. Rows another host owns
    are never binned and never transferred here, so host binning cost
    divides by the host count. No host sync anywhere (sync-point lint,
    tests/test_fit_pipeline.py); ``timeline`` records per-block bin/put
    spans without adding barriers."""
    from ..compile import cache as compilecache
    from ..utils.profiling import NULL_TIMELINE

    tl = timeline if timeline is not None else NULL_TIMELINE
    nd = mesh.shape[meshlib.DATA_AXIS]
    x, _ = meshlib.pad_to_multiple(np.ascontiguousarray(x), nd)
    n, fdim = x.shape
    ppd = n // nd
    spans = local_row_slices(mesh, n)
    if blk is None:
        blk = max(1_000_000 // nd, -(-ppd // 8))
    blk = max(1, min(int(blk), ppd))
    tl.meta["blk"] = int(blk * len(spans))
    tl.meta["n_blocks"] = 1 + len(range(blk, ppd, blk))
    tl.meta["ndev"] = int(nd)
    tl.meta["local_devices"] = len(spans)
    sharding = meshlib.data_sharding(mesh, 2)

    if blk >= ppd:
        pieces = []
        for dev, r0, r1 in spans:
            with tl.span(f"bin[{r0}]"):
                bk = bm.transform(x[r0:r1])
            with tl.span(f"put[{r0}]"):
                pieces.append(jax.device_put(bk, dev))
        return jax.make_array_from_single_device_arrays((n, fdim), sharding,
                                                        pieces)

    write = compilecache.cached_jit(
        lambda buf, block, i0: jax.lax.dynamic_update_slice(
            buf, block, (i0, 0)),
        key="binned_write2d", name="gbdt_binned_write", donate_argnums=0)
    bufs = [None] * len(spans)
    first_dtype = None
    for j0 in range(0, ppd, blk):
        # the final window shifts back to stay full-size (ONE compiled
        # write shape); its overlap rows re-bin to identical values
        k0 = min(j0, ppd - blk)
        for di, (dev, r0, _r1) in enumerate(spans):
            with tl.span(f"bin[{r0 + k0}]"):
                bk = bm.transform(x[r0 + k0:r0 + k0 + blk])
            with tl.span(f"put[{r0 + k0}]"):
                piece = jax.device_put(bk, dev)
                if bufs[di] is None:
                    first_dtype = piece.dtype
                    bufs[di] = jax.device_put(
                        jnp.zeros((ppd, fdim), first_dtype), dev)
                bufs[di] = write(bufs[di], piece, jnp.int32(k0))
    return jax.make_array_from_single_device_arrays((n, fdim), sharding,
                                                    bufs)


def store_binned_to_device(bm, store, mesh, blk: Optional[int] = None,
                           ring_depth: int = 2, timeline=None):
    """``binned_to_device`` fed from DISK: each host streams only the
    shard byte ranges its row spans live in (per-host shard ownership —
    rows another host owns are never read, let alone binned), through
    the bounded prefetch ring of io/shardstore.py. Returns the same
    (binned_global, aux) pair as ``shardstore.stream_fit_arrays``; thin
    delegator (lazy import: parallel/ stays importable without io/)."""
    from ..io import shardstore as sstore
    return sstore.stream_fit_arrays(bm, store, mesh=mesh, blk=blk,
                                    ring_depth=ring_depth,
                                    timeline=timeline)


# ----------------------------------------------------------------- bootstrap

def _default_reaper(grace_s: float) -> Callable[[List[int]], None]:
    """The host-lost action: a dead peer wedges every in-flight
    collective, and a main thread stuck inside XLA can run neither
    Python signal handlers nor a drain — so SIGTERM first (a fit that
    CAN drain, drains: PreemptionDrain finishes the chunk and
    snapshots), then a watchdog hard-exit with status 75 (EX_TEMPFAIL,
    the PreemptionDrain convention: retryable — resume from the last
    durable snapshot at the surviving device count)."""
    def reap(lost: List[int]) -> None:
        _publish("host", "lost")
        try:
            os.kill(os.getpid(), signal.SIGTERM)
        except OSError:
            pass
        t = threading.Timer(max(0.1, grace_s), lambda: os._exit(75))
        t.daemon = True
        t.start()
    return reap


class MultihostSession:
    """A connected fabric membership: identity, topology, liveness."""

    def __init__(self, process_id: int, num_hosts: int,
                 client: RendezvousClient,
                 heartbeater: Optional[Heartbeater]):
        self.process_id = int(process_id)
        self.num_hosts = int(num_hosts)
        self.client = client
        self.heartbeater = heartbeater
        self.topology = topology()

    def close(self) -> None:
        """Clean departure: stop the watch, then tell the coordinator we
        LEFT — a finished host must never surface in peers' lost lists
        (finishing first is not dying; rendezvous.leave)."""
        if self.heartbeater is not None:
            self.heartbeater.stop()
        try:
            self.client.leave(self.process_id)
        except Exception:  # noqa: BLE001 - a dead coordinator cannot
            pass           # distinguish leave from silence anyway


def connect(coordinator_address: str, num_hosts: int,
            name: Optional[str] = None, *, host_addr: str = "127.0.0.1",
            jax_port: Optional[int] = None, deadline_s: float = 120.0,
            heartbeat_interval_s: float = 2.0,
            initialization_timeout_s: Optional[float] = None,
            on_host_lost="exit",
            reap_grace_s: Optional[float] = None) -> MultihostSession:
    """Bring this process into the multi-host mesh, end to end:

    1. join the rendezvous coordinator (RetryPolicy-backed, bounded by
       ``deadline_s``) and receive this process's id;
    2. gate on the roster barrier — a late/missing host is a counted
       ``RendezvousTimeout`` naming the coordinator and the missing count;
    3. ``mesh.distributed_init`` against the distributed jax-coordinator
       address with the REMAINING deadline as initialization timeout;
    4. start the heartbeat watch. ``on_host_lost='exit'`` installs the
       reaper (SIGTERM + hard-exit after ``reap_grace_s``, default the
       MMLSPARK_TPU_DRAIN_GRACE_S drain grace); pass a callable for a
       custom action or None to disable the watch.

    ``jax_port``: a port this host reserved for the jax coordination
    service — the coordinator uses process 0's (addr, jax_port) unless an
    explicit jax_coordinator was pinned at coordinator construction.
    """
    deadline = time.monotonic() + float(deadline_s)
    client = RendezvousClient(coordinator_address)
    if name is None:
        name = f"{socket.gethostname()}-{os.getpid()}"
    joined = client.join(name, addr=host_addr, jax_port=jax_port,
                         deadline_s=deadline_s)
    pid = int(joined["process_id"])
    remaining = max(1.0, deadline - time.monotonic())
    roster = client.wait(deadline_s=remaining)
    jax_coordinator = roster.get("jax_coordinator")
    if num_hosts > 1 and not jax_coordinator:
        _publish("initialize", "no_jax_coordinator")
        raise RuntimeError(
            "rendezvous produced no jax coordinator address: pass jax_port "
            "at join time (process 0's is used) or pin jax_coordinator on "
            "the RendezvousCoordinator")
    remaining = max(1.0, deadline - time.monotonic())
    if initialization_timeout_s is None:
        initialization_timeout_s = remaining
    # a failed initialize is counted (timeout vs error) by
    # distributed_init itself — no second count here
    meshlib.distributed_init(
        jax_coordinator, num_processes=num_hosts, process_id=pid,
        initialization_timeout=initialization_timeout_s)
    _publish("initialize")
    hb = None
    if heartbeat_interval_s and on_host_lost is not None:
        if on_host_lost == "exit":
            if reap_grace_s is None:
                from ..resilience.elastic import DRAIN_GRACE_ENV
                reap_grace_s = float(os.environ.get(DRAIN_GRACE_ENV, "30"))
            on_host_lost = _default_reaper(reap_grace_s)
        hb = Heartbeater(client, pid, interval_s=heartbeat_interval_s,
                         on_host_lost=on_host_lost)
        hb.start()
    return MultihostSession(pid, num_hosts, client, hb)
