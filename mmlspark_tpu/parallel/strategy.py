"""Comm-model-driven tree-learner strategy selection for multi-chip fits.

The reference exposes `parallelism` as a flag the user must already
understand (LightGBMParams.scala:13-27: data_parallel reduces the full
child histogram slice per split, voting_parallel reduces only the
globally-voted top-k features). On a pod slice the right answer is a
property of the problem shape, not of the user: per-split allreduce
traffic has a closed form in (n_features, bins, num_leaves, top_k), the
8-device dryrun validates it against the traced program to within 4%
(MULTICHIP_r05: measured 2.04x vs closed form 1.97x at F=512), and
arxiv 1612.01437 shows comm/straggler structure — not FLOPs — dominates
distributed ML wall-clock. So `parallelism="auto"` (the default) picks
the learner from the model below, and the decision lands in the
telemetry registry where it can be audited.

Closed form per split (f32 payload bytes, validated by
tests/test_comm_volume.py's jaxpr psum-shape audit and the dryrun's
trip-count-weighted byte walk):

- data_parallel allreduces one child histogram slice ``[F, B, 3]``
  (sibling subtraction covers the parent), plus an amortized root pass
  and per-iteration metric scalars — measured ~3% above the slice alone.
- voting_parallel allreduces the voted hists ``[L, top_k, B, 3]``, the
  vote table ``[L, F]`` and per-leaf sums ``[L, 3]`` once per PASS; in
  strict leaf-wise growth one pass == one split.

The ratio dp/voting is independent of the device count (the ring factor
2*(ndev-1)/ndev multiplies both sides), so `ndev` only gates serial vs
sharded and scales the absolute byte gauges.

Multi-host extension (ISSUE 15): on a pod slice the per-split allreduce
crosses TWO link classes — ICI inside a host, DCN between hosts — and
the hierarchical form prices them separately: an intra-host
reduce-scatter/all-gather moves ``2*(ld-1)/ld`` payloads per device over
ICI, then a ring over the per-host leaders moves ``2*(H-1)/H`` payloads
per host over DCN (``inter_host_bytes_per_split``). The dp/voting ratio
STILL cancels (both strategies cross the same links), so the chooser's
learner decision is unchanged — what the hosts term adds is the absolute
inter-host traffic and the predicted wall (`allreduce_wall_model_s`),
plus the `dcn_dominance_hosts` breakeven: the host count at which the
DCN phase overtakes the ICI phase. With realistic dcn << ici that
breakeven is H=2 — crossing hosts at all makes DCN the bottleneck —
which is exactly the comm-dominance regime arxiv 1612.01437 measures.
`scripts/measure_podslice.py` grounds the model on a measured 2-host
CPU-mesh allreduce.
"""

from __future__ import annotations

import time
from typing import NamedTuple, Optional

#: bytes per histogram element (histograms allreduce in f32 even when the
#: MXU contraction runs bf16 — accumulation dtype, ops/histogram.py)
_F32 = 4

#: dryrun-measured dp-side overhead above the closed-form child slice
#: (root pass + per-iter metric scalars, amortized over splits):
#: MULTICHIP_r05 measured 203.2 KB/split vs 196.6 KB closed form at
#: F=512, B=32, L=31 — the voting side measured exactly closed-form.
MEASURED_DP_OVERHEAD = 203.2 / 196.6

#: minimum predicted dp/voting traffic ratio before `auto` deviates from
#: the exact data_parallel learner. Voting is an approximation (top-k
#: voted features can miss the globally best split), so it must buy a
#: real traffic cut — the same bar the dryrun asserts on the measured
#: ratio (__graft_entry__.dryrun_multichip: comm_ratio > 1.5) before
#: certifying voting at a shape.
VOTING_ADVANTAGE_THRESHOLD = 1.5

#: user-facing `parallelism` values -> canonical tree learner. The short
#: names are the documented surface; the long reference names
#: (LightGBMExecutionParams.parallelism) stay accepted for compat.
PARALLELISM_ALIASES = {
    "auto": "auto",
    "data": "data_parallel", "data_parallel": "data_parallel",
    "voting": "voting_parallel", "voting_parallel": "voting_parallel",
    "off": "serial", "serial": "serial",
}

#: calibration defaults for the two link classes (bytes/s). Order-of-
#: magnitude v5e-class figures — effective per-device ICI vs per-host
#: DCN NIC — used only where no measured bandwidth is available;
#: scripts/measure_podslice.py derives the measured effective values
#: from the 2-host allreduce wall and logs both next to these.
ICI_BYTES_PER_S_DEFAULT = 4.8e10
DCN_BYTES_PER_S_DEFAULT = 3.125e9


def normalize_parallelism(value: str) -> str:
    """Canonical learner name ('auto'|'serial'|'data_parallel'|
    'voting_parallel') or ValueError naming the accepted surface."""
    try:
        return PARALLELISM_ALIASES[str(value)]
    except KeyError:
        raise ValueError(
            f"parallelism must be one of {sorted(PARALLELISM_ALIASES)} "
            f"(auto = comm-model choice, off/serial = single device), "
            f"got {value!r}") from None


def comm_bytes_per_split(n_features: int, bins: int, num_leaves: int,
                         top_k: int, strategy: str) -> int:
    """Closed-form allreduce PAYLOAD bytes per split (f32, no ring
    factor) — the table the dryrun validates: 203.2/99.6 KB at
    (F=512, B=32, L=31, K=3)."""
    if strategy == "data_parallel":
        return _F32 * n_features * bins * 3
    if strategy == "voting_parallel":
        k = min(int(top_k), int(n_features))
        return _F32 * num_leaves * (k * bins * 3 + n_features + 3)
    raise ValueError(f"no comm model for strategy {strategy!r}")


def inter_host_bytes_per_split(n_features: int, bins: int, num_leaves: int,
                               top_k: int, strategy: str, hosts: int) -> int:
    """Closed-form DCN (cross-host) payload bytes per split: the
    hierarchical allreduce's leader ring moves ``2*(H-1)/H`` payloads per
    host across the host boundary. 0 on a single host — intra-host ICI
    traffic never touches the DCN."""
    if hosts <= 1:
        return 0
    payload = comm_bytes_per_split(n_features, bins, num_leaves, top_k,
                                   strategy)
    return int(round(payload * 2.0 * (hosts - 1) / hosts))


def allreduce_wall_model_s(payload_bytes: float, ndev: int, hosts: int = 1,
                           ici_bytes_per_s: float = ICI_BYTES_PER_S_DEFAULT,
                           dcn_bytes_per_s: float = DCN_BYTES_PER_S_DEFAULT
                           ) -> float:
    """Predicted wall of one payload allreduce over a (hosts x
    devices_per_host) mesh: intra-host reduce-scatter/all-gather over ICI
    plus the leader ring over DCN, serialized (the hierarchical schedule
    runs the phases back to back)."""
    hosts = max(1, int(hosts))
    ld = max(1, int(ndev) // hosts)
    intra = 2.0 * (ld - 1) / ld * payload_bytes / float(ici_bytes_per_s)
    inter = (2.0 * (hosts - 1) / hosts * payload_bytes
             / float(dcn_bytes_per_s)) if hosts > 1 else 0.0
    return intra + inter


def dcn_dominance_hosts(devices_per_host: int,
                        ici_bytes_per_s: float = ICI_BYTES_PER_S_DEFAULT,
                        dcn_bytes_per_s: float = DCN_BYTES_PER_S_DEFAULT
                        ) -> Optional[int]:
    """The multi-host breakeven: the smallest host count H >= 2 at which
    the DCN phase of the hierarchical allreduce takes at least as long as
    the ICI phase — 2*(H-1)/H / dcn >= 2*(ld-1)/ld / ici, i.e.
    (H-1)/H >= r with r = (dcn/ici) * (ld-1)/ld. None when DCN never
    dominates at this bandwidth pair (r >= 1). With realistic dcn << ici
    this returns 2: any cross-host hop makes DCN the bottleneck."""
    import math
    ld = max(1, int(devices_per_host))
    r = (float(dcn_bytes_per_s) / float(ici_bytes_per_s)) * (ld - 1) / ld
    if r >= 1.0:
        return None
    return max(2, math.ceil(1.0 / (1.0 - r)))


def voting_advantage(n_features: int, bins: int, num_leaves: int,
                     top_k: int) -> float:
    """Predicted dp/voting traffic ratio (>1 = voting saves bytes);
    ndev-independent (ring factor cancels)."""
    return (comm_bytes_per_split(n_features, bins, num_leaves, top_k,
                                 "data_parallel")
            / comm_bytes_per_split(n_features, bins, num_leaves, top_k,
                                   "voting_parallel"))


class StrategyDecision(NamedTuple):
    """The auditable record of one strategy choice (published to the
    metrics registry and embedded in bench JSON). The hosts fields
    (ISSUE 15) record the fleet topology the fit ran on and the
    closed-form DCN traffic it implies — 0 inter-host bytes on a single
    host."""
    strategy: str          # resolved learner: serial|data_parallel|voting_parallel
    requested: str         # normalized user request (may be 'auto')
    ndev: int              # data-axis extent the fit will use (1 = serial)
    advantage: float       # predicted dp/voting bytes ratio at this shape
    dp_bytes_per_split: int
    voting_bytes_per_split: int
    threshold: float
    reason: str
    hosts: int = 1                       # jax processes in the fit mesh
    devices_per_host: int = 0            # local devices per host (0 = n/a)
    dp_inter_host_bytes_per_split: int = 0
    voting_inter_host_bytes_per_split: int = 0

    def as_labels(self) -> dict:
        return {"strategy": self.strategy, "requested": self.requested,
                "hosts": str(self.hosts),
                "devices_per_host": str(self.devices_per_host)}


def choose_strategy(requested: str, ndev: int, n_features: int, bins: int,
                    num_leaves: int, top_k: int,
                    allow_voting: bool = True, hosts: int = 1,
                    devices_per_host: Optional[int] = None
                    ) -> StrategyDecision:
    """Resolve the user's `parallelism` request against the comm model.

    - explicit 'serial'/'data_parallel'/'voting_parallel' (or their short
      aliases) are honored verbatim — `auto` is a default, not a cage;
    - 'auto' on one device is serial;
    - 'auto' on >1 device picks voting_parallel exactly when the model
      predicts >= VOTING_ADVANTAGE_THRESHOLD traffic savings
      (allow_voting=False pins data_parallel — the vmapped sweep path,
      where per-candidate voting programs would defeat the single
      compiled batch).

    ``hosts``/``devices_per_host`` describe the fleet (multihost.topology):
    they do not change the learner choice (the dp/voting ratio crosses
    identical links, so bandwidth cancels) but land in the decision as
    the closed-form inter-host byte prediction and the topology labels.
    """
    req = normalize_parallelism(requested)
    adv = voting_advantage(n_features, bins, num_leaves, top_k)
    dp_b = comm_bytes_per_split(n_features, bins, num_leaves, top_k,
                                "data_parallel")
    vt_b = comm_bytes_per_split(n_features, bins, num_leaves, top_k,
                                "voting_parallel")
    hosts = max(1, int(hosts))
    if devices_per_host is None:
        devices_per_host = max(1, int(ndev) // hosts)

    def dec(strategy, reason):
        # ndev records the extent the fit WILL use: a serial resolution
        # runs on one device no matter how many are visible, and the
        # gbdt_fit_ndev gauge documents 1 = serial (one device is also
        # one host — a serial fit never crosses the DCN)
        h = 1 if strategy == "serial" else hosts
        return StrategyDecision(
            strategy, req, 1 if strategy == "serial" else ndev,
            adv, dp_b, vt_b, VOTING_ADVANTAGE_THRESHOLD, reason,
            hosts=h,
            devices_per_host=(1 if strategy == "serial"
                              else int(devices_per_host)),
            dp_inter_host_bytes_per_split=inter_host_bytes_per_split(
                n_features, bins, num_leaves, top_k, "data_parallel", h),
            voting_inter_host_bytes_per_split=inter_host_bytes_per_split(
                n_features, bins, num_leaves, top_k, "voting_parallel", h))

    if req != "auto":
        return dec(req, "explicit parallelism param")
    if ndev <= 1:
        return dec("serial", "one device visible")
    if allow_voting and adv >= VOTING_ADVANTAGE_THRESHOLD:
        return dec("voting_parallel",
                   f"comm model: voting cuts per-split traffic "
                   f"{adv:.2f}x >= {VOTING_ADVANTAGE_THRESHOLD}x")
    if not allow_voting and adv >= VOTING_ADVANTAGE_THRESHOLD:
        return dec("data_parallel",
                   "voting profitable but pinned to data_parallel "
                   "(vmapped candidate batch)")
    return dec("data_parallel",
               f"comm model: voting advantage {adv:.2f}x below "
               f"{VOTING_ADVANTAGE_THRESHOLD}x threshold")


def measure_allreduce_wall_s(mesh, n_features: int, bins: int,
                             reps: int = 10) -> float:
    """Measured wall of ONE child-slice ([F, B, 3] f32) allreduce over
    the mesh's data axis — the per-split collective the comm model
    prices. Warm compile excluded; min over reps (noisy-pool
    discipline). Used by scripts/measure_multichip_fit.py and bench to
    ground the closed-form byte gauges in a measured latency."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from . import mesh as meshlib

    axis = meshlib.DATA_AXIS
    ndev = mesh.shape[axis]
    payload = jnp.ones((ndev, n_features, bins, 3), jnp.float32)

    fn = jax.jit(meshlib.shard_map(
        lambda a: jax.lax.psum(a, axis), mesh=mesh,
        in_specs=P(axis), out_specs=P(axis), check_vma=False))
    sh = meshlib.data_sharding(mesh, payload.ndim)
    payload = jax.device_put(payload, sh)
    jax.block_until_ready(fn(payload))  # compile + warm
    best = float("inf")
    for _ in range(max(1, reps)):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(payload))
        best = min(best, time.perf_counter() - t0)
    return best
