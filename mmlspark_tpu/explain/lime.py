"""TabularLIME / ImageLIME — local interpretable model-agnostic explanations.

Reference: lime/LIME.scala:166-248 (`TabularLIME(Model)` — per-row perturbation
sampling from column STDs, model.transform over replicated samples, lasso fit
per row) and :258-317 (`ImageLIME` — SLIC superpixels, random masks, lasso on
mask states vs prediction).

TPU design (SURVEY.md §7: "perturbation batches are TPU-friendly"): all rows'
perturbed samples go through the model as ONE batch per chunk, and the per-row
lassos solve as one vmapped program (explain/lasso.py) — no per-row driver
loops.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..core import params as _p
from ..core.dataframe import DataFrame
from ..core.pipeline import Estimator, Model, Transformer
from .lasso import batched_lasso, lasso_fit
from .superpixel import Superpixel, slic_segments


def _model_outputs(model: Transformer, feats: np.ndarray, features_col: str,
                   target_col: Optional[str], target_class: int) -> np.ndarray:
    """Run the wrapped model on a feature batch; pull out the scalar being
    explained (probability of target class, else prediction)."""
    scored = model.transform(DataFrame({features_col: feats}))
    if target_col is None:
        target_col = next(
            (c for c in ("probability", "scored_probabilities", "prediction",
                         "scores") if c in scored), None)
        if target_col is None:
            raise ValueError(f"no model output column found in "
                             f"{scored.columns}")
    out = np.asarray(scored[target_col], np.float64)
    if out.ndim == 2:
        out = out[:, target_class]
    return out


class LIMEParams(_p.Params):
    model = _p.Param("model", "fitted model to explain", None, complex=True)
    numSamples = _p.Param("numSamples", "perturbation samples per row", 100,
                          int)
    regularization = _p.Param("regularization", "lasso alpha", 0.01, float)
    targetCol = _p.Param("targetCol", "model output column to explain "
                         "(auto: probability/prediction)", None)
    targetClass = _p.Param("targetClass",
                           "class index explained for vector outputs", 1, int)
    samplingFraction = _p.Param("samplingFraction",
                                "feature perturbation std multiplier", 1.0,
                                float)


class TabularLIME(Estimator, LIMEParams, _p.HasInputCol, _p.HasOutputCol,
                  _p.HasSeed):
    """fit() learns per-column STDs of the background data (LIME.scala:166-
    248); the model emits per-row coefficient vectors."""

    def __init__(self, **kw):
        kw.setdefault("inputCol", "features")
        kw.setdefault("outputCol", "weights")
        super().__init__(**kw)

    def _fit(self, df: DataFrame) -> "TabularLIMEModel":
        x = np.asarray(df[self.get("inputCol")], np.float64)
        stds = x.std(axis=0)
        stds[stds < 1e-12] = 1e-12
        out = TabularLIMEModel(column_stds=stds.astype(np.float32))
        for p in ("model", "numSamples", "regularization", "targetCol",
                  "targetClass", "samplingFraction", "inputCol", "outputCol",
                  "seed"):
            out.set(p, self.get(p))
        return out


class TabularLIMEModel(Model, LIMEParams, _p.HasInputCol, _p.HasOutputCol,
                       _p.HasSeed):
    columnSTDs = _p.Param("columnSTDs", "per-feature perturbation stds", None,
                          complex=True)

    def __init__(self, column_stds: Optional[np.ndarray] = None, **kw):
        super().__init__(**kw)
        if column_stds is not None:
            self.set("columnSTDs", column_stds)

    def transform(self, df: DataFrame) -> DataFrame:
        x = np.asarray(df[self.get("inputCol")], np.float32)
        n, d = x.shape
        s = self.get("numSamples")
        stds = (np.asarray(self.get("columnSTDs"), np.float32)
                * self.get("samplingFraction"))
        rng = np.random.default_rng(self.get("seed"))
        noise = rng.normal(size=(n, s, d)).astype(np.float32) * stds
        samples = x[:, None, :] + noise
        preds = _model_outputs(
            self.get("model"), samples.reshape(n * s, d),
            self.get("inputCol"), self.get("targetCol"),
            self.get("targetClass")).reshape(n, s).astype(np.float32)
        # states are standardized offsets => coefficients are per-std effects
        z = (noise / stds).astype(np.float32)
        w = np.ones((n, s), np.float32)
        coefs, _ = batched_lasso(z, preds, w,
                                 np.float32(self.get("regularization")))
        return df.with_column(self.get("outputCol"), np.asarray(coefs))


class ImageLIME(Transformer, LIMEParams, _p.HasInputCol, _p.HasOutputCol,
                _p.HasSeed):
    """Superpixel-mask LIME for image models (LIME.scala:258-317).

    transform(): per image — SLIC segments, `numSamples` random on/off masks,
    censored images batched through the model, one lasso per image over mask
    states. Output: per-superpixel weight vector (object column)."""

    cellSize = _p.Param("cellSize", "superpixel size", 16.0, float)
    modifier = _p.Param("modifier", "superpixel compactness", 130.0, float)
    superpixelCol = _p.Param("superpixelCol",
                             "optional precomputed segment column", None)

    def __init__(self, **kw):
        kw.setdefault("inputCol", "image")
        kw.setdefault("outputCol", "weights")
        super().__init__(**kw)

    def transform(self, df: DataFrame) -> DataFrame:
        imgs = df[self.get("inputCol")]
        s = self.get("numSamples")
        rng = np.random.default_rng(self.get("seed"))
        model = self.get("model")
        out = np.empty(len(df), dtype=object)
        seg_col = (df[self.get("superpixelCol")]
                   if self.get("superpixelCol") else None)
        for i in range(len(df)):
            img = np.asarray(imgs[i], np.float64)
            segments = (np.asarray(seg_col[i]) if seg_col is not None else
                        slic_segments(img, self.get("cellSize"),
                                      self.get("modifier")))
            k = int(segments.max()) + 1
            states = rng.random((s, k)) < 0.5
            batch = np.stack([
                Superpixel.censor(img, segments, st) for st in states])
            preds = _model_outputs(
                model, batch.astype(np.float32), self.get("inputCol"),
                self.get("targetCol"), self.get("targetClass"))
            coef, _ = lasso_fit(states.astype(np.float32),
                                preds.astype(np.float32),
                                alpha=self.get("regularization"))
            out[i] = coef
        return df.with_column(self.get("outputCol"), out)
