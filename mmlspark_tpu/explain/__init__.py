"""Model interpretability (reference: lime/, 4 files, 823 LoC)."""

from .lasso import batched_lasso, lasso_fit
from .lime import ImageLIME, TabularLIME, TabularLIMEModel
from .superpixel import Superpixel, SuperpixelTransformer, slic_segments

__all__ = ["TabularLIME", "TabularLIMEModel", "ImageLIME",
           "Superpixel", "SuperpixelTransformer", "slic_segments",
           "batched_lasso", "lasso_fit"]
