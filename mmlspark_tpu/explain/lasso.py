"""Batched lasso — the LIME local-surrogate solver, vmapped over instances.

Reference: lime/BreezeUtils.scala + LimeNamespaceInjections.fitLasso
(org/apache/spark/ml/LimeNamespaceInjections.scala:9-16) solve one lasso per
explained row on the driver. Here the whole batch of per-row problems is a
single ISTA (proximal gradient) program under `lax.scan`, vmapped over rows —
thousands of small lassos in one XLA launch.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


def _ista_single(z, y, w, alpha: float, iters: int):
    """One weighted lasso: min_w' sum_i w_i (z_i.w' + b - y_i)^2 / sum w
    + alpha * ||w'||_1.  z: [s,d], y: [s], w: [s] sample weights."""
    s, d = z.shape
    wsum = jnp.maximum(w.sum(), 1e-9)
    # weighted centering removes the intercept from the iteration
    zm = (w[:, None] * z).sum(0) / wsum
    ym = (w * y).sum() / wsum
    zc = z - zm
    yc = y - ym
    wz = w[:, None] * zc
    gram_diag_max = jnp.maximum((wz * zc).sum() / wsum, 1e-9)
    step = 1.0 / (2.0 * gram_diag_max)  # conservative Lipschitz bound

    def body(coef, _):
        resid = zc @ coef - yc
        grad = 2.0 * (wz.T @ resid) / wsum
        u = coef - step * grad
        coef = jnp.sign(u) * jnp.maximum(jnp.abs(u) - step * alpha, 0.0)
        return coef, None

    coef0 = jnp.zeros((d,), jnp.float32)
    coef, _ = jax.lax.scan(body, coef0, None, length=iters)
    intercept = ym - zm @ coef
    return coef, intercept


@partial(jax.jit, static_argnames=("iters",))
def batched_lasso(z, y, w, alpha, iters: int = 300):
    """vmapped lasso. z: [n,s,d] sample states per row; y: [n,s] model outputs;
    w: [n,s] sample weights; alpha: scalar. Returns (coefs [n,d], icepts [n])."""
    return jax.vmap(_ista_single, in_axes=(0, 0, 0, None, None))(
        z, y, w, alpha, iters)


def lasso_fit(z: np.ndarray, y: np.ndarray, w: np.ndarray = None,
              alpha: float = 0.01, iters: int = 300):
    """Host-friendly wrapper (single problem or batch)."""
    z = np.asarray(z, np.float32)
    y = np.asarray(y, np.float32)
    single = z.ndim == 2
    if single:
        z, y = z[None], y[None]
    if w is None:
        w = np.ones(z.shape[:2], np.float32)
    coef, icept = batched_lasso(jnp.asarray(z), jnp.asarray(y),
                                jnp.asarray(np.asarray(w, np.float32)),
                                jnp.float32(alpha), iters)
    coef, icept = np.asarray(coef), np.asarray(icept)
    return (coef[0], icept[0]) if single else (coef, icept)
