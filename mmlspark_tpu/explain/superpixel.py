"""SLIC superpixel clustering + SuperpixelTransformer.

Reference: lime/Superpixel.scala:26-300+ implements a BFS cluster-expansion
segmentation used by ImageLIME; lime/SuperpixelTransformer.scala exposes it as
a stage. Here the segmentation is SLIC (k-means in (x, y, rgb) space) with a
fixed iteration count — the assignment step is a vectorized distance argmin,
the update a segment mean, both TPU/numpy friendly, no per-pixel BFS.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from ..core import params as _p
from ..core.dataframe import DataFrame
from ..core.pipeline import Transformer


def slic_segments(img: np.ndarray, cell_size: float = 16.0,
                  modifier: float = 10.0, iters: int = 5) -> np.ndarray:
    """Segment an HWC float image into superpixels.

    cell_size ~ reference `cellSize`; modifier ~ reference `modifier`
    (SuperpixelTransformer params): color-vs-space tradeoff. Returns an int32
    [H,W] label map with contiguous ids."""
    img = np.asarray(img, np.float64)
    if img.ndim == 2:
        img = img[:, :, None]
    h, wdt, c = img.shape
    step = max(int(cell_size), 2)
    ys = np.arange(step // 2, h, step)
    xs = np.arange(step // 2, wdt, step)
    if ys.size == 0:  # image smaller than a cell: single center
        ys = np.array([h // 2])
    if xs.size == 0:
        xs = np.array([wdt // 2])
    cy, cx = np.meshgrid(ys, xs, indexing="ij")
    centers_xy = np.stack([cy.ravel(), cx.ravel()], 1).astype(np.float64)
    centers_rgb = img[centers_xy[:, 0].astype(int),
                      centers_xy[:, 1].astype(int)]
    yy, xx = np.meshgrid(np.arange(h), np.arange(wdt), indexing="ij")
    pix_xy = np.stack([yy.ravel(), xx.ravel()], 1).astype(np.float64)
    pix_rgb = img.reshape(-1, c)
    # spatial distances weighted so color differences of `modifier` match one
    # cell of spatial distance (SLIC compactness)
    ratio = (modifier / step) ** 2
    n_centers = len(centers_xy)
    assign = np.zeros(h * wdt, np.int64)
    for _ in range(max(iters, 1)):
        d_xy = ((pix_xy[:, None, :] - centers_xy[None, :, :]) ** 2).sum(-1)
        d_rgb = ((pix_rgb[:, None, :] - centers_rgb[None, :, :]) ** 2).sum(-1)
        assign = (d_rgb + ratio * d_xy).argmin(1)
        counts = np.bincount(assign, minlength=n_centers).astype(np.float64)
        live = counts > 0
        for d in range(2):
            s = np.bincount(assign, weights=pix_xy[:, d],
                            minlength=n_centers)
            centers_xy[live, d] = s[live] / counts[live]
        for d in range(c):
            s = np.bincount(assign, weights=pix_rgb[:, d],
                            minlength=n_centers)
            centers_rgb[live, d] = s[live] / counts[live]
    # compact ids
    uniq, remap = np.unique(assign, return_inverse=True)
    return remap.reshape(h, wdt).astype(np.int32)


class Superpixel:
    """API-parity holder (lime/Superpixel.scala): segmentation + censoring."""

    @staticmethod
    def get_clustered_image(img: np.ndarray, cell_size: float,
                            modifier: float) -> np.ndarray:
        return slic_segments(img, cell_size, modifier)

    @staticmethod
    def censor(img: np.ndarray, segments: np.ndarray,
               states: np.ndarray, background: Optional[float] = None
               ) -> np.ndarray:
        """Zero (or background-fill) the superpixels whose state is False."""
        img = np.asarray(img, np.float64)
        if background is None:
            background = img.mean()
        keep = states[segments]  # [H,W] bool
        out = np.where(keep[..., None] if img.ndim == 3 else keep,
                       img, background)
        return out


class SuperpixelTransformer(Transformer, _p.HasInputCol, _p.HasOutputCol):
    """Image column -> superpixel label-map column
    (lime/SuperpixelTransformer.scala)."""
    cellSize = _p.Param("cellSize", "target superpixel size in pixels", 16.0,
                        float)
    modifier = _p.Param("modifier", "color/space compactness tradeoff", 130.0,
                        float)

    def __init__(self, **kw):
        kw.setdefault("outputCol", "superpixels")
        super().__init__(**kw)

    def transform(self, df: DataFrame) -> DataFrame:
        col = df[self.get("inputCol")]
        out = np.empty(len(df), dtype=object)
        for i in range(len(df)):
            out[i] = slic_segments(col[i], self.get("cellSize"),
                                   self.get("modifier"))
        return df.with_column(self.get("outputCol"), out)
