"""Quantile binning — raw feature matrix -> small-int binned matrix.

Reference analogue: LightGBM's `LGBM_DatasetCreateFromMat` bin-mapper construction
(dataset generation in lightgbm/TrainUtils.scala:26-66 hands raw arrays to C++, which
quantile-bins them; `binSampleCount` param in lightgbm/LightGBMParams.scala). Here binning is
explicit and host-side (one-off O(N·F·logB) numpy work); the binned uint8 matrix is what lives
in HBM and feeds the Pallas/MXU histogram kernels.

Missing handling (upstream `use_missing=true`, `zero_as_missing=false`
semantics): features with NaN observed at fit time reserve bin 0 as the
missing bin (value bins shift up by one) so the split scan can LEARN the
default direction; features without training NaNs keep MissingType::None —
predict-time NaN coerces to the value 0.0. `BinMapper.fit(use_missing=False)`
restores the legacy NaN-to-lowest-bin behavior.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np


def _has_any_nan(X: np.ndarray) -> bool:
    """Cheap whole-matrix NaN probe: NaN propagates through summation, so a
    non-NaN total PROVES the matrix NaN-free with one vectorized reduce —
    ~25x cheaper than `np.isnan(X).any()` at bench shapes, and the fit/
    transform NaN bookkeeping (nanmin/nanmax, per-column isnan scans, the
    no-missing-feature NaN coercion pass) was half the host binning cost of
    a 4M-row fit (docs/PERF.md round-5 decomposition). ±inf pairs can
    false-POSITIVE (inf - inf = NaN) — the caller then takes the exact
    detailed path, which is merely slower, never wrong."""
    if X.dtype.kind != "f" or X.size == 0:
        return False
    with np.errstate(all="ignore"):
        return bool(np.isnan(np.sum(X, dtype=np.float64)))


def compute_bin_edges(X: np.ndarray, max_bins: int = 255,
                      sample_count: int = 200_000, seed: int = 0,
                      max_bins_by_feature: Optional[np.ndarray] = None
                      ) -> np.ndarray:
    """Per-feature quantile bin upper-edges.

    Returns edges [F, max_bins-1]; feature f's bin id = searchsorted(edges[f], x, 'left'),
    i.e. x <= edges[f][b] falls in bin <= b. Features with < max_bins distinct values get
    exact-value edges (padded with +inf), preserving categorical-as-int behavior.
    max_bins_by_feature (maxBinByFeature, LightGBMParams.scala): optional
    per-feature bin budget (<= max_bins); 0/negative entries mean "use
    max_bins".
    """
    X = np.asarray(X)
    n, f = X.shape
    # sample BEFORE the float64 conversion: converting the full matrix first
    # costs more than the whole quantile computation at bench shapes
    if n > sample_count:
        rng = np.random.default_rng(seed)
        idx = rng.choice(n, sample_count, replace=False)
        sample = np.asarray(X[idx], dtype=np.float64)
    else:
        sample = np.asarray(X, dtype=np.float64)
    edges = np.full((f, max_bins - 1), np.inf, dtype=np.float64)
    for j in range(f):
        mb = max_bins
        if max_bins_by_feature is not None and max_bins_by_feature[j] > 0:
            mb = min(int(max_bins_by_feature[j]), max_bins)
        col = sample[:, j]
        col = col[~np.isnan(col)]
        if col.size == 0:
            continue
        # ONE sort per column serves both the distinct-value check and the
        # quantiles (np.unique + np.quantile each re-sorted: 2x the work of
        # the whole fit at bench shapes)
        col.sort()
        distinct = np.empty(col.size, bool)
        distinct[0] = True
        np.not_equal(col[1:], col[:-1], out=distinct[1:])
        uniq = col[distinct]
        if uniq.size <= mb:
            # exact edges midway between consecutive distinct values
            if uniq.size > 1:
                mids = (uniq[:-1] + uniq[1:]) / 2.0
                edges[j, :mids.size] = mids
        else:
            # linear-interpolated quantiles straight off the sorted column
            # (same definition as np.quantile's default method)
            qs = np.linspace(0, 1, mb + 1)[1:-1]
            pos = qs * (col.size - 1)
            lo = pos.astype(np.int64)
            frac = pos - lo
            hi = np.minimum(lo + 1, col.size - 1)
            q = col[lo] * (1.0 - frac) + col[hi] * frac
            q = q[np.concatenate(([True], q[1:] != q[:-1]))]
            edges[j, :q.size] = q
    return edges


def apply_bins(X: np.ndarray, edges: np.ndarray) -> np.ndarray:
    """Map raw features to bin ids [N, F] (uint8 if max_bins<=256).

    Uses the C++ host kernel (utils/native.bin_matrix — the NativeLoader-style
    data-plane path) when the toolchain is available; identical numpy
    semantics otherwise (both map NaN to bin 0)."""
    max_bins = edges.shape[1] + 1
    from ..utils import native
    X = np.asarray(X)
    # the C++ kernel takes float32 rows; only exact for float32 inputs
    if X.dtype == np.float32 and native.get_lib() is not None:
        out = native.bin_matrix(X, edges)
        return out.astype(np.uint8) if max_bins <= 256 else out
    X = np.asarray(X, dtype=np.float64)
    # bin ids are < max_bins, so with <= 256 bins they fit uint8 directly —
    # writing the searchsorted results straight into the final-dtype buffer
    # skips an [N, F] int32 materialization + astype copy per call. The
    # row-block fit pipeline pays this path once per block on float64 /
    # no-toolchain fallbacks, so the copy was pure overhead there.
    out = np.empty(X.shape, dtype=np.uint8 if max_bins <= 256 else np.int32)
    for j in range(X.shape[1]):
        out[:, j] = np.searchsorted(edges[j], X[:, j], side="left")
    out[np.isnan(X)] = 0
    return out


def num_used_bins(edges: np.ndarray) -> np.ndarray:
    """Actual bin count per feature (edges padded with inf don't create bins)."""
    return (np.isfinite(edges).sum(axis=1) + 1).astype(np.int32)


class BinMapper:
    """Fitted binner: edges + apply; serializable as a plain array.

    Categorical features (categoricalSlotIndexes, lightgbm/LightGBMParams.scala;
    categorical index resolution in LightGBMUtils.scala:74-106) are binned by
    integer category code directly: bin id == code, no quantile edges.
    """

    def __init__(self, edges: np.ndarray,
                 categorical: Optional[Tuple[int, ...]] = None,
                 feature_min: Optional[np.ndarray] = None,
                 feature_max: Optional[np.ndarray] = None,
                 missing: Optional[np.ndarray] = None):
        self.edges = edges
        self.categorical = tuple(sorted(categorical)) if categorical else ()
        # real per-feature value ranges (upstream feature_infos [min:max]);
        # None on mappers restored from pre-0.2 checkpoints
        self.feature_min = feature_min
        self.feature_max = feature_max
        # numeric features with NaN observed at fit time get a RESERVED
        # missing bin 0 (value bins shift up by one) — upstream use_missing
        # semantics, enabling learned default directions; None/absent =
        # legacy NaN->lowest-bin behavior
        self.missing = (np.asarray(missing, bool) if missing is not None
                        else np.zeros(edges.shape[0], bool))

    @property
    def max_bins(self) -> int:
        return self.edges.shape[1] + 1

    @property
    def num_features(self) -> int:
        return self.edges.shape[0]

    @staticmethod
    def fit(X: np.ndarray, max_bins: int = 255, sample_count: int = 200_000,
            seed: int = 0,
            categorical: Optional[Tuple[int, ...]] = None,
            max_bins_by_feature: Optional[np.ndarray] = None,
            use_missing: bool = True) -> "BinMapper":
        if categorical:
            X = np.asarray(X)
            for j in categorical:
                top = np.nanmax(X[:, j]) if len(X) else 0
                if top >= max_bins:
                    import warnings
                    warnings.warn(
                        f"categorical feature {j} has {int(top) + 1} codes but "
                        f"maxBin={max_bins}; codes >= {max_bins} are clipped "
                        f"into one bin (raise maxBin to keep them distinct)")
        X = np.asarray(X)
        # one cheap reduce decides whether ANY NaN bookkeeping is needed:
        # when the matrix is provably clean (the common case), plain
        # min/max replace the masked nanmin/nanmax and the per-column
        # isnan scan is skipped outright
        any_nan = _has_any_nan(X) if len(X) else False
        with np.errstate(all="ignore"):
            if not len(X):
                fmin = fmax = None
            elif any_nan:
                fmin = np.nanmin(X, axis=0).astype(np.float64)
                fmax = np.nanmax(X, axis=0).astype(np.float64)
            else:
                fmin = X.min(axis=0).astype(np.float64)
                fmax = X.max(axis=0).astype(np.float64)
        f = X.shape[1] if X.ndim == 2 else 0
        missing = np.zeros(f, bool)
        if use_missing and len(X) and X.dtype.kind == "f" and any_nan:
            # full-data NaN scan (a sample could miss rare NaNs, and the
            # missing bin changes routing semantics for the whole feature)
            missing = np.isnan(X).any(axis=0)
            if categorical:
                missing[list(categorical)] = False  # cats bin by code
        if missing.any():
            # reserve one bin for missing: value bins budget drops by 1 (but
            # never to 0 — compute_bin_edges reads 0 as "uncapped", which
            # would overflow the trainer's bin range by one)
            mbbf = (np.asarray(max_bins_by_feature, np.int64).copy()
                    if max_bins_by_feature is not None
                    else np.zeros(f, np.int64))
            cap = np.where(mbbf > 0, np.minimum(mbbf, max_bins), max_bins)
            max_bins_by_feature = np.where(missing,
                                           np.maximum(cap - 1, 1), mbbf)
        return BinMapper(compute_bin_edges(X, max_bins, sample_count, seed,
                                           max_bins_by_feature),
                         categorical, fmin, fmax, missing)

    @staticmethod
    def fit_sampled(sample: np.ndarray, n_total: int, *,
                    feature_min: Optional[np.ndarray],
                    feature_max: Optional[np.ndarray],
                    missing_any: Optional[np.ndarray],
                    float_data: bool = True,
                    max_bins: int = 255, sample_count: int = 200_000,
                    seed: int = 0,
                    categorical: Optional[Tuple[int, ...]] = None,
                    max_bins_by_feature: Optional[np.ndarray] = None,
                    use_missing: bool = True) -> "BinMapper":
        """`fit` for out-of-core data: a gathered row sample plus exact
        full-pass stats instead of the in-RAM matrix.

        Bit-parity contract with `fit(X)` (pinned by the shard-store
        digest tests): `sample` must be the rows `fit` would have drawn —
        same seed/sample_count `rng.choice` indices (any row order: the
        per-column sorts in compute_bin_edges erase it) — and the stats
        must be full-pass exact: `feature_min`/`feature_max` combined per
        block via np.fmin/np.fmax of nanmin/nanmax (== nanmin/nanmax of
        the whole matrix, == min/max when NaN-free), `missing_any` the OR
        of per-block `np.isnan(block).any(axis=0)`. The whole-matrix sum
        probe `fit` uses is only a fast path around those same exact
        scans, so feeding the exact values reproduces its output in every
        case, including the ±inf false-positive one."""
        sample = np.asarray(sample, dtype=np.float64)
        if sample.shape[0] > sample_count:
            # compute_bin_edges would RE-sample with fresh rng state and
            # silently break parity with the in-memory fit
            raise ValueError(
                f"sample has {sample.shape[0]} rows > sample_count "
                f"{sample_count}; gather at most sample_count rows")
        f = sample.shape[1]
        fmin = (np.asarray(feature_min, np.float64)
                if feature_min is not None and n_total else None)
        fmax = (np.asarray(feature_max, np.float64)
                if feature_max is not None and n_total else None)
        if categorical and fmax is not None:
            for j in categorical:
                top = fmax[j]
                if top >= max_bins:
                    import warnings
                    warnings.warn(
                        f"categorical feature {j} has {int(top) + 1} codes but "
                        f"maxBin={max_bins}; codes >= {max_bins} are clipped "
                        f"into one bin (raise maxBin to keep them distinct)")
        missing = np.zeros(f, bool)
        if use_missing and n_total and float_data and missing_any is not None:
            missing = np.asarray(missing_any, bool).copy()
            if categorical:
                missing[list(categorical)] = False  # cats bin by code
        if missing.any():
            mbbf = (np.asarray(max_bins_by_feature, np.int64).copy()
                    if max_bins_by_feature is not None
                    else np.zeros(f, np.int64))
            cap = np.where(mbbf > 0, np.minimum(mbbf, max_bins), max_bins)
            max_bins_by_feature = np.where(missing,
                                           np.maximum(cap - 1, 1), mbbf)
        return BinMapper(compute_bin_edges(sample, max_bins, sample_count,
                                           seed, max_bins_by_feature),
                         categorical, fmin, fmax, missing)

    def transform(self, X: np.ndarray) -> np.ndarray:
        out = apply_bins(X, self.edges)
        X = np.asarray(X)
        is_float = X.dtype.kind == "f"
        # the one-reduce probe makes the clean path (no NaN anywhere) skip
        # every per-column isnan scan below — at 4M x 28 those scans plus
        # the X[:, njs] fancy-index copy cost more than apply_bins itself
        any_nan = _has_any_nan(X) if is_float else False
        # ONE full-matrix isnan serves both branches on the (rare)
        # NaN-present path; the clean path skips every scan
        nanmask = np.isnan(X) if any_nan else None
        if self.missing.any() and is_float and any_nan:
            # shift value bins up by one on missing-capable features; NaN
            # takes the reserved bin 0
            mjs = np.nonzero(self.missing)[0]
            out[:, mjs] = np.where(nanmask[:, mjs], 0, out[:, mjs] + 1)
        elif self.missing.any():
            out[:, self.missing] += 1   # NaN-free: pure shift
        no_miss = ~self.missing
        if no_miss.any() and is_float and any_nan:
            # NaN on a feature with no training missing = upstream
            # MissingType::None: treated as the value 0.0
            for j in np.nonzero(no_miss & nanmask.any(axis=0))[0]:
                j = int(j)
                out[nanmask[:, j], j] = int(np.searchsorted(
                    self.edges[j], 0.0, side="left"))
        if self.categorical:
            for j in self.categorical:
                col = np.nan_to_num(X[:, j], nan=0.0)
                out[:, j] = np.clip(col.astype(np.int64), 0,
                                    self.max_bins - 1).astype(out.dtype)
        return out

    def threshold_value(self, feature: int, bin_id: int) -> float:
        """Real-valued threshold for 'bin <= bin_id' splits (for model export:
        LightGBM text-format `threshold` entries). On missing-capable
        features bin 0 is the reserved missing bin, so value bin b maps to
        edge b-1."""
        b = int(bin_id)
        if self.missing[feature]:
            b -= 1
        b = int(np.clip(b, 0, self.edges.shape[1] - 1))
        v = self.edges[feature, b]
        if not np.isfinite(v):
            finite = self.edges[feature][np.isfinite(self.edges[feature])]
            v = finite[-1] if finite.size else 0.0
        return float(v)
