"""Pallas TPU kernels for the GBDT hot path.

The all-slots histogram kernel is the TPU replacement for LightGBM's C++
per-leaf histogram construction (driven from lightgbm/TrainUtils.scala:220-315
via `LGBM_BoosterUpdateOneIter`). Strategy (see ops/histogram.py): turn
scatter-add into a block-local one-hot × slot-expanded-gradient contraction
that runs on the MXU, accumulating the [F, B, L*C] histogram in VMEM across
sequential grid steps over row blocks.

Why Pallas beats the XLA one-hot formulation here: XLA materializes the
[chunk, F*B] one-hot operand in HBM before the matmul (matmul operands are
buffers, not fusion temporaries), so the XLA path moves ~2 * N * F * B bytes
of pure scaffolding per pass and is HBM-bound. This kernel generates both the
bin one-hot and the slot-expanded gradient matrix in VMEM, so HBM traffic is
just the [N, F] uint8 bins + [N, C] gradients — the kernel runs at the MXU
roofline instead.

Layout choices:
- grid = (feature_tiles, row_blocks) with row blocks minor, so each feature
  tile's [Ft, B, W] accumulator stays resident in VMEM across its row sweep
  (zero-at-first-visit / accumulate-afterwards revisiting pattern);
- output width W = num_slots * C (≈ 93 for 31 leaves) sits on lanes — most of
  one 128-wide MXU tile;
- when B < 128, feature pairs are packed into one [T, 2B] one-hot so the dot's
  M dimension fills the MXU's 128 sublanes;
- bf16 one-hot / gradient operands (exact for the 0/1 side), f32 accumulation.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _hist_slots_kernel(bins_ref, slot_ref, gh_ref, out_ref, *,
                       num_bins: int, num_slots: int, channels: int,
                       pack: int, op_dtype):
    @pl.when(pl.program_id(1) == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    bins = bins_ref[...]            # [T, Ft] int32
    slot = slot_ref[...]            # [T, 1] int32
    gh = gh_ref[...]                # [T, C] f32
    t, ft = bins.shape
    w = num_slots * channels

    # slot-expanded gradient matrix ghw[t, l*C + c] = gh[t, c] * 1[slot_t == l]
    w_iota = jax.lax.broadcasted_iota(jnp.int32, (t, w), 1)
    ghw = jnp.zeros((t, w), jnp.float32)
    for c in range(channels):
        ghw = ghw + jnp.where(w_iota % channels == c, gh[:, c][:, None], 0.0)
    ghw = jnp.where(slot == w_iota // channels, ghw, 0.0)
    ghw = ghw.astype(op_dtype)

    bin_iota = jax.lax.broadcasted_iota(jnp.int32, (t, num_bins), 1)
    for f0 in range(0, ft, pack):
        oh = jnp.concatenate(
            [(bins[:, f0 + p][:, None] == bin_iota) for p in range(pack)],
            axis=1).astype(op_dtype)                           # [T, pack*B]
        res = jax.lax.dot_general(
            oh, ghw, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            # f32 mode promises exact (multi-pass) MXU arithmetic — without
            # HIGHEST the MXU would round operands to bf16 passes anyway
            precision=(None if op_dtype == jnp.bfloat16
                       else jax.lax.Precision.HIGHEST))        # [pack*B, W]
        for p in range(pack):
            out_ref[f0 + p, :, :] += res[p * num_bins:(p + 1) * num_bins]


def hist_slots_pallas(binned: jax.Array, slot: jax.Array, gh: jax.Array,
                      num_slots: int, num_bins: int,
                      block_rows: int = 2048, feat_tile: int = 8,
                      dtype: str = "bf16",
                      interpret: bool | None = None) -> jax.Array:
    """All-slots Pallas histogram.

    binned [N, F] int, slot [N] int32, gh [N, C] f32
    -> [L, F, B, C] f32 where L = num_slots.

    dtype: MXU operand dtype — 'bf16' rounds gradients to ~3 decimal digits
    (one-hot side is exact either way, accumulation is always f32); 'f32'
    keeps exact operands for bit-reproducibility with the scatter oracle
    (near-tie split gains can flip under bf16).

    Rows are padded to a block multiple (padded rows carry zero gh); features
    are padded to the feature-tile multiple with bin id == num_bins, which
    matches no one-hot column and contributes nothing. On CPU backends runs in
    interpret mode so virtual-mesh tests exercise the same code path.
    """
    n, f = binned.shape
    c = gh.shape[1]
    w = num_slots * c
    if interpret is None:
        interpret = jax.default_backend() == "cpu"

    # pack features per dot while pack*B fits the MXU's 128 sublanes
    pack = max(1, min(feat_tile, 128 // num_bins))
    while feat_tile % pack:
        pack -= 1

    pad_n = (-n) % block_rows
    if pad_n:
        binned = jnp.pad(binned, ((0, pad_n), (0, 0)))
        slot = jnp.pad(slot, (0, pad_n))
        gh = jnp.pad(gh, ((0, pad_n), (0, 0)))
    pad_f = (-f) % feat_tile
    if pad_f:
        binned = jnp.pad(binned, ((0, 0), (0, pad_f)),
                         constant_values=num_bins)
    n_pad, f_pad = binned.shape
    grid = (f_pad // feat_tile, n_pad // block_rows)

    op_dtype = jnp.bfloat16 if dtype == "bf16" else jnp.float32
    out = pl.pallas_call(
        functools.partial(_hist_slots_kernel, num_bins=num_bins,
                          num_slots=num_slots, channels=c, pack=pack,
                          op_dtype=op_dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, feat_tile), lambda i, j: (j, i)),
            pl.BlockSpec((block_rows, 1), lambda i, j: (j, 0)),
            pl.BlockSpec((block_rows, c), lambda i, j: (j, 0)),
        ],
        out_specs=pl.BlockSpec((feat_tile, num_bins, w),
                               lambda i, j: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((f_pad, num_bins, w), jnp.float32),
        interpret=interpret,
    )(binned.astype(jnp.int32), slot.astype(jnp.int32)[:, None],
      gh.astype(jnp.float32))
    out = out[:f].reshape(f, num_bins, num_slots, c)
    return out.transpose(2, 0, 1, 3)               # [L, F, B, C]


def hist_pallas(binned: jax.Array, gh: jax.Array, num_bins: int,
                block_rows: int = 2048,
                interpret: bool | None = None) -> jax.Array:
    """Single-histogram Pallas build: [N,F] x [N,C] -> [F, B, C].

    Thin wrapper over the all-slots kernel with one slot; kept for the
    `build_histogram(..., method='pallas')` API surface and tests.
    """
    slot = jnp.zeros((binned.shape[0],), jnp.int32)
    out = hist_slots_pallas(binned, slot, gh, 1, num_bins,
                            block_rows=block_rows, interpret=interpret)
    return out[0]
