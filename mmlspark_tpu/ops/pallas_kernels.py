"""Pallas TPU kernels for the GBDT hot path.

The all-slots histogram kernel is the TPU replacement for LightGBM's C++
per-leaf histogram construction (driven from lightgbm/TrainUtils.scala:220-315
via `LGBM_BoosterUpdateOneIter`). Strategy (see ops/histogram.py): turn
scatter-add into a block-local one-hot × slot-expanded-gradient contraction
that runs on the MXU, accumulating the [F, B, L*C] histogram in VMEM across
sequential grid steps over row blocks.

Why Pallas beats the XLA one-hot formulation here: XLA materializes the
[chunk, F*B] one-hot operand in HBM before the matmul (matmul operands are
buffers, not fusion temporaries), so the XLA path moves ~2 * N * F * B bytes
of pure scaffolding per pass and is HBM-bound (~7 GB/pass at the bench shape).
This kernel generates both the bin one-hot and the slot-expanded gradient
matrix in VMEM, so HBM traffic is just the [F, N] bins + [8, N] gradient pack
— the kernel runs at the MXU roofline instead.

Layout (all blocks respect the TPU's (8, 128) f32 / (8, 128) int32 tiling —
the first version of this kernel used row-major [N, F] blocks with minor dims
28/1/3 wide and never lowered on real hardware):
- bins are TRANSPOSED to [F_pad, N_pad] int32: features on sublanes (padded to
  the 8-multiple feature tile), rows on lanes (padded to the 128-multiple row
  block). The transpose is loop-invariant — XLA's while-loop LICM hoists it
  out of the boosting loop, so it is paid once per fit, not per pass;
- gh channels and the slot id ride one [8, N_pad] f32 operand (rows 0..C-1 =
  grad/hess/mask, row C = slot id, rest zero) so the row-block slice is one
  aligned block;
- the one-hot is generated directly in [pack*B_pad, T] orientation and the
  slot-expanded gradients in [W_pad, T]; the dot contracts the row dimension
  of both (no transposes in VMEM);
- output width W = num_slots * C (≈ 93 for 31 leaves) is padded to 128 lanes
  — exactly one MXU tile; bins pad to B_pad = 8-multiple sublanes;
- when B_pad < 128, feature pairs are packed into one [pack*B_pad, T] one-hot
  so the dot's M dimension fills the MXU's 128 sublanes;
- bf16 one-hot / gradient operands (exact for the 0/1 side), f32 accumulation.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def _hist_slots_kernel(bins_ref, ghs_ref, out_ref, *,
                       b_pad: int, channels: int, pack: int, op_dtype):
    # bins_ref [FT, T] int8 or int32 (features x rows), ghs_ref [8, T] f32,
    # out_ref [FT, B_pad, W_pad] f32 — resident across the row-block sweep
    @pl.when(pl.program_id(1) == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    ft, t = bins_ref.shape
    w_pad = out_ref.shape[2]
    bins = bins_ref[...].astype(jnp.int32)

    # slot-expanded gradient matrix ghw[w, t] = gh[w % C, t] * 1[slot_t == w//C],
    # built WITHOUT integer div/mod: key_t = slot_t * C, then row w of channel
    # c matches where w_iota == key_t + c (measured equal-speed to the div/mod
    # form at the bench shape — the dot dominates — but fewer ops and no
    # multi-op integer division on the VPU). Rows w >= num_slots*C can never
    # equal key+c => they stay zero, which zero-pads the output width.
    key = ghs_ref[channels, :].astype(jnp.int32) * channels     # [T]
    w_iota = jax.lax.broadcasted_iota(jnp.int32, (w_pad, t), 0)
    ghw = jnp.zeros((w_pad, t), jnp.float32)
    for c in range(channels):
        ghw = jnp.where(w_iota == key[None, :] + c,
                        ghs_ref[c, :][None, :], ghw)
    ghw = ghw.astype(op_dtype)

    precision = (None if op_dtype == jnp.bfloat16
                 # f32 mode promises exact (multi-pass) MXU arithmetic —
                 # without HIGHEST the MXU would round to bf16 passes anyway
                 else jax.lax.Precision.HIGHEST)
    bin_iota = jax.lax.broadcasted_iota(jnp.int32, (b_pad, t), 0)
    for f0 in range(0, ft, pack):
        oh = jnp.concatenate(
            [(bins[f0 + p, :][None, :] == bin_iota) for p in range(pack)],
            axis=0).astype(op_dtype)                            # [pack*Bp, T]
        res = jax.lax.dot_general(
            oh, ghw, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=precision)                                # [pack*Bp, Wp]
        for p in range(pack):
            out_ref[f0 + p, :, :] += res[p * b_pad:(p + 1) * b_pad]


def _pallas_layout(n: int, f: int, c: int, num_slots: int, num_bins: int,
                   block_rows: int, feat_tile: int):
    """Static layout decisions shared by the kernel call and the
    `prepare_bins_t` pre-layout helper (so a caller can build the transposed
    bins operand ONCE per fit instead of once per pass)."""
    b_pad = _round_up(num_bins, 8)
    w_pad = _round_up(num_slots * c, 128)
    block_rows = _round_up(block_rows, 128)
    # int8 bins when ids (incl. the b_pad feature-padding sentinel) fit a
    # signed byte: 4x less HBM residency + bins read traffic than int32. The
    # int8 memory tile is (32, 128), so the feature tile widens to 32.
    bins_i8 = b_pad < 127
    if bins_i8:
        feat_tile = _round_up(min(max(feat_tile, 32), _round_up(f, 32)), 32)
    else:
        feat_tile = _round_up(min(feat_tile, _round_up(f, 8)), 8)
    # pack features per dot while pack*B_pad fills <= 256 MXU sublanes
    pack = max(1, min(feat_tile, 256 // b_pad))
    while feat_tile % pack:
        pack -= 1
    # clamp the row block so the kernel's VMEM temporaries (ghw + iotas + the
    # packed one-hot, all [*, T]) stay inside the scoped budget: wide B/L
    # configs (e.g. B=255, L=63) otherwise blow the stack allocation
    temp_bytes_per_row = 4 * (3 * w_pad + 2 * pack * b_pad + 2 * b_pad)
    budget = 24 << 20
    while block_rows > 128 and temp_bytes_per_row * block_rows > budget:
        block_rows = max(128, _round_up(block_rows // 2, 128))
    pad_n = (-n) % block_rows
    f_pad = _round_up(f, feat_tile)
    return b_pad, w_pad, block_rows, feat_tile, pack, bins_i8, pad_n, f_pad


def prepare_bins_t(binned: jax.Array, num_bins: int, num_slots: int,
                   channels: int = 3, block_rows: int = 4096,
                   feat_tile: int = 32) -> jax.Array:
    """Pre-layout the transposed bins operand [F_pad, N_pad] for
    `hist_slots_pallas(bins_t=...)`.

    The transpose+pad moves the whole dataset (~N*F bytes); it is invariant
    across every histogram pass of a fit, so callers on the hot path build it
    once (make_train_fn hoists it out of BOTH the boosting-iteration scan and
    the per-split fori_loop, where XLA's loop-invariant code motion is not
    guaranteed to reach across the nesting). Feature padding uses bin id ==
    B_pad, which matches no one-hot row; row padding is harmless because
    padded rows carry zero gh."""
    n, f = binned.shape
    (b_pad, _, _, _, _, bins_i8, pad_n, f_pad) = _pallas_layout(
        n, f, channels, num_slots, num_bins, block_rows, feat_tile)
    return jnp.pad(binned.astype(jnp.int8 if bins_i8 else jnp.int32).T,
                   ((0, f_pad - f), (0, pad_n)), constant_values=b_pad)


def hist_slots_pallas(binned: jax.Array, slot: jax.Array, gh: jax.Array,
                      num_slots: int, num_bins: int,
                      block_rows: int = 4096, feat_tile: int = 32,
                      dtype: str = "bf16",
                      interpret: bool | None = None,
                      bins_t: jax.Array | None = None) -> jax.Array:
    """All-slots Pallas histogram.

    binned [N, F] int, slot [N] int32, gh [N, C] f32
    -> [L, F, B, C] f32 where L = num_slots.

    dtype: MXU operand dtype — 'bf16' rounds gradients to ~3 decimal digits
    (one-hot side is exact either way, accumulation is always f32); 'f32'
    keeps exact operands for bit-reproducibility with the scatter oracle
    (near-tie split gains can flip under bf16).

    bins_t: optional pre-laid-out transposed bins from `prepare_bins_t`
    (same num_bins/block_rows/feat_tile) — hot-path callers pass it to pay
    the transpose once per fit instead of once per pass.

    Rows pad to the 128-multiple block (padded rows carry zero gh => zero
    contribution); features pad to the tile multiple with bin id == B_pad,
    which matches no one-hot row. On CPU backends runs in interpret mode so
    virtual-mesh tests exercise the same code path.
    """
    n, f = binned.shape
    c = gh.shape[1]
    assert c <= 7, "gh channel pack rides one 8-sublane operand"
    if interpret is None:
        interpret = jax.default_backend() == "cpu"

    (b_pad, w_pad, block_rows, feat_tile, pack, bins_i8, pad_n,
     f_pad) = _pallas_layout(n, f, c, num_slots, num_bins, block_rows,
                             feat_tile)
    if bins_t is None:
        bins_t = prepare_bins_t(binned, num_bins, num_slots, c, block_rows,
                                feat_tile)
    else:
        assert bins_t.shape == (f_pad, n + pad_n), (
            f"bins_t laid out as {bins_t.shape}, kernel expects "
            f"{(f_pad, n + pad_n)} — prepare_bins_t config mismatch")
    ghs = jnp.concatenate(
        [gh.astype(jnp.float32).T,
         slot.astype(jnp.float32)[None, :],
         jnp.zeros((8 - c - 1, n), jnp.float32)], axis=0)       # [8, N]
    if pad_n:
        ghs = jnp.pad(ghs, ((0, 0), (0, pad_n)))
    n_pad = n + pad_n
    grid = (f_pad // feat_tile, n_pad // block_rows)

    op_dtype = jnp.bfloat16 if dtype == "bf16" else jnp.float32
    out = pl.pallas_call(
        functools.partial(_hist_slots_kernel, b_pad=b_pad,
                          channels=c, pack=pack, op_dtype=op_dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((feat_tile, block_rows), lambda i, j: (i, j)),
            pl.BlockSpec((8, block_rows), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((feat_tile, b_pad, w_pad),
                               lambda i, j: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((f_pad, b_pad, w_pad), jnp.float32),
        compiler_params=None if interpret else pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary"),
            vmem_limit_bytes=100 << 20),
        interpret=interpret,
    )(bins_t, ghs)
    out = out[:f, :num_bins, :num_slots * c]
    return out.reshape(f, num_bins, num_slots, c).transpose(2, 0, 1, 3)


def hist_pallas(binned: jax.Array, gh: jax.Array, num_bins: int,
                block_rows: int = 4096, dtype: str = "bf16",
                interpret: bool | None = None) -> jax.Array:
    """Single-histogram Pallas build: [N,F] x [N,C] -> [F, B, C].

    Thin wrapper over the all-slots kernel with one slot; kept for the
    `build_histogram(..., method='pallas')` API surface and tests.
    """
    slot = jnp.zeros((binned.shape[0],), jnp.int32)
    out = hist_slots_pallas(binned, slot, gh, 1, num_bins,
                            block_rows=block_rows, dtype=dtype,
                            interpret=interpret)
    return out[0]
