"""Pallas TPU kernels for the GBDT hot path.

The histogram kernel is the TPU replacement for LightGBM's C++ per-leaf histogram
construction (driven from lightgbm/TrainUtils.scala:220-315 via
`LGBM_BoosterUpdateOneIter`). Strategy (see ops/histogram.py): turn scatter-add into a
block-local one-hot × gradient contraction that runs on the MXU, accumulating the
[F, B, C] histogram in VMEM across sequential grid steps over row blocks.

Layout choices:
- accumulator kept as [F, C, B] inside the kernel so the large B dimension sits on
  lanes (128-wide) and the tiny C=3 channel dim on sublanes; transposed on return.
- per-feature unrolled dots: [C, T] x [T, B] — M=C pads to 8 sublanes, N=B lanes,
  K=T contraction; f32 accumulation throughout (bf16 MXU passes flip near-tie splits).
- rows are chunked by the grid; the whole accumulator uses the standard
  zero-at-step-0 / accumulate-afterwards revisiting pattern (TPU grids are sequential).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _hist_kernel(bins_ref, gh_ref, out_ref, *, num_features: int,
                 num_bins: int):
    @pl.when(pl.program_id(0) == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    bins = bins_ref[...]            # [T, F] int32
    gh = gh_ref[...]                # [T, C] f32
    t = bins.shape[0]
    ght = gh.T                      # [C, T]
    bin_iota = jax.lax.broadcasted_iota(jnp.int32, (t, num_bins), 1)
    for f in range(num_features):   # static unroll; F is small
        onehot = (bins[:, f][:, None] == bin_iota).astype(jnp.float32)
        contrib = jax.lax.dot_general(
            ght, onehot, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)      # [C, B]
        out_ref[f, :, :] += contrib


def hist_pallas(binned: jax.Array, gh: jax.Array, num_bins: int,
                block_rows: int = 1024,
                interpret: bool | None = None) -> jax.Array:
    """Pallas histogram: binned [N, F] int, gh [N, C] f32 -> [F, B, C] f32.

    Pads rows to a block multiple (padded rows carry zero gh, contributing
    nothing). On CPU backends runs in interpret mode so virtual-mesh tests
    exercise the same code path.
    """
    n, f = binned.shape
    c = gh.shape[1]
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    pad = (-n) % block_rows
    if pad:
        binned = jnp.pad(binned, ((0, pad), (0, 0)))
        gh = jnp.pad(gh, ((0, pad), (0, 0)))
    n_pad = binned.shape[0]
    grid = (n_pad // block_rows,)

    out = pl.pallas_call(
        functools.partial(_hist_kernel, num_features=f, num_bins=num_bins),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, f), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, c), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((f, c, num_bins), lambda i: (0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((f, c, num_bins), jnp.float32),
        interpret=interpret,
    )(binned.astype(jnp.int32), gh.astype(jnp.float32))
    return out.transpose(0, 2, 1)   # [F, B, C]
