"""Mixture-of-Experts FFN with expert parallelism (ep) over a mesh axis.

No reference analogue — SURVEY.md §5 records that the reference has no
model-parallel taxonomy at all; this is part of the TPU-native distributed
story (tp/pp/dp/sp/ep) alongside ring/Ulysses sequence parallelism.

Design (Switch Transformer, arXiv:2101.03961, re-derived for shard_map):
- top-1 softmax routing; each token's output is its expert's FFN output
  scaled by the router probability (the prob keeps routing differentiable).
- fixed expert capacity C = ceil(tokens/E * capacity_factor): position
  within an expert's buffer comes from a cumsum over the token order;
  tokens past capacity are DROPPED (output 0 for that token — Switch
  semantics; ample capacity => no drops, pinned by tests).
- dispatch/combine are one-hot einsum contractions (MXU-friendly), not
  gather/scatter.
- expert parallelism: experts are sharded over `axis_name`; one
  all_to_all swaps the per-expert buffers [E, C, D] so each device holds
  ALL tokens routed to ITS local experts, the local expert FFNs run, and a
  second all_to_all sends results back to the tokens' home devices. With
  data (tokens) also sharded over the same axis this is the canonical
  ep x dp layout: routing is token-local, compute is expert-local, and the
  only cross-device traffic is the two all_to_alls.

Aux load-balancing loss (`aux_loss`): E * sum_e f_e * P_e (Switch eq. 4),
f_e = fraction of tokens dispatched to expert e, P_e = mean router prob —
minimized at uniform routing; add it to the task loss scaled by ~1e-2.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def init_moe_params(key, num_experts: int, d_model: int, d_ff: int):
    """Router + per-expert FFN stacks ([E, ...] leading expert axis)."""
    ks = jax.random.split(key, 3)
    s1 = np.sqrt(2.0 / (d_model + d_ff))
    return {
        "router": {"w": jax.random.normal(ks[0], (d_model, num_experts))
                   * np.sqrt(1.0 / d_model)},
        "ff1": {"w": jax.random.normal(ks[1], (num_experts, d_model, d_ff))
                * s1, "b": jnp.zeros((num_experts, d_ff))},
        "ff2": {"w": jax.random.normal(ks[2], (num_experts, d_ff, d_model))
                * s1, "b": jnp.zeros((num_experts, d_model))},
    }


def _route(params, x, num_experts: int, capacity: int):
    """Token routing -> (dispatch [T,E,C], combine [T,E,C], aux_loss).

    x: [T, D] flattened tokens. dispatch is 0/1; combine = dispatch *
    router prob. Tokens whose position within their expert's buffer
    exceeds C get an all-zero row (dropped)."""
    logits = x @ params["router"]["w"]                      # [T, E]
    probs = jax.nn.softmax(logits, axis=-1)
    top = jnp.argmax(probs, axis=-1)                        # [T]
    gate = jnp.take_along_axis(probs, top[:, None], axis=1)[:, 0]  # [T]
    onehot = jax.nn.one_hot(top, num_experts, dtype=x.dtype)      # [T, E]
    # position of each token within its expert's capacity buffer
    pos = (jnp.cumsum(onehot, axis=0) - onehot) * onehot          # [T, E]
    keep = onehot * (pos < capacity)                              # [T, E]
    pos_oh = jax.nn.one_hot(jnp.sum(pos, axis=-1).astype(jnp.int32),
                            capacity, dtype=x.dtype)              # [T, C]
    dispatch = keep[:, :, None] * pos_oh[:, None, :]              # [T, E, C]
    combine = dispatch * gate[:, None, None]
    # Switch aux loss: E * sum_e (fraction dispatched)*(mean prob)
    f = jnp.mean(onehot, axis=0)
    p = jnp.mean(probs, axis=0)
    aux = num_experts * jnp.sum(f * p)
    return dispatch, combine, aux


def _expert_ffn(ff1, ff2, buf):
    """buf: [E, C, D] -> per-expert FFN, batched over the expert axis."""
    h = jnp.einsum("ecd,edf->ecf", buf, ff1["w"]) + ff1["b"][:, None, :]
    return (jnp.einsum("ecf,efd->ecd", jax.nn.gelu(h), ff2["w"])
            + ff2["b"][:, None, :])


def moe_ffn(params, x: jax.Array, num_experts: int,
            capacity_factor: float = 2.0,
            axis_name: Optional[str] = None
            ) -> Tuple[jax.Array, jax.Array]:
    """MoE FFN. x: [B, S, D] (shard-local when `axis_name` is set inside
    shard_map). Returns (y [B,S,D], aux_loss scalar — psum-averaged over
    the axis when sharded).

    Sharded contract: experts AND tokens are sharded over `axis_name`
    (P devices): this device holds experts [idx*E_loc, (idx+1)*E_loc) and
    num_experts = P * E_loc must divide by P. Capacity is per
    (device, expert) pair, computed from local tokens.
    """
    b, s, d = x.shape
    t = b * s
    xt = x.reshape(t, d)
    if axis_name is None:
        cap = int(np.ceil(t / num_experts * capacity_factor))
        dispatch, combine, aux = _route(params, xt, num_experts, cap)
        buf = jnp.einsum("tec,td->ecd", dispatch, xt)       # [E, C, D]
        out = _expert_ffn(params["ff1"], params["ff2"], buf)
        y = jnp.einsum("tec,ecd->td", combine, out)
        return y.reshape(b, s, d), aux

    p_count = jax.lax.psum(1, axis_name)
    if num_experts % p_count:
        raise ValueError(
            f"expert parallelism needs num_experts ({num_experts}) "
            f"divisible by the '{axis_name}' axis size ({p_count})")
    e_loc = num_experts // p_count
    cap = int(np.ceil(t / num_experts * capacity_factor))
    dispatch, combine, aux = _route(params, xt, num_experts, cap)
    buf = jnp.einsum("tec,td->ecd", dispatch, xt)           # [E, C, D]
    # all_to_all: [E=P*e_loc, C, D] -> [P*e_loc, C, D] where the leading
    # axis becomes (home peer, local expert): this device now holds every
    # peer's tokens for its OWN e_loc experts
    buf = buf.reshape(p_count, e_loc, cap, d)
    buf = jax.lax.all_to_all(buf, axis_name, split_axis=0, concat_axis=0,
                             tiled=True)                    # [P*e_loc, C, D]
    buf = buf.reshape(p_count, e_loc, cap, d).transpose(1, 0, 2, 3)
    buf = buf.reshape(e_loc, p_count * cap, d)              # [e_loc, P*C, D]
    # local experts: params sharded — this device's slice is [e_loc, ...]
    out = _expert_ffn(params["ff1"], params["ff2"], buf)
    # reverse the shuffle: back to [E, C, D] with tokens on home devices
    out = out.reshape(e_loc, p_count, cap, d).transpose(1, 0, 2, 3)
    out = out.reshape(p_count * e_loc, cap, d)
    out = jax.lax.all_to_all(out, axis_name, split_axis=0, concat_axis=0,
                             tiled=True)
    y = jnp.einsum("tec,ecd->td", combine, out)
    return y.reshape(b, s, d), jax.lax.pmean(aux, axis_name)


def shard_moe_params(params, rank: int, p_count: int):
    """Slice the expert stacks to rank's local experts; router replicated."""
    e = params["ff1"]["w"].shape[0]
    e_loc = e // p_count
    sl = slice(rank * e_loc, (rank + 1) * e_loc)
    return {
        "router": params["router"],
        "ff1": {"w": params["ff1"]["w"][sl], "b": params["ff1"]["b"][sl]},
        "ff2": {"w": params["ff2"]["w"][sl], "b": params["ff2"]["b"][sl]},
    }
