"""Gradient/hessian histogram construction — the GBDT hot kernel.

Reference analogue: the histogram build inside `LGBM_BoosterUpdateOneIter`
(lightgbm/TrainUtils.scala:220-315 drives it; the C++ core builds per-leaf per-feature
histograms and allreduces them over its socket ring in `data_parallel` mode,
lightgbm/LightGBMParams.scala:13-18).

TPU-first design: scatter-add is hostile to the VPU, so the histogram is computed as a
chunked one-hot contraction that lands on the MXU:

    hist[f, b, c] = sum_n onehot(bin[n, f] == b) * gh[n, c]

with rows chunked by `lax.scan` so the one-hot block stays VMEM-sized. `gh` packs
(grad, hess, count-mask) as 3 channels so one contraction produces all three histograms.
A Pallas kernel variant (mmlspark_tpu.ops.pallas_kernels) implements the same contraction
with explicit VMEM accumulation; `scatter` mode (jnp .at[].add) is kept as a cross-check
oracle for tests.

Distribution: callers wrap this in shard_map and `psum` the result over the data axis —
the ICI replacement for LightGBM's `LGBM_NetworkInit` TCP ring (TrainUtils.scala:496-512).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp


def _pad_rows(binned, gh, chunk):
    n = binned.shape[0]
    pad = (-n) % chunk
    if pad:
        binned = jnp.pad(binned, ((0, pad), (0, 0)))
        gh = jnp.pad(gh, ((0, pad), (0, 0)))
    return binned, gh


def hist_onehot(binned: jax.Array, gh: jax.Array, num_bins: int,
                chunk: int = 512, dtype: str = "f32") -> jax.Array:
    """One-hot/MXU histogram. binned [N,F] int, gh [N,C] float -> [F, B, C] float32.

    dtype: 'f32' runs the contraction at Precision.HIGHEST (exact but 3-6 MXU
    passes); 'bf16' casts operands to bfloat16 with f32 accumulation — the one-hot
    side is exact in bf16 (0/1), gradients round to ~3 decimal digits, which is
    statistically immaterial for million-row histogram sums and ~3-6x faster.
    """
    f = binned.shape[1]
    c = gh.shape[1]
    binned, gh = _pad_rows(binned, gh, chunk)
    n_chunks = binned.shape[0] // chunk
    bins_c = binned.reshape(n_chunks, chunk, f)
    gh_c = gh.reshape(n_chunks, chunk, c)

    bin_iota = jnp.arange(num_bins, dtype=jnp.int32)
    op_dtype = jnp.bfloat16 if dtype == "bf16" else jnp.float32
    precision = (None if dtype == "bf16" else jax.lax.Precision.HIGHEST)

    def body(acc, xs):
        bins_t, gh_t = xs
        onehot = (bins_t[:, :, None] == bin_iota[None, None, :])
        onehot = onehot.astype(op_dtype).reshape(chunk, f * num_bins)
        acc = acc + jnp.dot(onehot.T, gh_t.astype(op_dtype),
                            preferred_element_type=jnp.float32,
                            precision=precision)
        return acc, None

    acc0 = jnp.zeros((f * num_bins, c), jnp.float32)
    acc, _ = jax.lax.scan(body, acc0, (bins_c, gh_c))
    return acc.reshape(f, num_bins, c)


def hist_scatter(binned: jax.Array, gh: jax.Array, num_bins: int) -> jax.Array:
    """Scatter-add histogram (XLA scatter); test oracle + small-data path."""
    n, f = binned.shape
    c = gh.shape[1]
    feat_iota = jnp.arange(f, dtype=jnp.int32)
    flat_idx = (feat_iota[None, :] * num_bins + binned.astype(jnp.int32)).reshape(-1)
    contrib = jnp.broadcast_to(gh[:, None, :].astype(jnp.float32),
                               (n, f, c)).reshape(-1, c)
    out = jnp.zeros((f * num_bins, c), jnp.float32).at[flat_idx].add(contrib)
    return out.reshape(f, num_bins, c)


def hist_slots_onehot(binned: jax.Array, slot: jax.Array, gh: jax.Array,
                      num_slots: int, num_bins: int, chunk: int = 8192,
                      dtype: str = "bf16") -> jax.Array:
    """All-slots MXU histogram: one pass builds EVERY leaf's histogram.

    binned [N,F] int, slot [N] int32 (leaf slot of each row), gh [N,C] float
    -> [L, F, B, C] float32.

    This is the hot kernel of the whole framework. The per-leaf formulation
    (mask gh to one leaf, contract to [F*B, C]) leaves the MXU ~C/128 utilized
    because the matmul's output width is C=3; expanding the channel dim to
    (slot × channel) makes the output width L*C (≈ 93 for num_leaves=31, i.e.
    most of one 128-wide MXU tile) at identical pass count — a ~L× speedup
    measured on v5e. Rows carry their slot id; padded rows must carry gh == 0.

        hist[l, f, b, c] = sum_n 1[slot_n == l] * 1[bin_nf == b] * gh[n, c]
    """
    n, f = binned.shape
    c = gh.shape[1]
    w = num_slots * c
    # cap the materialized [chunk, F*B] one-hot operand at ~256 MB so wide
    # problems (large F*B) can't OOM; rounding down to a power of two keeps
    # padding predictable
    budget = 256 << 20
    max_chunk = max(budget // (2 * f * num_bins), 128)
    if chunk > max_chunk:
        chunk = 1 << (max_chunk.bit_length() - 1)
    pad = (-n) % chunk
    if pad:
        binned = jnp.pad(binned, ((0, pad), (0, 0)))
        slot = jnp.pad(slot, (0, pad))
        gh = jnp.pad(gh, ((0, pad), (0, 0)))
    n_chunks = binned.shape[0] // chunk
    bins_c = binned.reshape(n_chunks, chunk, f)
    slot_c = slot.reshape(n_chunks, chunk)
    gh_c = gh.reshape(n_chunks, chunk, c)

    bin_iota = jnp.arange(num_bins, dtype=jnp.int32)
    slot_iota = jnp.arange(num_slots, dtype=jnp.int32)
    op_dtype = jnp.bfloat16 if dtype == "bf16" else jnp.float32
    precision = (None if dtype == "bf16" else jax.lax.Precision.HIGHEST)

    def body(acc, xs):
        bins_t, slot_t, gh_t = xs
        onehot = (bins_t[:, :, None] == bin_iota[None, None, :])
        onehot = onehot.astype(op_dtype).reshape(chunk, f * num_bins)
        slot_oh = (slot_t[:, None] == slot_iota[None, :]).astype(op_dtype)
        ghw = (slot_oh[:, :, None] * gh_t[:, None, :].astype(op_dtype))
        ghw = ghw.reshape(chunk, w)
        acc = acc + jnp.dot(onehot.T, ghw,
                            preferred_element_type=jnp.float32,
                            precision=precision)
        return acc, None

    acc0 = jnp.zeros((f * num_bins, w), jnp.float32)
    acc, _ = jax.lax.scan(body, acc0, (bins_c, slot_c, gh_c))
    return acc.reshape(f, num_bins, num_slots, c).transpose(2, 0, 1, 3)


def hist_slots_scatter(binned: jax.Array, slot: jax.Array, gh: jax.Array,
                       num_slots: int, num_bins: int) -> jax.Array:
    """All-slots scatter-add histogram (CPU/test path). -> [L, F, B, C]."""
    n, f = binned.shape
    c = gh.shape[1]
    feat_iota = jnp.arange(f, dtype=jnp.int32)
    flat_idx = (slot.astype(jnp.int32)[:, None] * (f * num_bins)
                + feat_iota[None, :] * num_bins
                + binned.astype(jnp.int32)).reshape(-1)
    contrib = jnp.broadcast_to(gh[:, None, :].astype(jnp.float32),
                               (n, f, c)).reshape(-1, c)
    out = jnp.zeros((num_slots * f * num_bins, c), jnp.float32)
    out = out.at[flat_idx].add(contrib)
    return out.reshape(num_slots, f, num_bins, c)


def hist_slots(binned: jax.Array, slot: jax.Array, gh: jax.Array,
               num_slots: int, num_bins: int, method: str = "auto",
               chunk: int = 8192, dtype: str = "bf16",
               bins_t: Optional[jax.Array] = None) -> jax.Array:
    """Dispatch the all-slots histogram build. gh channels: [grad, hess, mask].

    bins_t: optional pre-laid-out transposed bins (pallas_kernels.
    prepare_bins_t) — used by the pallas path only, so hot loops pay the
    [N, F] transpose once per fit instead of once per pass."""
    method = resolve_hist_method(method)
    if method == "onehot":
        return hist_slots_onehot(binned, slot, gh, num_slots, num_bins,
                                 chunk, dtype)
    if method == "scatter":
        return hist_slots_scatter(binned, slot, gh, num_slots, num_bins)
    if method == "pallas":
        from .pallas_kernels import hist_slots_pallas
        return hist_slots_pallas(binned, slot, gh, num_slots, num_bins,
                                 block_rows=chunk, dtype=dtype, bins_t=bins_t)
    raise ValueError(f"unknown histogram method {method!r}")


_PALLAS_OK: Optional[bool] = None


def _pallas_lowers() -> bool:
    """One-time probe: compile+run a tiny all-slots Pallas histogram on the
    live backend. Guards the 'auto' default — a Mosaic lowering change (or a
    TPU generation with different tiling rules) degrades auto to the XLA
    one-hot path instead of failing every fit."""
    global _PALLAS_OK
    if _PALLAS_OK is None:
        try:
            from .pallas_kernels import hist_slots_pallas
            import numpy as np
            out = hist_slots_pallas(
                jnp.asarray(np.zeros((8, 2), np.uint8)),
                jnp.zeros((8,), jnp.int32),
                jnp.ones((8, 3), jnp.float32), 3, 4, interpret=False)
            jax.block_until_ready(out)
            _PALLAS_OK = True
        except Exception:  # noqa: BLE001 - any lowering failure disables it
            _PALLAS_OK = False
    return _PALLAS_OK


def resolve_hist_method(method: str) -> str:
    """'auto' picks per backend: on TPU the Pallas kernel is the measured
    winner (2.9 vs 4.1 ms/pass at the bench shape — docs/KERNELS.md), with a
    one-time lowering probe falling back to the XLA one-hot contraction;
    other accelerators get the one-hot path; on CPU (tests, virtual meshes)
    XLA's native scatter-add is far cheaper (~27x)."""
    if method == "auto":
        backend = jax.default_backend()
        if backend == "cpu":
            return "scatter"
        return "pallas" if backend == "tpu" and _pallas_lowers() else "onehot"
    return method


def build_histogram(binned: jax.Array, gh: jax.Array, num_bins: int,
                    method: str = "auto", chunk: int = 512,
                    dtype: str = "bf16") -> jax.Array:
    """Dispatch histogram build. gh channels: [grad, hess, mask]."""
    method = resolve_hist_method(method)
    if method == "onehot":
        return hist_onehot(binned, gh, num_bins, chunk, dtype)
    if method == "scatter":
        return hist_scatter(binned, gh, num_bins)
    if method == "pallas":
        from .pallas_kernels import hist_pallas
        return hist_pallas(binned, gh, num_bins, dtype=dtype)
    raise ValueError(f"unknown histogram method {method!r}")
