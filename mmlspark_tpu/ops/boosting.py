"""Leaf-wise GBDT tree building + boosting loop, fully jit-compiled.

Reference analogue: the per-iteration native training loop `trainCore`
(lightgbm/TrainUtils.scala:220-315) and everything `LGBM_BoosterUpdateOneIter` does inside
C++: per-leaf histogram build, split-gain scan, leaf-wise split selection, row partition
update. Distribution follows LightGBM `data_parallel` (lightgbm/LightGBMParams.scala:13-18):
rows are sharded, local histograms are summed across workers — here a `jax.lax.psum` over a
mesh axis (ICI) instead of the C++ socket ring (`LGBM_NetworkInit`,
TrainUtils.scala:496-512).

TPU-first structure:
- the whole multi-iteration training run is ONE jit program: `lax.scan` over boosting
  iterations, `lax.fori_loop` over the (num_leaves-1) leaf-wise splits of each tree;
- the binned [N, F] uint8 matrix stays resident in HBM; histograms come from the
  MXU-friendly one-hot contraction (ops/histogram.py);
- sibling histograms use the subtraction trick (right child built, left = parent - right)
  — SURVEY.md §7 "hard parts";
- validation rows ride along with zero histogram weight (they receive leaf assignments,
  contribute nothing to splits) — replacing the reference's separate valid dataset plumbing
  (LightGBMBase.scala:214-219).
"""

from __future__ import annotations

import functools
from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .histogram import hist_slots, resolve_hist_method
from .objectives import Objective, get_objective

_NEG_INF = -1e30
_MIN_GAIN_EPS = 1e-10


class GBDTConfig(NamedTuple):
    """Static (trace-time) boosting configuration. Mirrors the LightGBM param surface
    (lightgbm/LightGBMParams.scala): names keep their LightGBM meanings."""
    num_leaves: int = 31
    num_iterations: int = 100
    learning_rate: float = 0.1
    max_bins: int = 255
    max_depth: int = -1  # <=0: unlimited
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    min_gain_to_split: float = 0.0
    bagging_fraction: float = 1.0
    bagging_freq: int = 0
    # class-specific bagging (binary): keep probability per class; < 0 means
    # follow bagging_fraction (posBaggingFraction/negBaggingFraction)
    pos_bagging_fraction: float = -1.0
    neg_bagging_fraction: float = -1.0
    feature_fraction: float = 1.0
    max_delta_step: float = 0.0  # >0: cap |leaf output| (maxDeltaStep)
    num_class: int = 1
    objective: str = "regression"
    alpha: float = 0.9           # quantile/huber alpha
    tweedie_variance_power: float = 1.5
    boost_from_average: bool = True
    top_rate: float = 0.2       # goss
    other_rate: float = 0.1     # goss
    boosting_type: str = "gbdt"  # gbdt | goss | rf | dart
    drop_rate: float = 0.1      # dart (LightGBM drop_rate)
    skip_drop: float = 0.5      # dart: P(no dropout this iteration)
    has_init_score: bool = False  # row init margins supplied (disables boost_from_average)
    max_position: int = 20   # lambdarank NDCG truncation (maxPosition)
    eval_at: int = 0         # NDCG@k for the eval metric (evalAt[0]; 0 = use
                             # max_position)
    sigma: float = 1.0       # lambdarank sigmoid steepness
    max_label: int = 31      # lambdarank max relevance label (label_gain table size)
    label_gain_table: Optional[Tuple[float, ...]] = None  # custom labelGain
    # categorical features (LightGBM one-vs-rest sorted-subset splits;
    # categoricalSlotIndexes in LightGBMParams.scala)
    categorical_features: Tuple[int, ...] = ()
    # numeric features whose bin 0 is a RESERVED missing bin (NaN observed at
    # fit — BinMapper.missing): the split scan evaluates BOTH default
    # directions for these (upstream use_missing semantics) and the learned
    # direction lands in Tree.split_default_left
    missing_features: Tuple[int, ...] = ()
    cat_smooth: float = 10.0          # denominator smoothing for g/h sort key
    max_cat_threshold: int = 32       # max categories on the left side
    seed: int = 0
    bagging_seed: int = 3
    hist_method: str = "auto"
    hist_chunk: int = 512
    hist_dtype: str = "bf16"  # MXU operand dtype for the one-hot contraction
    axis_name: Optional[str] = None  # shard_map data axis; None = single shard
    # tree learner: "data_parallel" allreduces full [L,F,B,3] histograms;
    # "voting_parallel" (LightGBMParams.scala:13-27) allreduces only the
    # top_k globally-voted features' histograms per slot — the cross-pod/DCN
    # bandwidth mode (traffic cut by F/top_k at mild split-quality cost)
    tree_learner: str = "data_parallel"
    top_k: int = 20
    # histogram refresh policy (TPU-native optimization, no reference
    # analogue): "eager" = exact LightGBM leaf-wise, one all-slots pass per
    # split; "lazy" = split best-first among leaves whose histograms are
    # current and re-histogram only when that pool dries — ~one pass per tree
    # LEVEL instead of per split (~log2(L) vs L-1 for balanced trees), at the
    # cost that a new child enters the candidate pool one refresh late.
    # Distributed caveat: lazy allreduces the FULL [L,F,B,3] histogram per
    # refresh (~L*log2(L)/(L-1) ≈ 6x eager's per-split [F,B,3] traffic at 31
    # leaves) — it trades interconnect for compute, so prefer eager on
    # bandwidth-bound multi-host meshes
    split_refresh: str = "eager"
    # per-split histogram construction (eager refresh only). "full" = one
    # all-slots pass over every row per split; "compact" = rows are kept
    # PARTITIONED by leaf (a permutation with one contiguous segment per
    # slot, the TPU equivalent of LightGBM's DataPartition), and each split
    # histograms only the parent's segment, padded to a power-of-two bucket
    # under lax.switch so every shape is static. One masked 2-slot pass
    # yields BOTH children exactly (no sibling-subtraction cancellation), so
    # per-tree histogram work drops from (L-1) full passes to ~sum of parent
    # segment sizes (~= N * avg depth, the same work model as upstream's
    # smaller-child trick) while split selection stays exact leaf-wise.
    split_scan: str = "full"
    # batched leaf-wise growth (eager/full only): apply the top
    # `splits_per_pass` best splits — necessarily on DISTINCT leaves, so
    # their gains are mutually independent — then refresh all children with
    # ONE all-slots pass. 1 = strict leaf-wise (exact LightGBM order); k>1
    # cuts histogram passes per tree from L-1 to ~(L-1)/k + ramp at the cost
    # that children created in a pass cannot compete for splits until the
    # next pass (a k-step lookahead restriction — gains used are never
    # stale, unlike split_refresh='lazy'). TPU-native optimization.
    splits_per_pass: int = 1
    # evaluation metric (LightGBMParams.scala:310-342 `metric`): "" = the
    # objective's default. Canonical names: l1 l2 rmse mape auc
    # binary_logloss binary_error multi_logloss multi_error ndcg. Metrics
    # where higher is better (auc, ndcg) are reported as 1 - value so the
    # early-stopping machinery is uniformly lower-is-better.
    eval_metric: str = ""


class HParams(NamedTuple):
    """CONTINUOUS hyperparameters as traced jnp scalars — unlike GBDTConfig
    (static, baked into the compiled program), these are runtime inputs, so
    `jax.vmap` over an HParams batch trains MANY configurations in ONE
    compiled program (the TPU-first realization of the reference's
    `Estimator.fit(dataset, paramMaps)` surface and TuneHyperparameters'
    thread-pool, automl/TuneHyperparameters.scala:37-203). Defaults are
    taken from the config by `HParams.from_config`."""
    learning_rate: jax.Array
    lambda_l1: jax.Array
    lambda_l2: jax.Array
    min_gain_to_split: jax.Array
    min_sum_hessian_in_leaf: jax.Array
    min_data_in_leaf: jax.Array
    bagging_fraction: jax.Array

    @staticmethod
    def from_config(cfg: "GBDTConfig") -> "HParams":
        lr = 1.0 if cfg.boosting_type == "rf" else cfg.learning_rate
        return HParams(*[jnp.float32(v) for v in (
            lr, cfg.lambda_l1, cfg.lambda_l2, cfg.min_gain_to_split,
            cfg.min_sum_hessian_in_leaf, float(cfg.min_data_in_leaf),
            cfg.bagging_fraction)])


class Tree(NamedTuple):
    """One fitted tree in slot representation (see build_tree). Arrays may carry leading
    batch dims for [iteration] or [iteration, class] stacking."""
    split_slot: jax.Array   # [L-1] int32 — slot that was split at step s
    split_feat: jax.Array   # [L-1] int32
    split_bin: jax.Array    # [L-1] int32 — go left iff bin <= split_bin
    split_valid: jax.Array  # [L-1] bool
    split_gain: jax.Array   # [L-1] float32
    leaf_value: jax.Array   # [L] float32 (already includes learning-rate shrinkage)
    leaf_count: jax.Array   # [L] float32 — training rows per leaf (global across
                            # shards; basis for SHAP covers and leaf_count export)
    split_is_cat: jax.Array  # [L-1] bool — categorical (bin-subset) split
    split_mask: jax.Array    # [L-1, Bm] bool — bins going LEFT for categorical
                             # splits (Bm = max_bins when categoricals are
                             # configured, else 1 to keep the model tiny)
    split_default_left: jax.Array  # [L-1] bool — missing goes left (LightGBM
                                   # decision_type bit 1)
    split_missing_type: jax.Array  # [L-1] int32 — 0 None, 1 Zero, 2 NaN
                                   # (LightGBM decision_type bits 2-3)


def _split_score(g, h, lambda_l1, lambda_l2):
    """LightGBM leaf objective: ThresholdL1(g)^2 / (h + l2)."""
    t = jnp.sign(g) * jnp.maximum(jnp.abs(g) - lambda_l1, 0.0)
    return t * t / (h + lambda_l2 + 1e-15)


def _leaf_output(g, h, lambda_l1, lambda_l2):
    t = jnp.sign(g) * jnp.maximum(jnp.abs(g) - lambda_l1, 0.0)
    return -t / (h + lambda_l2 + 1e-15)


def _cat_ratio(h3, cfg: GBDTConfig):
    """Sort key for categorical subset splits: g/(h + cat_smooth), empty bins
    pushed to the end. h3: [..., B, 3]. Single source of truth — the split scan
    and the mask reconstruction in build_tree MUST order bins identically."""
    ratio = h3[..., 0] / (h3[..., 1] + cfg.cat_smooth)
    return jnp.where(h3[..., 2] > 0, ratio, -jnp.inf)


def _cat_sort_order(hists, cfg: GBDTConfig):
    """Per-(slot, feature) bin permutation for categorical splits: descending
    g/(h + cat_smooth) — LightGBM's sorted one-vs-rest subset search."""
    return jnp.argsort(-_cat_ratio(hists, cfg), axis=2)           # [L,F,B]


def _miss_mask_global(f: int, miss) -> jax.Array:
    """[F] bool mask of missing-capable features (single construction shared
    by build_tree's row routing and the gain table's default mask)."""
    return jnp.zeros((f,), bool).at[jnp.asarray(miss)].set(True)


def _cat_mask_global(f: int, cat) -> jax.Array:
    """[F] bool mask of categorical features (same sharing contract as
    _miss_mask_global)."""
    return jnp.zeros((f,), bool).at[jnp.asarray(cat)].set(True)


def _split_gain_table(hists, sums, cfg: GBDTConfig, feature_mask,
                      hp: "HParams", miss_mask=None, cat_mask=None):
    """Masked split-gain table over [L, F, B, 3] histograms -> [L, F, B, 2].

    The last axis is the missing-value default direction: 0 = missing goes
    LEFT (the only direction for features without a reserved missing bin),
    1 = missing goes RIGHT (evaluated only for cfg.missing_features, whose
    bin 0 holds the missing stats — upstream use_missing both-direction
    scan). feature_mask may be [F] (shared across slots) or [L, F]
    (per-slot, used by the voting-parallel learner). miss_mask overrides
    the cfg-derived missing-feature mask when the feature axis is NOT the
    global one (the voting learner passes is_miss[sel], [L, k], aligned
    with its per-slot voted features). Invalid cells (min_data /
    min_hessian / masked features) are _NEG_INF. Reference semantics:
    LightGBM FeatureHistogram::FindBestThreshold(Categorical), driven from
    TrainUtils.scala:220-315.
    """
    l, f, b, _ = hists.shape
    cat = cfg.categorical_features
    miss = cfg.missing_features
    if cat:
        # cat_mask overrides the cfg-derived global mask when the feature
        # axis is voted ([L, k] per-slot columns — same contract as
        # miss_mask)
        if cat_mask is None:
            cat_mask = _cat_mask_global(f, cat)
        ic = (cat_mask[None, :, None] if cat_mask.ndim == 1
              else cat_mask[:, :, None])
        order = _cat_sort_order(hists, cfg)
        sorted_h = jnp.take_along_axis(hists, order[..., None], axis=2)
        scan_h = jnp.where(ic[..., None], sorted_h, hists)
    else:
        ic = None
        scan_h = hists

    cum = jnp.cumsum(scan_h, axis=2)             # [L,F,B,3] left stats for bin<=b
    tot = sums[:, None, None, :]                 # [L,1,1,3]
    left_g, left_h, left_n = cum[..., 0], cum[..., 1], cum[..., 2]
    tot_g, tot_h, tot_n = tot[..., 0], tot[..., 1], tot[..., 2]
    right_g, right_h, right_n = tot_g - left_g, tot_h - left_h, tot_n - left_n

    def gain_of(lg, lh):
        return (_split_score(lg, lh, hp.lambda_l1, hp.lambda_l2)
                + _split_score(tot_g - lg, tot_h - lh,
                               hp.lambda_l1, hp.lambda_l2)
                - _split_score(tot_g, tot_h, hp.lambda_l1, hp.lambda_l2))

    gain0 = gain_of(left_g, left_h)

    fm = (feature_mask[None, :, None] if feature_mask.ndim == 1
          else feature_mask[:, :, None])
    min_data = jnp.maximum(hp.min_data_in_leaf, 1.0)

    def ok_of(ln, lh, rn, rh):
        return ((ln >= min_data) & (rn >= min_data)
                & (lh >= hp.min_sum_hessian_in_leaf)
                & (rh >= hp.min_sum_hessian_in_leaf) & fm)

    ok0 = ok_of(left_n, left_h, right_n, right_h)
    if cat:
        # categorical prefixes are capped at max_cat_threshold categories
        prefix_len = jnp.arange(b)[None, None, :] + 1
        ok0 = ok0 & (~ic | (prefix_len <= cfg.max_cat_threshold))
    if miss:
        if miss_mask is None:
            miss_mask = _miss_mask_global(f, miss)
        im = (miss_mask[None, :, None] if miss_mask.ndim == 1
              else miss_mask[:, :, None])
        bin_ge1 = (jnp.arange(b) >= 1)[None, None, :]
        # bin 0 is the reserved missing bin: value splits start at b >= 1 (a
        # missing-only left side is not expressible as a value threshold)
        ok0 = ok0 & (~im | bin_ge1)
        # direction 1: missing stats (bin 0) move to the right side
        h0 = hists[:, :, 0, :]                           # [L,F,3]
        lg1 = left_g - h0[..., 0][:, :, None]
        lh1 = left_h - h0[..., 1][:, :, None]
        ln1 = left_n - h0[..., 2][:, :, None]
        gain1 = gain_of(lg1, lh1)
        ok1 = (ok_of(ln1, lh1, tot_n - ln1, tot_h - lh1)
               & im & bin_ge1)
        g1 = jnp.where(ok1, gain1, _NEG_INF)
    else:
        g1 = jnp.full((l, f, b), _NEG_INF)
    return jnp.stack([jnp.where(ok0, gain0, _NEG_INF), g1], axis=-1)


def _best_split_per_slot(hists, sums, cfg: GBDTConfig, feature_mask,
                         hp: "HParams", miss_mask=None, cat_mask=None):
    """Vectorized split-gain scan over [L, F, B, 2] gain tables.

    Returns per-slot (best_gain [L], best_feat [L], best_bin [L],
    default_left [L] bool). For categorical features `best_bin` is the
    (sorted-order) prefix length - 1; the caller reconstructs the category
    subset mask.
    """
    l, f, b, _ = hists.shape
    gain = _split_gain_table(hists, sums, cfg, feature_mask, hp, miss_mask,
                             cat_mask)
    flat = gain.reshape(l, f * b * 2)
    best_idx = jnp.argmax(flat, axis=1)
    best_gain = jnp.take_along_axis(flat, best_idx[:, None], axis=1)[:, 0]
    best_feat = (best_idx // (b * 2)).astype(jnp.int32)
    best_bin = ((best_idx // 2) % b).astype(jnp.int32)
    default_left = (best_idx % 2) == 0
    return best_gain, best_feat, best_bin, default_left


def build_tree(binned: jax.Array, gh3: jax.Array, cfg: GBDTConfig,
               feature_mask: jax.Array,
               hp: Optional["HParams"] = None,
               bins_t: Optional[jax.Array] = None) -> Tuple[Tree, jax.Array]:
    """Grow one leaf-wise tree.

    binned: [N, F] int — bin ids (shard-local rows when distributed)
    gh3:    [N, 3] float32 — (grad*w, hess*w, hist-weight); hist-weight is 0 for
            validation / bagged-out / padding rows
    feature_mask: [F] bool — feature_fraction subset for this tree

    Returns (tree, slot_of_row [N] int32). Slot semantics: slot 0 is the root; the split
    recorded at step s sends its right child to slot s+1, the left child keeps the parent's
    slot. Replaying splits in order reproduces leaf assignments exactly.

    Kernel structure: each refresh runs ONE all-slots histogram pass
    (ops/histogram.hist_slots) producing every current leaf's [F, B, 3]
    histogram in a single MXU contraction of output width num_leaves*3 (the
    narrow per-leaf pass would cost the same — the MXU pads output width to
    128 lanes either way). The carry holds global histograms plus a per-slot
    cache of best splits (bg/bf/bb): after a split, eager mode refreshes the
    new child with one pass (sibling subtraction covers the parent) and
    rescans only the two changed slots; lazy mode defers both children and
    re-passes only when the candidate pool dries (cfg.split_refresh).
    """
    if hp is None:
        hp = HParams.from_config(cfg)
    n, f = binned.shape
    lcap = cfg.num_leaves
    b = cfg.max_bins
    cat = cfg.categorical_features
    bm = b if cat else 1  # split-mask width (1 keeps numeric-only models tiny)
    is_cat_f = _cat_mask_global(f, cat) if cat else None
    voting = (cfg.tree_learner == "voting_parallel"
              and cfg.axis_name is not None)
    k_top = min(cfg.top_k, f) if voting else 0
    if cfg.split_refresh not in ("eager", "lazy"):
        raise ValueError(
            f"split_refresh must be 'eager' or 'lazy', got "
            f"{cfg.split_refresh!r}")
    if cfg.split_refresh == "lazy" and voting:
        raise NotImplementedError(
            "lazy histogram refresh does not compose with voting_parallel "
            "(votes must be recast per split); use data_parallel")
    lazy = cfg.split_refresh == "lazy"
    if cfg.split_scan not in ("full", "compact"):
        raise ValueError(
            f"split_scan must be 'full' or 'compact', got "
            f"{cfg.split_scan!r}")
    compact = cfg.split_scan == "compact"
    k_batch = int(cfg.splits_per_pass)
    if k_batch < 1:
        raise ValueError(f"splits_per_pass must be >= 1, got {k_batch}")
    # more than lcap-1 splits can never apply in one pass (and lax.top_k
    # requires k <= its operand length)
    k_batch = min(k_batch, lcap - 1)
    batched = k_batch > 1
    if batched and (lazy or compact):
        raise NotImplementedError(
            "splits_per_pass > 1 batches the eager scan's split "
            "applications; it does not compose with split_refresh='lazy' "
            "(no per-split pass to batch — lazy already amortizes passes) "
            "or split_scan='compact' (its segment walk is inherently "
            "one-split-at-a-time)")
    if compact and (voting or lazy):
        raise NotImplementedError(
            "split_scan='compact' replaces the per-split full pass of the "
            "eager data_parallel path; it does not compose with "
            "voting_parallel (needs full local histograms to vote) or "
            "split_refresh='lazy' (has no per-split pass to compact)")

    def psum_(v):
        return jax.lax.psum(v, cfg.axis_name) if cfg.axis_name else v

    resolved_method = resolve_hist_method(cfg.hist_method)
    if bins_t is None and resolved_method == "pallas":
        # transpose+pad the bins operand here (invariant across every full
        # histogram pass of this tree) instead of relying on XLA
        # loop-invariant code motion to hoist it out of the split fori_loop.
        # make_train_fn passes bins_t built ONCE PER FIT, hoisting it out of
        # the boosting-iteration scan as well.
        from .pallas_kernels import prepare_bins_t
        bins_t = prepare_bins_t(binned, b, lcap, 3, cfg.hist_chunk)
    bins_t_full = bins_t if resolved_method == "pallas" else None

    def hist_local(slot_of_row):
        return hist_slots(binned, slot_of_row, gh3, lcap, b, resolved_method,
                          cfg.hist_chunk, cfg.hist_dtype,
                          bins_t=bins_t_full)   # [L, F, B, 3]

    def scan_splits_voting(slot_of_row, feature_mask):
        """Voting-parallel split scan: one all-slots LOCAL histogram pass;
        each shard votes its local top-2k features per slot, only the globally
        top-k voted features' histograms are allreduced, and the split is
        chosen among those (LightGBM voting-parallel semantics,
        LightGBMParams.scala:13-27). Allreduce traffic per step is
        [L, top_k, B, 3] instead of data_parallel's [F, B, 3] sibling slice.
        Returns (hists [L,k,B,3], sums [L,3], gains [L], feats [L] global
        ids, bins [L], default_left [L], hrow [L,B,3] — the chosen
        feature's allreduced histogram row per slot, for apply_split's
        categorical-mask reconstruction).
        """
        local = hist_local(slot_of_row)
        local_sums = local[:, 0].sum(axis=1)
        sums = psum_(local_sums)
        # local vote: best local gain per (slot, feature)
        local_gain = _split_gain_table(local, local_sums, cfg,
                                       feature_mask, hp).max(axis=(2, 3))
        k2 = min(2 * k_top, f)
        _, vote_idx = jax.lax.top_k(local_gain, k2)
        vote_ok = (jnp.take_along_axis(local_gain, vote_idx, axis=1)
                   > _NEG_INF / 2)
        votes = jnp.zeros((lcap, f), jnp.float32).at[
            jnp.arange(lcap)[:, None], vote_idx].add(
                vote_ok.astype(jnp.float32))
        votes = psum_(votes)                      # global vote counts [L,F]
        _, sel = jax.lax.top_k(votes, k_top)      # [L,k] voted features
        hist_v = psum_(jnp.take_along_axis(
            local, sel[:, :, None, None], axis=1))           # [L,k,B,3]
        # voted feature axis: per-slot masks must be gathered through sel
        # (global [F] masks don't align with the [L, k] voted columns)
        gains, f_idx, bins_, dls = _best_split_per_slot(
            hist_v, sums, cfg, feature_mask[sel], hp,
            miss_mask=(is_miss_f[sel] if miss else None),
            cat_mask=(is_cat_f[sel] if cat else None))
        feats = jnp.take_along_axis(sel, f_idx[:, None], axis=1)[:, 0]
        # chosen-feature histogram row per slot [L, B, 3]: apply_split's
        # categorical-mask reconstruction needs the allreduced row of the
        # feature actually chosen, and hist_v's voted axis can't be
        # indexed by global feature id
        hrow = jnp.take_along_axis(
            hist_v, f_idx[:, None, None, None], axis=1)[:, 0]
        return hist_v, sums, gains, feats.astype(jnp.int32), bins_, dls, hrow

    depth_of_slot = jnp.zeros((lcap,), jnp.int32)
    slot_of_row = jnp.zeros((n,), jnp.int32)
    s_slot = jnp.zeros((lcap - 1,), jnp.int32)
    s_feat = jnp.zeros((lcap - 1,), jnp.int32)
    s_bin = jnp.zeros((lcap - 1,), jnp.int32)
    s_valid = jnp.zeros((lcap - 1,), bool)
    s_gain = jnp.zeros((lcap - 1,), jnp.float32)
    s_is_cat = jnp.zeros((lcap - 1,), bool)
    s_mask = jnp.zeros((lcap - 1, bm), bool)
    s_dl = jnp.ones((lcap - 1,), bool)   # learned default direction
    done = jnp.array(False)
    miss = cfg.missing_features
    is_miss_f = _miss_mask_global(f, miss) if miss else None

    if not voting:
        # data_parallel keeps GLOBAL histograms in the loop carry: the local
        # all-slots pass still runs once per step (that's where the MXU win
        # is), but only the new right child's [F, B, 3] slice rides the ICI
        # allreduce — the parent updates by sibling subtraction, so per-step
        # interconnect traffic matches LightGBM data_parallel's per-leaf
        # reduce-scatter (TrainUtils.scala:496-512), not L x it.
        # Per-slot best splits (bg/bf/bb) are CACHED in the carry and only
        # rescanned for slots whose histogram changed — the full [L, F, B]
        # gain table is built once here, not once per split step.
        root_local = hist_local(slot_of_row)
        root = psum_(root_local[0])                            # [F,B,3]
        g_hists = jnp.zeros((lcap, f, b, 3), jnp.float32).at[0].set(root)
        g_sums = jnp.zeros((lcap, 3), jnp.float32).at[0].set(
            root[0].sum(axis=0))
        bg, bf_, bb, bd = _best_split_per_slot(g_hists, g_sums, cfg,
                                               feature_mask, hp)
        hist_valid = jnp.ones((lcap,), bool)

    if compact:
        # bucket ladder for the parent-segment lax.switch: powers of two
        # from 4096 (smaller segments just use the smallest bucket — a
        # 4096-row pass is ~free) up to pow2ceil(n). perm is padded by the
        # largest bucket so a segment slice can never run off the end.
        pmax = 1 << max(int(max(n - 1, 1)).bit_length(), 7)
        pmin = min(4096, pmax)
        bucket_sizes = []
        p_ = pmin
        while p_ <= pmax:
            bucket_sizes.append(p_)
            p_ *= 2
        perm = jnp.pad(jnp.arange(n, dtype=jnp.int32), (0, pmax))
        seg_start = jnp.zeros((lcap,), jnp.int32)
        seg_len = jnp.zeros((lcap,), jnp.int32).at[0].set(n)

    thresh = hp.min_gain_to_split + _MIN_GAIN_EPS

    def split_decision(slot_f, hists_f, feats_f, bins_f, dls_f,
                       hrow_f=None):
        """Resolve one slot's chosen split into its routing ingredients:
        (feat_b, bin_b, dl_b, mask [B or bm], feat_cat). The categorical
        mask is rebuilt from the sorted-order prefix exactly as the gain
        scan ordered bins (_cat_sort_order is the shared source of truth).
        hrow_f ([L, B, 3], voting path): pre-gathered chosen-feature
        histogram rows when hists_f's feature axis is voted rather than
        global."""
        feat_b = feats_f[slot_f]
        bin_b = bins_f[slot_f]
        dl_b = dls_f[slot_f]
        if cat:
            hrow = (hists_f[slot_f, feat_b] if hrow_f is None
                    else hrow_f[slot_f])                         # [B,3]
            order_b = jnp.argsort(-_cat_ratio(hrow, cfg))
            mask = jnp.zeros((b,), bool).at[order_b].set(
                jnp.arange(b) <= bin_b)                          # left subset
            feat_cat = is_cat_f[feat_b]
        else:
            mask = jnp.zeros((bm,), bool)
            feat_cat = jnp.array(False)
        return feat_b, bin_b, dl_b, mask, feat_cat

    def record_split(do_f, slot_f, rec_f, gain_f, feat_b, bin_b, dl_b,
                     mask, feat_cat, depth_of_slot, new_slot_f,
                     s_slot, s_feat, s_bin, s_valid, s_gain, s_is_cat,
                     s_mask, s_dl):
        """Depth updates + the eight split-record writes for one split,
        masked by do_f (rec_f may alias an existing record in the batched
        path's clipped tail — every write keeps the current value when
        do_f is False)."""
        child_depth = depth_of_slot[slot_f] + 1
        depth_of_slot = depth_of_slot.at[new_slot_f].set(
            jnp.where(do_f, child_depth, depth_of_slot[new_slot_f]))
        depth_of_slot = depth_of_slot.at[slot_f].set(
            jnp.where(do_f, child_depth, depth_of_slot[slot_f]))
        s_slot = s_slot.at[rec_f].set(jnp.where(do_f, slot_f, s_slot[rec_f]))
        s_feat = s_feat.at[rec_f].set(jnp.where(do_f, feat_b, s_feat[rec_f]))
        s_bin = s_bin.at[rec_f].set(jnp.where(do_f, bin_b, s_bin[rec_f]))
        s_valid = s_valid.at[rec_f].set(s_valid[rec_f] | do_f)
        s_gain = s_gain.at[rec_f].set(jnp.where(do_f, gain_f, s_gain[rec_f]))
        s_is_cat = s_is_cat.at[rec_f].set(
            jnp.where(do_f, feat_cat, s_is_cat[rec_f]))
        s_mask = s_mask.at[rec_f].set(
            jnp.where(do_f, mask[:bm], s_mask[rec_f]))
        s_dl = s_dl.at[rec_f].set(jnp.where(do_f, dl_b, s_dl[rec_f]))
        return (depth_of_slot, s_slot, s_feat, s_bin, s_valid, s_gain,
                s_is_cat, s_mask, s_dl)

    def apply_split(do_f, slot_f, rec_f, new_slot_f, gain_f, hists_f,
                    feats_f, bins_f, dls_f, slot_of_row, depth_of_slot,
                    s_slot, s_feat, s_bin, s_valid, s_gain, s_is_cat,
                    s_mask, s_dl, hrow_f=None):
        """Apply ONE split decision, masked by do_f, writing record rec_f
        and sending the right child to slot new_slot_f: row routing
        (categorical bitset + learned missing direction), depth updates,
        and the split-record writes. Shared by the strict leaf-wise body,
        the compact scan, and the batched bodies (apply_topk_splits calls
        this once per selected split) so split semantics cannot
        diverge."""
        feat_b, bin_b, dl_b, mask, feat_cat = split_decision(
            slot_f, hists_f, feats_f, bins_f, dls_f, hrow_f)
        col = jnp.take(binned, feat_b, axis=1).astype(jnp.int32)
        in_leaf = slot_of_row == slot_f
        if cat:
            go_right = jnp.where(feat_cat, ~mask[col], col > bin_b)
        else:
            go_right = col > bin_b
        if miss:
            # bin 0 of a missing-capable feature = NaN rows: route by the
            # LEARNED default direction, not the value comparison
            go_right = jnp.where(is_miss_f[feat_b] & (col == 0),
                                 ~dl_b, go_right)
        slot_of_row = jnp.where(in_leaf & go_right & do_f, new_slot_f,
                                slot_of_row)
        (depth_of_slot, s_slot, s_feat, s_bin, s_valid, s_gain, s_is_cat,
         s_mask, s_dl) = record_split(
            do_f, slot_f, rec_f, gain_f, feat_b, bin_b, dl_b, mask,
            feat_cat, depth_of_slot, new_slot_f, s_slot, s_feat, s_bin,
            s_valid, s_gain, s_is_cat, s_mask, s_dl)
        return (go_right, slot_of_row, depth_of_slot, s_slot, s_feat,
                s_bin, s_valid, s_gain, s_is_cat, s_mask, s_dl)

    def body(s, carry):
        if voting:
            (depth_of_slot, slot_of_row, s_slot, s_feat, s_bin,
             s_valid, s_gain, s_is_cat, s_mask, s_dl, done) = carry
            (hists, sums, gains_all, feats_all, bins_all,
             dls_all, hrow_all) = scan_splits_voting(slot_of_row,
                                                     feature_mask)
        elif compact:
            (depth_of_slot, slot_of_row, s_slot, s_feat, s_bin,
             s_valid, s_gain, s_is_cat, s_mask, s_dl, done,
             g_hists, g_sums, bg, bf_, bb, bd, hist_valid,
             perm, seg_start, seg_len) = carry
        else:
            (depth_of_slot, slot_of_row, s_slot, s_feat, s_bin,
             s_valid, s_gain, s_is_cat, s_mask, s_dl, done,
             g_hists, g_sums, bg, bf_, bb, bd, hist_valid) = carry
        slot_exists = jnp.arange(lcap) <= s
        if cfg.max_depth > 0:
            slot_exists = slot_exists & (depth_of_slot < cfg.max_depth)

        if not voting and lazy:
            # refresh when the current-histogram candidate pool is dry but
            # deferred children exist; one pass re-validates every slot
            gains0 = jnp.where(slot_exists & hist_valid, bg, _NEG_INF)
            need = ((jnp.max(gains0) <= thresh)
                    & jnp.any(slot_exists & ~hist_valid) & (~done))

            def _refresh(args):
                slot_of_row, *_ = args
                gh_full = psum_(hist_local(slot_of_row))       # [L,F,B,3]
                gs = gh_full[:, 0].sum(axis=1)                 # [L,B,3]->[L,3]
                nbg, nbf, nbb, nbd = _best_split_per_slot(gh_full, gs, cfg,
                                                          feature_mask, hp)
                return (gh_full, gs, nbg, nbf, nbb, nbd,
                        jnp.ones((lcap,), bool))

            def _keep(args):
                _, g_hists, g_sums, bg, bf_, bb, bd, hist_valid = args
                return g_hists, g_sums, bg, bf_, bb, bd, hist_valid

            (g_hists, g_sums, bg, bf_, bb, bd, hist_valid) = jax.lax.cond(
                need, _refresh, _keep,
                (slot_of_row, g_hists, g_sums, bg, bf_, bb, bd, hist_valid))

        if not voting:
            hists = g_hists
            gains_all, feats_all, bins_all, dls_all = bg, bf_, bb, bd
            avail = slot_exists & hist_valid if lazy else slot_exists
        else:
            avail = slot_exists
        gains = jnp.where(avail, gains_all, _NEG_INF)
        best_slot = jnp.argmax(gains).astype(jnp.int32)
        best_gain = gains[best_slot]
        do = (best_gain > thresh) & (~done)

        new_slot = (s + 1).astype(jnp.int32)
        (go_right, slot_of_row, depth_of_slot, s_slot, s_feat, s_bin,
         s_valid, s_gain, s_is_cat, s_mask, s_dl) = apply_split(
            do, best_slot, s, new_slot, best_gain, hists,
            feats_all, bins_all, dls_all, slot_of_row, depth_of_slot,
            s_slot, s_feat, s_bin, s_valid, s_gain, s_is_cat, s_mask, s_dl,
            hrow_f=hrow_all if voting else None)
        done = done | ~do
        if voting:
            return (depth_of_slot, slot_of_row, s_slot, s_feat,
                    s_bin, s_valid, s_gain, s_is_cat, s_mask, s_dl, done)

        if lazy:
            # both split products have stale histograms: mark deferred; they
            # rejoin the candidate pool at the next refresh
            inval = jnp.array([True, True])
            idx2 = jnp.stack([best_slot, new_slot])
            hist_valid = hist_valid.at[idx2].set(
                jnp.where(do, ~inval, hist_valid[idx2]))
            bg = bg.at[idx2].set(jnp.where(do, _NEG_INF, bg[idx2]))
            return (depth_of_slot, slot_of_row, s_slot, s_feat,
                    s_bin, s_valid, s_gain, s_is_cat, s_mask, s_dl, done,
                    g_hists, g_sums, bg, bf_, bb, bd, hist_valid)

        if compact:
            # compact scan: the parent's rows live in perm[st:st+ln]; pad
            # that segment to the next power-of-two bucket (static shapes
            # for XLA) and build BOTH children's histograms in one masked
            # 2-slot pass over just those rows, partitioning the segment
            # in the same branch. Shard-local segment lengths may pick
            # different buckets per device — the branches contain no
            # collectives, so SPMD divergence is safe; the psum happens on
            # the uniform [2, F, B, 3] result below.
            st = jnp.clip(seg_start[best_slot], 0, max(n - 1, 0))
            ln = seg_len[best_slot]
            gr8 = go_right.astype(jnp.int8)          # [N] original row order
            sizes_arr = jnp.asarray(bucket_sizes, jnp.int32)
            kidx = jnp.minimum(jnp.sum((sizes_arr < ln).astype(jnp.int32)),
                               len(bucket_sizes) - 1)

            def mk_branch(p_):
                def br(perm, gr8, gh3):
                    seg = jax.lax.dynamic_slice(perm, (st,), (p_,))
                    pos = jnp.arange(p_, dtype=jnp.int32)
                    valid = pos < ln
                    gr = (gr8[seg] > 0) & valid
                    lf = valid & ~gr
                    cl = jnp.cumsum(lf.astype(jnp.int32))
                    cr = jnp.cumsum(gr.astype(jnp.int32))
                    n_left = cl[p_ - 1]
                    # stable partition: left rows keep order at the front,
                    # right rows at the back; overhang (rows of later
                    # segments caught by the pow2 slice) stays put
                    npos = jnp.where(lf, cl - 1, n_left + cr - 1)
                    npos = jnp.where(valid, npos, p_)           # drop
                    seg_p = jnp.zeros((p_,), jnp.int32).at[npos].set(
                        seg, mode="drop")
                    merged = jnp.where(valid, seg_p, seg)
                    perm2 = jax.lax.dynamic_update_slice(perm, merged, (st,))
                    bi_seg = jnp.take(binned, seg, axis=0)      # [P, F]
                    gh_seg = jnp.take(gh3, seg, axis=0) * valid[:, None]
                    h2 = hist_slots(bi_seg, gr.astype(jnp.int32), gh_seg,
                                    2, b, resolved_method, cfg.hist_chunk,
                                    cfg.hist_dtype)             # [2, F, B, 3]
                    return perm2, h2, n_left
                return br

            perm2, h2, n_left = jax.lax.switch(
                kidx, [mk_branch(p_) for p_ in bucket_sizes],
                perm, gr8, gh3)
            h2 = psum_(h2)
            left_h, right_h = h2[0], h2[1]
            perm = jnp.where(do, perm2, perm)
            seg_start = seg_start.at[new_slot].set(
                jnp.where(do, st + n_left, seg_start[new_slot]))
            seg_len = seg_len.at[new_slot].set(
                jnp.where(do, ln - n_left, seg_len[new_slot]))
            seg_len = seg_len.at[best_slot].set(
                jnp.where(do, n_left, seg_len[best_slot]))
            # both children measured directly — no sibling-subtraction
            # cancellation; parent hist is simply replaced
            g_hists = g_hists.at[new_slot].set(
                jnp.where(do, right_h, 0.0))
            g_hists = g_hists.at[best_slot].set(
                jnp.where(do, left_h, g_hists[best_slot]))
            g_sums = g_sums.at[new_slot].set(
                jnp.where(do, right_h[0].sum(axis=0), g_sums[new_slot]))
            g_sums = g_sums.at[best_slot].set(
                jnp.where(do, left_h[0].sum(axis=0), g_sums[best_slot]))
        else:
            # eager full scan: post-split all-slots pass; only the new
            # child's slice is allreduced, the parent updates by sibling
            # subtraction, and only the two changed slots are rescanned
            local = hist_local(slot_of_row)
            right = psum_(jnp.take(local, new_slot, axis=0))   # [F,B,3]
            right = jnp.where(do, right, 0.0)
            right_sum = right[0].sum(axis=0)
            g_hists = g_hists.at[new_slot].set(right)
            g_hists = g_hists.at[best_slot].add(-right)        # sibling sub
            g_sums = g_sums.at[new_slot].set(right_sum)
            g_sums = g_sums.at[best_slot].add(-right_sum)
        idx2 = jnp.stack([best_slot, new_slot])
        pg, pf, pb, pd = _best_split_per_slot(g_hists[idx2], g_sums[idx2],
                                              cfg, feature_mask, hp)
        bg = bg.at[idx2].set(jnp.where(do, pg, bg[idx2]))
        bf_ = bf_.at[idx2].set(jnp.where(do, pf, bf_[idx2]))
        bb = bb.at[idx2].set(jnp.where(do, pb, bb[idx2]))
        bd = bd.at[idx2].set(jnp.where(do, pd, bd[idx2]))
        out = (depth_of_slot, slot_of_row, s_slot, s_feat,
               s_bin, s_valid, s_gain, s_is_cat, s_mask, s_dl, done,
               g_hists, g_sums, bg, bf_, bb, bd, hist_valid)
        if compact:
            out = out + (perm, seg_start, seg_len)
        return out

    def apply_topk_splits(next_rec, done, depth_of_slot, slot_of_row,
                          s_slot, s_feat, s_bin, s_valid, s_gain, s_is_cat,
                          s_mask, s_dl, gains_all, hists_f, feats_f,
                          bins_f, dls_f, hrow_f=None):
        """Apply the top `k_batch` best splits of one batched pass
        (distinct leaves — their gains are mutually independent, so this
        equals k consecutive strict leaf-wise steps restricted from
        choosing children created within the pass). Valid splits form a
        PREFIX of the gain-sorted selection (gains descend and the
        record-budget check only tightens with j), so the j-th valid
        split's record index is exactly next_rec + j. Shared by
        body_batched and body_batched_voting so the selection semantics
        (slot-exists guard, record-budget clip) cannot diverge."""
        slot_exists = jnp.arange(lcap) <= next_rec
        if cfg.max_depth > 0:
            slot_exists = slot_exists & (depth_of_slot < cfg.max_depth)
        gains = jnp.where(slot_exists, gains_all, _NEG_INF)
        top_g, sel = jax.lax.top_k(gains, k_batch)
        do_js, parents, children = [], [], []
        # k sequential apply_split updates, each routing with a scalar
        # column dynamic-slice — the same per-split routing the strict
        # body uses. A fused single-pass alternative (per-slot routing
        # tables + one take_along_axis(binned, feat_of[slot]) gather)
        # measured ~11 ms/pass SLOWER on chip at 1M x 28 (k4 123.4 vs
        # eager 92.4 ms/iter, docs/PERF_scan_modes.log 2026-08-01): the
        # per-row gather over [N, F] plus the [N]-gathers from the [L]
        # tables are exactly the access pattern the TPU punishes, while
        # k column slices + vector wheres cost ~0.2 ms each. The updates
        # commute (parents are distinct pre-pass leaves; children —
        # slots > next_rec — can never be parents within the pass), so
        # application order is irrelevant.
        for j in range(k_batch):
            rec = next_rec + j
            do_j = (top_g[j] > thresh) & (rec < lcap - 1) & (~done)
            rec_c = jnp.minimum(rec, lcap - 2)
            new_slot = rec_c + 1
            (_, slot_of_row, depth_of_slot, s_slot, s_feat, s_bin,
             s_valid, s_gain, s_is_cat, s_mask, s_dl) = apply_split(
                do_j, sel[j], rec_c, new_slot, top_g[j], hists_f,
                feats_f, bins_f, dls_f, slot_of_row, depth_of_slot,
                s_slot, s_feat, s_bin, s_valid, s_gain, s_is_cat, s_mask,
                s_dl, hrow_f=hrow_f)
            do_js.append(do_j)
            parents.append(sel[j])
            children.append(new_slot)
        applied = sum(d.astype(jnp.int32) for d in do_js)
        return (next_rec + applied, done | (applied == 0), depth_of_slot,
                slot_of_row, s_slot, s_feat, s_bin, s_valid, s_gain,
                s_is_cat, s_mask, s_dl, do_js, parents, children)

    def body_batched(carry):
        """One batched pass: apply the top-k cached best splits, then ONE
        all-slots refresh covering every child created this pass."""
        (step, next_rec, done, depth_of_slot, slot_of_row, s_slot, s_feat,
         s_bin, s_valid, s_gain, s_is_cat, s_mask, s_dl,
         g_hists, g_sums, bg, bf_, bb, bd) = carry
        (next_rec, done, depth_of_slot, slot_of_row, s_slot, s_feat,
         s_bin, s_valid, s_gain, s_is_cat, s_mask, s_dl, do_js, parents,
         children) = apply_topk_splits(
            next_rec, done, depth_of_slot, slot_of_row, s_slot, s_feat,
            s_bin, s_valid, s_gain, s_is_cat, s_mask, s_dl,
            bg, g_hists, bf_, bb, bd)
        # ONE refresh pass covers every child created this pass; only the
        # k child slices ride the allreduce (same total ICI traffic as k
        # eager steps, k x fewer latency hops), parents update by sibling
        # subtraction
        local = hist_local(slot_of_row)
        ch_idx = jnp.stack(children)
        childs = psum_(jnp.take(local, ch_idx, axis=0))          # [k,F,B,3]
        for j in range(k_batch):
            cj = jnp.where(do_js[j], childs[j], 0.0)
            cs = cj[0].sum(axis=0)
            g_hists = g_hists.at[children[j]].set(
                jnp.where(do_js[j], cj, g_hists[children[j]]))
            g_hists = g_hists.at[parents[j]].add(-cj)
            g_sums = g_sums.at[children[j]].set(
                jnp.where(do_js[j], cs, g_sums[children[j]]))
            g_sums = g_sums.at[parents[j]].add(
                jnp.where(do_js[j], -cs, jnp.zeros_like(cs)))
        idx2k = jnp.stack(parents + children)                    # [2k]
        pg, pf, pb, pd = _best_split_per_slot(g_hists[idx2k], g_sums[idx2k],
                                              cfg, feature_mask, hp)
        # Non-applied entries are masked OUT of the scatter (index lcap is
        # out of bounds -> dropped), not merged via where(do2, ...): when
        # the record budget clips (rec_c pinned to lcap-2), idx2k can name
        # slot lcap-1 twice — an applied child and a clipped non-applied
        # entry — and a duplicate-index scatter is nondeterministic about
        # which value lands. Applied indices are provably unique (top_k
        # parents are distinct, applied children are consecutive fresh
        # slots above next_rec), so the masked scatter is deterministic.
        do2 = jnp.stack(do_js + do_js)
        safe = jnp.where(do2, idx2k, lcap)
        bg = bg.at[safe].set(pg, mode="drop")
        bf2 = bf_.at[safe].set(pf, mode="drop")
        bb2 = bb.at[safe].set(pb, mode="drop")
        bd2 = bd.at[safe].set(pd, mode="drop")
        return (step + 1, next_rec, done, depth_of_slot, slot_of_row,
                s_slot, s_feat, s_bin, s_valid, s_gain, s_is_cat, s_mask,
                s_dl, g_hists, g_sums, bg, bf2, bb2, bd2)

    def body_batched_voting(carry):
        """Batched voting-parallel pass: one local all-slots pass + vote +
        top-k-feature allreduce (scan_splits_voting), then apply the top
        `k_batch` best voted splits on distinct leaves. Voting recomputes
        every slot's histogram from scratch each pass (no sibling-
        subtraction carry), so batching k splits per pass divides BOTH the
        local histogram passes and the [L, top_k, B, 3] allreduce rounds
        by ~k — the production multi-pod config (traffic mode x perf
        mode, which the reference's C++ also composes,
        LightGBMParams.scala:20-27)."""
        (step, next_rec, done, depth_of_slot, slot_of_row, s_slot, s_feat,
         s_bin, s_valid, s_gain, s_is_cat, s_mask, s_dl) = carry
        (hists_v, _sums_v, gains_all, feats_all, bins_all,
         dls_all, hrow_all) = scan_splits_voting(slot_of_row, feature_mask)
        (next_rec, done, depth_of_slot, slot_of_row, s_slot, s_feat,
         s_bin, s_valid, s_gain, s_is_cat, s_mask, s_dl, _, _, _
         ) = apply_topk_splits(
            next_rec, done, depth_of_slot, slot_of_row, s_slot, s_feat,
            s_bin, s_valid, s_gain, s_is_cat, s_mask, s_dl,
            gains_all, hists_v, feats_all, bins_all, dls_all,
            hrow_f=hrow_all)
        return (step + 1, next_rec, done, depth_of_slot, slot_of_row,
                s_slot, s_feat, s_bin, s_valid, s_gain, s_is_cat, s_mask,
                s_dl)

    if batched:
        def cond_batched(carry):
            step, next_rec, done = carry[0], carry[1], carry[2]
            # step < lcap-1 is the safety bound (1 split/pass worst case);
            # the typical trip count is ~(L-1)/k + a short ramp
            return (~done) & (next_rec < lcap - 1) & (step < lcap - 1)

        if voting:
            init = (jnp.int32(0), jnp.int32(0), done, depth_of_slot,
                    slot_of_row, s_slot, s_feat, s_bin, s_valid, s_gain,
                    s_is_cat, s_mask, s_dl)
            fin = jax.lax.while_loop(cond_batched, body_batched_voting,
                                     init)
            (_, _, _, _, slot_of_row, s_slot, s_feat, s_bin, s_valid,
             s_gain, s_is_cat, s_mask, s_dl) = fin
        else:
            init = (jnp.int32(0), jnp.int32(0), done, depth_of_slot,
                    slot_of_row, s_slot, s_feat, s_bin, s_valid, s_gain,
                    s_is_cat, s_mask, s_dl, g_hists, g_sums, bg, bf_, bb,
                    bd)
            fin = jax.lax.while_loop(cond_batched, body_batched, init)
            (_, _, _, _, slot_of_row, s_slot, s_feat, s_bin, s_valid,
             s_gain, s_is_cat, s_mask, s_dl, _, g_sums_f, *_rest) = fin
            sums = g_sums_f
    else:
        carry = (depth_of_slot, slot_of_row, s_slot, s_feat, s_bin,
                 s_valid, s_gain, s_is_cat, s_mask, s_dl, done)
        if not voting:
            carry = carry + (g_hists, g_sums, bg, bf_, bb, bd, hist_valid)
        if compact:
            carry = carry + (perm, seg_start, seg_len)
        carry = jax.lax.fori_loop(0, lcap - 1, body, carry)
        (_, slot_of_row, s_slot, s_feat, s_bin, s_valid, s_gain,
         s_is_cat, s_mask, s_dl, _) = carry[:11]

    if batched and not voting:
        pass
    elif voting or lazy:
        # post-split leaf stats via a slot-onehot contraction (O(N*L), no
        # histogram pass needed; in lazy mode the carried g_sums are stale
        # for slots split after the last refresh)
        slot_oh = (slot_of_row[:, None]
                   == jnp.arange(lcap)[None, :]).astype(jnp.float32)
        sums = psum_(jnp.dot(slot_oh.T, gh3,
                             preferred_element_type=jnp.float32))    # [L,3]
    else:
        sums = carry[12]                                       # carried g_sums

    raw_out = _leaf_output(sums[:, 0], sums[:, 1], hp.lambda_l1,
                           hp.lambda_l2)
    if cfg.max_delta_step > 0:
        # maxDeltaStep: cap the unshrunk leaf output (upstream max_delta_step,
        # the poisson/unbalanced-logit stabilizer)
        raw_out = jnp.clip(raw_out, -cfg.max_delta_step, cfg.max_delta_step)
    leaf_value = raw_out * hp.learning_rate
    # slots that never received rows keep value 0 (their sums are 0).
    # decision_type per split: missing-capable features carry the LEARNED
    # default direction + missing_type NaN; features that saw no missing at
    # fit carry missing_type None (upstream: predict-time NaN coerces to
    # 0.0, matching BinMapper.transform's bin-of-zero mapping); categorical
    # splits carry missing None so raw NaN coerces to category 0
    if miss:
        split_miss = jnp.where(is_miss_f[s_feat] & ~s_is_cat, 2, 0)
    else:
        split_miss = jnp.zeros_like(s_feat)
    tree = Tree(s_slot, s_feat, s_bin, s_valid, s_gain, leaf_value,
                sums[:, 2], s_is_cat, s_mask,
                s_dl,
                split_miss.astype(s_feat.dtype))
    return tree, slot_of_row


def tree_apply_binned(tree: Tree, binned: jax.Array) -> jax.Array:
    """Leaf-slot assignment for rows by replaying splits in order. [N] int32.

    Splits with missing_type NaN (2) treat bin 0 as the reserved missing bin
    and route it by the LEARNED default direction, matching the training
    loop and tree_apply_raw."""
    n = binned.shape[0]
    nsplit = tree.split_slot.shape[0]

    bm = tree.split_mask.shape[-1]

    def body(s, slot):
        feat = tree.split_feat[s]
        col = jnp.take(binned, feat, axis=1).astype(jnp.int32)
        mask = (slot == tree.split_slot[s]) & tree.split_valid[s]
        go_right = col > tree.split_bin[s]
        go_right = jnp.where(
            (tree.split_missing_type[s] == 2) & (col == 0),
            ~tree.split_default_left[s], go_right)
        if bm > 1:
            # LightGBM bitset semantics: categories outside the bitset go RIGHT
            in_range = (col >= 0) & (col < bm)
            cat_left = in_range & tree.split_mask[s][jnp.clip(col, 0, bm - 1)]
            go_right = jnp.where(tree.split_is_cat[s], ~cat_left, go_right)
        return jnp.where(mask & go_right, s + 1, slot)

    slot = jax.lax.fori_loop(0, nsplit, body, jnp.zeros((n,), jnp.int32))
    return slot


def tree_predict_binned(tree: Tree, binned: jax.Array) -> jax.Array:
    return tree.leaf_value[tree_apply_binned(tree, binned)]


def tree_apply_raw(tree: Tree, x: jax.Array, thresholds: jax.Array) -> jax.Array:
    """Leaf assignment on raw features with upstream-LightGBM decision
    semantics (tree.h numerical_decision): missing_type None coerces NaN to
    0.0 before comparing; missing_type Zero routes |x|<=1e-35 and NaN to the
    default side; missing_type NaN routes NaN to the default side; the default
    side is decision_type's default_left bit. Models trained here carry
    (default_left=True, missing NaN) — matching their NaN->bin0 binning."""
    n = x.shape[0]
    nsplit = tree.split_slot.shape[0]
    bm = tree.split_mask.shape[-1]

    def body(s, slot):
        feat = tree.split_feat[s]
        col = jnp.take(x, feat, axis=1)
        mask = (slot == tree.split_slot[s]) & tree.split_valid[s]
        mt = tree.split_missing_type[s]
        is_nan = jnp.isnan(col)
        col0 = jnp.where(is_nan, 0.0, col)
        is_zero = jnp.abs(col0) <= 1e-35
        is_missing = jnp.where(mt == 2, is_nan,
                               jnp.where(mt == 1, is_zero | is_nan,
                                         jnp.zeros_like(is_nan)))
        go_right = jnp.where(is_missing, ~tree.split_default_left[s],
                             col0 > thresholds[s])
        if bm > 1:
            # categorical: raw value IS the category code == bin id, with
            # upstream CategoricalDecision semantics: out-of-bitset codes go
            # RIGHT; NaN with missing_type NaN goes right, otherwise NaN
            # coerces to category 0. Boosters trained here pre-clip codes into
            # bin range upstream of this kernel (Booster._prep_x), matching
            # their BinMapper clipping at training time.
            nan_code = jnp.where(mt == 2, -1.0, 0.0)
            code = jnp.where(is_nan, nan_code, col).astype(jnp.int32)
            in_range = (code >= 0) & (code < bm)
            cat_left = in_range & tree.split_mask[s][jnp.clip(code, 0, bm - 1)]
            go_right = jnp.where(tree.split_is_cat[s], ~cat_left, go_right)
        return jnp.where(mask & go_right, s + 1, slot)

    return jax.lax.fori_loop(0, nsplit, body, jnp.zeros((n,), jnp.int32))


# ---------------------------------------------------------------------------
# Boosting loop
# ---------------------------------------------------------------------------

class BoostResult(NamedTuple):
    trees: Tree               # arrays stacked [T, (K,) ...]
    init_score: jax.Array     # [] or [K]
    train_metric: jax.Array   # [T]
    valid_metric: jax.Array   # [T] (NaN when no validation rows)


def _goss_weights(key, g_abs, cfg: GBDTConfig):
    """GOSS: keep top_rate largest-gradient rows, sample other_rate of the rest with
    amplification (1-top_rate)/other_rate."""
    n = g_abs.shape[0]
    k_top = max(int(cfg.top_rate * n), 1)
    thresh = jnp.sort(g_abs)[n - k_top]
    is_top = g_abs >= thresh
    keep_other = jax.random.bernoulli(key, cfg.other_rate, (n,))
    amp = (1.0 - cfg.top_rate) / max(cfg.other_rate, 1e-6)
    w = jnp.where(is_top, 1.0, jnp.where(keep_other, amp, 0.0))
    return w.astype(jnp.float32)


def binned_weighted_auc(scores, y, w, k=1024, axis_name=None):
    """Distributed weighted AUC via a fixed score histogram: per-bin
    positive/negative weights are psum-able across shards, and the ROC
    integral over k sigmoid-space bins (with the within-bin tie correction
    pos*neg/2) is exact to bin resolution. This is the shard-decomposable
    formulation — exact rank-based AUC would need a global sort
    (replaces upstream's in-C++ exact AUC, LightGBMBooster.scala eval path).

    Error bound (pinned by tests/test_binned_auc.py): only pairs whose
    scores land in the SAME sigmoid-space bin can be mis-scored — each
    same-bin (pos, neg) pair contributes 0.5 instead of its exact 0, 0.5,
    or 1 — so

        |binned - exact| <= 0.5 * sum_b pos_b * neg_b / (P * N)

    where pos_b/neg_b are the per-bin positive/negative weights and P, N
    the totals. With k=1024, any score distribution spread over more than
    a few bins (sigmoid-space width >> 1e-3) makes the bound negligible;
    the adversarial extreme — ALL scores inside one bin — collapses the
    estimate to 0.5 exactly as the bound predicts. DISTRIBUTED
    (cfg.axis_name set) `metric='auc'` — including early stopping —
    consumes this estimator, so improvements smaller than the bound at
    near-constant score distributions are not trustworthy signal there;
    the serial path uses `exact_weighted_auc` and has no such bound.
    """
    chunk = 8192
    p = jax.nn.sigmoid(scores)
    b = jnp.clip((p * k).astype(jnp.int32), 0, k - 1)
    pn = jnp.stack([w * y, w * (1.0 - y)], axis=1)       # [N, 2]
    pad = (-b.shape[0]) % chunk
    if pad:
        b = jnp.pad(b, (0, pad))
        pn = jnp.pad(pn, ((0, pad), (0, 0)))             # zero weight
    bc = b.reshape(-1, chunk)
    pnc = pn.reshape(-1, chunk, 2)
    iota = jnp.arange(k, dtype=jnp.int32)

    def body(acc, xs):
        bt, pt = xs
        oh = (bt[:, None] == iota[None, :]).astype(jnp.bfloat16)
        return acc + jnp.dot(oh.T, pt.astype(jnp.bfloat16),
                             preferred_element_type=jnp.float32), None

    acc, _ = jax.lax.scan(body, jnp.zeros((k, 2), jnp.float32),
                          (bc, pnc))
    if axis_name:
        acc = jax.lax.psum(acc, axis_name)
    pos, neg = acc[:, 0], acc[:, 1]
    cum_neg = jnp.cumsum(neg) - neg                      # negatives below
    num = jnp.sum(pos * cum_neg + pos * neg * 0.5)
    den = jnp.sum(pos) * jnp.sum(neg)
    # single-class set: undefined — 0.5 by convention (matches exact path)
    return jnp.where(den > 0, num / jnp.maximum(den, 1e-12), 0.5)


def exact_weighted_auc(scores, y, w):
    """Exact rank-based weighted AUC with the standard tie credit
    (pos*neg/2 within equal-score groups), jit-friendly: one sort +
    segment sums, O(n log n). This is the metric upstream computes in C++
    (metric/binary_metric.hpp AUCMetric) and backs `metric='auc'` on the
    SERIAL path, where the global sort is available. The distributed path
    defaults to the shard-decomposable `binned_weighted_auc`;
    `metric='auc_exact'` opts into an all_gather of (score, y, w) and runs
    THIS function on the gathered arrays — exact at O(N) ICI traffic per
    eval."""
    n = scores.shape[0]
    order = jnp.argsort(scores)
    s = scores[order]
    pos = (w * y)[order]
    neg = (w * (1.0 - y))[order]
    # equal-score runs become segments; ties get the pos*neg/2 credit
    new_seg = jnp.concatenate([jnp.zeros(1, jnp.int32),
                               (s[1:] != s[:-1]).astype(jnp.int32)])
    seg = jnp.cumsum(new_seg)
    seg_neg = jax.ops.segment_sum(neg, seg, num_segments=n)
    cum_before = jnp.cumsum(seg_neg) - seg_neg
    num = jnp.sum(pos * (cum_before[seg] + 0.5 * seg_neg[seg]))
    den = jnp.sum(pos) * jnp.sum(neg)
    # single-class set: AUC is undefined — 0.5 by convention (upstream
    # AUCMetric semantics), never a confident 0 or 1
    return jnp.where(den > 0, num / jnp.maximum(den, 1e-12), 0.5)


def make_train_fn(cfg: GBDTConfig):
    """Build the jit-able full training program.

    Signature of the returned fn:
        (binned [N,F] int, y [N] float/int, w [N] float, is_train [N] float,
         key) -> BoostResult
    w: instance weights, 0.0 for padding rows. is_train: 1.0 train rows, 0.0
    validation rows. Training weight = w * is_train; validation-metric weight =
    w * (1 - is_train); padding rows (w == 0) are excluded from both.
    When cfg.axis_name is set the caller wraps this in shard_map; all inputs are
    shard-local and histograms/metrics psum over the axis.
    """
    ranking = cfg.objective == "lambdarank"
    obj = None if ranking else get_objective(
        cfg.objective, cfg.num_class, alpha=cfg.alpha,
        tweedie_variance_power=cfg.tweedie_variance_power)
    multiclass = cfg.objective in ("multiclass", "multiclassova")
    if multiclass and cfg.split_scan == "compact":
        # per-class trees are built under jax.vmap, where lax.switch lowers
        # to executing EVERY bucket branch and selecting — the compact scan
        # would do ~2*pow2ceil(N) rows of work per split instead of ~the
        # parent segment. Fall back to the full scan (identical trees).
        cfg = cfg._replace(split_scan="full")
    k = cfg.num_class if multiclass else 1
    if ranking:
        from . import ranking as _rk
        _label_gain = jnp.asarray(
            np.asarray(cfg.label_gain_table, np.float32)
            if cfg.label_gain_table
            else _rk.default_label_gain(cfg.max_label))

    def psum(v):
        return jax.lax.psum(v, cfg.axis_name) if cfg.axis_name else v

    def wmean(v, w):
        return psum(jnp.sum(v * w)) / jnp.maximum(psum(jnp.sum(w)), 1e-12)

    def auc_metric(scores, y, w):
        # serial: exact rank AUC (upstream parity); sharded: binned
        # histogram AUC by default (shard-decomposable, documented bound),
        # or EXACT via an all_gather of (score, y, w) when the user opts
        # into metric='auc_exact' — O(N) ICI traffic per eval in exchange
        # for removing the bin-resolution bound entirely
        if cfg.axis_name is None:
            return exact_weighted_auc(scores, y, w)
        if cfg.eval_metric == "auc_exact":
            g = lambda a: jax.lax.all_gather(a, cfg.axis_name, tiled=True)
            return exact_weighted_auc(g(scores), g(y), g(w))
        return binned_weighted_auc(scores, y, w, axis_name=cfg.axis_name)

    def metric_of(scores, y, w):
        # global (cross-shard) metric via weighted-mean decomposition
        name = cfg.eval_metric
        if ranking:
            raise AssertionError("ranking metric is computed inside train()")
        if multiclass:
            if name == "multi_error":
                pred = jnp.argmax(scores, axis=1).astype(y.dtype)
                return wmean((pred != y).astype(jnp.float32), w)
            if cfg.objective == "multiclassova":
                # OVA logloss: per-class sigmoid probabilities renormalized
                # (upstream multi_logloss under multiclass_ova) — softmax of
                # sigmoid margins would track the wrong quantity
                p = jax.nn.sigmoid(scores)
                p = p / jnp.maximum(p.sum(axis=1, keepdims=True), 1e-15)
                logp = jnp.log(jnp.clip(p, 1e-15, 1.0))
            else:
                logp = jax.nn.log_softmax(scores, axis=1)
            picked = jnp.take_along_axis(
                logp, y[:, None].astype(jnp.int32), axis=1)[:, 0]
            return wmean(-picked, w)
        if name in ("auc", "auc_exact"):
            return 1.0 - auc_metric(scores, y, w)
        if name == "binary_error":
            pred = (scores > 0.0).astype(jnp.float32)
            return wmean(jnp.abs(pred - y), w)
        if name == "l1":
            return wmean(jnp.abs(scores - y), w)
        if name == "rmse":
            return jnp.sqrt(wmean((scores - y) ** 2, w))
        if name == "mape":
            return wmean(jnp.abs(scores - y)
                         / jnp.maximum(jnp.abs(y), 1.0), w)
        if name == "l2":
            return wmean((scores - y) ** 2, w)
        if cfg.objective in ("binary", "cross_entropy"):
            p = jnp.clip(jax.nn.sigmoid(scores), 1e-15, 1 - 1e-15)
            return wmean(-(y * jnp.log(p) + (1 - y) * jnp.log(1 - p)), w)
        if cfg.objective == "poisson":
            return wmean(jnp.exp(scores) - y * scores, w)
        if cfg.objective == "gamma":
            return wmean(scores + y * jnp.exp(-scores), w)
        if cfg.objective == "tweedie":
            rho = cfg.tweedie_variance_power
            mu = jnp.exp(scores)
            dev = 2 * (jnp.power(jnp.maximum(y, 0.0), 2 - rho)
                       / ((1 - rho) * (2 - rho))
                       - y * jnp.power(mu, 1 - rho) / (1 - rho)
                       + jnp.power(mu, 2 - rho) / (2 - rho))
            return wmean(dev, w)
        if cfg.objective == "quantile":
            d = y - scores
            return wmean(jnp.maximum(cfg.alpha * d, (cfg.alpha - 1) * d), w)
        if cfg.objective in ("regression_l1", "mape"):
            scale = (jnp.maximum(jnp.abs(y), 1.0)
                     if cfg.objective == "mape" else 1.0)
            return wmean(jnp.abs(scores - y) / scale, w)
        return wmean((scores - y) ** 2, w)

    rf = cfg.boosting_type == "rf"
    dart = cfg.boosting_type == "dart"

    def _env(binned, y, w_all, is_train, init_margin, group_idx, hp):
        """Shared setup: init score, starting margins, and the per-iteration
        `step` closure — used by both the full scan (`train`) and the chunked
        scan (`train.chunk`, host-driven early stopping)."""
        n, f = binned.shape
        w = w_all * is_train           # training weight
        w_valid = w_all * (1.0 - is_train)  # validation-metric weight
        yf = y.astype(jnp.float32)

        if resolve_hist_method(cfg.hist_method) == "pallas":
            # bins operand pre-layout for the pallas kernel, built ONCE PER
            # FIT — hoisted out of the boosting-iteration scan AND the
            # per-split fori_loop, neither of which XLA's loop-invariant
            # code motion is guaranteed to cross
            from .pallas_kernels import prepare_bins_t
            bins_t = prepare_bins_t(binned, cfg.max_bins, cfg.num_leaves, 3,
                                    cfg.hist_chunk)
        else:
            bins_t = None

        if ranking:
            assert group_idx is not None, "lambdarank requires group_idx"
            from .ranking import ndcg_per_group, _gather_padded

            def rank_metric(scores1d, row_w):
                """1 - weighted-mean NDCG@maxPosition (lower is better, so the
                early-stopping machinery needs no special-casing)."""
                val = _gather_padded(jnp.where(row_w > 0, 1.0, 0.0),
                                     group_idx, 0.0)
                s_g = _gather_padded(scores1d.astype(jnp.float32), group_idx, 0.0)
                y_g = _gather_padded(yf, group_idx, 0.0)
                ndcg, has_rel = ndcg_per_group(s_g, y_g, val, _label_gain,
                                               cfg.eval_at or cfg.max_position)
                g_w = (val.max(axis=1) * has_rel.astype(jnp.float32))
                num = psum(jnp.sum(ndcg * g_w))
                den = jnp.maximum(psum(jnp.sum(g_w)), 1e-12)
                return 1.0 - num / den

        if (cfg.boost_from_average and not multiclass and not ranking
                and not cfg.has_init_score):
            tot_wy = psum(jnp.sum(yf * w))
            tot_w = jnp.maximum(psum(jnp.sum(w)), 1e-12)
            mean = tot_wy / tot_w
            if cfg.objective == "binary":
                p = jnp.clip(mean, 1e-7, 1 - 1e-7)
                init = jnp.log(p / (1 - p))
            elif cfg.objective in ("tweedie", "poisson"):
                init = jnp.log(jnp.maximum(mean, 1e-12))
            else:
                init = mean
        else:
            init = jnp.float32(0.0)
        init = jnp.asarray(init, jnp.float32)

        scores0 = init + init_margin.astype(jnp.float32)  # [N, K]
        t_cap = cfg.num_iterations

        def step(carry, xs):
            it, lr_mult = xs
            scores, deltas, tree_scale, key = carry
            key, k_bag, k_feat, k_drop = jax.random.split(key, 4)

            if dart:
                # DART (Rashmi & Gilad-Bachrach): drop a random subset of prior
                # ITERATIONS, fit the residual, rescale new trees by 1/(k+1)
                # and the dropped ones by k/(k+1). Multiclass drops whole
                # iterations (all num_class trees together), matching
                # LightGBM's DART at num_tree_per_iteration granularity;
                # deltas carries [T, N, K] per-iteration score deltas.
                drop = (jax.random.bernoulli(k_drop, cfg.drop_rate, (t_cap,))
                        & (jnp.arange(t_cap) < it))
                # skip_drop: with this probability the iteration runs as a
                # plain gbdt step (no trees dropped) — LightGBM skip_drop,
                # default 0.5. fold_in keeps the 4-way key split (and thus
                # every non-dart PRNG stream) unchanged.
                skip = (jax.random.uniform(jax.random.fold_in(k_drop, 7), ())
                        < cfg.skip_drop)
                drop = drop & ~skip
                kdrop = drop.sum().astype(jnp.float32)
                drop_sum = jnp.einsum("t,tnk->nk", drop.astype(jnp.float32),
                                      deltas)                     # [N, K]
                grad_scores = scores - drop_sum
            else:
                grad_scores = scores0 if rf else scores
                drop = None
                kdrop = jnp.float32(0.0)
                drop_sum = None

            if ranking:
                from .ranking import lambdarank_grad_hess
                g, h = lambdarank_grad_hess(
                    grad_scores[:, 0], yf, group_idx, _label_gain,
                    cfg.max_position, cfg.sigma,
                    row_valid=jnp.where(w > 0, 1.0, 0.0))
                g, h = g[:, None], h[:, None]
            elif multiclass:
                g, h = obj.grad_hess(grad_scores, y.astype(jnp.int32))
            else:
                g, h = obj.grad_hess(grad_scores[:, 0], yf)
                g, h = g[:, None], h[:, None]

            row_w = w
            class_bag = (cfg.pos_bagging_fraction >= 0.0
                         or cfg.neg_bagging_fraction >= 0.0)
            if cfg.boosting_type == "goss":
                g_tot = jnp.abs(g).sum(axis=1) * jnp.where(w > 0, 1.0, 0.0)
                row_w = w * _goss_weights(k_bag, g_tot, cfg)
            elif (cfg.bagging_freq > 0
                  and (cfg.bagging_fraction < 1.0 or class_bag)):
                window = it // cfg.bagging_freq
                k_window = jax.random.fold_in(
                    jax.random.PRNGKey(cfg.bagging_seed), window)
                if class_bag:
                    # per-class keep probability (pos/negBaggingFraction)
                    p_pos = (cfg.pos_bagging_fraction
                             if cfg.pos_bagging_fraction >= 0.0
                             else hp.bagging_fraction)
                    p_neg = (cfg.neg_bagging_fraction
                             if cfg.neg_bagging_fraction >= 0.0
                             else hp.bagging_fraction)
                    u = jax.random.uniform(k_window, (n,))
                    keep = u < jnp.where(yf > 0.5, p_pos, p_neg)
                    sub = keep.astype(jnp.float32)
                else:
                    sub = jax.random.bernoulli(
                        k_window, hp.bagging_fraction,
                        (n,)).astype(jnp.float32)
                row_w = w * sub

            if cfg.feature_fraction < 1.0:
                n_keep = max(int(round(cfg.feature_fraction * f)), 1)
                order = jax.random.permutation(k_feat, f)
                fmask = jnp.zeros((f,), bool).at[order[:n_keep]].set(True)
            else:
                fmask = jnp.ones((f,), bool)

            def build_for_class(gk, hk):
                gh3 = jnp.stack(
                    [gk * row_w, hk * row_w, jnp.where(row_w > 0, 1.0, 0.0)],
                    axis=1).astype(jnp.float32)
                tree, slot = build_tree(binned, gh3, cfg, fmask, hp,
                                        bins_t=bins_t)
                # lr_mult: per-iteration learning-rate multiplier relative to
                # cfg.learning_rate (delegate dynamic learning rate —
                # LightGBMDelegate.scala getLearningRate, TrainUtils.scala:213+)
                tree = tree._replace(leaf_value=tree.leaf_value * lr_mult)
                return tree, tree.leaf_value[slot]

            if multiclass:
                tree, delta = jax.vmap(build_for_class, in_axes=(1, 1),
                                       out_axes=(0, 0))(g, h)
                delta_nk = delta.T                               # [N, K]
            else:
                tree, delta = build_for_class(g[:, 0], h[:, 0])
                delta_nk = delta[:, None]                        # [N, 1]
            if dart:
                norm = 1.0 / (kdrop + 1.0)
                # rescale dropped iterations in place, store the new
                # (scaled) per-class delta
                deltas = deltas * jnp.where(drop, kdrop * norm,
                                            1.0)[:, None, None]
                deltas = deltas.at[it].set(delta_nk * norm)
                tree_scale = tree_scale * jnp.where(drop, kdrop * norm, 1.0)
                tree_scale = tree_scale.at[it].set(norm)
                scores = scores + delta_nk * norm \
                    - drop_sum * (1.0 - kdrop * norm)
            else:
                scores = scores + delta_nk

            ys = y if multiclass else yf
            if rf:
                eval_scores = scores0 + (scores - scores0) / (
                    it.astype(jnp.float32) + 1.0)
            else:
                eval_scores = scores
            sc = eval_scores if multiclass else eval_scores[:, 0]
            if ranking:
                tm = rank_metric(sc, w)
                vm = rank_metric(sc, w_valid)
            else:
                tm = metric_of(sc, ys, w)
                vm = metric_of(sc, ys, w_valid)
            return (scores, deltas, tree_scale, key), (tree, tm, vm)

        deltas0 = (jnp.zeros((t_cap, n, k if multiclass else 1), jnp.float32)
                   if dart else jnp.zeros((1, 1, 1), jnp.float32))
        tree_scale0 = jnp.ones((t_cap,), jnp.float32)
        return step, scores0, init, deltas0, tree_scale0

    def train(binned, y, w_all, is_train, init_margin, key, group_idx=None,
              lr_mult=None, hp=None):
        """init_margin [N, K]: per-row starting margins (initScoreCol / warm
        start / batch training — LightGBMBase.scala:29-50, TrainUtils.scala:57-129).
        Zeros when absent. group_idx [NG, G] (lambdarank only): padded
        gather-index group layout from ops.ranking.make_group_layout.
        lr_mult [T] (optional): per-iteration learning-rate multipliers.
        hp (optional HParams of traced scalars): continuous hyperparameters;
        defaults to the config's values. `jax.vmap` over an HParams batch
        (shared data in_axes=None) trains many configurations in one
        program — see models/lightgbm LightGBMBase.fit(df, paramMaps)."""
        if hp is None:
            hp = HParams.from_config(cfg)
        step, scores0, init, deltas0, tree_scale0 = _env(
            binned, y, w_all, is_train, init_margin, group_idx, hp)
        lr = (jnp.ones((cfg.num_iterations,), jnp.float32) if lr_mult is None
              else jnp.asarray(lr_mult, jnp.float32))
        (scores, _, tree_scale, _), (trees, train_m, valid_m) = jax.lax.scan(
            step, (scores0, deltas0, tree_scale0, key),
            (jnp.arange(cfg.num_iterations), lr))
        if dart:
            # bake final DART scales into the leaf values; leaf_value is
            # [T, L] single-output or [T, K, L] multiclass — the per-
            # iteration scale broadcasts over every trailing axis
            scale = tree_scale.reshape(
                tree_scale.shape + (1,) * (trees.leaf_value.ndim - 1))
            trees = trees._replace(leaf_value=trees.leaf_value * scale)
        init_out = jnp.full((k,), init) if multiclass else init
        return BoostResult(trees, init_out, train_m, valid_m)

    def train_chunk(binned, y, w_all, is_train, init_margin, key, start,
                    scores_in, lr_mult, group_idx=None, hp=None,
                    deltas_in=None, tree_scale_in=None):
        """Run ONE chunk of iterations [start, start+C) where C =
        len(lr_mult), carrying raw scores AND the PRNG key across chunks —
        chunk boundaries are invisible: any partition of [0, T) into chunks
        reproduces the one-program fit bit-for-bit, for every stochastic
        mode (feature_fraction, goss, dart dropout all draw from the
        carried key exactly as the full scan does).

        This is the jit-friendly analogue of the reference's `trainCore` loop
        actually HALTING on early stopping (TrainUtils.scala:220-315): the
        host checks the returned validation metrics between chunks and simply
        stops launching further chunks. At start == 0 the carried scores are
        ignored and the init-score margins are used.

        dart additionally carries (deltas_in [T,N,K], tree_scale_in [T]) —
        the per-iteration score deltas and cumulative rescales that dropout
        reads and retroactively updates. Chunked dart trees come back with
        leaf values NOT yet scaled by the final tree_scale (later chunks
        may still rescale earlier iterations); the caller bakes the LAST
        chunk's tree_scale into the accumulated trees once training halts
        (LightGBMClassifier._run_chunked), matching the full scan's
        end-of-fit baking.

        Returns (trees [C,...], train_metric [C], valid_metric [C],
        scores [N,K], key_out, init_score) — dart inserts
        (deltas [T,N,K], tree_scale [T]) before init_score."""
        if hp is None:
            hp = HParams.from_config(cfg)
        step, scores0, init, deltas0, tree_scale0 = _env(
            binned, y, w_all, is_train, init_margin, group_idx, hp)
        scores_start = jnp.where(start == 0, scores0, scores_in)
        if dart:
            assert deltas_in is not None and tree_scale_in is not None, (
                "chunked dart requires the carried deltas/tree_scale state")
            deltas_start, scale_start = deltas_in, tree_scale_in
        else:
            deltas_start, scale_start = deltas0, tree_scale0
        c = lr_mult.shape[0]
        its = start + jnp.arange(c)
        ((scores, deltas, tree_scale, key_out),
         (trees, train_m, valid_m)) = jax.lax.scan(
            step, (scores_start, deltas_start, scale_start, key),
            (its, jnp.asarray(lr_mult, jnp.float32)))
        init_out = jnp.full((k,), init) if multiclass else init
        if dart:
            return (trees, train_m, valid_m, scores, key_out, deltas,
                    tree_scale, init_out)
        return trees, train_m, valid_m, scores, key_out, init_out

    train.chunk = train_chunk
    return train
