"""Ring attention: sequence-parallel exact attention over a device mesh.

The long-context primitive for the deep-inference path (models/deep): the
reference scales deep scoring by replicating the CNTK graph per executor and
splitting ROWS (cntk/CNTKModel.scala:30-140); the TPU-native scaling axis for
transformer workloads is the SEQUENCE — shard Q/K/V over the mesh and rotate
K/V blocks around the ring with `jax.lax.ppermute` (ICI neighbor exchange)
while accumulating flash-style streaming softmax, so attention over a
sequence of length S costs each device O(S * S/P) FLOPs and O(S/P) memory
with communication fully overlappable — no [S, S] score matrix ever exists.

Math (single pass per incoming block, numerically stable):
    m'   = max(m, rowmax(q k'^T))
    c    = exp(m - m')
    p    = exp(q k'^T - m')
    l'   = l * c + rowsum(p)
    acc' = acc * c + p v'
and out = acc / l after all P blocks have visited.
"""

from __future__ import annotations

import functools
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from ..parallel.mesh import shard_map as _shard_map
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def attention_reference(q: jax.Array, k: jax.Array, v: jax.Array,
                        causal: bool = False) -> jax.Array:
    """Exact single-device attention. q,k,v: [B, S, H, D] -> [B, S, H, D]."""
    scale = 1.0 / np.sqrt(q.shape[-1])
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        sq, sk = scores.shape[-2], scores.shape[-1]
        mask = jnp.arange(sq)[:, None] >= jnp.arange(sk)[None, :]
        scores = jnp.where(mask, scores, -jnp.inf)
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _block_update(q, k_blk, v_blk, m, l, acc, q_pos, k_pos, causal):
    """One streaming-softmax update with an incoming K/V block."""
    scale = 1.0 / np.sqrt(q.shape[-1])
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk) * scale
    if causal:
        ok = q_pos[:, None] >= k_pos[None, :]
        scores = jnp.where(ok[None, None], scores, -jnp.inf)
    m_new = jnp.maximum(m, scores.max(axis=-1))
    # blocks can be fully masked: keep exp() finite and their weight zero
    m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
    corr = jnp.exp(jnp.where(jnp.isneginf(m), m_new, m) - m_safe)
    p = jnp.exp(scores - m_safe[..., None])
    l_new = l * corr + p.sum(axis=-1)
    acc_new = acc * corr[..., None] + jnp.einsum("bhqk,bkhd->bhqd", p, v_blk)
    return m_new, l_new, acc_new


def ring_attention_sharded(q: jax.Array, k: jax.Array, v: jax.Array,
                           axis_name: str, causal: bool = False) -> jax.Array:
    """Shard-local ring attention body (call inside shard_map/pjit).

    q, k, v: [B, S_local, H, D] — the local sequence shard, laid out so that
    device i on `axis_name` holds global positions [i*S_local, (i+1)*S_local).
    Returns the local [B, S_local, H, D] output shard.
    """
    p_count = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, s_loc, h, d = q.shape

    q_pos = idx * s_loc + jnp.arange(s_loc)
    m0 = jnp.full((b, h, s_loc), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, s_loc), jnp.float32)
    acc0 = jnp.zeros((b, h, s_loc, d), jnp.float32)
    perm = [(j, (j + 1) % p_count) for j in range(p_count)]

    def step(t, carry):
        k_cur, v_cur, m, l, acc = carry
        # after t rotations this device holds the block born on (idx - t) % P
        src = jnp.mod(idx - t, p_count)
        k_pos = src * s_loc + jnp.arange(s_loc)
        m, l, acc = _block_update(q, k_cur, v_cur, m, l, acc,
                                  q_pos, k_pos, causal)
        # rotate AFTER consuming; the final rotation is skipped by the loop
        # bound so every device ends one full cycle with its own block back
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return k_nxt, v_nxt, m, l, acc

    _, _, m, l, acc = jax.lax.fori_loop(
        0, p_count, step, (k, v, m0, l0, acc0))
    out = acc / jnp.maximum(l, 1e-30)[..., None]      # [B,H,S,D]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)  # [B,S,H,D]


def ring_attention(q, k, v, mesh, axis_name: str = "data",
                   causal: bool = False) -> jax.Array:
    """Driver: shard q/k/v over `axis_name` on the sequence dimension and run
    the ring. q,k,v: [B, S, H, D] with S divisible by the mesh axis size."""
    from jax.sharding import PartitionSpec as P

    spec = P(None, axis_name, None, None)
    fn = _shard_map(
        partial(ring_attention_sharded, axis_name=axis_name, causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)


def ulysses_attention_sharded(q: jax.Array, k: jax.Array, v: jax.Array,
                              axis_name: str,
                              causal: bool = False) -> jax.Array:
    """Shard-local Ulysses (all-to-all) sequence parallelism body (call
    inside shard_map/pjit). The complementary long-context strategy to the
    ppermute ring: one all-to-all converts the SEQUENCE sharding into a
    HEAD sharding (each device receives the FULL sequence for H/P of the
    heads), exact attention runs locally per head group, and a second
    all-to-all restores sequence sharding.

    q, k, v: [B, S_local, H, D] with H divisible by the axis size.

    Trade-off vs the ring (DeepSpeed-Ulysses, arXiv:2309.14509): 4
    all-to-alls of O(B*S_local*H*D) activations per call (q, k, v in, one
    out) vs the ring's P-1 ppermutes of K/V — fewer, larger collectives
    (better when ICI latency dominates and H >= P), at the cost of holding
    full-S K/V per device (the ring never materializes more than one
    remote block). No reference analogue — SURVEY.md §5 records the
    reference has no sequence parallelism at all.
    """
    p_count = jax.lax.psum(1, axis_name)
    h = q.shape[2]
    if h % p_count:
        raise ValueError(
            f"ulysses needs heads ({h}) divisible by the '{axis_name}' "
            f"axis ({p_count} devices); use ring attention otherwise")

    def seq_to_heads(x):
        # [B, S_loc, H, D] --all_to_all(H->S)--> [B, S_loc*P, H/P, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=2,
                                  concat_axis=1, tiled=True)

    qg, kg, vg = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    out = attention_reference(qg, kg, vg, causal=causal)
    # [B, S, H/P, D] --all_to_all(S->H)--> [B, S_loc, H, D]
    return jax.lax.all_to_all(out, axis_name, split_axis=1,
                              concat_axis=2, tiled=True)


def ulysses_attention(q, k, v, mesh, axis_name: str = "data",
                      causal: bool = False) -> jax.Array:
    """Driver: shard q/k/v over `axis_name` on the sequence dimension and
    run the all-to-all path. q,k,v: [B, S, H, D]; S divisible by the axis
    size, H divisible by the axis size."""
    from jax.sharding import PartitionSpec as P

    spec = P(None, axis_name, None, None)
    fn = _shard_map(
        partial(ulysses_attention_sharded, axis_name=axis_name,
                causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)


# ---------------------------------------------------------------------------
# Single-device flash attention (Pallas)
# ---------------------------------------------------------------------------

def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref, *,
                  block_q: int, block_k: int, s: int, d: int, causal: bool):
    # grid (BH, S/Bq, S/Bk), k-blocks minor. q_ref [1, Bq, Dp]; k/v [1, Bk, Dp];
    # o_ref [1, Bq, Dp]; scratch m/l [Bq, 128], acc [Bq, Dp] persist across
    # the k sweep of one (bh, qi) cell.
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    i = pl.program_id(1)
    q_pos = i * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    k_pos = j * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    run = True
    if causal:
        # skip k-blocks strictly above the diagonal (their mask is all-False)
        run = (j * block_k) <= (i * block_q + block_q - 1)

    @pl.when(run)
    def _step():
        q = q_ref[0].astype(jnp.float32)             # [Bq, Dp]
        k = k_ref[0].astype(jnp.float32)             # [Bk, Dp]
        scale = 1.0 / np.sqrt(d)                     # true head dim, not Dp
        scores = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale    # [Bq, Bk]
        valid = k_pos < s
        if causal:
            valid = valid & (q_pos >= k_pos)
        scores = jnp.where(valid, scores, -jnp.inf)

        m_prev = m_ref[:, 0]                         # [Bq]
        m_new = jnp.maximum(m_prev, scores.max(axis=1))
        m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        corr = jnp.exp(jnp.where(jnp.isneginf(m_prev), m_new, m_prev) - m_safe)
        p = jnp.exp(scores - m_safe[:, None])        # [Bq, Bk]
        l_ref[...] = (l_ref[...] * corr[:, None]
                      + jnp.broadcast_to(p.sum(axis=1)[:, None],
                                         l_ref.shape))
        acc_ref[...] = (acc_ref[...] * corr[:, None]
                        + jax.lax.dot_general(
                            p, v_ref[0].astype(jnp.float32),
                            (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32))
        m_ref[...] = jnp.broadcast_to(m_new[:, None], m_ref.shape)

    @pl.when(j == pl.num_programs(2) - 1)
    def _finish():
        o_ref[0] = (acc_ref[...]
                    / jnp.maximum(l_ref[:, 0], 1e-30)[:, None]
                    ).astype(o_ref.dtype)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = False, block_q: int = 256,
                    block_k: int = 256,
                    interpret: bool | None = None) -> jax.Array:
    """Fused single-device attention: no [S, S] score matrix ever reaches
    HBM (the XLA reference materializes [B, H, S, S], which at S=8k, H=8 is
    2 GB per batch element). q, k, v: [B, S, H, D] -> [B, S, H, D].

    Complements ring attention: the ring shards the sequence ACROSS devices
    (ops/attention.ring_attention); this kernel streams k-blocks WITHIN a
    device. Head dim pads to 128 lanes; sequence pads to the block size
    (padded k positions are masked, padded q rows are sliced off).
    """
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    b, s, h, d = q.shape
    d_pad = _round_up(d, 128)
    block_q = min(block_q, _round_up(s, 128))
    block_k = min(block_k, _round_up(s, 128))
    s_pad = _round_up(s, max(block_q, block_k))

    def prep(x):
        x = x.transpose(0, 2, 1, 3).reshape(b * h, s, d)
        return jnp.pad(x, ((0, 0), (0, s_pad - s), (0, d_pad - d)))

    qp, kp, vp = prep(q), prep(k), prep(v)
    grid = (b * h, s_pad // block_q, s_pad // block_k)
    out = pl.pallas_call(
        functools.partial(_flash_kernel, block_q=block_q, block_k=block_k,
                          s=s, d=d, causal=causal),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d_pad), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, block_k, d_pad), lambda bh, i, j: (bh, j, 0)),
            pl.BlockSpec((1, block_k, d_pad), lambda bh, i, j: (bh, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d_pad),
                               lambda bh, i, j: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, s_pad, d_pad), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, d_pad), jnp.float32),
        ],
        compiler_params=None if interpret else pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
            vmem_limit_bytes=100 << 20),
        interpret=interpret,
    )(qp, kp, vp)
    out = out[:, :s, :d].reshape(b, h, s, d)
    return out.transpose(0, 2, 1, 3)
