"""Ring attention: sequence-parallel exact attention over a device mesh.

The long-context primitive for the deep-inference path (models/deep): the
reference scales deep scoring by replicating the CNTK graph per executor and
splitting ROWS (cntk/CNTKModel.scala:30-140); the TPU-native scaling axis for
transformer workloads is the SEQUENCE — shard Q/K/V over the mesh and rotate
K/V blocks around the ring with `jax.lax.ppermute` (ICI neighbor exchange)
while accumulating flash-style streaming softmax, so attention over a
sequence of length S costs each device O(S * S/P) FLOPs and O(S/P) memory
with communication fully overlappable — no [S, S] score matrix ever exists.

Math (single pass per incoming block, numerically stable):
    m'   = max(m, rowmax(q k'^T))
    c    = exp(m - m')
    p    = exp(q k'^T - m')
    l'   = l * c + rowsum(p)
    acc' = acc * c + p v'
and out = acc / l after all P blocks have visited.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def attention_reference(q: jax.Array, k: jax.Array, v: jax.Array,
                        causal: bool = False) -> jax.Array:
    """Exact single-device attention. q,k,v: [B, S, H, D] -> [B, S, H, D]."""
    scale = 1.0 / np.sqrt(q.shape[-1])
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        sq, sk = scores.shape[-2], scores.shape[-1]
        mask = jnp.arange(sq)[:, None] >= jnp.arange(sk)[None, :]
        scores = jnp.where(mask, scores, -jnp.inf)
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _block_update(q, k_blk, v_blk, m, l, acc, q_pos, k_pos, causal):
    """One streaming-softmax update with an incoming K/V block."""
    scale = 1.0 / np.sqrt(q.shape[-1])
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk) * scale
    if causal:
        ok = q_pos[:, None] >= k_pos[None, :]
        scores = jnp.where(ok[None, None], scores, -jnp.inf)
    m_new = jnp.maximum(m, scores.max(axis=-1))
    # blocks can be fully masked: keep exp() finite and their weight zero
    m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
    corr = jnp.exp(jnp.where(jnp.isneginf(m), m_new, m) - m_safe)
    p = jnp.exp(scores - m_safe[..., None])
    l_new = l * corr + p.sum(axis=-1)
    acc_new = acc * corr[..., None] + jnp.einsum("bhqk,bkhd->bhqd", p, v_blk)
    return m_new, l_new, acc_new


def ring_attention_sharded(q: jax.Array, k: jax.Array, v: jax.Array,
                           axis_name: str, causal: bool = False) -> jax.Array:
    """Shard-local ring attention body (call inside shard_map/pjit).

    q, k, v: [B, S_local, H, D] — the local sequence shard, laid out so that
    device i on `axis_name` holds global positions [i*S_local, (i+1)*S_local).
    Returns the local [B, S_local, H, D] output shard.
    """
    p_count = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, s_loc, h, d = q.shape

    q_pos = idx * s_loc + jnp.arange(s_loc)
    m0 = jnp.full((b, h, s_loc), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, s_loc), jnp.float32)
    acc0 = jnp.zeros((b, h, s_loc, d), jnp.float32)
    perm = [(j, (j + 1) % p_count) for j in range(p_count)]

    def step(t, carry):
        k_cur, v_cur, m, l, acc = carry
        # after t rotations this device holds the block born on (idx - t) % P
        src = jnp.mod(idx - t, p_count)
        k_pos = src * s_loc + jnp.arange(s_loc)
        m, l, acc = _block_update(q, k_cur, v_cur, m, l, acc,
                                  q_pos, k_pos, causal)
        # rotate AFTER consuming; the final rotation is skipped by the loop
        # bound so every device ends one full cycle with its own block back
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return k_nxt, v_nxt, m, l, acc

    _, _, m, l, acc = jax.lax.fori_loop(
        0, p_count, step, (k, v, m0, l0, acc0))
    out = acc / jnp.maximum(l, 1e-30)[..., None]      # [B,H,S,D]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)  # [B,S,H,D]


def ring_attention(q, k, v, mesh, axis_name: str = "data",
                   causal: bool = False) -> jax.Array:
    """Driver: shard q/k/v over `axis_name` on the sequence dimension and run
    the ring. q,k,v: [B, S, H, D] with S divisible by the mesh axis size."""
    from jax.sharding import PartitionSpec as P

    spec = P(None, axis_name, None, None)
    fn = jax.shard_map(
        partial(ring_attention_sharded, axis_name=axis_name, causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)
