"""Measured kernel selection for the histogram hot path.

Round-1 verdict: `hist_chunk`/`hist_dtype` were static defaults and `auto`
was a backend lookup, with no measured operating curves (VERDICT Weak #4/#5).
This module picks the histogram kernel + block size by TIMING the candidates
on the live backend at the problem's actual (N, F, B, L) — the same
philosophy as LightGBM's own `force_col_wise/force_row_wise` auto-probe: the
first histogram build pays a short benchmark, every later build uses the
winner. Results are cached per (backend, shape bucket) in-process and in a
small JSON sidecar, so repeated fits and serving restarts skip the probe.
"""

from __future__ import annotations

import json
import os
import tempfile
import time
from typing import Dict, Optional, Tuple

import numpy as np

#: candidate (method, chunk/block_rows) grid per backend. CPU keeps scatter
#: (XLA's native scatter-add wins there by orders of magnitude); accelerator
#: candidates cover the MXU one-hot scan vs the Pallas VMEM kernel.
_ACCEL_CANDIDATES = (
    ("onehot", 4096),
    ("onehot", 16384),
    ("pallas", 2048),
    ("pallas", 4096),
    ("pallas", 8192),
)

_cache: Dict[Tuple, Tuple[str, int]] = {}


def _bucket(n: int) -> int:
    """Shape bucket: power-of-two rows so near sizes share a tuning."""
    return 1 << max(int(n) - 1, 1).bit_length()


def _sidecar_path() -> str:
    base = os.environ.get("MMLSPARK_TPU_CACHE",
                          os.path.join(tempfile.gettempdir(),
                                       "mmlspark_tpu_native"))
    os.makedirs(base, exist_ok=True)
    # v2: bumped when the timing methodology changed (host-fetch barrier) so
    # winners recorded with the broken block_until_ready timing are discarded
    return os.path.join(base, "hist_autotune_v2.json")


def _load_sidecar() -> Dict[str, list]:
    try:
        with open(_sidecar_path()) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def _store_sidecar(key: str, val: Tuple[str, int]) -> None:
    data = _load_sidecar()
    data[key] = list(val)
    try:
        tmp = _sidecar_path() + f".tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(data, f)
        os.replace(tmp, _sidecar_path())
    except OSError:
        pass


def measure_hist(method: str, chunk: int, n: int, f: int, b: int, l: int,
                 dtype: str = "bf16", repeats: int = 3,
                 inner: int = 16) -> float:
    """Median seconds per all-slots histogram pass at the given shape.

    Timing methodology for remote/tunneled backends, where three pitfalls
    were hit in round 2: (a) `block_until_ready` can return before the
    computation finishes (0.02 ms/pass readings for a 1M-row pass), so the
    barrier is a host FETCH of a scalar; (b) each dispatch+fetch pays the
    tunnel round trip (~60 ms), so passes run inside ONE jit program via
    lax.scan (gh perturbed per step to defeat CSE); (c) subtracting a
    separately-measured dispatch overhead is unstable when the relay jitters
    by more than the probe's compute (the recorded 0.00 ms/pass sweeps), so
    the per-pass time is the DIFFERENCE between a 3*inner-pass and an
    inner-pass program — the round trip cancels within each pair instead of
    across separate calibration calls."""
    import jax
    import jax.numpy as jnp
    from .histogram import hist_slots

    rng = np.random.default_rng(0)
    binned = jnp.asarray(rng.integers(0, b, (n, f)), jnp.uint8)
    slot = jnp.asarray(rng.integers(0, l, (n,)), jnp.int32)
    gh = jnp.asarray(rng.normal(size=(n, 3)), jnp.float32)

    def k_passes(k):
        def run(bi, sl, g):
            def body(acc, j):
                gj = g * (1.0 + 1e-6 * j.astype(jnp.float32))
                h = hist_slots(bi, sl, gj, l, b, method, chunk, dtype)
                return acc + jnp.sum(h), None
            acc, _ = jax.lax.scan(body, jnp.float32(0.0), jnp.arange(k))
            return acc
        return jax.jit(run)

    fn1, fn3 = k_passes(inner), k_passes(3 * inner)
    float(fn1(binned, slot, gh))                      # compile + settle
    float(fn3(binned, slot, gh))
    diffs = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        float(fn1(binned, slot, gh))
        t1 = time.perf_counter()
        float(fn3(binned, slot, gh))
        t2 = time.perf_counter()
        diffs.append((t2 - t1) - (t1 - t0))
    return max(float(np.median(diffs)), 1e-9) / (2 * inner)


def pick_hist_config(n: int, f: int, b: int, l: int, dtype: str = "bf16",
                     probe_rows: int = 262_144,
                     verbose: bool = False) -> Tuple[str, int]:
    """Measured (method, chunk) for the backend at this shape.

    Probes at min(n, probe_rows) rows — per-pass time is linear in N, so the
    ranking transfers while the probe stays < a few seconds.
    """
    import jax
    backend = jax.default_backend()
    if backend == "cpu":
        return "scatter", 512
    key = (backend, _bucket(n), f, b, l, dtype)
    if key in _cache:
        return _cache[key]
    skey = "/".join(map(str, key))
    side = _load_sidecar()
    if skey in side:
        best = (str(side[skey][0]), int(side[skey][1]))
        _cache[key] = best
        return best

    n_probe = int(min(n, probe_rows))
    results = {}
    for method, chunk in _ACCEL_CANDIDATES:
        try:
            results[(method, chunk)] = measure_hist(method, chunk, n_probe,
                                                    f, b, l, dtype)
        except Exception:  # noqa: BLE001 - a kernel variant may not lower
            continue
    if not results:
        return "onehot", 8192
    best = min(results, key=results.get)
    if verbose:
        for (m, c), t in sorted(results.items(), key=lambda kv: kv[1]):
            print(f"  hist autotune {m:7s} chunk={c:<6d} "
                  f"{t * 1e3:8.2f} ms/pass")
    _cache[key] = best
    _store_sidecar(skey, best)
    return best
