"""LambdaRank gradients + NDCG — group-padded, fully batched for TPU.

Reference analogue: `LightGBMRanker` (lightgbm/LightGBMRanker.scala:24-162) sets
`objective=lambdarank` and hands group-sorted partitions to the LightGBM C++ core, which
computes pairwise lambda gradients per query group. Here the same math runs as one jit
program: groups are padded to a common width G and laid out as a gather-index matrix
[NG, G] into row space, so every pairwise [G, G] interaction is a dense batched op on the
VPU/MXU instead of the C++ per-group loops.

Group layout convention: `group_idx[q, i]` is the row index of the i-th document of query
q, or `n` (one past the last row) for padding. Gathers use a scores vector padded with one
sentinel entry; scatters back to row space use mode='drop' so padding vanishes.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class GroupLayout(NamedTuple):
    """Host-computed padded group layout (static shapes for jit)."""
    group_idx: np.ndarray   # [NG, G] int32; padding entries == n_rows
    order: np.ndarray       # [N] int32 — row permutation that sorted groups contiguously


def make_group_layout(groups: np.ndarray) -> GroupLayout:
    """Build the padded gather layout from a per-row group-id column.

    Rows of one group need not be contiguous in the input (the reference enforces
    contiguity with repartitionByGroupingColumn, LightGBMRanker.scala:77+; here the
    gather layout makes physical order irrelevant).
    """
    groups = np.asarray(groups)
    n = groups.shape[0]
    order = np.argsort(groups, kind="stable").astype(np.int32)
    sorted_g = groups[order]
    # group boundaries
    starts = np.flatnonzero(np.r_[True, sorted_g[1:] != sorted_g[:-1]])
    ends = np.r_[starts[1:], n]
    sizes = ends - starts
    ng, g = len(starts), int(sizes.max()) if len(starts) else 1
    idx = np.full((ng, g), n, dtype=np.int32)
    for q, (s, e) in enumerate(zip(starts, ends)):
        idx[q, : e - s] = order[s:e]
    return GroupLayout(idx, order)


def _gather_padded(v: jax.Array, group_idx: jax.Array, fill: float):
    """v [N] -> [NG, G] with `fill` in padding slots."""
    vp = jnp.concatenate([v, jnp.full((1,), fill, v.dtype)])
    return vp[group_idx]


def label_gains(labels: jax.Array, label_gain: jax.Array) -> jax.Array:
    """Graded-relevance gain: label_gain[label] (default 2^l - 1, LightGBM
    `label_gain`; maxPosition/labelGain params at LightGBMRanker.scala:24-162)."""
    return label_gain[jnp.clip(labels.astype(jnp.int32), 0,
                               label_gain.shape[0] - 1)]


def _dcg_discount(ranks: jax.Array, max_position: int) -> jax.Array:
    """1/log2(2+rank) for rank < max_position else 0."""
    d = 1.0 / jnp.log2(2.0 + ranks.astype(jnp.float32))
    return jnp.where(ranks < max_position, d, 0.0)


def ndcg_per_group(scores_g: jax.Array, labels_g: jax.Array, valid_g: jax.Array,
                   label_gain: jax.Array, max_position: int
                   ) -> Tuple[jax.Array, jax.Array]:
    """(ndcg [NG], has_rel [NG]) — NDCG@max_position per padded group.

    scores_g/labels_g/valid_g: [NG, G]; valid_g 0.0 in padding slots.
    """
    neg = jnp.float32(-1e30)
    s = jnp.where(valid_g > 0, scores_g, neg)
    gains = jnp.where(valid_g > 0, label_gains(labels_g, label_gain), 0.0)
    # rank of each doc under the model = position in descending score order
    order = jnp.argsort(-s, axis=1)
    ranks = jnp.argsort(order, axis=1)
    dcg = jnp.sum(gains * _dcg_discount(ranks, max_position), axis=1)
    ideal = -jnp.sort(-gains, axis=1)
    g = gains.shape[1]
    idcg = jnp.sum(ideal * _dcg_discount(jnp.arange(g)[None, :], max_position),
                   axis=1)
    has_rel = idcg > 0
    return jnp.where(has_rel, dcg / jnp.maximum(idcg, 1e-12), 0.0), has_rel


def lambdarank_grad_hess(scores: jax.Array, labels: jax.Array,
                         group_idx: jax.Array, label_gain: jax.Array,
                         max_position: int = 20, sigma: float = 1.0,
                         row_valid: Optional[jax.Array] = None
                         ) -> Tuple[jax.Array, jax.Array]:
    """Pairwise lambda gradients with |ΔNDCG| weighting, scattered back to rows.

    scores/labels: [N]; group_idx: [NG, G]; row_valid: [N] 1.0 for rows allowed
    to form pairs (training rows — excludes validation/padding rows so their
    labels can't leak into gradients). Returns (grad [N], hess [N]).
    Matches LightGBM's lambdarank objective (norm=true style: ΔNDCG normalized by
    group IDCG).
    """
    n = scores.shape[0]
    row_valid = (jnp.ones((n,), jnp.float32) if row_valid is None
                 else row_valid.astype(jnp.float32))
    valid = _gather_padded(row_valid, group_idx, 0.0)
    s = _gather_padded(scores.astype(jnp.float32), group_idx, 0.0)
    y = _gather_padded(labels.astype(jnp.float32), group_idx, 0.0)

    gains = jnp.where(valid > 0, label_gains(y, label_gain), 0.0)  # [NG,G]
    neg = jnp.float32(-1e30)
    sm = jnp.where(valid > 0, s, neg)
    order = jnp.argsort(-sm, axis=1)
    ranks = jnp.argsort(order, axis=1)                              # [NG,G]
    disc = _dcg_discount(ranks, max_position)                       # [NG,G]

    g_w = gains.shape[1]
    ideal = -jnp.sort(-gains, axis=1)
    idcg = jnp.sum(ideal * _dcg_discount(jnp.arange(g_w)[None, :], max_position),
                   axis=1)
    inv_idcg = jnp.where(idcg > 0, 1.0 / jnp.maximum(idcg, 1e-12), 0.0)  # [NG]

    # pairwise [NG, G, G]: i relevant-er than j
    sd = s[:, :, None] - s[:, None, :]
    rel = gains[:, :, None] - gains[:, None, :]
    pair_ok = ((rel > 0) & (valid[:, :, None] > 0) & (valid[:, None, :] > 0))
    # |ΔNDCG| of swapping i and j
    ddisc = jnp.abs(disc[:, :, None] - disc[:, None, :])
    delta_ndcg = jnp.abs(rel) * ddisc * inv_idcg[:, None, None]
    rho = jax.nn.sigmoid(-sigma * sd)           # P(wrong order) for i>j pairs
    lam = jnp.where(pair_ok, sigma * rho * delta_ndcg, 0.0)
    hij = jnp.where(pair_ok, sigma * sigma * rho * (1.0 - rho) * delta_ndcg, 0.0)

    # doc i as the "better" side gets -lam, as the "worse" side gets +lam
    grad_g = -jnp.sum(lam, axis=2) + jnp.sum(lam, axis=1)
    hess_g = jnp.sum(hij, axis=2) + jnp.sum(hij, axis=1)

    grad = jnp.zeros((n,), jnp.float32).at[group_idx.reshape(-1)].add(
        grad_g.reshape(-1), mode="drop")
    hess = jnp.zeros((n,), jnp.float32).at[group_idx.reshape(-1)].add(
        hess_g.reshape(-1), mode="drop")
    # LightGBM floors the hessian to keep leaf outputs bounded
    return grad, jnp.maximum(hess, 1e-6)


def default_label_gain(max_label: int = 31) -> np.ndarray:
    """2^l - 1 (LightGBMConstants / lambdarank default label_gain)."""
    return (np.power(2.0, np.arange(max_label + 1)) - 1.0).astype(np.float32)


class ShardedGroupLayout(NamedTuple):
    """Group-aligned sharding: whole query groups per shard (the TPU analogue of
    LightGBMRanker.repartitionByGroupingColumn — a group must never straddle the
    data axis or its pairwise lambdas would need cross-shard traffic)."""
    order: np.ndarray       # [nd * R] int64 — row index into original arrays, -1 = padding
    group_idx: np.ndarray   # [nd * NG, G] int32 — shard-local; split along axis 0 by shard
    rows_per_shard: int     # R
    groups_per_shard: int   # NG


def make_sharded_group_layout(groups: np.ndarray, nd: int) -> ShardedGroupLayout:
    """Greedy size-balanced assignment of groups to `nd` shards + padded layouts."""
    groups = np.asarray(groups)
    n = groups.shape[0]
    base = make_group_layout(groups)
    sorted_g = groups[base.order]
    starts = np.flatnonzero(np.r_[True, sorted_g[1:] != sorted_g[:-1]])
    ends = np.r_[starts[1:], n]
    sizes = ends - starts
    g_max = int(sizes.max()) if sizes.size else 1

    by_size = np.argsort(-sizes, kind="stable")
    shard_of = np.empty(len(starts), np.int64)
    load = np.zeros(nd, np.int64)
    for q in by_size:
        s = int(np.argmin(load))
        shard_of[q] = s
        load[s] += sizes[q]

    r = int(load.max()) if nd else 0
    ng = max(int(np.max(np.bincount(shard_of, minlength=nd))), 1)
    order = np.full((nd, r), -1, np.int64)
    gidx = np.full((nd, ng, g_max), r, np.int32)  # pad = shard-local n (== R)
    fill = np.zeros(nd, np.int64)
    gcount = np.zeros(nd, np.int64)
    for q, (s0, e0) in enumerate(zip(starts, ends)):
        s = shard_of[q]
        rows = base.order[s0:e0]
        at = fill[s]
        order[s, at:at + len(rows)] = rows
        gidx[s, gcount[s], : len(rows)] = np.arange(at, at + len(rows))
        fill[s] += len(rows)
        gcount[s] += 1
    return ShardedGroupLayout(order.reshape(-1), gidx.reshape(nd * ng, g_max),
                              r, ng)
