"""TPU compute kernels: GBDT engine, histograms, attention, binning, ranking."""

from .attention import (attention_reference, ring_attention,
                        ulysses_attention)
from .histogram import build_histogram, hist_slots

__all__ = ["attention_reference", "ring_attention",
           "ulysses_attention", "build_histogram",
           "hist_slots"]
