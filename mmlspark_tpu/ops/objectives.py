"""GBDT objectives: per-row gradient/hessian, init score, link, eval metric.

Reference analogue: LightGBM's objective zoo as surfaced by the param traits
(lightgbm/LightGBMParams.scala:206+ `objective`; LightGBMRegressor.scala:29-139 quantile
`alpha` / `tweedieVariancePower`; LightGBMConstants.scala objectives list). The C++ core
computes these per row; here each objective is a pure jnp function evaluated under jit on
the whole score vector, so it fuses into the boosting scan.

All functions take raw margin scores and labels shaped [N] (binary/regression) or
[N, K] scores with int labels (multiclass).
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp


class Objective(NamedTuple):
    name: str
    # (scores, y) -> (grad, hess), same shape as scores
    grad_hess: Callable
    # (y, w) -> scalar init margin (boost_from_average)
    init_score: Callable
    # scores -> prediction-space output (sigmoid/softmax/identity/exp)
    link: Callable
    # (scores, y, w) -> scalar eval metric value (lower is better unless noted)
    metric: Callable
    metric_name: str
    larger_is_better: bool = False


def _wmean(v, w):
    return jnp.sum(v * w) / jnp.maximum(jnp.sum(w), 1e-12)


# ----------------------------------------------------------------- binary
def _binary_grad_hess(scores, y):
    p = jax.nn.sigmoid(scores)
    return p - y, p * (1.0 - p)


def _binary_init(y, w):
    p = jnp.clip(_wmean(y, w), 1e-7, 1 - 1e-7)
    return jnp.log(p / (1 - p))


def _binary_logloss(scores, y, w):
    p = jnp.clip(jax.nn.sigmoid(scores), 1e-15, 1 - 1e-15)
    return _wmean(-(y * jnp.log(p) + (1 - y) * jnp.log(1 - p)), w)


binary = Objective("binary", _binary_grad_hess, _binary_init,
                   jax.nn.sigmoid, _binary_logloss, "binary_logloss")


# ------------------------------------------------------------- multiclass
def _multiclass_grad_hess(scores, y):
    # scores [N,K], y int [N]
    k = scores.shape[1]
    p = jax.nn.softmax(scores, axis=1)
    onehot = jax.nn.one_hot(y, k, dtype=scores.dtype)
    factor = k / (k - 1.0)
    return p - onehot, factor * p * (1.0 - p)


def _multiclass_init(y, w):
    return 0.0


def _multiclass_logloss(scores, y, w):
    logp = jax.nn.log_softmax(scores, axis=1)
    picked = jnp.take_along_axis(logp, y[:, None].astype(jnp.int32), axis=1)[:, 0]
    return _wmean(-picked, w)


multiclass = Objective("multiclass", _multiclass_grad_hess, _multiclass_init,
                       lambda s: jax.nn.softmax(s, axis=-1),
                       _multiclass_logloss, "multi_logloss")


def _ova_grad_hess(scores, y):
    """multiclassova: K independent binary sigmoid problems on one-hot labels
    (upstream multiclass_ova), unlike softmax's coupled gradients."""
    k = scores.shape[1]
    p = jax.nn.sigmoid(scores)
    onehot = jax.nn.one_hot(y, k, dtype=scores.dtype)
    return p - onehot, jnp.maximum(p * (1.0 - p), 1e-16)


def _ova_link(s):
    p = jax.nn.sigmoid(s)
    return p / jnp.maximum(p.sum(axis=-1, keepdims=True), 1e-15)


def _ova_logloss(scores, y, w):
    k = scores.shape[1]
    p = jnp.clip(jax.nn.sigmoid(scores), 1e-15, 1 - 1e-15)
    onehot = jax.nn.one_hot(y, k, dtype=scores.dtype)
    ll = -(onehot * jnp.log(p) + (1 - onehot) * jnp.log(1 - p)).sum(axis=1)
    return _wmean(ll, w)


multiclassova = Objective("multiclassova", _ova_grad_hess, _multiclass_init,
                          _ova_link, _ova_logloss, "multi_logloss")


# ------------------------------------------------------------- regression
def _l2_grad_hess(scores, y):
    return scores - y, jnp.ones_like(scores)


def _l2_init(y, w):
    return _wmean(y, w)


def _l2_metric(scores, y, w):
    return _wmean((scores - y) ** 2, w)


regression = Objective("regression", _l2_grad_hess, _l2_init,
                       lambda s: s, _l2_metric, "l2")


def _l1_grad_hess(scores, y):
    return jnp.sign(scores - y), jnp.ones_like(scores)


def _l1_init(y, w):
    # weighted-median init approximated by mean (LightGBM uses median)
    return _wmean(y, w)


regression_l1 = Objective(
    "regression_l1", _l1_grad_hess, _l1_init, lambda s: s,
    lambda s, y, w: _wmean(jnp.abs(s - y), w), "l1")


def make_huber(alpha: float = 0.9) -> Objective:
    def gh(scores, y):
        d = scores - y
        return jnp.clip(d, -alpha, alpha), jnp.ones_like(scores)
    return Objective("huber", gh, _l2_init, lambda s: s, _l2_metric, "huber")


def make_quantile(alpha: float = 0.9) -> Objective:
    """Pinball-loss quantile regression (LightGBMRegressor `alpha`)."""
    def gh(scores, y):
        d = scores - y
        g = jnp.where(d >= 0, 1.0 - alpha, -alpha)
        return g, jnp.ones_like(scores)

    def metric(s, y, w):
        d = y - s
        return _wmean(jnp.maximum(alpha * d, (alpha - 1) * d), w)
    return Objective("quantile", gh, _l2_init, lambda s: s, metric, "quantile")


def make_tweedie(rho: float = 1.5) -> Objective:
    """Tweedie deviance, log-link (LightGBMRegressor `tweedieVariancePower`)."""
    def gh(scores, y):
        g = -y * jnp.exp((1 - rho) * scores) + jnp.exp((2 - rho) * scores)
        h = (-y * (1 - rho) * jnp.exp((1 - rho) * scores)
             + (2 - rho) * jnp.exp((2 - rho) * scores))
        return g, jnp.maximum(h, 1e-12)

    def init(y, w):
        return jnp.log(jnp.maximum(_wmean(y, w), 1e-12))

    def metric(s, y, w):
        mu = jnp.exp(s)
        dev = 2 * (jnp.power(jnp.maximum(y, 0), 2 - rho) / ((1 - rho) * (2 - rho))
                   - y * jnp.power(mu, 1 - rho) / (1 - rho)
                   + jnp.power(mu, 2 - rho) / (2 - rho))
        return _wmean(dev, w)
    return Objective("tweedie", gh, init, jnp.exp, metric, "tweedie")


def make_poisson() -> Objective:
    def gh(scores, y):
        mu = jnp.exp(scores)
        return mu - y, mu
    return Objective("poisson", gh,
                     lambda y, w: jnp.log(jnp.maximum(_wmean(y, w), 1e-12)),
                     jnp.exp,
                     lambda s, y, w: _wmean(jnp.exp(s) - y * s, w), "poisson")


def _fair_c(c: float = 1.0):
    def gh(scores, y):
        d = scores - y
        g = c * d / (jnp.abs(d) + c)
        h = c * c / (jnp.abs(d) + c) ** 2
        return g, h
    return gh


fair = Objective("fair", _fair_c(), _l2_init, lambda s: s, _l2_metric, "fair")


def make_gamma() -> Objective:
    """Gamma regression NLL with log link (upstream objective=gamma):
    grad = 1 - y*exp(-s), hess = y*exp(-s)."""
    def gh(scores, y):
        e = y * jnp.exp(-scores)
        return 1.0 - e, e
    return Objective("gamma", gh,
                     lambda y, w: jnp.log(jnp.maximum(_wmean(y, w), 1e-12)),
                     jnp.exp,
                     lambda s, y, w: _wmean(s + y * jnp.exp(-s), w), "gamma")


def make_mape() -> Objective:
    """MAPE (upstream mean_absolute_percentage_error): L1 scaled by 1/|y|
    (|y| floored at 1 like upstream's label clip)."""
    def gh(scores, y):
        inv = 1.0 / jnp.maximum(jnp.abs(y), 1.0)
        return jnp.sign(scores - y) * inv, inv
    return Objective(
        "mape", gh,
        _l1_init, lambda s: s,
        lambda s, y, w: _wmean(jnp.abs(s - y)
                               / jnp.maximum(jnp.abs(y), 1.0), w), "mape")


def make_cross_entropy() -> Objective:
    """cross_entropy (xentropy): sigmoid link with CONTINUOUS labels in
    [0, 1] — binary's gradient form, unrestricted label support."""
    def gh(scores, y):
        p = jax.nn.sigmoid(scores)
        return p - y, jnp.maximum(p * (1.0 - p), 1e-16)
    def init(y, w):
        m = jnp.clip(_wmean(y, w), 1e-7, 1 - 1e-7)
        return jnp.log(m / (1 - m))
    def metric(s, y, w):
        p = jnp.clip(jax.nn.sigmoid(s), 1e-15, 1 - 1e-15)
        return _wmean(-(y * jnp.log(p) + (1 - y) * jnp.log(1 - p)), w)
    return Objective("cross_entropy", gh, init, jax.nn.sigmoid, metric,
                     "xentropy")


def get_objective(name: str, num_class: int = 1, alpha: float = 0.9,
                  tweedie_variance_power: float = 1.5) -> Objective:
    """Resolve by LightGBM objective string (TrainParams.scala objective values)."""
    name = {"regression_l2": "regression", "mean_squared_error": "regression",
            "mse": "regression", "l2": "regression", "l1": "regression_l1",
            "mae": "regression_l1", "multiclass_ova": "multiclassova",
            "ova": "multiclassova", "ovr": "multiclassova",
            "softmax": "multiclass",
            "mean_absolute_percentage_error": "mape",
            "xentropy": "cross_entropy"}.get(name, name)
    table = {
        "binary": binary,
        "multiclass": multiclass,
        "multiclassova": multiclassova,
        "regression": regression,
        "regression_l1": regression_l1,
        "huber": make_huber(alpha),
        "quantile": make_quantile(alpha),
        "tweedie": make_tweedie(tweedie_variance_power),
        "poisson": make_poisson(),
        "fair": fair,
        "gamma": make_gamma(),
        "mape": make_mape(),
        "cross_entropy": make_cross_entropy(),
        # lambdarank grad/hess live in ops.ranking (they need group structure);
        # this entry provides link/metric surfaces for fitted-model scoring
        "lambdarank": Objective(
            "lambdarank",
            lambda s, y: (_ for _ in ()).throw(
                RuntimeError("lambdarank gradients require group layout")),
            lambda y, w: 0.0, lambda s: s,
            lambda s, y, w: 0.0, "ndcg", larger_is_better=True),
    }
    if name not in table:
        raise ValueError(f"unknown objective {name!r}; known: {sorted(table)}")
    return table[name]
