"""Ranking evaluation + adapters + train/validation split.

Reference: recommendation/RankingEvaluator.scala:98-152 (+
`AdvancedRankingMetrics` :15-97 — ndcgAt, map, precisionAtk, recallAtK,
diversityAtK, maxDiversity), recommendation/RankingAdapter.scala:67-151 (turn a
recommender into a ranking-evaluable stage), and
recommendation/RankingTrainValidationSplit.scala:24-328 (per-user stratified
split + param sweep).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..core import params as _p
from ..core.dataframe import DataFrame
from ..core.pipeline import Estimator, Evaluator, Model, Transformer


class AdvancedRankingMetrics:
    """Per-dataset ranking metrics over (predicted items, relevant items).

    Semantics match the reference `AdvancedRankingMetrics`
    (RankingEvaluator.scala:15-97), which mixes Spark mllib RankingMetrics
    (map over the FULL prediction list divided by label-set size; ndcgAt /
    precisionAt truncated at k; empty ground truth contributes 0) with its own
    recallAtK (divided by the PREDICTION list length, :28-31), mrr (first-hit
    reciprocal rank, :43-61) and fcp (positionwise concordance, :62-74).
    """

    def __init__(self, pred_lists: Sequence[Sequence], label_lists:
                 Sequence[Sequence], k: int, n_items: int):
        self.preds = [list(p) for p in pred_lists]          # full lists
        self.label_lists = [list(l) for l in label_lists]   # ordered
        self.labels = [set(l) for l in label_lists]
        self.k = k
        self.n_items = n_items

    def ndcg_at(self) -> float:
        vals = []
        for pred, rel in zip(self.preds, self.labels):
            dcg = sum(1.0 / np.log2(i + 2)
                      for i, p in enumerate(pred[:self.k]) if p in rel)
            idcg = sum(1.0 / np.log2(i + 2)
                       for i in range(min(len(rel), self.k)))
            vals.append(dcg / idcg if idcg > 0 else 0.0)
        return float(np.mean(vals)) if vals else 0.0

    def mean_average_precision(self) -> float:
        # Spark meanAveragePrecision: full prediction list, / label-set size.
        vals = []
        for pred, rel in zip(self.preds, self.labels):
            hits, s = 0, 0.0
            for i, p in enumerate(pred):
                if p in rel:
                    hits += 1
                    s += hits / (i + 1)
            vals.append(s / len(rel) if rel else 0.0)
        return float(np.mean(vals)) if vals else 0.0

    def precision_at_k(self) -> float:
        # Spark precisionAt(k): hit count over first k (duplicates count), / k.
        vals = [sum(1 for p in pred[:self.k] if p in rel) / self.k
                for pred, rel in zip(self.preds, self.labels)]
        return float(np.mean(vals)) if vals else 0.0

    def recall_at_k(self) -> float:
        # Reference recallAtK divides by the prediction-list length
        # (RankingEvaluator.scala:28-31), not the relevant-set size.
        vals = [len(set(pred) & rel) / len(pred) if pred else 0.0
                for pred, rel in zip(self.preds, self.labels)]
        return float(np.mean(vals)) if vals else 0.0

    def mean_reciprocal_rank(self) -> float:
        vals = []
        for pred, rel in zip(self.preds, self.labels):
            rr = 0.0
            if rel:
                for i, p in enumerate(pred):
                    if p in rel:
                        rr = 1.0 / (i + 1)
                        break
            vals.append(rr)
        return float(np.mean(vals)) if vals else 0.0

    def fraction_concordant_pairs(self) -> float:
        vals = []
        for pred, lab in zip(self.preds, self.label_lists):
            nc = sum(1 for i, p in enumerate(pred)
                     if i < len(lab) and p == lab[i])
            nd = sum(1 for i, p in enumerate(pred)
                     if i < len(lab) and p != lab[i])
            vals.append(nc / (nc + nd) if nc + nd else 0.0)
        return float(np.mean(vals)) if vals else 0.0

    def diversity_at_k(self) -> float:
        """Distinct recommended items in the top k / catalog size
        (RankingEvaluator diversityAtK — the reference receives exactly-k
        lists from RankingAdapter, so "at K" = truncate here)."""
        distinct = set()
        for pred in self.preds:
            distinct.update(pred[:self.k])
        return len(distinct) / max(self.n_items, 1)

    def max_diversity(self) -> float:
        distinct = set()
        for lab in self.labels:
            distinct.update(lab)
        for pred in self.preds:
            distinct.update(pred[:self.k])
        return len(distinct) / max(self.n_items, 1)

    def _table(self):
        return {"ndcgAt": self.ndcg_at, "map": self.mean_average_precision,
                "precisionAtk": self.precision_at_k,
                "recallAtK": self.recall_at_k,
                "diversityAtK": self.diversity_at_k,
                "maxDiversity": self.max_diversity,
                "mrr": self.mean_reciprocal_rank,
                "fcp": self.fraction_concordant_pairs}

    def get(self, name: str) -> float:
        table = self._table()
        if name not in table:
            raise ValueError(f"unknown ranking metric {name!r}; "
                             f"known: {sorted(table)}")
        return table[name]()

    def all(self) -> Dict[str, float]:
        return {name: fn() for name, fn in self._table().items()}


class RankingEvaluator(Evaluator):
    k = _p.Param("k", "cutoff", 10, int)
    metricName = _p.Param("metricName", "ndcgAt | map | precisionAtk | "
                          "recallAtK | diversityAtK | maxDiversity | mrr | "
                          "fcp", "ndcgAt")
    nItems = _p.Param("nItems", "catalog size (for diversity metrics)", 0, int)
    predictionCol = _p.Param("predictionCol",
                             "column of recommended item lists", "prediction")
    labelCol = _p.Param("labelCol", "column of relevant item lists", "label")

    def _metrics(self, df: DataFrame) -> AdvancedRankingMetrics:
        return AdvancedRankingMetrics(
            df[self.get("predictionCol")], df[self.get("labelCol")],
            self.get("k"), self.get("nItems"))

    def evaluate(self, df: DataFrame) -> float:
        return self._metrics(df).get(self.get("metricName"))

    def get_metrics_map(self, df: DataFrame) -> Dict[str, float]:
        """Every ranking metric at once (RankingEvaluator.getMetricsMap —
        the surface RankingEvaluatorSpec drives)."""
        return self._metrics(df).all()

    getMetricsMap = get_metrics_map

    def is_larger_better(self) -> bool:
        return True


class RankingAdapter(Estimator):
    """Fit the wrapped recommender; transform emits per-user
    (prediction=list of recommended items, label=list of observed items) for
    RankingEvaluator (RankingAdapter.scala:67-151, mode=allUsers)."""

    recommender = _p.Param("recommender", "inner recommender estimator", None,
                           complex=True)
    k = _p.Param("k", "recommendations per user", 10, int)

    def __init__(self, recommender: Optional[Estimator] = None, **kw):
        super().__init__(**kw)
        if recommender is not None:
            self.set("recommender", recommender)

    def _fit(self, df: DataFrame) -> "RankingAdapterModel":
        est = self.get("recommender")
        inner = est.fit(df)
        model = RankingAdapterModel(inner_model=inner)
        model.set("k", self.get("k"))
        model.set("userCol", inner.get("userCol"))
        model.set("itemCol", inner.get("itemCol"))
        try:
            model.set("ratingCol", est.get("ratingCol"))
        except ValueError:   # recommender without a ratingCol param
            pass
        return model


class RankingAdapterModel(Model):
    innerModel = _p.Param("innerModel", "fitted recommender", None,
                          complex=True)
    k = _p.Param("k", "recommendations per user", 10, int)
    userCol = _p.Param("userCol", "user column", "user")
    itemCol = _p.Param("itemCol", "item column", "item")
    ratingCol = _p.Param("ratingCol", "rating column (label ordering)",
                         "rating")

    def __init__(self, inner_model=None, **kw):
        super().__init__(**kw)
        if inner_model is not None:
            self.set("innerModel", inner_model)

    def transform(self, df: DataFrame) -> DataFrame:
        """Reference semantics (RankingAdapterModel.transform,
        RankingAdapter.scala:117-141): label = the user's TOP-K observed
        items ordered by (rating desc, item asc) — not every observed item
        — and prediction = the recommender's raw (unfiltered) top-k, i.e.
        recommendForAllUsers with seen items INCLUDED."""
        ucol, icol = self.get("userCol"), self.get("itemCol")
        k = self.get("k")
        inner = self.get("innerModel")
        try:
            recs = inner.recommend_for_all_users(k, remove_seen=False)
        except TypeError as e:
            # only fall back when the TypeError is the signature rejecting
            # the kwarg — a TypeError raised INSIDE a supporting recommender
            # must propagate, not silently flip to the seen-filtered path
            if "remove_seen" not in str(e):
                raise
            recs = inner.recommend_for_all_users(k)
        rec_map: Dict[int, List] = {
            int(u): [r["item"] for r in rl]
            for u, rl in zip(recs[ucol], recs["recommendations"])}
        users = np.asarray(df[ucol], np.int64)
        items = np.asarray(df[icol], np.int64)
        rcol = self.get("ratingCol")
        ratings = (np.asarray(df[rcol], np.float64) if rcol in df
                   else np.ones(len(users), np.float64))
        uniq = np.unique(users)
        preds = np.empty(len(uniq), dtype=object)
        labels = np.empty(len(uniq), dtype=object)
        for i, u in enumerate(uniq):
            mask = users == u
            order = sorted(zip(-ratings[mask], items[mask]))
            labels[i] = [int(it) for _, it in order[:k]]
            preds[i] = rec_map.get(int(u), [])
        return DataFrame({ucol: uniq, "prediction": preds, "label": labels})


class RankingTrainValidationSplit(Estimator):
    """Per-user stratified split + (optional) param sweep
    (RankingTrainValidationSplit.scala:24-328)."""

    estimator = _p.Param("estimator", "recommender estimator", None,
                         complex=True)
    evaluator = _p.Param("evaluator", "RankingEvaluator", None, complex=True)
    estimatorParamMaps = _p.Param("estimatorParamMaps",
                                  "list of param override dicts", None,
                                  complex=True)
    trainRatio = _p.Param("trainRatio", "per-user train fraction", 0.75, float)
    userCol = _p.Param("userCol", "user column", "user")
    itemCol = _p.Param("itemCol", "item column", "item")
    seed = _p.Param("seed", "split seed", 0, int)

    def __init__(self, estimator: Optional[Estimator] = None, **kw):
        super().__init__(**kw)
        if estimator is not None:
            self.set("estimator", estimator)

    def _split(self, df: DataFrame):
        users = np.asarray(df[self.get("userCol")], np.int64)
        rng = np.random.default_rng(self.get("seed"))
        ratio = self.get("trainRatio")
        train_mask = np.zeros(len(df), bool)
        for u in np.unique(users):
            idx = np.flatnonzero(users == u)
            rng.shuffle(idx)
            cut = max(1, int(round(len(idx) * ratio)))
            train_mask[idx[:cut]] = True
        return df.filter(train_mask), df.filter(~train_mask)

    def _fit(self, df: DataFrame) -> "RankingTrainValidationSplitModel":
        train, valid = self._split(df)
        est = self.get("estimator")
        evaluator = self.get("evaluator") or RankingEvaluator()
        maps = self.get("estimatorParamMaps") or [{}]
        k = evaluator.get("k")
        best, best_metric, metrics = None, -np.inf, []
        for overrides in maps:
            adapter = RankingAdapter(recommender=est.copy(overrides), k=k)
            fitted = adapter.fit(train)
            metric = evaluator.evaluate(fitted.transform(valid))
            metrics.append(metric)
            if not np.isfinite(metric):
                # never let a NaN candidate pin best_metric (it would defeat
                # all later comparisons) — same policy as automl.tune
                if best is None:
                    best = fitted
                continue
            better = (metric > best_metric if evaluator.is_larger_better()
                      else metric < best_metric)
            if best is None or not np.isfinite(best_metric) or better:
                best, best_metric = fitted, metric
        out = RankingTrainValidationSplitModel(best_model=best,
                                               validation_metrics=metrics)
        return out


class RankingTrainValidationSplitModel(Model):
    bestModel = _p.Param("bestModel", "winning fitted adapter", None,
                         complex=True)
    validationMetrics = _p.Param("validationMetrics", "per-candidate metrics",
                                 None, complex=True)

    def __init__(self, best_model=None, validation_metrics=None, **kw):
        super().__init__(**kw)
        if best_model is not None:
            self._set(bestModel=best_model,
                      validationMetrics=list(validation_metrics or []))

    def transform(self, df: DataFrame) -> DataFrame:
        return self.get("bestModel").transform(df)

    def recommend_for_all_users(self, k: int) -> DataFrame:
        return self.get("bestModel").get("innerModel"
                                         ).recommend_for_all_users(k)
