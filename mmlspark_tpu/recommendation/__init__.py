"""Recommendation layer (reference: recommendation/, 6 files, 1225 LoC)."""

from .ranking import (AdvancedRankingMetrics, RankingAdapter,
                      RankingAdapterModel, RankingEvaluator,
                      RankingTrainValidationSplit,
                      RankingTrainValidationSplitModel)
from .sar import SAR, RecommendationIndexer, RecommendationIndexerModel, SARModel

__all__ = [
    "SAR", "SARModel",
    "RecommendationIndexer", "RecommendationIndexerModel",
    "RankingAdapter", "RankingAdapterModel",
    "RankingEvaluator", "AdvancedRankingMetrics",
    "RankingTrainValidationSplit", "RankingTrainValidationSplitModel",
]
