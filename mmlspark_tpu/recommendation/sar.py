"""SAR — Smart Adaptive Recommendations, TPU-native.

Reference: recommendation/SAR.scala:38-206 — user-item affinity with time decay
(:84-121), item-item similarity from co-occurrence counts with cooccurrence /
lift / jaccard metrics (:152-205, broadcast sparse matrix multiply), and
recommendation/SARModel.scala:23-169 (recommendForAllUsers via affinity x
similarity score matrix).

TPU design: the co-occurrence matrix is one [I,U]x[U,I] MXU contraction over
the dense user-item interaction matrix; scoring is affinity @ similarity with
seen-item masking and lax.top_k — no broadcast joins, no sparse multiplies.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core import params as _p
from ..core.dataframe import DataFrame
from ..core.pipeline import Estimator, Model


class SAR(Estimator):
    userCol = _p.Param("userCol", "user index column", "user")
    itemCol = _p.Param("itemCol", "item index column", "item")
    ratingCol = _p.Param("ratingCol", "rating column (optional)", "rating")
    timeCol = _p.Param("timeCol", "event-time column (epoch seconds) for "
                       "affinity decay", None)
    supportThreshold = _p.Param("supportThreshold",
                                "min co-occurrence support", 4, int)
    similarityFunction = _p.Param(
        "similarityFunction", "jaccard | lift | cooccurrence", "jaccard")
    timeDecayCoeff = _p.Param("timeDecayCoeff",
                              "half-life in days for affinity decay", 30, int)
    alpha = _p.Param("alpha", "weight of rating in affinity", 1.0, float)
    startTime = _p.Param(
        "startTime", "decay reference time (string, parsed with "
        "startTimeFormat); default = the latest event time", None)
    startTimeFormat = _p.Param(
        "startTimeFormat", "Java SimpleDateFormat pattern for startTime "
        "(SAR.scala setStartTimeFormat)", "yyyy/MM/dd'T'h:mm:ss")
    activityTimeFormat = _p.Param(
        "activityTimeFormat", "Java SimpleDateFormat pattern for string "
        "timeCol values; numeric timeCol = epoch seconds",
        "yyyy/MM/dd'T'h:mm:ss")

    def _fit(self, df: DataFrame) -> "SARModel":
        users = np.asarray(df[self.get("userCol")], np.int64)
        items = np.asarray(df[self.get("itemCol")], np.int64)
        n_users = int(users.max()) + 1
        n_items = int(items.max()) + 1
        ratings = (np.asarray(df[self.get("ratingCol")], np.float64)
                   if self.get("ratingCol") in df
                   else np.ones(len(df), np.float64))

        # --- user-item affinity with time decay (SAR.scala:84-121):
        # a(u,i) = sum_events rating * 2^(-minutes(t_ref - t) / half_life);
        # upstream truncates the difference to whole MINUTES (Java long
        # division by 1000*60, SAR.scala:90-93) — replicated here so the
        # TLC golden affinities match bit-for-bit
        if self.get("timeCol") and self.get("timeCol") in df:
            t_raw = df[self.get("timeCol")]
            t = _to_epoch_seconds(t_raw, self.get("activityTimeFormat"))
            if self.get("startTime"):
                ref = _parse_java_datetime(self.get("startTime"),
                                           self.get("startTimeFormat"))
            else:
                ref = t.max()
            half_life_min = float(self.get("timeDecayCoeff")) * 24.0 * 60.0
            minutes = np.trunc((ref - t) / 60.0)
            decay = np.exp2(-minutes / half_life_min)
        else:
            decay = np.ones(len(df), np.float64)
        affinity = np.zeros((n_users, n_items), np.float32)
        np.add.at(affinity, (users, items),
                  (self.get("alpha") * ratings * decay).astype(np.float32))

        # --- item-item similarity from co-occurrence (SAR.scala:152-205)
        seen = np.zeros((n_users, n_items), np.float32)
        seen[users, items] = 1.0
        cooc = np.asarray(_cooccurrence(jnp.asarray(seen)))  # [I,I] on MXU
        support = np.diag(cooc).copy()
        thresh = float(self.get("supportThreshold"))
        cooc = np.where(cooc >= thresh, cooc, 0.0)
        kind = self.get("similarityFunction")
        if kind == "cooccurrence":
            sim = cooc
        elif kind == "lift":
            denom = np.outer(support, support)
            sim = np.divide(cooc, denom, out=np.zeros_like(cooc),
                            where=denom > 0)
        elif kind == "jaccard":
            denom = support[:, None] + support[None, :] - cooc
            sim = np.divide(cooc, denom, out=np.zeros_like(cooc),
                            where=denom > 0)
        else:
            raise ValueError(f"unknown similarityFunction {kind!r}")

        model = SARModel(affinity=affinity.astype(np.float32),
                         similarity=sim.astype(np.float32),
                         seen=seen)
        for p in ("userCol", "itemCol"):
            model.set(p, self.get(p))
        return model


def _java_fmt_to_strptime(fmt: str) -> str:
    """Translate the Java SimpleDateFormat subset the reference uses
    (SAR.scala startTimeFormat/activityTimeFormat defaults and the TLC
    test's yyyy/MM/dd'T'h:mm:ss) into a strptime pattern. 'h' is Java's
    12-hour field, but SimpleDateFormat parses leniently so h:mm:ss accepts
    24-hour values — %H reproduces that for the formats in play.

    Pattern letters outside the supported subset (e.g. 'a' AM/PM, 'z'
    timezone) raise rather than silently parsing to wrong epoch seconds.
    """
    import re
    literals: list = []

    def _hide(m):
        literals.append(m.group(1))
        return "\x00%d\x00" % (len(literals) - 1)

    # SimpleDateFormat: '' is a literal apostrophe (inside or outside a
    # quoted section) — protect it before the quoted-section scan
    out = re.sub(r"'([^']*)'", _hide, fmt.replace("''", "\x01"))
    for java, py in (("yyyy", "%Y"), ("MM", "%m"), ("dd", "%d"),
                     ("HH", "%H"), ("hh", "%H"), ("h", "%H"),
                     ("mm", "%M"), ("ss", "%S")):
        out = out.replace(java, py)
    bad = sorted(set(re.findall(r"[A-Za-z]", re.sub(r"%[A-Za-z]", "", out))))
    if bad:
        raise ValueError(
            f"unsupported SimpleDateFormat token(s) {bad} in {fmt!r}; "
            "supported subset: yyyy MM dd HH hh h mm ss + quoted literals")
    for i, lit in enumerate(literals):
        out = out.replace("\x00%d\x00" % i, lit)
    return out.replace("\x01", "'")


def _parse_java_datetime(value: str, fmt: str) -> float:
    from datetime import datetime, timezone
    dt = datetime.strptime(str(value), _java_fmt_to_strptime(fmt))
    return dt.replace(tzinfo=timezone.utc).timestamp()


def _to_epoch_seconds(col, fmt: str) -> np.ndarray:
    arr = np.asarray(col)
    if np.issubdtype(arr.dtype, np.number):
        return arr.astype(np.float64)
    if np.issubdtype(arr.dtype, np.datetime64):
        return arr.astype("datetime64[s]").astype(np.float64)
    return np.asarray([_parse_java_datetime(v, fmt) for v in arr],
                      np.float64)


@jax.jit
def _cooccurrence(seen):
    return seen.T @ seen


@jax.jit
def _affinity_scores(affinity_rows, similarity):
    return affinity_rows @ similarity


@jax.jit
def _sar_scores(affinity, similarity, seen):
    """score = affinity @ similarity, masking already-seen items to -inf."""
    scores = affinity @ similarity
    return jnp.where(seen > 0, -jnp.inf, scores)


class SARModel(Model):
    userCol = _p.Param("userCol", "user index column", "user")
    itemCol = _p.Param("itemCol", "item index column", "item")
    affinity = _p.Param("affinity", "user-item affinity [U,I]", None,
                        complex=True)
    similarity = _p.Param("similarity", "item-item similarity [I,I]", None,
                          complex=True)
    seen = _p.Param("seen", "user-item seen mask [U,I]", None, complex=True)

    def __init__(self, affinity=None, similarity=None, seen=None, **kw):
        super().__init__(**kw)
        if affinity is not None:
            self._set(affinity=affinity, similarity=similarity, seen=seen)

    def get_item_similarity(self) -> np.ndarray:
        return self.get("similarity")

    getItemSimilarity = get_item_similarity

    def recommend_for_all_users(self, num_items: int,
                                remove_seen: bool = True) -> DataFrame:
        """Reference: SARModel.recommendForAllUsers (:23-169). Output rows:
        (user, recommendations=[{item, rating}...]).

        remove_seen=True (default) masks items the user already interacted
        with; remove_seen=False reproduces the reference's raw
        affinity @ similarity top-k (SARModel.scala recommendForAll does
        not filter seen items — its tests filter manually), which
        RankingAdapterModel relies on for metric parity."""
        if remove_seen:
            scores = np.asarray(_sar_scores(
                jnp.asarray(self.get("affinity")),
                jnp.asarray(self.get("similarity")),
                jnp.asarray(self.get("seen"))))
        else:
            scores = np.asarray(_affinity_scores(
                jnp.asarray(self.get("affinity")),
                jnp.asarray(self.get("similarity"))))
        k = min(num_items, scores.shape[1])
        neg, idx = jax.lax.top_k(jnp.asarray(scores), k)
        top_scores, top_items = np.asarray(neg), np.asarray(idx)
        n_users = scores.shape[0]
        recs = np.empty(n_users, dtype=object)
        for u in range(n_users):
            recs[u] = [{"item": int(i), "rating": float(s)}
                       for i, s in zip(top_items[u], top_scores[u])
                       if np.isfinite(s)]
        return DataFrame({self.get("userCol"): np.arange(n_users),
                          "recommendations": recs})

    recommendForAllUsers = recommend_for_all_users

    def transform(self, df: DataFrame) -> DataFrame:
        """Score (user, item) pairs. Only the rows for users actually present
        are contracted (affinity[uniq] @ similarity), not the full [U,I]
        score matrix. Out-of-range ids (e.g. the -1 sentinel emitted by
        RecommendationIndexerModel for unseen values) predict NaN."""
        users = np.asarray(df[self.get("userCol")], np.int64)
        items = np.asarray(df[self.get("itemCol")], np.int64)
        affinity = self.get("affinity")
        similarity = self.get("similarity")
        n_users, n_items = affinity.shape
        valid = ((users >= 0) & (users < n_users)
                 & (items >= 0) & (items < n_items))
        uniq, inv = np.unique(users[valid], return_inverse=True)
        pred = np.full(len(users), np.nan)
        if uniq.size:
            sub = np.asarray(_affinity_scores(
                jnp.asarray(affinity[uniq]), jnp.asarray(similarity)))
            pred[valid] = sub[inv, items[valid]]
        return df.with_column("prediction", pred)


class RecommendationIndexer(Estimator):
    """String user/item ids -> contiguous ints (reference:
    recommendation/RecommendationIndexer.scala)."""

    userInputCol = _p.Param("userInputCol", "raw user column", "user")
    itemInputCol = _p.Param("itemInputCol", "raw item column", "item")
    userOutputCol = _p.Param("userOutputCol", "indexed user column",
                             "user_idx")
    itemOutputCol = _p.Param("itemOutputCol", "indexed item column",
                             "item_idx")

    def _fit(self, df: DataFrame) -> "RecommendationIndexerModel":
        users = sorted(set(df[self.get("userInputCol")].tolist()), key=str)
        items = sorted(set(df[self.get("itemInputCol")].tolist()), key=str)
        model = RecommendationIndexerModel(user_levels=users,
                                           item_levels=items)
        for p in ("userInputCol", "itemInputCol", "userOutputCol",
                  "itemOutputCol"):
            model.set(p, self.get(p))
        return model


class RecommendationIndexerModel(Model):
    userInputCol = _p.Param("userInputCol", "raw user column", "user")
    itemInputCol = _p.Param("itemInputCol", "raw item column", "item")
    userOutputCol = _p.Param("userOutputCol", "indexed user column",
                             "user_idx")
    itemOutputCol = _p.Param("itemOutputCol", "indexed item column",
                             "item_idx")
    userLevels = _p.Param("userLevels", "ordered user ids", None, complex=True)
    itemLevels = _p.Param("itemLevels", "ordered item ids", None, complex=True)

    def __init__(self, user_levels=None, item_levels=None, **kw):
        super().__init__(**kw)
        if user_levels is not None:
            self._set(userLevels=list(user_levels),
                      itemLevels=list(item_levels))

    def transform(self, df: DataFrame) -> DataFrame:
        u_lookup = {v: i for i, v in enumerate(self.get("userLevels"))}
        i_lookup = {v: i for i, v in enumerate(self.get("itemLevels"))}
        u = np.array([u_lookup.get(v, -1)
                      for v in df[self.get("userInputCol")]], np.int64)
        it = np.array([i_lookup.get(v, -1)
                       for v in df[self.get("itemInputCol")]], np.int64)
        return (df.with_column(self.get("userOutputCol"), u)
                  .with_column(self.get("itemOutputCol"), it))
